"""Static analysis over the Program IR: def-use chains, liveness,
side-effect classification, and the program verifier.

Capability parity with the reference's C++-layer well-formedness
enforcement (operator.cc OperatorBase checks, tools/check_op_desc.py
schema gates) plus the move MLIR makes with its between-pass verifier:
every pass in the pre-lowering pipeline (framework/passes.py) rewrites a
cloned program based on invariants, and nothing used to check a pass's
OUTPUT — a buggy rewrite surfaced as a deep lowering KeyError or, behind
a compile-cache hit, silently wrong numerics. This module is the shared
substrate:

- one authoritative purity/side-effect classifier (:data:`SIDE_EFFECT_OPS`,
  :func:`is_side_effect_type`, :func:`is_pure_op`) — previously copied
  ad hoc inside ``passes.py``;
- SSA-style def-use chains keyed on binding versions
  (:func:`block_def_use`): the IR rebinds names (optimizer in-place
  writes, BN stats), so a value is identified by ``(name, version)``;
- reachability/liveness from the fetch/persistable/side-effect roots
  (:func:`live_op_ids`) — the single implementation DCE consumes;
- sub-block-aware read/write sets (:func:`op_reads` / :func:`op_writes`);
- registry-driven shape/dtype inference checking
  (:func:`check_shapes`) — re-derives output shapes through each op's
  registered lowering (jax.eval_shape) and compares to the declared
  VarDescs;
- :func:`verify_program`: the checker suite, raising a typed
  :class:`ProgramVerifyError` carrying op index and producing-pass
  provenance instead of a runtime KeyError;
- :class:`PipelineValidator`: per-pass translation validation (Pnueli's
  "verify each output instead of trusting the transformation") run by
  ``optimize_program`` under ``FLAGS_verify_passes`` — well-formedness
  diffs against the pipeline input plus semantic preservation checks
  (live RNG streams, side-effect ops, persistable writes, and
  writes-before-observer ordering).
"""
import collections

import numpy as np

from ..resilience import EnforceNotMet

# ---------------------------------------------------------------------------
# Authoritative purity / side-effect classification.
# ---------------------------------------------------------------------------

# Ops whose execution is observable beyond their outputs (host printing,
# RPC/parameter-server traffic, user callbacks, runtime checks): DCE
# roots, never CSE candidates. Collective "c_*"-prefixed ops are treated
# the same without being listed.
# Discard sentinel for unneeded grad outputs (reference kEmptyVarName):
# a write sink, legitimately repeated within one grad op, never read.
EMPTY_VAR = "@EMPTY@"

SIDE_EFFECT_OPS = frozenset({
    "print", "py_func", "runtime_assert", "assert", "feed", "fetch",
    "send", "recv", "send_barrier", "fetch_barrier", "listen_and_serv",
    "distributed_lookup_table", "pull_sparse", "pull_sparse_v2",
    "push_sparse", "push_sparse_v2", "pull_box_sparse", "push_box_sparse",
    "broadcast", "alltoall", "run_program",
})


# type -> bool memo: classification is pure string logic over a frozen
# set, and the verifier asks tens of thousands of times per pipeline
_side_effect_memo = {}


def is_side_effect_type(t):
    """Side-effecting op types, including their grad ops: a custom grad
    lowering can carry the effect itself (distributed_lookup_table_grad
    pushes sparse grads to the pserver via io_callback — removing it as
    'dead' silently stops the embedding from learning)."""
    r = _side_effect_memo.get(t)
    if r is None:
        if t in SIDE_EFFECT_OPS or t.startswith("c_"):
            r = True
        else:
            r = t.endswith("_grad") and is_side_effect_type(t[:-5])
        _side_effect_memo[t] = r
    return r


def has_sub_block(op):
    attrs = op.attrs
    # inlined Program._SUB_BLOCK_ATTRS: this sits on every per-op walk
    return (attrs.get("sub_block") is not None
            or attrs.get("sub_block_true") is not None
            or attrs.get("sub_block_false") is not None)


_OPS = None          # registry.OPS, bound on first use (mutated in
                     # place by register_op/tests, so the ref stays live)


def _ops():
    global _OPS
    if _OPS is None:
        from .registry import OPS
        _OPS = OPS
    return _OPS


def needs_rng(op):
    """Whether `op` consumes the program RNG stream (its own
    ``__rng_seed__`` attr, or a registry op marked needs_rng — grad ops
    inherit the forward op's classification). The registry is consulted
    live (never memoized): tests and load_op_library mutate OPS."""
    if "__rng_seed__" in op.attrs:
        return True
    OPS = _ops()
    t = op.type
    base = OPS.get(t) or (OPS.get(t[:-5]) if t.endswith("_grad") else None)
    return bool(base is not None and base.needs_rng)


def rng_seed_of(op):
    """The seed identifying an op's PRNG stream: its own
    ``__rng_seed__``, the forward op's seed for grad ops (carried inside
    ``__fwd_op__`` so fwd/bwd dropout masks match), or a user-pinned
    ``seed`` attr. None = no stream identity (the missing-rng-seed
    diagnostic)."""
    seed = op.attrs.get("__rng_seed__")
    if seed is not None:
        return seed
    fwd = op.attrs.get("__fwd_op__")
    if isinstance(fwd, dict):
        seed = fwd.get("attrs", {}).get("__rng_seed__")
        if seed is not None:
            return seed
    return op.attrs.get("seed") or None


def writes_persistable(block, op):
    for n in op.output_arg_names:
        try:
            if block.var(n).persistable:
                return True
        except ValueError:
            continue
    return False


def is_pure_op(op):
    """Pure = removable when its outputs are dead, mergeable when its
    value is duplicated: registered, no side effects, no sub-block, no
    RNG stream."""
    from .registry import has_op
    return (has_op(op.type) and not is_side_effect_type(op.type)
            and not has_sub_block(op) and not needs_rng(op))


# ---------------------------------------------------------------------------
# Sub-block-aware read/write sets.
# ---------------------------------------------------------------------------

def sub_block_bound_names(op):
    """Names a control-flow op itself binds inside its sub-block (scan
    slices, loop memories, branch operands): defined there, not read
    from the enclosing frame."""
    bound = set(op.attrs.get("step_input_vars", ()))
    for m in op.attrs.get("memories", ()):
        # the lowering (analyze_block_io) binds the memory's FIRST name
        # at sub-block entry; later names are produced by sub-block ops
        bound.add(m[0] if isinstance(m, (list, tuple)) else m)
    bound.update(op.attrs.get("x_names", ()))
    if "x_name" in op.attrs:
        bound.add(op.attrs["x_name"])
    return bound


def op_reads(program, op):
    """All var names an op (transitively, through its sub-blocks) reads
    from its defining block's frame."""
    return program._op_reads(op)


def op_writes(program, op, _seen=None):
    """All var names an op writes into its defining block's frame: its
    own outputs plus sub-block op outputs (the lowering runs sub-block
    ops over the SHARED env, so their writes are visible after the
    control-flow op) that the sub-block did not bind locally. Dangling
    or cyclic ``sub_block`` attrs (a corrupted artifact) are skipped —
    the verifier's sub-block-scope checker is where they get reported."""
    from .core import Program
    writes = set(op.output_arg_names)
    if _seen is None:
        _seen = set()
    for attr in Program._SUB_BLOCK_ATTRS:
        sb = op.attrs.get(attr)
        if sb is None:
            continue
        if not isinstance(sb, int) or not 0 <= sb < len(program.blocks) \
                or sb in _seen:
            continue     # dangling/cyclic attr: the verifier reports it
        _seen.add(sb)
        inner = sub_block_bound_names(op)
        for sop in program.blocks[sb].ops:
            writes.update(n for n in op_writes(program, sop, _seen)
                          if n not in inner)
    return writes


def sub_block_pinned_reads(program):
    """Every name a control-flow op (transitively) reads: renames don't
    descend into sub-blocks, so these names must stay fixed under CSE
    and act as observation points for fusion/reorder checks."""
    pinned = set()
    for blk in program.blocks:
        for op in blk.ops:
            if has_sub_block(op):
                pinned |= op_reads(program, op)
    return pinned


# ---------------------------------------------------------------------------
# SSA-style def-use chains keyed on binding versions.
# ---------------------------------------------------------------------------

class OpSite:
    """One op occurrence in a block walk: reads/writes as
    (name, version) pairs. The classifier bits are the standalone
    functions above (is_side_effect_type, has_sub_block, ...) — kept off
    this record so building def-use for a 200-op program stays a single
    cheap walk."""

    __slots__ = ("index", "op", "reads", "writes")

    def __init__(self, index, op, reads, writes):
        self.index = index
        self.op = op
        self.reads = reads            # tuple[(name, version-read)]
        self.writes = writes          # tuple[(name, version-created)]


class BlockDefUse:
    """Def-use over one block's linear op list. A value is
    ``(name, version)``: version 0 is the binding live at block entry
    (feed / scope state), each write creates version+1.

    - ``sites``: one :class:`OpSite` per op, in order
    - ``defs``: (name, version) -> defining op index (version >= 1)
    - ``uses``: (name, version) -> [op indices reading that binding]
    - ``def_count``: name -> number of writes in the block
    """

    def __init__(self, program, block):
        self.program = program
        self.block = block
        self.sites = []
        self.defs = {}
        self.uses = collections.defaultdict(list)
        self.def_count = collections.Counter()
        version = collections.Counter()
        for i, op in enumerate(block.ops):
            reads = tuple((n, version[n]) for n in op.input_arg_names)
            for n, v in reads:
                self.uses[(n, v)].append(i)
            writes = []
            for n in op.output_arg_names:
                version[n] += 1
                self.def_count[n] += 1
                writes.append((n, version[n]))
                self.defs[(n, version[n])] = i
            self.sites.append(OpSite(i, op, reads, tuple(writes)))
        self._final_version = version

    def readers_of(self, name, version):
        return self.uses.get((name, version), [])

    def last_version(self, name):
        return self._final_version[name]


def block_def_use(program, block_idx=0):
    """Build :class:`BlockDefUse` for one block (default: global)."""
    return BlockDefUse(program, program.blocks[block_idx])


# ---------------------------------------------------------------------------
# Liveness: reachability from fetch / persistable-write / side-effect
# roots — THE definition DCE and the translation summaries share.
# ---------------------------------------------------------------------------

def global_persistable_names(program):
    """Global-block persistable var names (the DCE/verifier root set)."""
    return {n for n, v in program.global_block().vars.items()
            if v.persistable}


def live_op_ids(program, fetch_names=(), _pset=None):
    """ids of the global-block ops reachable (backwards) from the fetch
    targets, persistable writes, and side-effect roots. Control-flow ops
    keep their whole sub-block; only block 0 is analyzed (sub-block ops
    live iff their owner does). The root predicate — side-effecting,
    has a sub-block, output-less, unregistered type, or writes a
    persistable — lives inlined in the loop below; it has no other
    copy."""
    block = program.global_block()
    if isinstance(fetch_names, str):
        fetch_names = (fetch_names,)
    needed = set(fetch_names or ())
    pset = global_persistable_names(program) if _pset is None else _pset
    live = set()
    OPS = _ops()
    for op in reversed(block.ops):
        t = op.type
        sub = has_sub_block(op)
        if (is_side_effect_type(t) or sub or not op.outputs
                or not (t in OPS
                        or (t.endswith("_grad") and t[:-5] in OPS))
                or any(n in pset or n in needed
                       for ns in op.outputs.values() for n in ns)):
            live.add(id(op))
            if sub:
                needed.update(op_reads(program, op))
            else:
                for ns in op.inputs.values():
                    needed.update(ns)
    return live


# ---------------------------------------------------------------------------
# The program verifier.
# ---------------------------------------------------------------------------

#: code -> one-line description (the diagnostics catalog; every checker
#: in verify_program emits exactly one of these codes)
CHECKS = {
    "unknown-op": "op type is not in the registry (framework.registry."
                  "OPS) and has no generic grad fallback",
    "missing-rng-seed": "an RNG-consuming op lost its __rng_seed__ attr "
                        "(its stream would collide with seed 0)",
    "dangling-read": "op reads a var no op defines that is neither "
                     "persistable, fed, nor data",
    "use-before-def": "op reads a var that is only defined by a LATER "
                      "op in the same block",
    "duplicate-output": "one op lists the same output name more than "
                        "once (ambiguous binding)",
    "dead-persistable-write": "a pure op's persistable write is "
                              "clobbered before any op reads it "
                              "(pedantic tier: per-pass validation and "
                              "lint --pedantic only — user programs "
                              "legally double-init shared params)",
    "sub-block-scope": "a sub-block op reads a name invisible in its "
                       "frame chain, or a sub_block attr points at a "
                       "missing/mis-parented block",
    "unreachable-fetch": "a fetch target no op produces and the scope "
                         "cannot supply",
    "shape-mismatch": "declared output shape disagrees with the "
                      "registry lowering's inferred shape",
    "dtype-mismatch": "declared output dtype disagrees with the "
                      "registry lowering's inferred dtype",
    # translation-validation codes (pass-pair checks; PipelineValidator)
    "rng-stream-dropped": "a live RNG op's stream disappeared across a "
                          "pass (e.g. CSE merged two dropout ops)",
    "side-effect-dropped": "a live side-effecting op disappeared across "
                           "a pass",
    "persistable-write-dropped": "a live persistable write (e.g. an "
                                 "optimizer update) disappeared across "
                                 "a pass",
    "reordered-past-observer": "a write moved across a side-effect/"
                               "sub-block op that observes that var",
}


class Diagnostic:
    """One verifier finding. ``key`` is stable across op-index shifts so
    pipeline-input findings can be suppressed when re-checking a pass's
    output."""

    __slots__ = ("code", "message", "block_idx", "op_index", "op_type",
                 "var")

    def __init__(self, code, message, block_idx=0, op_index=None,
                 op_type=None, var=None):
        self.code = code
        self.message = message
        self.block_idx = block_idx
        self.op_index = op_index
        self.op_type = op_type
        self.var = var

    @property
    def key(self):
        return (self.code, self.block_idx, self.op_type, self.var)

    def __str__(self):
        loc = f"block {self.block_idx}"
        if self.op_index is not None:
            loc += f" op #{self.op_index}"
        if self.op_type:
            loc += f" ({self.op_type})"
        return f"[{self.code}] {loc}: {self.message}"

    def __repr__(self):
        return f"Diagnostic({self!s})"


class ProgramVerifyError(EnforceNotMet):
    """A program failed verification. Carries the structured location —
    ``code`` (one of :data:`CHECKS`), ``op_index``/``op_type``/
    ``block_idx``/``var`` — plus ``pass_name``, the producing pass when
    the failure came from per-pass translation validation
    (``FLAGS_verify_passes``), and ``diagnostics``, every finding of the
    run (the message shows the first)."""

    def __init__(self, diagnostics, pass_name=None, program_desc=None):
        if isinstance(diagnostics, Diagnostic):
            diagnostics = [diagnostics]
        self.diagnostics = list(diagnostics)
        first = self.diagnostics[0]
        self.code = first.code
        self.op_index = first.op_index
        self.op_type = first.op_type
        self.block_idx = first.block_idx
        self.var = first.var
        self.pass_name = pass_name
        parts = []
        if pass_name:
            parts.append(f"pass {pass_name!r} produced an invalid "
                         f"program")
        elif program_desc:
            parts.append(f"program verification failed ({program_desc})")
        else:
            parts.append("program verification failed")
        parts.append(str(first))
        if len(self.diagnostics) > 1:
            parts.append(f"(+{len(self.diagnostics) - 1} more finding"
                         f"{'s' if len(self.diagnostics) > 2 else ''})")
        super().__init__(": ".join(parts[:2]) + (
            " " + parts[2] if len(parts) > 2 else ""))


class _WalkState:
    """Shared mutable state for the fused verifier walk (one traversal
    runs the schema, def-use, duplicate-output, and dead-persistable
    checkers together — this executes per pass under
    FLAGS_verify_passes, so the op loop must stay single-visit)."""

    __slots__ = ("diags", "all_defs", "pset0", "pending", "pversion",
                 "visited", "pedantic")

    def __init__(self, diags, all_defs, pset0, pedantic=False):
        self.diags = diags
        self.all_defs = all_defs     # every name any op writes
        self.pset0 = pset0           # global-block persistable names
        self.pending = {}            # unread pure persistable writes
        self.pversion = collections.Counter()
        self.visited = set()         # block idxs reached from block 0
        self.pedantic = pedantic     # dead-persistable-write tier


def _walk_block(program, block_idx, defined, st, depth=0):
    """The fused verifier walk: `defined` is the set of names bound at
    this block's entry (mutated as ops write). Per op: registry/RNG
    schema checks, duplicate outputs, read binding (dangling-read /
    use-before-def / sub-block-scope), dead-persistable-write tracking
    (block 0 straight line), and sub-block descent."""
    from .core import Program
    st.visited.add(block_idx)
    diags = st.diags
    all_defs = st.all_defs
    block = program.blocks[block_idx]
    OPS = _ops()
    for i, op in enumerate(block.ops):
        t = op.type
        # --- schema: registered type, RNG stream identity (one registry
        # lookup per op — this loop runs per pass under the flag)
        opdef = OPS.get(t) or (OPS.get(t[:-5])
                               if t.endswith("_grad") else None)
        if opdef is None:
            diags.append(Diagnostic(
                "unknown-op",
                f"op type {t!r} is not registered (version skew, or a "
                f"pass invented it); known ops live in "
                f"framework.registry.OPS", block_idx, i, t))
            registered = False
        else:
            registered = True
            if opdef.needs_rng and rng_seed_of(op) is None:
                diags.append(Diagnostic(
                    "missing-rng-seed",
                    f"RNG op lost its __rng_seed__ attr: its stream "
                    f"would collide with every other seedless op",
                    block_idx, i, t))
        # --- duplicate outputs within one op (@EMPTY@ is a discard sink)
        outs = op.output_arg_names
        if len(outs) != len(set(outs)):
            culled = [n for n in outs if n != EMPTY_VAR]
            if len(culled) != len(set(culled)):
                dup = next(n for n in culled if culled.count(n) > 1)
                diags.append(Diagnostic(
                    "duplicate-output",
                    f"op writes {dup!r} more than once in one "
                    f"invocation", block_idx, i, t, dup))
        # --- reads must be bound (and settle pending persistable writes)
        pending = st.pending
        for ns in op.inputs.values():
            for n in ns:
                if pending:
                    pending.pop(n, None)
                if n in defined:
                    continue
                if n in all_defs:
                    code = ("sub-block-scope" if depth
                            else "use-before-def")
                    msg = (f"reads {n!r}, which is only defined "
                           f"{'outside this frame chain' if depth else 'by a later op'}")
                else:
                    code = ("sub-block-scope" if depth
                            else "dangling-read")
                    msg = (f"reads {n!r}, which no op defines and "
                           f"which is neither persistable, fed, nor "
                           f"data")
                diags.append(Diagnostic(code, msg, block_idx, i, t, n))
                defined.add(n)      # report each missing name once
        # --- descend into sub-blocks with the frame visible here
        if has_sub_block(op):
            for attr in Program._SUB_BLOCK_ATTRS:
                sb = op.attrs.get(attr)
                if sb is None:
                    continue
                if not isinstance(sb, int) or \
                        not 0 <= sb < len(program.blocks):
                    diags.append(Diagnostic(
                        "sub-block-scope",
                        f"attr {attr!r} points at missing block {sb!r}",
                        block_idx, i, t))
                    continue
                if sb in st.visited:
                    # every sub-block has exactly one owning op in this
                    # IR (_create_block per control-flow op): a re-visit
                    # means a cyclic or shared sub_block attr — report
                    # it instead of recursing forever over a corrupted
                    # artifact
                    diags.append(Diagnostic(
                        "sub-block-scope",
                        f"attr {attr!r} points at block {sb}, which is "
                        f"already owned by another op (cyclic or "
                        f"mis-parented sub_block)", block_idx, i, t))
                    continue
                inner = set(defined) | sub_block_bound_names(op)
                _walk_block(program, sb, inner, st, depth + 1)
                # sub-block writes land in the shared env: visible after
                defined.update(n for n in inner if n not in defined
                               and n in all_defs)
        # --- writes: bind names; in pedantic mode also track
        # clobbered pure persistable writes (block 0 straight line only
        # — control-flow/side-effect/unknown writers are observable in
        # other ways, a sub-block write is CONDITIONAL so it settles the
        # pending write rather than flagging it, and user programs
        # legitimately double-init shared params, which is why this
        # checker only runs pedantic: per-pass validation diffs it
        # against the pipeline input, and the lint CLI gates it behind
        # --pedantic)
        if not st.pedantic:
            for n in outs:
                defined.add(n)
        else:
            exempt = (not registered or is_side_effect_type(t)
                      or has_sub_block(op))
            for n in outs:
                defined.add(n)
                if n in st.pset0:
                    if depth:
                        pending.pop(n, None)
                        continue
                    st.pversion[n] += 1
                    prior = pending.pop(n, None)
                    if prior is not None and not exempt:
                        diags.append(Diagnostic(
                            "dead-persistable-write",
                            f"write #{prior[2]} of persistable {n!r} "
                            f"is clobbered by a later write with no "
                            f"read in between", 0, prior[0],
                            prior[1].type, n))
                    if not exempt:
                        pending[n] = (i, op, st.pversion[n])


def collect_diagnostics(program, fetch_names=(), feed_names=(),
                        scope_names=None, check_shapes=False,
                        pedantic=False):
    """Run every checker; return the full Diagnostic list (empty =
    verifier-clean). :func:`verify_program` is the raising wrapper.
    ``pedantic`` adds the dead-persistable-write checker — off for user
    programs (the shared-param double-init idiom is legal), on inside
    per-pass validation where the pipeline-input diff absorbs it."""
    if isinstance(fetch_names, str):
        fetch_names = (fetch_names,)
    if isinstance(feed_names, str):
        feed_names = (feed_names,)
    diags = []

    # names bound before the first op runs: feeds, data vars, scope
    # state. Without a concrete scope, persistable vars stand in for it
    # (the startup program/init story); with one, its actual keys do too.
    entry = set(feed_names or ())
    for blk in program.blocks:
        for n, v in blk.vars.items():
            if v.persistable or v.is_data:
                entry.add(n)
    pset0 = global_persistable_names(program)
    if scope_names is not None:
        entry.update(scope_names)

    all_defs = set()
    for blk in program.blocks:
        for op in blk.ops:
            for ns in op.outputs.values():
                all_defs.update(ns)

    st = _WalkState(diags, all_defs, pset0, pedantic=pedantic)
    _walk_block(program, 0, set(entry), st)
    # blocks unreachable from block 0 (no sub_block attr points at them,
    # e.g. leftovers of a pruning pass) still get the schema checks
    for blk in program.blocks:
        if blk.idx in st.visited:
            continue
        for i, op in enumerate(blk.ops):
            t = op.type
            if not (t in _ops()
                    or (t.endswith("_grad") and t[:-5] in _ops())):
                diags.append(Diagnostic(
                    "unknown-op",
                    f"op type {t!r} is not registered", blk.idx, i, t))
            elif needs_rng(op) and rng_seed_of(op) is None:
                diags.append(Diagnostic(
                    "missing-rng-seed",
                    f"RNG op lost its __rng_seed__ attr", blk.idx, i, t))

    # fetch reachability (all_defs == every produced name)
    for n in (fetch_names or ()):
        if n in all_defs or n in entry:
            continue
        diags.append(Diagnostic(
            "unreachable-fetch",
            f"fetch target {n!r}: no op produces it and it is neither "
            f"persistable, fed, nor scope state", 0, None, None, n))

    if check_shapes:
        diags.extend(infer_shape_diagnostics(program))
    return diags


def verify_program(program, fetch_names=(), feed_names=(),
                   scope_names=None, check_shapes=False,
                   provenance=None, pedantic=False):
    """Verify program well-formedness; raise :class:`ProgramVerifyError`
    on the first finding (all findings ride on ``.diagnostics``).

    - ``fetch_names`` / ``feed_names``: the run's fetch/feed bindings.
    - ``scope_names``: names the executing scope holds, when known —
      reads/fetches of scope state then verify exactly (without it,
      persistable/data vars stand in).
    - ``check_shapes``: also re-derive output shapes/dtypes through each
      op's registered lowering and compare to the declared VarDescs
      (slower; the lint tool's --shapes mode).
    - ``provenance``: producing-pass name to carry on the error.
    """
    diags = collect_diagnostics(program, fetch_names, feed_names,
                                scope_names, check_shapes, pedantic)
    if diags:
        raise ProgramVerifyError(diags, pass_name=provenance)


# ---------------------------------------------------------------------------
# Registry-driven shape/dtype inference checking.
# ---------------------------------------------------------------------------

def infer_shape_diagnostics(program):
    """Compare each global-block op's DECLARED output shapes/dtypes
    against what its registered lowering infers (jax.eval_shape — the
    same machinery registry.infer_op_shapes uses at build time, run
    non-destructively). Ops with custom/disabled infer_shape, grad ops
    (they need runtime __fwd_op__ context), and ops with unknown or
    dynamic input shapes are skipped. -1 dims are wildcards."""
    import jax

    from .dtype import np_dtype
    from .lowering import LowerCtx
    from .registry import OPS, normalize_outs

    diags = []
    block = program.global_block()
    for i, op in enumerate(block.ops):
        opdef = OPS.get(op.type)
        if opdef is None or opdef.infer_shape is not None:
            continue                 # unknown/custom/disabled: skip
        if op.type.endswith("_grad") or "__fwd_op__" in op.attrs:
            continue
        ins = {}
        ok = True
        for slot, names in op.inputs.items():
            arrs = []
            for n in names:
                try:
                    v = block.var(n)
                except ValueError:
                    ok = False
                    break
                if v.shape is None or any(int(s) < 0 for s in v.shape):
                    ok = False
                    break
                try:
                    arrs.append(jax.ShapeDtypeStruct(
                        tuple(v.shape), np_dtype(v.dtype)))
                except (TypeError, ValueError):
                    ok = False
                    break
            if not ok:
                break
            ins[slot] = arrs
        if not ok:
            continue
        ctx = LowerCtx(program, block, env=None, base_key=None,
                       abstract=True)

        def fn(ins):
            raw = opdef.lower(ctx, dict(ins), op.attrs)
            return normalize_outs(op.outputs, raw)

        try:
            out_shapes = jax.eval_shape(fn, ins)
        except Exception:
            continue                 # value-dependent op: not checkable
        for slot, names in op.outputs.items():
            shapes = out_shapes.get(slot)
            if shapes is None:
                continue
            for n, sd in zip(names, shapes):
                if sd is None:
                    continue
                try:
                    var = block.var(n)
                except ValueError:
                    continue
                decl = var.shape
                if decl is None:
                    continue
                inferred = tuple(int(d) for d in sd.shape)
                if len(decl) != len(inferred) or any(
                        d != -1 and d != e
                        for d, e in zip(decl, inferred)):
                    diags.append(Diagnostic(
                        "shape-mismatch",
                        f"{n!r} declared {tuple(decl)} but the "
                        f"registered lowering infers {inferred}",
                        0, i, op.type, n))
                    continue
                inf_dtype = ("bfloat16"
                             if sd.dtype == jax.numpy.bfloat16
                             else str(np.dtype(sd.dtype)))
                if str(var.dtype) != inf_dtype:
                    diags.append(Diagnostic(
                        "dtype-mismatch",
                        f"{n!r} declared dtype {var.dtype} but the "
                        f"registered lowering infers {inf_dtype}",
                        0, i, op.type, n))
    return diags


# ---------------------------------------------------------------------------
# Per-pass translation validation.
# ---------------------------------------------------------------------------

class TranslationSummary:
    """What a correct pass must preserve about a program, cheap enough
    to recompute per pass: multisets over LIVE ops (so a correct DCE
    changes nothing) plus per-observer write-order counts."""

    __slots__ = ("rng_seeds", "side_effects", "persist_writes",
                 "observer_counts")

    def __init__(self, program, fetch_names=()):
        pset = global_persistable_names(program)
        live = live_op_ids(program, fetch_names, _pset=pset)
        block = program.global_block()
        self.rng_seeds = collections.Counter()
        self.side_effects = collections.Counter()
        # a MULTISET of live persistable writes per name: a pass
        # dropping one of several live writes to the same var must not
        # hide behind the surviving one
        self.persist_writes = collections.Counter()
        self.observer_counts = {}
        observers = None
        for op in block.ops:
            if id(op) not in live:
                continue
            if needs_rng(op):
                self.rng_seeds[(op.type, rng_seed_of(op))] += 1
            side = is_side_effect_type(op.type)
            if side:
                self.side_effects[op.type] += 1
            for ns in op.outputs.values():
                for n in ns:
                    if n in pset:
                        self.persist_writes[n] += 1
            if side or has_sub_block(op):
                observers = observers or []
                observers.append(op)
        if observers:
            # second walk only when the program HAS observation points:
            # what each observer has seen = number of writes to each name
            # it reads that happened before it ran
            obs_ids = {id(op) for op in observers}
            writes_so_far = {}
            for op in block.ops:
                if id(op) not in live:
                    continue
                if id(op) in obs_ids:
                    self.observer_counts[id(op)] = {
                        n: writes_so_far.get(n, 0)
                        for n in op_reads(program, op)}
                for ns in op.outputs.values():
                    for n in ns:
                        writes_so_far[n] = writes_so_far.get(n, 0) + 1


def compare_summaries(before, after):
    """Diagnostics for semantic invariants a pass broke: live RNG
    streams, side-effect ops, and persistable writes must survive
    (additions are allowed — instrumentation passes create them);
    observers present in both programs must have seen the same number of
    writes to every name they read."""
    diags = []
    missing_rng = before.rng_seeds - after.rng_seeds
    for (t, seed), cnt in missing_rng.items():
        diags.append(Diagnostic(
            "rng-stream-dropped",
            f"{cnt} live {t!r} op(s) with __rng_seed__={seed} "
            f"disappeared (RNG ops are never mergeable/removable while "
            f"live)", 0, None, t))
    missing_se = before.side_effects - after.side_effects
    for t, cnt in missing_se.items():
        diags.append(Diagnostic(
            "side-effect-dropped",
            f"{cnt} live side-effecting {t!r} op(s) disappeared", 0,
            None, t))
    for n, cnt in sorted(
            (before.persist_writes - after.persist_writes).items()):
        diags.append(Diagnostic(
            "persistable-write-dropped",
            f"{cnt} live write(s) of persistable {n!r} (e.g. an "
            f"optimizer update) disappeared", 0, None, None, n))
    for oid, counts in before.observer_counts.items():
        now = after.observer_counts.get(oid)
        if now is None:
            continue                 # observer itself flagged above
        for name, cnt in counts.items():
            if name not in now:
                continue             # legitimately renamed (CSE merge of
                                     # a pure producer feeding the
                                     # observer); persistables — the
                                     # reorder threat — are never renamed
            if now[name] != cnt:
                diags.append(Diagnostic(
                    "reordered-past-observer",
                    f"the observer saw {cnt} write(s) of {name!r} "
                    f"before the pass but {now[name]} after — a "
                    f"write moved across an op that observes it", 0,
                    None, None, name))
    return diags


class PipelineValidator:
    """Per-pass translation validation for ``optimize_program``.

    Fast path (every run): snapshot the pipeline INPUT's diagnostics
    (pre-existing user-program findings are never blamed on a pass) and
    its :class:`TranslationSummary`; after every pass compare summaries
    — the semantic preservation checks (live RNG streams, side-effect
    ops, persistable writes, observer write-order) raise immediately
    naming the pass. The full well-formedness collect runs ONCE, on the
    pipeline output (:meth:`finalize`).

    Slow path (only on a finalize finding): re-run the pipeline from a
    fresh clone, re-collecting after each pass, to attribute the
    diagnostic to the pass that introduced it — correctness checking
    stays O(pipeline) in the common all-green case, and the raised
    :class:`ProgramVerifyError` still names the guilty pass.

    ``verify_ms`` accumulates the total validation wall time (the bench
    overhead measurement); each pass's share lands in ``last_pass_ms``.
    """

    def __init__(self, program, fetch_names=(), replay=None):
        import time
        t0 = time.perf_counter()
        if isinstance(fetch_names, str):
            fetch_names = (fetch_names,)
        self.fetch_names = tuple(fetch_names or ())
        self._replay = replay        # () -> (fresh clone, [passes])
        # the input's diagnostic keys are only needed once the OUTPUT
        # shows a finding (to avoid blaming pre-existing user-program
        # findings on a pass): with a replay callback available they are
        # collected lazily from a fresh input clone on that rare path —
        # the all-green fast path never pays the input collect
        self.baseline = None
        if replay is None:
            self.baseline = collections.Counter(
                d.key for d in collect_diagnostics(program,
                                                   self.fetch_names,
                                                   pedantic=True))
        self.summary = TranslationSummary(program, self.fetch_names)
        self.verify_ms = (time.perf_counter() - t0) * 1e3
        self.last_pass_ms = 0.0

    def _baseline_keys(self):
        if self.baseline is None:
            prog, _ = self._replay()
            self.baseline = collections.Counter(
                d.key for d in collect_diagnostics(prog,
                                                   self.fetch_names,
                                                   pedantic=True))
        return self.baseline

    def _new_diags(self, program):
        diags = collect_diagnostics(program, self.fetch_names,
                                    pedantic=True)
        if not diags:
            return diags
        # MULTISET suppression: a key is stable across op-index shifts,
        # but a pass that introduces a SECOND finding colliding with a
        # pre-existing one on (code, block, op_type, var) must still be
        # caught — only up to the baseline's count is forgiven
        baseline = self._baseline_keys()
        seen = collections.Counter()
        fresh = []
        for d in diags:
            seen[d.key] += 1
            if seen[d.key] > baseline.get(d.key, 0):
                fresh.append(d)
        return fresh

    def after_pass(self, program, pass_name):
        import time
        t0 = time.perf_counter()
        try:
            summary = TranslationSummary(program, self.fetch_names)
            sem = compare_summaries(self.summary, summary)
            if sem:
                raise ProgramVerifyError(sem, pass_name=pass_name)
            self.summary = summary
        finally:
            self.last_pass_ms = (time.perf_counter() - t0) * 1e3
            self.verify_ms += self.last_pass_ms

    def finalize(self, program, last_pass_name=None):
        """Full well-formedness collect over the pipeline OUTPUT; on a
        new finding, replay the pipeline pass-by-pass to name the pass
        that introduced it."""
        import time
        t0 = time.perf_counter()
        try:
            diags = self._new_diags(program)
            if not diags:
                return
            guilty = last_pass_name
            if self._replay is not None:
                prog, pipeline = self._replay()
                for p in pipeline:
                    pname = (getattr(p, "name", None)
                             or type(p).__name__)
                    prog = p(prog) or prog
                    step = self._new_diags(prog)
                    if step:
                        raise ProgramVerifyError(step, pass_name=pname)
            raise ProgramVerifyError(diags, pass_name=guilty)
        finally:
            self.verify_ms += (time.perf_counter() - t0) * 1e3
