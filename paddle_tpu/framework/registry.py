"""Op registry: schema + JAX lowering + grad rule per op type.

TPU-native replacement for the reference's static kernel registry
(/root/reference/paddle/fluid/framework/op_registry.h:68,
 /root/reference/paddle/fluid/framework/operator.h:442). Instead of per-device
C++/CUDA kernels chosen by (place, dtype, layout) at every run
(operator.cc:1032), each op registers ONE pure-JAX lowering function; the whole
program is traced once and compiled by XLA, which does the fusion/layout work
the reference does by hand.

Gradients: the reference registers hand-written grad kernels plus C++
GradOpDescMakers (/root/reference/paddle/fluid/framework/grad_op_desc_maker.h).
Here the default grad op for type T is `T_grad`, whose lowering calls
``jax.vjp`` on T's forward lowering — the duplicated forward computation is
deduplicated by XLA CSE, and ops that need a bespoke backward can register a
custom grad lowering.
"""
import jax
import jax.numpy as jnp
import numpy as np

from .dtype import np_dtype

# Dummy dim used in place of -1 during build-time abstract shape inference.
# Prime so products/sums involving the batch dim stay divisible by it, and
# large so a real static dim is essentially never a multiple of it.
_DYN = 7919


class OpDef:
    def __init__(self, type, lower, grad=None, infer_shape=None,
                 needs_rng=False, custom_grad_lower=None):
        self.type = type
        self.lower = lower              # (ctx, ins, attrs) -> {slot: [arr]}
        # grad: None -> generic vjp grad; False -> non-differentiable
        self.grad = grad
        self.infer_shape = infer_shape  # None=generic eval_shape; False=skip; callable=custom
        self.needs_rng = needs_rng
        self.custom_grad_lower = custom_grad_lower


OPS = {}


def register_op(type, grad=None, infer_shape=None, needs_rng=False):
    """Decorator: register `fn(ctx, ins, attrs) -> {slot: array|[arrays]}`."""
    def deco(fn):
        OPS[type] = OpDef(type, fn, grad=grad, infer_shape=infer_shape,
                          needs_rng=needs_rng)
        return fn
    return deco


def register_grad_lower(fwd_type):
    """Register a custom lowering for `<fwd_type>_grad` (bespoke backward,
    e.g. flash-attention Pallas kernels with their own VJP)."""
    def deco(fn):
        OPS[fwd_type].custom_grad_lower = fn
        return fn
    return deco


def load_op_library(lib):
    """Load an out-of-tree op library and register its ops.

    The public custom-op extension point (reference
    /root/reference/python/paddle/fluid/framework.py:5365
    ``fluid.load_op_library('custom_op.so')`` + the build story under
    tests/custom_op/). The reference's "op library" is a compiled C++
    kernel .so; the TPU-native equivalent is a Python module whose
    import-time side effect is calling :func:`register_op` /
    :func:`register_grad_lower` — the lowering is a pure JAX function
    (optionally a Pallas kernel), so there is nothing to compile ahead
    of time: XLA compiles it with the rest of the program.

    `lib` may be:
      - a path to a ``.py`` file (imported under a synthetic module name),
      - a dotted module name on sys.path.

    Contract for the module: for each op, call

        @register_op("my_op")                 # generic vjp backward
        def my_op(ctx, ins, attrs):
            x = ins["X"][0]
            return {"Out": <jax expression>}

    Input slots arrive as {slot: [jax arrays]}; return {slot: array or
    [arrays]}. Build-time shapes are inferred by jax.eval_shape over the
    lowering — no InferShape function to write. A bespoke backward (when
    the vjp of the forward is not what you want) registers
    ``@register_grad_lower("my_op")`` receiving forward inputs plus
    ``Out@GRAD`` and returning ``{"X@GRAD": [...]}``. Ops become usable
    from programs immediately — e.g. via ``fluid.layers.custom_op`` or a
    LayerHelper wrapper — in both static graph and dygraph.

    Returns the imported module.
    """
    import importlib
    import importlib.util
    import os
    import sys

    # snapshot op types AND lowering identities: a library whose only
    # side effect is register_grad_lower on (or re-registration of)
    # existing ops is valid
    def _snapshot():
        return {(t, id(d.lower), id(d.custom_grad_lower))
                for t, d in OPS.items()}
    before = _snapshot()
    if os.path.sep in str(lib) or str(lib).endswith(".py"):
        path = os.path.abspath(lib)
        name = "paddle_tpu_oplib_" + \
            os.path.splitext(os.path.basename(path))[0]
        spec = importlib.util.spec_from_file_location(name, path)
        if spec is None:
            raise ImportError(f"cannot load op library from {path!r}")
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
    else:
        mod = importlib.import_module(str(lib))
    after = _snapshot()
    if after == before:
        import warnings
        warnings.warn(
            f"load_op_library({lib!r}): module imported but registered "
            f"no new ops (did it call register_op?)", stacklevel=2)
    return mod


def get_op_def(type):
    opdef = OPS.get(type)
    if opdef is None:
        if type.endswith("_grad") and type[:-5] in OPS:
            return _grad_op_def(type[:-5])
        raise NotImplementedError(f"op {type!r} is not registered")
    return opdef


def has_op(type):
    return type in OPS or (type.endswith("_grad") and type[:-5] in OPS)


def normalize_outs(op_outputs, raw):
    """Lowering may return {slot: arr | [arrs]}; normalize to {slot: [arrs]}."""
    out = {}
    for slot, v in raw.items():
        out[slot] = list(v) if isinstance(v, (list, tuple)) else [v]
    return out


# --------------------------------------------------------------------------
# Generic vjp-based grad op
# --------------------------------------------------------------------------

def _grad_op_def(fwd_type):
    fwd_def = OPS[fwd_type]
    if fwd_def.custom_grad_lower is not None:
        return OpDef(fwd_type + "_grad", fwd_def.custom_grad_lower,
                     grad=False, needs_rng=fwd_def.needs_rng)

    def lower(ctx, ins, attrs):
        return generic_grad_lower(ctx, ins, attrs, fwd_def)

    return OpDef(fwd_type + "_grad", lower, grad=False,
                 needs_rng=fwd_def.needs_rng)


def generic_grad_lower(ctx, ins, attrs, fwd_def):
    """Backward of any op via jax.vjp over its forward lowering.

    The grad op carries the forward op spec in attrs["__fwd_op__"]; forward
    inputs arrive under their original slot names, upstream grads under
    "<slot>@GRAD". RNG ops stay consistent because keys derive from a
    per-op seed attr folded into the run key (same seed in fwd and grad).
    """
    fwd = attrs["__fwd_op__"]
    fwd_attrs = fwd["attrs"]
    # which inputs need grads
    req = attrs["__grad_inputs__"]  # {slot: [bool per index]}
    # only grad-requiring slots become vjp primals; the rest stay
    # closure-captured with their ORIGINAL values. This keeps host-side
    # shape carriers (ShapeTensorList from the `shape` op) as concrete
    # numpy — jnp.asarray-ing them into tracers broke
    # _resolve_shape_tensors' int() concretization in backward passes
    in_slots = [s for s in fwd["inputs"]
                if s in ins and any(req.get(s) or ())]
    primals = {s: ins[s] for s in in_slots}

    def f(p):
        full = dict(ins)
        full.update(p)
        raw = fwd_def.lower(ctx, {s: full.get(s) for s in fwd["inputs"]},
                            fwd_attrs)
        outs = normalize_outs(fwd["outputs"], raw)
        # only differentiate through outputs wired in the forward op
        return {s: outs[s] for s in fwd["outputs"] if s in outs}

    diff_primals = {s: [jnp.asarray(a) for a in arrs]
                    for s, arrs in primals.items()}
    outs, vjp_fn = jax.vjp(f, diff_primals)

    out_mask = attrs.get("__out_grad_mask__", {})
    cts = {}
    for slot, arrs in outs.items():
        gs = list(ins.get(slot + "@GRAD") or [])
        mask = out_mask.get(slot)
        it = iter(gs)
        lst = []
        for i, a in enumerate(arrs):
            has = mask[i] if mask is not None and i < len(mask) else bool(gs)
            g = next(it, None) if has else None
            if g is None:
                # integer/bool outputs take float0 cotangents under jax.vjp
                if jnp.issubdtype(a.dtype, jnp.inexact):
                    lst.append(jnp.zeros(a.shape, a.dtype))
                else:
                    lst.append(np.zeros(a.shape, jax.dtypes.float0))
            else:
                lst.append(jnp.asarray(g, a.dtype))
        cts[slot] = lst
    (gprimals,) = vjp_fn(cts)

    result = {}
    for slot, flags in req.items():
        grads = gprimals.get(slot)
        if grads is None:
            continue
        vals = []
        for i, need in enumerate(flags):
            if not need:
                vals.append(None)
                continue
            g = grads[i]
            # float0 tangents (int inputs) -> no grad
            if g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0):
                vals.append(None)
            else:
                vals.append(g)
        result[slot + "@GRAD"] = vals
    return result


# --------------------------------------------------------------------------
# Build-time shape inference via jax.eval_shape over the lowering
# --------------------------------------------------------------------------

def infer_op_shapes(block, op):
    """Populate output VarDesc shapes/dtypes by abstractly evaluating the
    lowering (replaces the reference's per-op InferShape functions,
    operator.cc:966 — but at build time, once)."""
    opdef = get_op_def(op.type)
    if opdef.infer_shape is False:
        return
    if callable(opdef.infer_shape):
        opdef.infer_shape(block, op)
        return

    ins = {}
    had_dynamic = False
    for slot, names in op.inputs.items():
        arrs = []
        for n in names:
            v = block.var(n)
            if v.shape is None:
                return  # can't infer; executor will bind real shapes
            had_dynamic = had_dynamic or any(s == -1 for s in v.shape)
            shape = tuple(_DYN if s == -1 else s for s in v.shape)
            arrs.append(jax.ShapeDtypeStruct(shape, np_dtype(v.dtype)))
        ins[slot] = arrs

    from .lowering import LowerCtx
    ctx = LowerCtx(block.program, block, env=None, base_key=None,
                   abstract=True)

    def fn(ins):
        raw = opdef.lower(ctx, dict(ins), op.attrs)
        return normalize_outs(op.outputs, raw)

    try:
        out_shapes = jax.eval_shape(fn, ins)
    except Exception as e:  # pragma: no cover - surfacing build-time errors
        raise RuntimeError(
            f"shape inference failed for op {op.type!r} "
            f"(inputs={{{', '.join(f'{s}: {[a.shape for a in v]}' for s, v in ins.items())}}}): {e}") from e

    for slot, names in op.outputs.items():
        shapes = out_shapes.get(slot)
        if shapes is None:
            continue
        for n, sd in zip(names, shapes):
            if sd is None:
                continue
            var = block.vars.get(n) or block.var(n)
            # dims that are multiples of the dummy came from a dynamic input
            # dim (directly or via products/sums); map them back to -1.
            var.shape = tuple(
                -1 if (had_dynamic and d % _DYN == 0 and d > 0) else d
                for d in sd.shape)
            var.dtype = str(np.dtype(sd.dtype)) if sd.dtype != jnp.bfloat16 \
                else "bfloat16"
