"""Parameter initializers (reference: python/paddle/fluid/initializer.py).

Each initializer appends a fill op for the parameter into the *startup
program* — preserving the reference's two-program convention. On TPU the
startup program compiles to one XLA module that materializes every parameter
on device (sharded per dist_attr when a mesh is active).
"""
import math

from .core import default_startup_program


class Initializer:
    def __call__(self, var, block=None):
        raise NotImplementedError


def _startup_block(var):
    prog = default_startup_program()
    block = prog.global_block()
    if var.name not in block.vars:
        nv = block.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                              persistable=True)
        nv.dist_attr = var.dist_attr
    return block


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, var, block=None):
        block = block if block is not None else _startup_block(var)
        return block.append_op(
            type="fill_constant", outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "value": float(self.value)}, infer_shape=False)


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block=None):
        block = block if block is not None else _startup_block(var)
        return block.append_op(
            type="uniform_random", outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "min": float(self.low), "max": float(self.high),
                   "seed": self.seed}, infer_shape=False)


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block=None):
        block = block if block is not None else _startup_block(var)
        return block.append_op(
            type="gaussian_random", outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": float(self.loc), "std": float(self.scale),
                   "seed": self.seed}, infer_shape=False)


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block=None):
        block = block if block is not None else _startup_block(var)
        return block.append_op(
            type="truncated_gaussian_random", outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": float(self.loc), "std": float(self.scale),
                   "seed": self.seed}, infer_shape=False)


def _fan_in_out(var):
    shape = var.shape
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = 1
    for s in shape[2:]:
        receptive *= s
    return shape[1] * receptive, shape[0] * receptive


class XavierInitializer(Initializer):
    """Glorot (reference initializer.py XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = \
            uniform, fan_in, fan_out, seed

    def __call__(self, var, block=None):
        fin, fout = _fan_in_out(var)
        fin = self.fan_in if self.fan_in is not None else fin
        fout = self.fan_out if self.fan_out is not None else fout
        if self.uniform:
            limit = math.sqrt(6.0 / (fin + fout))
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / (fin + fout))
        return NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """Kaiming He init (reference initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block=None):
        fin, _ = _fan_in_out(var)
        fin = self.fan_in if self.fan_in is not None else fin
        if self.uniform:
            limit = math.sqrt(6.0 / fin)
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / fin)
        return NormalInitializer(0.0, std, self.seed)(var, block)


class BilinearInitializer(Initializer):
    """Bilinear upsample kernel init (used by conv_transpose upsampling)."""

    def __call__(self, var, block=None):
        import numpy as np
        shape = var.shape
        f = math.ceil(shape[-1] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype="float32")
        size = shape[-1] * shape[-2]
        for i in range(int(np.prod(shape))):
            x = i % shape[-1]
            y = (i // shape[-1]) % shape[-2]
            idx = np.unravel_index(i, shape)
            weight[idx] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return NumpyArrayInitializer(weight)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        import numpy as np
        self.value = np.asarray(value)

    def __call__(self, var, block=None):
        block = block if block is not None else _startup_block(var)
        return block.append_op(
            type="assign_value", outputs={"Out": [var.name]},
            attrs={"shape": list(self.value.shape), "dtype": var.dtype,
                   "values": self.value}, infer_shape=False)


# fluid-style aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def _global_weight_initializer():
    return XavierInitializer()


def _global_bias_initializer():
    return ConstantInitializer(0.0)
