"""Scope + Executor.

Capability parity with the reference's Scope
(/root/reference/paddle/fluid/framework/scope.h:46) and Executor
(/root/reference/paddle/fluid/framework/executor.cc:184,495;
 python/paddle/fluid/executor.py:882). TPU-first re-design: `Executor.run`
jit-compiles the whole program once per (program-version, feed-shape,
fetch-list) key and replays the compiled XLA executable — there is no per-op
dispatch loop, no per-run InferShape, and no feed/fetch op injection; feeds
bind directly into the traced env and fetches read out of it.
"""
import time

import numpy as np
import jax
import jax.numpy as jnp

from .core import Program, Variable, default_main_program
from .dtype import np_dtype
from .lowering import analyze_block_io, build_block_fn, build_multi_step_fn
from ..flags import flag as _flag
from ..observability import utilization as _util
from ..observability import metrics as _obs_metrics
from ..observability.metrics import default_registry as _registry
from ..observability.recorder import flight_recorder as _flightrec
from ..resilience import NonFiniteError
from ..resilience import maybe_fail as _maybe_fail

RNG_STATE_NAME = "@RNG_KEY@"

# cache_stats() key -> exported metric (name, kind)
_CACHE_METRICS = (
    ("hits", "executor_cache_hits_total", "counter"),
    ("misses", "executor_cache_misses_total", "counter"),
    ("evictions", "executor_cache_evictions_total", "counter"),
    ("inserts", "executor_cache_inserts_total", "counter"),
    ("entries", "executor_cache_entries_count", "gauge"),
    ("bytes", "executor_cache_bytes", "gauge"),
    ("pass_ms", "executor_compile_pass_ms_total", "counter"),
    ("trace_ms", "executor_compile_trace_ms_total", "counter"),
    ("compile_ms", "executor_compile_xla_ms_total", "counter"),
    ("verify_ms", "executor_compile_verify_ms_total", "counter"),
    ("compiles", "executor_compiles_total", "counter"),
)


# live-executor aggregation: counters bank on GC so exported *_total
# stays monotonic across executor churn (tests, rolling in-process
# restarts); gauges — entries/bytes — retire to zero with the cache
# they described (observability.metrics.InstanceAggregator)
_exec_agg = _obs_metrics.InstanceAggregator(
    [k for k, _n, kd in _CACHE_METRICS if kd == "counter"])


def _collect_executors():
    """Scrape-time collector: Executor.cache_stats() summed across
    every live executor plus the retired totals of collected ones (the
    Python payload stays per-instance)."""
    totals = _exec_agg.totals(
        lambda exe: exe.cache_stats(),
        live_only_keys=[k for k, _n, kd in _CACHE_METRICS
                        if kd == "gauge"])
    return [{"name": name, "kind": kind,
             "help": f"Executor cache_stats() {key!r} (summed across "
                     f"live executors)",
             "labels": (), "samples": [((), totals[key])]}
            for key, name, kind in _CACHE_METRICS]


_registry().register_collector(
    _collect_executors,
    families=[{"name": name, "kind": kind,
               "help": f"Executor cache_stats() {key!r}", "labels": ()}
              for key, name, kind in _CACHE_METRICS])


def _nonfinite_count(value):
    """Count nan/inf elements host-side. Integer/bool tensors are always
    finite; non-native floats (bfloat16 & friends) go through float32."""
    arr = np.asarray(value)
    kind = arr.dtype.kind
    if kind in "iub" or arr.size == 0:
        return 0
    if kind not in "fc":
        try:
            arr = arr.astype(np.float32)
        except (TypeError, ValueError):
            return 0
    return int((~np.isfinite(arr)).sum())


def _scan_nonfinite(fetch_names, fetches, new_state):
    """FLAGS_check_nan_inf scan (reference
    framework/details/nan_inf_utils_detail.cc checks every op output; one
    compiled XLA module has no per-op boundary, so the observable surface
    is fetched outputs + updated state). Returns (kind, name, count) for
    the first offender or None."""
    for name, val in zip(fetch_names, fetches):
        n = _nonfinite_count(val)
        if n:
            return "fetched output", name, n
    for name, val in new_state.items():
        if name == RNG_STATE_NAME:
            continue
        n = _nonfinite_count(val)
        if n:
            return "updated variable", name, n
    return None


class Scope:
    """name -> device array table (reference: framework/scope.h:46). Flat —
    the reference's scope tree existed to manage per-run temporaries, which
    XLA now owns inside the compiled executable."""

    def __init__(self):
        self._vars = {}

    def find_var(self, name):
        return self._vars.get(name)

    def var(self, name):
        return self._vars.setdefault(name, None)

    def set(self, name, value):
        self._vars[name] = value

    def erase(self, name):
        self._vars.pop(name, None)

    def keys(self):
        return self._vars.keys()

    def items(self):
        return self._vars.items()

    def __contains__(self, name):
        return name in self._vars


_global_scope = Scope()


def global_scope():
    return _global_scope


class _scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        global _global_scope
        self.old = _global_scope
        _global_scope = self.scope

    def __exit__(self, *a):
        global _global_scope
        _global_scope = self.old


def scope_guard(scope):
    return _scope_guard(scope)


_RAW_KEY_SHAPES = {"threefry2x32": (2,), "rbg": (4,), "unsafe_rbg": (4,)}


def _key_impl_mismatch(key):
    """True when a RAW uint32 key's shape doesn't match the current
    default PRNG impl (typed keys carry their impl in the dtype and
    never mismatch)."""
    if jnp.issubdtype(getattr(key, "dtype", None), jax.dtypes.prng_key):
        return False
    expect = _RAW_KEY_SHAPES.get(jax.config.jax_default_prng_impl)
    return expect is not None and tuple(key.shape) != expect


def _check_int64_feed(name, arr):
    """Int64 policy (PARITY.md): with jax_enable_x64 off (the default)
    int64 device tensors are stored int32. A fed value outside int32
    range would silently wrap on device (the reference's kernels are true
    int64, e.g. operators/lookup_table_op.h) — validate at the feed
    boundary and raise instead."""
    if arr.dtype == np.int64 and arr.size \
            and not jax.config.jax_enable_x64:
        lo, hi = arr.min(), arr.max()
        if lo < -2**31 or hi >= 2**31:
            raise ValueError(
                f"feed {name!r} holds int64 values outside int32 range "
                f"([{lo}, {hi}]); TPU tensors are 32-bit by default — "
                f"enable jax_enable_x64 for true int64 (PARITY.md "
                f"int64 policy)")


def _sanitize_np_feed(gblock, name, arr):
    """Host-feed sanitation shared by run/run_steps/_device_put_slab:
    cast to the program var's dtype and validate int64 range at the
    feed boundary (np-path only — device arrays are already placed)."""
    var = gblock.vars.get(name) if gblock is not None else None
    if var is not None and arr.dtype != np_dtype(var.dtype):
        arr = arr.astype(np_dtype(var.dtype))
    _check_int64_feed(name, arr)
    return arr


class Executor:
    """Compile-and-run executor with a program cache
    (the reference caches prepared contexts at executor.py:1169; we cache
    jitted callables keyed on program version + feed signature).

    The cache is an LRU capped at ``FLAGS_executor_cache_entries``
    (previously unbounded: every new feed-shape signature grew it
    forever — a shape-diverse inference caller leaked compiled
    executables). Eviction only drops the jitted callable; the next use
    of that signature recompiles. ``cache_stats()`` exposes
    hit/miss/evict counters."""

    def __init__(self, place=None):
        from ..utils.lru import LRUCache
        self.place = place
        self._cache = LRUCache(max_entries=_flag("executor_cache_entries"))
        # optimized-program memo: the pass pipeline's output depends on
        # (program, fetch set, pass config) but NOT on feed shapes — a
        # shape-diverse caller must not re-clone + re-optimize per shape
        # signature, and all shape entries share ONE optimized clone
        self._opt_cache = LRUCache(max_entries=32)
        # cumulative cache-miss cost split: program passes, python
        # trace+StableHLO lowering, XLA compilation (milliseconds)
        self._compile_stats = {"pass_ms": 0.0, "trace_ms": 0.0,
                               "compile_ms": 0.0, "compiles": 0,
                               "verify_ms": 0.0}
        # cost_analysis memo per executable (False = backend reports
        # nothing) + the previous dispatch mark, for the live MFU/HBM
        # gauges (steady-state dispatch-to-dispatch timing — no sync)
        self._exec_costs = LRUCache(max_entries=256)
        self._last_dispatch = None
        self._gap_streak = 0    # consecutive over-cadence deltas
        # FLAGS_profile_ops sampling counters, per cache key (bounded:
        # cleared when the key universe outgrows the compile cache)
        self._profile_seq = {}
        # closures bind the stat containers, never self; clearing the
        # cache on retire drops the compiled executables (device memory)
        _exec_agg.track(
            self,
            lambda cache=self._cache, cs=self._compile_stats:
                {**cache.stats(), **cs},
            extra_retire=self._cache.clear)

    def cache_stats(self):
        """Compile-cache occupancy, hit/miss/evict counters, and the
        cumulative cost split of every cache miss: ``pass_ms``
        (pre-lowering optimization pipeline), ``trace_ms`` (python
        trace + StableHLO lowering), ``compile_ms`` (XLA compile),
        ``compiles`` (miss count), ``verify_ms`` (FLAGS_verify_passes
        program verification + per-pass translation validation)."""
        return {**self._cache.stats(), **self._compile_stats}

    def _observe_utilization(self, where, cost_key, compiled):
        """Feed the live MFU / HBM-bandwidth gauges: the executable's
        cost_analysis() flops/bytes (memoized once per executable)
        attached to the dispatch-to-dispatch wall time. Only
        consecutive dispatches of the SAME executable are measured —
        the steady-state training/inference loop — so no device sync is
        ever forced for telemetry. A delta far above the loop's recent
        cadence is an idle pause, not a slow step: it is dropped so the
        gauge keeps the utilization-while-executing semantics the
        serving stages report (utilization.py module docstring)."""
        now = time.perf_counter()
        cost = _util.cost_for(self._exec_costs, cost_key, compiled)
        prev = self._last_dispatch
        delta = cadence = None
        if prev is not None and prev[0] == cost_key:
            delta = now - prev[1]
            cadence = prev[2]
            if cadence is None:
                # first delta only SEEDS the cadence baseline — it may
                # span an arbitrary idle gap after warmup, which must
                # not inflate device_compute_ms_total
                cadence, delta = delta, None
                self._gap_streak = 0
            elif delta > 10.0 * cadence:
                # one or two outliers are idle gaps; a RUN of them
                # means the loop is durably slower, and a frozen
                # baseline would classify every future delta as idle —
                # gauges stuck at the pre-slowdown reading forever.
                # Re-seed exactly like the first delta above.
                self._gap_streak += 1
                if self._gap_streak >= 3:
                    cadence, delta = delta, None
                    self._gap_streak = 0
                else:
                    delta = None
            else:
                cadence = delta
                self._gap_streak = 0
        self._last_dispatch = (cost_key, now, cadence)
        if delta is not None and cost:
            _util.observe_execution(where, cost, delta)

    def _maybe_shard_obs(self, where, cache_key, compiled, mesh,
                         program, feed_names, batch_dim=0):
        """FLAGS_shard_audit / FLAGS_comms_ledger hook: audit one NEWLY
        compiled mesh executable's actual shardings and parse its HLO
        for collective traffic (observability/sharding.py + comms.py).
        Sits on the compile-miss path only, so it runs once per
        executable by construction; with both flags off the shared
        front door costs two flag reads per compile and nothing on the
        hot path (the cost_for read lands in the same memo
        _observe_utilization fills on this step anyway). The audit
        only reads the compiled artifact — numerics are
        bitwise-unchanged either way."""
        if mesh is None:
            return
        from ..observability.sharding import maybe_observe
        maybe_observe(
            where, compiled, mesh, program=program,
            feed_names=feed_names, batch_dim=batch_dim,
            cost=_util.cost_for(self._exec_costs, cache_key, compiled),
            tag=f"program_{program._uid}")

    def _optimize(self, program, fetch_names, feed_names=(), scope=None):
        """Run the FLAGS_program_passes pipeline over a clone of
        `program` (framework/passes.py), charging the span to
        ``pass_ms`` and the ``pass/program_<uid>`` profiler event. With
        the pipeline off the original program is returned untouched —
        bitwise the unoptimized lowering.

        Under ``FLAGS_verify_passes`` every compile-cache miss also
        verifies the USER program (framework/analysis.verify_program,
        with the live scope's names so scope-state reads/fetches check
        exactly) and each pass's output — a malformed program fails with
        a typed ProgramVerifyError naming the op (and producing pass)
        instead of a deep lowering KeyError. Verification wall time
        accumulates in ``cache_stats()['verify_ms']``."""
        from .. import profiler as _prof
        from .passes import _last_stats as _pass_stats
        from .passes import optimize_program, pipeline_signature
        sig = pipeline_signature()
        verify = _flag("verify_passes")
        if not sig and not verify:
            return program
        if verify:
            # verify on EVERY executable-cache miss, before the
            # optimized-program memo: feeds/scope/flag state differ per
            # call, so a memoized clean verdict from one (feed, scope)
            # must not silence a later broken binding (~1 ms against a
            # compile measured in hundreds)
            from .analysis import verify_program
            t0 = time.perf_counter()
            verify_program(
                program, fetch_names=fetch_names, feed_names=feed_names,
                scope_names=(set(scope.keys())
                             if scope is not None else None))
            self._compile_stats["verify_ms"] += \
                (time.perf_counter() - t0) * 1e3
        if not sig:
            return program
        # verify is part of the key: an optimized clone memoized with
        # validation off must not be served as 'validated' after the
        # operator flips FLAGS_verify_passes on to debug that program
        key = (program._uid, program.version, tuple(fetch_names), sig,
               verify)
        opt = self._opt_cache.get(key)
        if opt is not None:
            return opt
        t0 = time.perf_counter()
        opt = optimize_program(program, fetch_names=fetch_names)
        if opt is not program:
            dt = time.perf_counter() - t0
            vms = _pass_stats.get("verify_ms", 0.0) if verify else 0.0
            # the optimize span includes the per-pass validation when
            # the flag is on; split it out so pass_ms + verify_ms sum
            # to the miss cost instead of double-counting validation
            self._compile_stats["pass_ms"] += max(dt * 1e3 - vms, 0.0)
            self._compile_stats["verify_ms"] += vms
            _prof.record_duration(f"pass/program_{program._uid}",
                                  max(dt - vms / 1e3, 0.0))
        self._opt_cache[key] = opt
        return opt

    def _lower_and_compile(self, jitted, event, args):
        """Explicit trace (``jitted.lower``) / XLA-compile split so the
        two are separately measurable (``trace/<event>`` and
        ``compile/<event>`` profiler rows, cache_stats() totals). The
        returned AOT executable is what the cache replays."""
        from .. import profiler as _prof
        t0 = time.perf_counter()
        lowered = jitted.lower(*args)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
        self._compile_stats["trace_ms"] += (t1 - t0) * 1e3
        self._compile_stats["compile_ms"] += (t2 - t1) * 1e3
        self._compile_stats["compiles"] += 1
        _prof.record_duration(f"trace/{event}", t1 - t0)
        _prof.record_duration(f"compile/{event}", t2 - t1)
        return compiled

    @staticmethod
    def _state_fetches(program, fetch_names, feed_names, state_in, scope):
        """Fetch targets no op produces and no feed binds are reads of
        scope state (e.g. PTQ fetching calibrated weights): they must
        ride state_in into the env even when DCE pruned every op that
        read them. Only names the scope actually holds qualify — a
        typo'd fetch stays out of state_in and surfaces as the
        trace-time \"fetch target was never computed\" KeyError instead
        of a misleading not-initialized error. Returns
        (state_in + extras, extras): the extras are scope-DEPENDENT, so
        cache entries record them and a hit under a scope that lacks one
        recompiles instead of replaying a wrong binding."""
        produced = {n for blk in program.blocks for op in blk.ops
                    for n in op.output_arg_names}
        known = produced | set(feed_names) | set(state_in)
        extras = [n for n in fetch_names
                  if n not in known and scope.find_var(n) is not None]
        return state_in + extras, tuple(extras)

    @staticmethod
    def _entry_valid(entry, scope):
        """A cached entry is replayable under `scope` iff every
        scope-state fetch it was compiled with is still present."""
        return all(scope.find_var(n) is not None for n in entry[-1])

    def _invoke(self, compiled, jitted, args, event, cache_key=None):
        """Replay the AOT executable; if the call-time avals drifted from
        the lowered ones (e.g. scope state replaced with a different
        weak-type/sharding after a checkpoint load), RE-lower+compile
        under the new avals and refresh the cache entry, so later calls
        return to the AOT fast path instead of paying a raised-and-caught
        validation error per step. Only input-validation failures recover
        — the AOT call validates BEFORE executing (and before any buffer
        donation), so nothing runs twice and the args are intact for the
        recompile; the recompile shows up in cache_stats() ``compiles``
        and the ``trace/``/``compile/`` events. Any other error
        propagates."""
        try:
            return compiled(*args)
        except (TypeError, ValueError) as e:
            if "compiled" not in str(e).lower():
                raise
            new_compiled = self._lower_and_compile(jitted, event, args)
            if cache_key is not None:
                ent = self._cache.get(cache_key)
                if ent is not None:
                    self._cache[cache_key] = \
                        (new_compiled,) + tuple(ent[1:])
            return new_compiled(*args)

    # -- helpers ---------------------------------------------------------
    @staticmethod
    def _feed_dict(feed):
        out = {}
        for k, v in (feed or {}).items():
            name = k.name if isinstance(k, Variable) else k
            out[name] = v
        return out

    @staticmethod
    def _fetch_names(fetch_list):
        names = []
        for f in fetch_list or []:
            names.append(f.name if isinstance(f, Variable) else str(f))
        return names

    @staticmethod
    def _split_scope_state(scope, state_in, state_out_set):
        """Bind state_in vars from the scope into (mutable, read-only)
        dicts — shared by run() and run_steps()."""
        state_mut, state_ro = {}, {}
        for n in state_in:
            v = scope.find_var(n)
            if v is None:
                raise RuntimeError(
                    f"variable {n!r} is not initialized in the scope — "
                    f"run the startup program first (fluid semantics: "
                    f"exe.run(fluid.default_startup_program()))")
            (state_mut if n in state_out_set else state_ro)[n] = v
        return state_mut, state_ro

    @staticmethod
    def _reshard_state_to_scope(scope, program, mesh, state_mut, state_ro):
        """Place state per dist_attr and write resharded arrays back so
        later runs see them already placed — shared by run()/run_steps()."""
        for st in (state_mut, state_ro):
            if _shard_state(st, mesh, program):
                for n, a in st.items():
                    scope.set(n, a)

    def _ensure_rng(self, scope, program):
        key = scope.find_var(RNG_STATE_NAME)
        if key is None or _key_impl_mismatch(key):
            # (re-)seed under the CURRENT default PRNG impl: a raw key
            # minted under threefry (shape (2,)) is rejected by
            # split/fold_in once the app switches to rbg (shape (4,)) —
            # e.g. bench.py enables rbg after tests populated the scope
            seed = program.random_seed or 0
            key = jax.random.PRNGKey(seed)
            scope.set(RNG_STATE_NAME, key)
        return key

    # -- main entry ------------------------------------------------------
    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, use_program_cache=True,
            check_nan_inf=None, skip_nonfinite_steps=False):
        """``check_nan_inf`` (default: FLAGS_check_nan_inf) scans fetched
        outputs and updated variables for nan/inf after the step and
        raises NonFiniteError (an EnforceNotMet) naming the first
        offender. ``skip_nonfinite_steps`` instead ROLLS BACK the step —
        scope state and RNG are restored to their pre-step values and the
        (non-finite) fetches are returned, so one bad batch cannot poison
        the parameters (the trainer loop moves on to the next batch)."""
        from ..parallel.compiler import CompiledProgram
        mesh = None
        if isinstance(program, CompiledProgram):
            mesh = program.mesh
            program = program.program
        if program is None:
            program = default_main_program()
        scope = scope or global_scope()
        ls_op = next((op for op in program.global_block().ops
                      if op.type == "listen_and_serv"), None)
        if ls_op is not None:
            return self._run_pserver(ls_op, scope)
        feed = self._feed_dict(feed)
        fetch_names = self._fetch_names(fetch_list)

        feed_arrays = {}
        feed_sig = []
        for name, val in feed.items():
            arr = np.asarray(val) if not isinstance(val, jax.Array) else val
            if isinstance(arr, np.ndarray):
                arr = _sanitize_np_feed(program.global_block(), name, arr)
            feed_arrays[name] = arr
            feed_sig.append((name, tuple(arr.shape), str(arr.dtype)))

        from .passes import pipeline_signature
        cache_key = (program._uid, program.version, tuple(sorted(feed_sig)),
                     tuple(fetch_names), id(mesh), pipeline_signature())
        entry = self._cache.get(cache_key) if use_program_cache else None
        if entry is not None and not self._entry_valid(entry, scope):
            entry = None               # scope-state fetch binding changed
        if entry is not None:
            compiled, jitted, state_in, state_out, state_fetches = entry
        else:
            opt_prog = self._optimize(program, fetch_names,
                                      feed_names=feed_arrays.keys(),
                                      scope=scope)
            state_in, state_out = analyze_block_io(
                opt_prog, 0, list(feed_arrays.keys()))
            state_in, state_fetches = self._state_fetches(
                opt_prog, fetch_names, feed_arrays, state_in, scope)

        base_key = self._ensure_rng(scope, program)
        state_out_set = set(state_out)
        state_mut, state_ro = self._split_scope_state(scope, state_in,
                                                      state_out_set)

        if mesh is not None:
            feed_arrays = _shard_feed(feed_arrays, mesh, program)
            # esp. read-only params of inference programs
            self._reshard_state_to_scope(scope, program, mesh, state_mut,
                                         state_ro)

        if entry is None:
            fn = build_block_fn(opt_prog, 0, list(feed_arrays.keys()),
                                fetch_names, state_in, state_out,
                                mesh=mesh)
            if mesh is not None:
                jitted = _jit_with_mesh(fn, mesh, opt_prog)
            else:
                jitted = jax.jit(fn, donate_argnums=(0,))
            compiled = self._lower_and_compile(
                jitted, f"program_{program._uid}",
                (state_mut, state_ro, feed_arrays, base_key))
            if use_program_cache:
                self._cache[cache_key] = (compiled, jitted, state_in,
                                          state_out, state_fetches)
            self._maybe_shard_obs("step", cache_key, compiled, mesh,
                                  program, tuple(feed_arrays))
            if mesh is not None and "dcn_dp" in mesh.axis_names \
                    and _flag("dcn_hierarchical") \
                    and any(op.type == "hier_allreduce"
                            for op in program.global_block().ops):
                # the single-step run() path lowers through plain GSPMD:
                # hier_allreduce collapses to identity (no bound axes) and
                # the gradient sync comes back as ONE flat all-reduce over
                # dcn_dp+dp — numerically right, but every byte of it
                # crosses the DCN. Warn once per compiled executable; the
                # decomposed path is run_steps.
                _flightrec().record(
                    "hier_single_step_flat",
                    where=f"program_{program._uid}",
                    mesh_axes=",".join(mesh.axis_names),
                    hint="FLAGS_dcn_hierarchical is on and the program "
                         "carries hier_allreduce sync ops, but "
                         "Executor.run lowers flat-GSPMD; use "
                         "run_steps for the hierarchical DCN path")

        if check_nan_inf is None:
            check_nan_inf = _flag("check_nan_inf")
        backup = None
        if skip_nonfinite_steps:
            # the executable donates state_mut buffers, so rollback needs
            # host copies taken BEFORE the step (the price of the opt-in)
            backup = {n: np.asarray(v) for n, v in state_mut.items()}

        # sampled measured op profiling (FLAGS_profile_ops=N): every
        # N-th dispatch of a program replays the optimized clone
        # op-by-op BEFORE the fused invoke (its buffers are donated
        # after). The committed result below is still the fused
        # executable's — numerics are untouched; with the default N=0
        # this costs one flag read.
        prof_n = int(_flag("profile_ops"))
        if prof_n > 0 and mesh is None:
            self._maybe_profile_ops(prof_n, cache_key, program,
                                    fetch_names, feed_arrays, state_mut,
                                    state_ro, base_key, scope)

        from .. import profiler as _prof
        invoke_args = (compiled, jitted,
                       (state_mut, state_ro, feed_arrays, base_key),
                       f"program_{program._uid}",
                       cache_key if use_program_cache else None)
        if _prof.is_profiling():
            with _prof.record_event(f"run/program_{program._uid}"):
                fetches, new_state, new_key = self._invoke(*invoke_args)
                jax.block_until_ready(fetches)
        else:
            fetches, new_state, new_key = self._invoke(*invoke_args)
        self._observe_utilization("step", cache_key, compiled)

        bad = None
        if check_nan_inf or skip_nonfinite_steps:
            bad = _scan_nonfinite(fetch_names, fetches, new_state)
        if bad is not None and skip_nonfinite_steps:
            # roll the step back: pre-step params/accumulators and RNG go
            # back into the scope, nothing is committed
            kind, name, count = bad
            _flightrec().record("nonfinite", program=program._uid,
                                var=name, count=count, where=kind,
                                rolled_back=True)
            for n, a in backup.items():
                scope.set(n, a)
            scope.set(RNG_STATE_NAME, base_key)
            print(f"[executor] skip_nonfinite_steps: {kind} {name!r} has "
                  f"{count} non-finite value(s) — step rolled back")
            if return_numpy:
                return [np.asarray(f) for f in fetches]
            return fetches

        # commit even when about to raise: state_mut buffers were donated
        # to the jit, so the scope must reference the step's outputs (the
        # error is a diagnostic about the step, not a rollback)
        for n, v in new_state.items():
            scope.set(n, v)
        scope.set(RNG_STATE_NAME, new_key)
        if bad is not None:
            kind, name, count = bad
            _flightrec().record("nonfinite", program=program._uid,
                                var=name, count=count, where=kind)
            raise NonFiniteError(
                f"Operator output contains Inf/Nan (FLAGS_check_nan_inf): "
                f"{kind} {name!r} has {count} non-finite value(s) in "
                f"program_{program._uid}. Feed data, learning rate, or "
                f"loss scaling are the usual suspects.",
                var_name=name, count=count)

        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return fetches

    # -- fused multi-step entry -----------------------------------------
    def run_steps(self, program=None, feed=None, fetch_list=None,
                  scope=None, return_numpy=True, use_program_cache=True,
                  check_nan_inf=None, skip_nonfinite_steps=False,
                  steps_per_run=None, unroll=None):
        """Run K training steps as ONE compiled executable: a jitted
        ``lax.scan`` over feeds stacked on a leading K axis (a "slab").
        Bitwise-identical to K sequential :meth:`run` calls — state
        threads through the scan carry with buffer donation and the RNG
        chain advances per step exactly as the unfused path does — but
        pays Python dispatch, H2D binding, and (optionally) fetch
        materialization once per slab instead of once per step.

        `feed` is either a dict of arrays with a leading K axis or a list
        of K per-step feed dicts (stacked here). Fetches come back
        stacked on a leading K axis, transferred in ONE device->host copy
        when `return_numpy` (device arrays, sync-free, otherwise).

        ``check_nan_inf`` (default FLAGS_check_nan_inf) compiles an
        on-device guard into the scan: each step emits a non-finite
        violation count + first-offender slot index, and the host reads
        back one small int vector per slab — no parameter transfer.
        NOTE: the raised NonFiniteError names the FIRST bad step, but
        all K steps have executed and the scope holds end-of-slab state
        (stopping mid-slab would need a per-step host sync — the cost
        this path removes). To preserve usable state past a bad batch
        use ``skip_nonfinite_steps`` (in-graph rollback); for
        first-failure forensics run with steps_per_run=1.
        ``skip_nonfinite_steps`` compiles the rollback IN-GRAPH: a
        ``lax.cond`` selects the pre-step state (and pre-step RNG key)
        when the step produced non-finite values, so no host backup
        copies exist and mesh-sharded state rolls back without a gather.

        ``unroll`` (default FLAGS_scan_unroll) is the scan unroll
        factor. The loop form (1) is bitwise-identical to sequential
        run(); 0 = auto picks full unroll on the CPU backend (whose
        while-loop bodies lose intra-op threading) — unrolled steps may
        fuse across step boundaries, numerically equivalent but not
        bit-identical.
        """
        from ..parallel.compiler import CompiledProgram
        mesh = None
        if isinstance(program, CompiledProgram):
            mesh = program.mesh
            program = program.program
        if program is None:
            program = default_main_program()
        scope = scope or global_scope()
        if isinstance(feed, (list, tuple)):
            feed = _stack_feed_slab([self._feed_dict(f) for f in feed])
        feed = self._feed_dict(feed)
        if not feed:
            raise ValueError(
                "run_steps needs at least one fed variable: the slab's "
                "leading axis defines the step count")
        fetch_names = self._fetch_names(fetch_list)

        feed_arrays = {}
        feed_sig = []
        k_steps = None
        for name, val in feed.items():
            arr = np.asarray(val) if not isinstance(val, jax.Array) else val
            if arr.ndim == 0:
                raise ValueError(
                    f"feed {name!r} is a scalar — run_steps feeds need a "
                    f"leading steps axis")
            if k_steps is None:
                k_steps = int(arr.shape[0])
            elif int(arr.shape[0]) != k_steps:
                raise ValueError(
                    f"feed {name!r} has {arr.shape[0]} steps on its "
                    f"leading axis, other feeds have {k_steps}")
            if isinstance(arr, np.ndarray):
                arr = _sanitize_np_feed(program.global_block(), name, arr)
            feed_arrays[name] = arr
            feed_sig.append((name, tuple(arr.shape), str(arr.dtype)))
        if steps_per_run is not None and int(steps_per_run) != k_steps:
            raise ValueError(
                f"steps_per_run={steps_per_run} but the fed slab carries "
                f"{k_steps} steps on its leading axis")

        if check_nan_inf is None:
            check_nan_inf = _flag("check_nan_inf")
        guard = bool(check_nan_inf or skip_nonfinite_steps)
        if unroll is None:
            unroll = _flag("scan_unroll")
        unroll = int(unroll)
        if unroll <= 0:
            # auto: XLA CPU runs while-loop bodies without intra-op
            # threading — full unroll restores it; accelerators keep the
            # loop form so compile time stays K-independent
            unroll = k_steps if jax.default_backend() == "cpu" else 1

        # hierarchical multi-slice path: a dcn_dp mesh whose program went
        # through the hier_grad_sync pass runs under shard_map so the
        # gradient reduction decomposes per fabric (RS in-slice / AR
        # cross-slice / AG in-slice). Requires the explicit sync ops —
        # without them per-device state would silently diverge — and a
        # pure data-parallel mesh (tp/pp/sp compose via GSPMD only).
        # FLAGS_dcn_hierarchical=False is the flat-GSPMD A/B baseline:
        # same program, hier_allreduce collapses to identity.
        from .lowering import hier_dp_axes
        hier_axes = ()
        if mesh is not None and _flag("dcn_hierarchical") \
                and set(mesh.axis_names) <= {"dcn_dp", "dp"} \
                and any(op.type == "hier_allreduce"
                        for op in program.global_block().ops):
            hier_axes = hier_dp_axes(mesh)
        hier_on = bool(hier_axes)

        from .passes import pipeline_signature
        cache_key = (program._uid, program.version,
                     tuple(sorted(feed_sig)), tuple(fetch_names), id(mesh),
                     "steps", k_steps, guard, bool(skip_nonfinite_steps),
                     unroll, hier_on, pipeline_signature())
        entry = self._cache.get(cache_key) if use_program_cache else None
        if entry is not None and not self._entry_valid(entry, scope):
            entry = None               # scope-state fetch binding changed
        fresh_compile = entry is None
        if entry is not None:
            (compiled, jitted, state_in, state_out, mut_names, slot_names,
             wo_avals, state_fetches) = entry
        else:
            opt_prog = self._optimize(program, fetch_names,
                                      feed_names=feed_arrays.keys(),
                                      scope=scope)
            state_in, state_out = analyze_block_io(
                opt_prog, 0, list(feed_arrays.keys()))
            state_in, state_fetches = self._state_fetches(
                opt_prog, fetch_names, feed_arrays, state_in, scope)

        base_key = self._ensure_rng(scope, program)
        state_out_set = set(state_out)
        state_mut, state_ro = self._split_scope_state(scope, state_in,
                                                      state_out_set)

        if mesh is not None:
            feed_arrays = _shard_feed_slab(feed_arrays, mesh)
            self._reshard_state_to_scope(scope, program, mesh, state_mut,
                                         state_ro)

        from .. import profiler as _prof
        if fresh_compile:
            step_fn = build_block_fn(
                opt_prog, 0, list(feed_arrays.keys()), fetch_names,
                state_in, state_out, mesh=mesh)
            feed_row = {n: jax.ShapeDtypeStruct(a.shape[1:], a.dtype)
                        for n, a in feed_arrays.items()}
            _, new_state_s, _ = jax.eval_shape(
                step_fn, state_mut, state_ro, feed_row, base_key)
            mut_names = [n for n in state_in if n in state_out_set]
            slot_names = (["fetched output " + repr(n)
                           for n in fetch_names]
                          + ["updated variable " + repr(n)
                             for n in new_state_s])
            wo_avals = {n: jax.ShapeDtypeStruct(s.shape, s.dtype)
                        for n, s in new_state_s.items()
                        if n not in state_mut}

        # write-only persistable outputs ride the scan carry so a
        # rolled-back step restores what the scope held (sequential-skip
        # parity); vars the scope has never seen are seeded with zeros
        # and un-committed below if every step rolled back
        absent_wo = set()
        for n, aval in wo_avals.items():
            v = scope.find_var(n)
            if v is None:
                v = np.zeros(aval.shape, aval.dtype)
                absent_wo.add(n)
            state_mut[n] = v
        if mesh is not None and wo_avals:
            tmp = {n: state_mut[n] for n in wo_avals}
            _shard_state(tmp, mesh, program)
            state_mut.update(tmp)

        if fresh_compile:
            fn = build_multi_step_fn(
                opt_prog, 0, list(feed_arrays.keys()), fetch_names,
                state_in, state_out, mut_names, mesh=mesh,
                guard=guard,
                skip_nonfinite=bool(skip_nonfinite_steps),
                unroll=unroll,
                viol_axes=hier_axes)
            if hier_on:
                from .lowering import wrap_hier_dp_steps
                jitted = jax.jit(wrap_hier_dp_steps(fn, mesh, feed_arrays),
                                 donate_argnums=(0,))
            elif mesh is not None:
                jitted = _jit_with_mesh_steps(fn, mesh)
            else:
                jitted = jax.jit(fn, donate_argnums=(0,))
            compiled = self._lower_and_compile(
                jitted, f"fused_program_{program._uid}_x{k_steps}",
                (state_mut, state_ro, feed_arrays, base_key))
            if use_program_cache:
                self._cache[cache_key] = (compiled, jitted, state_in,
                                          state_out, mut_names,
                                          slot_names, wo_avals,
                                          state_fetches)
            # batch_dim=1: the slab's leading K axis replicates by
            # design; the batch dim the dp axis should shard sits
            # under it
            self._maybe_shard_obs("train", cache_key, compiled, mesh,
                                  program, tuple(feed_arrays),
                                  batch_dim=1)
            if hier_on and _flag("dcn_assert_hier"):
                # pre-burn gate: parse the compiled HLO and prove the
                # hierarchical decomposition landed — DCN-priced traffic
                # only on the designated axes, cross-slice wire bytes
                # strictly below the flat all-reduce — BEFORE the first
                # slab is dispatched to hardware
                from ..observability.comms import assert_hier_decomposition
                assert_hier_decomposition(
                    compiled, mesh,
                    where=f"fused_program_{program._uid}_x{k_steps}")

        # chaos point for the training dispatch stage: fires BEFORE the
        # executable runs, so the scope still holds pre-slab state and a
        # supervised restart resumes bitwise from the last checkpoint
        _maybe_fail("train.dispatch")
        if hier_axes:
            # chaos point for the cross-slice reduction stage: raising
            # simulates a slice whose DCN collective fails; delay=
            # simulates a straggling slice stretching the step
            _maybe_fail("train.allreduce_dcn")
        profiling = _prof.is_profiling()
        t0 = time.perf_counter()
        fetches, final_state, final_key, viols, slots = self._invoke(
            compiled, jitted, (state_mut, state_ro, feed_arrays, base_key),
            f"fused_program_{program._uid}_x{k_steps}",
            cache_key if use_program_cache else None)
        if profiling:
            t1 = time.perf_counter()
            jax.block_until_ready(fetches if fetches else final_key)
            span = time.perf_counter() - t0
            _prof.record_duration(
                f"dispatch/program_{program._uid}_x{k_steps}", t1 - t0)
            _prof.record_duration(
                f"scan/program_{program._uid}_x{k_steps}", span)
            _prof.record_step_time(span / k_steps, k_steps)
        self._observe_utilization("train", cache_key, compiled)

        v = np.asarray(viols) if guard else None  # ONE small readback
        # commit (buffers were donated); guard diagnostics after. If
        # EVERY step rolled back, scope-absent write-only vars stay
        # uncommitted — K sequential skipped run() calls never create
        # them either (their committed value would be the zeros seed).
        all_rolled = bool(skip_nonfinite_steps and v is not None
                          and v.size and (v > 0).all())
        for n, val in final_state.items():
            if all_rolled and n in absent_wo:
                continue
            scope.set(n, val)
        scope.set(RNG_STATE_NAME, final_key)

        if guard and v.any():
            first = int(np.argmax(v > 0))
            name = self._slot_name(slots, first, slot_names)
            _flightrec().record(
                "nonfinite", program=program._uid, var=name,
                count=int(v[first]), where=f"fused step {first}",
                rolled_back=bool(skip_nonfinite_steps))
            if skip_nonfinite_steps:
                rolled = int((v > 0).sum())
                print(f"[executor] skip_nonfinite_steps: {rolled} of "
                      f"{k_steps} fused step(s) rolled back in-graph "
                      f"(first at slab step {first}: {int(v[first])} "
                      f"non-finite value(s) across outputs/state, "
                      f"first offender {name})")
            else:
                raise NonFiniteError(
                    f"Operator output contains Inf/Nan "
                    f"(FLAGS_check_nan_inf): fused step "
                    f"{first}/{k_steps} of program_{program._uid} "
                    f"produced {int(v[first])} non-finite value(s) "
                    f"across outputs/state; first offender {name}. "
                    f"Feed data, learning rate, or loss scaling are "
                    f"the usual suspects.",
                    var_name=name, count=int(v[first]))

        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return fetches

    @staticmethod
    def _slot_name(slots, step_idx, slot_names):
        i = int(np.asarray(slots)[step_idx])
        return slot_names[i] if 0 <= i < len(slot_names) else f"slot {i}"

    def _maybe_profile_ops(self, every_n, cache_key, program,
                           fetch_names, feed_arrays, state_mut,
                           state_ro, base_key, scope):
        """The FLAGS_profile_ops sampling gate + measured replay: every
        ``every_n``-th dispatch of ``cache_key``, interpret the pass
        pipeline's optimized CLONE eagerly with per-op timing
        (observability.profiling.measure_op_times — spans, the
        hbm_live_bytes counter track, and the last_op_profile() table).
        Failures are swallowed: profiling must never break a step."""
        if len(self._profile_seq) > 512:
            self._profile_seq.clear()
        seq = self._profile_seq.get(cache_key, 0) + 1
        self._profile_seq[cache_key] = seq
        if (seq - 1) % max(every_n, 1):
            return
        try:
            from ..observability import profiling as _opprof
            opt = self._optimize(program, fetch_names,
                                 feed_names=feed_arrays.keys(),
                                 scope=scope)
            env = dict(state_ro)
            env.update(state_mut)
            env.update(feed_arrays)
            env[RNG_STATE_NAME] = base_key
            _opprof.measure_op_times(opt, env,
                                     tag=f"program_{program._uid}")
        except Exception:  # noqa: BLE001 — telemetry never kills a step
            pass

    def _run_pserver(self, ls_op, scope):
        """Host parameter-server event loop (reference
        listen_and_serv_op.cc:333 RunImpl — the op IS the server). Blocks
        until every trainer sent `stop`; the final tables are written back
        to the scope."""
        import numpy as np
        from ..distributed.ps import ParameterServer

        attrs = ls_op.attrs
        server = ParameterServer(attrs["endpoint"],
                                 trainers=int(attrs.get("Fanin", 1)),
                                 sync_mode=bool(attrs.get("sync_mode",
                                                          True)),
                                 heartbeat_timeout=attrs.get(
                                     "heartbeat_timeout"))
        for name in attrs.get("hosted_vars", []):
            val = scope.find_var(name)
            if val is None:
                raise RuntimeError(
                    f"pserver var {name!r} not initialized — run the "
                    f"pserver startup program first (transpiler."
                    f"get_startup_program(endpoint))")
            server.tables[name] = np.asarray(val)
        server.optimize_blocks = dict(attrs.get("optimize_blocks", {}))
        for name, lr in attrs.get("sparse_tables", {}).items():
            server.sparse_lr[name] = float(lr)
        server.serve(block=True)
        for name, val in server.tables.items():
            scope.set(name, val)
        return []

    def close(self):
        self._cache.clear()
        self._opt_cache.clear()

    # ---- dataset ingestion (reference executor.py:1440 train_from_dataset
    # -> C++ trainer threads; here the host parses/batches and the compiled
    # step consumes, with XLA overlapping H2D against compute) ----
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None, skip_nonfinite_steps=False,
                           steps_per_run=None, fetch_every_n=None):
        """``steps_per_run=K`` (default FLAGS_steps_per_run) drives the
        fused :meth:`run_steps` path: the dataset collates K-step slabs
        (``batch_iterator(slab=K)``), the next slab's H2D transfer is
        dispatched while the current slab executes, and ``print_period``
        reports from the slab's already-materialized stacked fetches —
        no mid-loop device sync. ``fetch_every_n=N`` (default
        FLAGS_fetch_every_n) > 1 skips fetch materialization entirely on
        slabs that contain neither a ``print_period`` step nor an N-th
        slab boundary (those slabs run a fetch-free executable; the
        final slab always fetches so the return value is fresh). Under
        the fused path the returned last-fetches are stacked per-step
        arrays with a leading slab axis, not single-step values."""
        assert dataset is not None, "train_from_dataset needs a dataset"
        k_steps = int(steps_per_run if steps_per_run is not None
                      else _flag("steps_per_run"))
        fetch_every = int(fetch_every_n if fetch_every_n is not None
                          else _flag("fetch_every_n"))
        fetch_names = self._fetch_names(fetch_list)
        fetch_info = fetch_info or fetch_names
        monitor = None
        if fetch_handler is not None:
            monitor = _FetchHandlerMonitor(scope or global_scope(),
                                           fetch_handler)
            monitor.start()
        try:
            if k_steps > 1:
                return self._train_fused(
                    program, dataset, scope, fetch_list, fetch_names,
                    fetch_info, print_period, skip_nonfinite_steps,
                    k_steps, fetch_every)
            return self._train_stepwise(
                program, dataset, scope, fetch_list, fetch_names,
                fetch_info, print_period, skip_nonfinite_steps)
        finally:
            if monitor is not None:
                monitor.stop()

    def _train_stepwise(self, program, dataset, scope, fetch_list,
                        fetch_names, fetch_info, print_period,
                        skip_nonfinite_steps):
        """One run() per batch. Steps dispatch asynchronously
        (return_numpy=False); fetches only materialize on a reporting
        step — a print_period hit no longer forces a device sync on every
        non-reporting step, and step 0 (untrained params) is not
        reported."""
        last = None
        for step, feed in enumerate(dataset.batch_iterator()):
            out = self.run(program, feed=feed,
                           fetch_list=fetch_list, scope=scope,
                           return_numpy=False,
                           skip_nonfinite_steps=skip_nonfinite_steps)
            last = out
            if fetch_names and print_period and step \
                    and step % print_period == 0:
                vals = [np.asarray(v) for v in out]
                msg = ", ".join(f"{i}={v.mean():.6f}"
                                for i, v in zip(fetch_info, vals))
                print(f"step {step}: {msg}")
            elif step % 64 == 63:
                # backpressure: async dispatch with no fetch sync would
                # otherwise let in-flight steps (and their feed buffers)
                # pile up without bound on the device queue
                _block_on_step(out, scope)
        if last is not None:
            last = [np.asarray(v) for v in last]
        return last

    def _train_fused(self, program, dataset, scope, fetch_list,
                     fetch_names, fetch_info, print_period,
                     skip_nonfinite_steps, k_steps, fetch_every):
        """Slab loop behind train_from_dataset(steps_per_run=K): full
        slabs go through run_steps (one compiled scan), the short tail
        slab (dataset length not divisible by K, or a partial final
        batch) falls back to sequential run() calls so no second
        executable is compiled for a shape seen once."""
        from ..parallel.compiler import CompiledProgram
        if program is None:
            # resolve here, not just in run_steps: _device_put_slab
            # needs the program for feed dtype casts + int64 validation
            program = default_main_program()
        # mesh feeds are placed by _shard_feed_slab at run time; plain
        # device_put here would pin them to device 0 first
        prefetch = not isinstance(program, CompiledProgram)
        try:
            it = dataset.batch_iterator(slab=k_steps)
        except TypeError:
            # duck-typed dataset without the slab kwarg: collate here
            from ..dataio.dataset import DatasetBase
            it = DatasetBase._slab_batches(dataset.batch_iterator(),
                                           k_steps)
        last = None
        step = 0
        slab_idx = 0
        cur = next(it, None)
        if cur is not None and prefetch:
            cur = _device_put_slab(cur, program)
        while cur is not None:
            # prefetch BEFORE dispatching: the next slab's H2D is in
            # flight while this slab executes even when the guard makes
            # run_steps block on its per-slab violation readback
            nxt = next(it, None)
            if nxt is not None and prefetch:
                nxt = _device_put_slab(nxt, program)
            k = int(next(iter(cur.values())).shape[0])
            hit = bool(print_period) and fetch_names and any(
                (step + j) and (step + j) % print_period == 0
                for j in range(k))
            want = bool(fetch_names) and (
                fetch_every <= 1 or hit or slab_idx % fetch_every == 0
                or nxt is None)  # final slab: the return value is fresh
            flist = fetch_list if want else []
            if k == k_steps:
                out = self.run_steps(
                    program, feed=cur, fetch_list=flist, scope=scope,
                    return_numpy=False,
                    skip_nonfinite_steps=skip_nonfinite_steps)
            else:
                outs = [self.run(program,
                                 feed={n: a[j] for n, a in cur.items()},
                                 fetch_list=flist, scope=scope,
                                 return_numpy=False,
                                 skip_nonfinite_steps=skip_nonfinite_steps)
                        for j in range(k)]
                out = [np.stack([np.asarray(o[i]) for o in outs])
                       for i in range(len(fetch_names))] if want else []
            if want and out:
                mats = [np.asarray(v) for v in out]  # one copy per slab
                last = mats
                if hit:
                    for j in range(k):
                        g = step + j
                        if g and g % print_period == 0:
                            msg = ", ".join(
                                f"{i}={np.asarray(v[j]).mean():.6f}"
                                for i, v in zip(fetch_info, mats))
                            print(f"step {g}: {msg}")
            if not want and slab_idx % 8 == 7:
                _block_on_step(out, scope)  # bound the dispatch queue
            step += k
            slab_idx += 1
            cur = nxt
        if last is None and not fetch_names and slab_idx:
            last = []  # match the stepwise path's no-fetch return
        return last

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        prog = program.clone(for_test=True) if program is not None else None
        return self.train_from_dataset(prog, dataset, scope, thread, debug,
                                       fetch_list, fetch_info, print_period)


def _block_on_step(out, scope):
    """Periodic backpressure for the async training loops: wait for the
    newest dispatched step (its fetches, or the committed RNG key when
    nothing was fetched) so unmaterialized in-flight steps can't grow
    the device queue without bound."""
    ref = out if out else (scope or global_scope()).find_var(
        RNG_STATE_NAME)
    if ref is not None:
        jax.block_until_ready(ref)


def _stack_feed_slab(feeds):
    """Stack a list of per-step feed dicts on a new leading K axis.
    Key ORDER may differ between steps; the variable set may not."""
    if not feeds:
        raise ValueError("run_steps got an empty feed list")
    names = list(feeds[0].keys())
    for f in feeds[1:]:
        if set(f.keys()) != set(names):
            raise ValueError(
                "run_steps feed dicts must bind the same variables in "
                f"every step: {sorted(names)} vs {sorted(f.keys())}")
    return {n: np.stack([np.asarray(f[n]) for f in feeds]) for n in names}


def _device_put_slab(slab, program=None):
    """Async H2D of a host slab (dispatch-only timing: device_put
    returns before the copy lands, which is the point — the transfer
    overlaps the previous slab's compute). Applies the same var-dtype
    cast and int64 feed-boundary validation run() would, BEFORE the
    value becomes a device array and skips that np-path."""
    from .. import profiler as _prof
    _maybe_fail("train.h2d")    # chaos point: slab H2D transfer stage
    gblock = program.global_block() if program is not None else None
    t0 = time.perf_counter()
    out = {}
    for n, a in slab.items():
        if isinstance(a, np.ndarray):
            a = _sanitize_np_feed(gblock, n, a)
        out[n] = jax.device_put(a)
    _prof.record_duration("h2d/slab", time.perf_counter() - t0)
    return out


def _jit_with_mesh(fn, mesh, program):
    """Data-parallel / SPMD jit: params replicated (or sharded per their
    dist_attr), feed sharded on the leading batch dim. XLA GSPMD inserts the
    collectives the reference built by hand in its multi-device SSA graph
    (ir/multi_devices_graph_pass/multi_devices_graph_pass.cc:456)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def sharded_fn(state_mut, state_ro, feed, base_key):
        feed = {
            n: jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, _batch_pspec(mesh, a)))
            for n, a in feed.items()
        }
        return fn(state_mut, state_ro, feed, base_key)

    return jax.jit(sharded_fn, donate_argnums=(0,))


def _batch_pspec(mesh, arr):
    return _batch_pspec_shape(mesh, tuple(arr.shape))


def _batch_pspec_shape(mesh, shape):
    from jax.sharding import PartitionSpec as P
    from ..parallel.mesh import partition_spec
    if not shape:
        return P()
    if "dcn_dp" in mesh.axis_names and "dp" in mesh.axis_names:
        # multi-slice: the batch dim shards jointly over the cross-slice
        # and in-slice data axes (dcn_dp-major, so each slice holds a
        # contiguous block of the global batch)
        return partition_spec(mesh, (("dcn_dp", "dp"),), shape)
    axis = "dp" if "dp" in mesh.axis_names else mesh.axis_names[0]
    return partition_spec(mesh, (axis,), shape)


def _slab_pspec(mesh, arr):
    """Batch pspec shifted one axis right for a K-leading feed slab: the
    steps axis replicates (every step runs on the whole mesh), the batch
    dim under it shards exactly as the unfused feed would."""
    from jax.sharding import PartitionSpec as P
    if arr.ndim <= 1:
        return P()
    return P(None, *_batch_pspec_shape(mesh, tuple(arr.shape[1:])))


def _jit_with_mesh_steps(fn, mesh):
    """Fused-scan variant of _jit_with_mesh: the same GSPMD treatment,
    with the sharding constraint applied under the slab's leading K
    axis."""
    from jax.sharding import NamedSharding

    def sharded_fn(state_mut, state_ro, feed_slab, base_key):
        feed_slab = {
            n: jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, _slab_pspec(mesh, a)))
            for n, a in feed_slab.items()
        }
        return fn(state_mut, state_ro, feed_slab, base_key)

    return jax.jit(sharded_fn, donate_argnums=(0,))


def _shard_feed_slab(feed_arrays, mesh):
    """_shard_feed for K-leading slabs: single-process shards the batch
    dim under the steps axis; multi-host assembles each trainer's local
    slab into one global array along dp (reference semantics — every
    trainer feeds its own shard)."""
    from jax.sharding import NamedSharding
    out = {}
    multi = jax.process_count() > 1
    for n, a in feed_arrays.items():
        arr = np.asarray(a) if not isinstance(a, jax.Array) else a
        sharding = NamedSharding(mesh, _slab_pspec(mesh, arr))
        if multi:
            out[n] = jax.make_array_from_process_local_data(
                sharding, np.asarray(arr))
        else:
            out[n] = jax.device_put(arr, sharding)
    return out


def _shard_state(state, mesh, program):
    """Place scope state per its Variable dist_attr (params annotated for tp
    are split across the mesh; everything else replicates). The jitted step
    then respects these input shardings — the GSPMD replacement for the
    reference's BCastParamsToDevices (parallel_executor.cc:739). Multi-host:
    every process holds the full value, so each assembles its addressable
    shards via make_array_from_callback."""
    from ..parallel.mesh import sharding_for
    gblock = program.global_block()
    changed = False
    for n, a in state.items():
        var = gblock.vars.get(n)
        target = sharding_for(mesh, var)
        if isinstance(a, jax.Array) and a.sharding == target:
            continue
        if jax.process_count() > 1:
            if isinstance(a, jax.Array) and not a.is_fully_addressable:
                # already a distributed global array on a different
                # sharding: reshard with a compiled identity (collectives
                # do the cross-host movement; np.asarray would raise)
                state[n] = jax.jit(lambda v: v, out_shardings=target)(a)
            else:
                arr = np.asarray(a)
                state[n] = jax.make_array_from_callback(
                    arr.shape, target, lambda idx, _arr=arr: _arr[idx])
        else:
            state[n] = jax.device_put(a, target)
        changed = True
    return changed


def _shard_feed(feed_arrays, mesh, program):
    """Single-process: shard the full fed batch over the mesh. Multi-host
    (fleet): each trainer process feeds its OWN local batch (reference
    semantics — every trainer reads its own data shard), assembled into one
    global array along the dp axis."""
    from jax.sharding import NamedSharding
    out = {}
    multi = jax.process_count() > 1
    for n, a in feed_arrays.items():
        arr = np.asarray(a)
        sharding = NamedSharding(mesh, _batch_pspec(mesh, arr))
        if multi:
            out[n] = jax.make_array_from_process_local_data(sharding, arr)
        else:
            out[n] = jax.device_put(arr, sharding)
    return out


class FetchHandler:
    """Periodic background metric reporter during dataset training
    (reference executor.py:429 FetchHandler + the FetchHandlerMonitor
    thread): `var_dict` maps display keys to scope var names; `handler`
    receives {key: numpy value} every `period_secs`."""

    def __init__(self, var_dict=None, period_secs=60):
        assert var_dict is not None
        self.var_dict = dict(var_dict)
        self.period_secs = float(period_secs)

    def handler(self, res_dict):
        import sys
        for key, val in res_dict.items():
            if isinstance(val, np.ndarray) and val.size:
                sys.stdout.write(f"{key}[0]: {val.reshape(-1)[0]} ")
        sys.stdout.write("\n")

    @staticmethod
    def help():
        print("FetchHandler(var_dict={key: var_or_name}, period_secs=60); "
              "override handler(res_dict) for custom reporting")


class _FetchHandlerMonitor:
    def __init__(self, scope, fetch_handler):
        import threading
        self._scope = scope
        self._fh = fetch_handler
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self):
        self._thread.start()

    def _loop(self):
        import traceback
        while not self._stop.wait(self._fh.period_secs):
            res = {}
            try:
                for key, var in self._fh.var_dict.items():
                    name = var if isinstance(var, str) else var.name
                    val = self._scope.find_var(name)
                    if val is not None:
                        res[key] = np.asarray(val)
            except Exception:
                # racing the training step (e.g. reading a buffer the jit
                # just donated) must not kill the monitor — skip the tick
                continue
            try:
                self._fh.handler(res)
            except Exception:
                # a buggy user handler must neither die silently nor kill
                # the monitor: report it, keep ticking
                traceback.print_exc()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)
