"""Program -> JAX function lowering.

TPU-native replacement for the reference's executors: instead of an op-by-op
interpreter loop (/root/reference/paddle/fluid/framework/executor.cc:471) or an
SSA-graph thread pool (details/fast_threaded_ssa_graph_executor.cc:54), a Block
lowers to ONE pure function over an environment of named arrays, jit-compiled
by XLA. Sequential in-place semantics of the reference (optimizer writes, BN
running stats) are recovered by name rebinding in the env; persistable writes
flow back to the Scope.
"""
import math

import jax
import jax.numpy as jnp

from .registry import get_op_def, normalize_outs


class LowerCtx:
    """State threaded through op lowerings: the env, rng base key, mesh."""

    def __init__(self, program, block, env, base_key, mesh=None,
                 abstract=False):
        self.program = program
        self.block = block
        self.env = env
        self.base_key = base_key
        self.mesh = mesh
        self.abstract = abstract

    def op_key(self, attrs):
        """Deterministic per-op PRNG key: fold the op's build-time seed into
        the run key. Forward and vjp-recomputed forward fold the same seed, so
        stochastic ops (dropout) reuse identical masks in backward."""
        seed = attrs.get("__rng_seed__", 0)
        user_seed = attrs.get("seed", 0)
        if self.abstract or self.base_key is None:
            base = jax.random.PRNGKey(user_seed or 0)
        elif user_seed:
            base = jax.random.PRNGKey(user_seed)
        else:
            base = self.base_key
        return jax.random.fold_in(base, seed)

    def sub_ctx(self, block_idx, env):
        return LowerCtx(self.program, self.program.blocks[block_idx], env,
                        self.base_key, mesh=self.mesh, abstract=self.abstract)

    def lower_block_ops(self, block_idx, env):
        """Run a sub-block's ops over `env` (control-flow op support)."""
        ctx = self.sub_ctx(block_idx, env)
        run_ops(ctx)
        return env

    def lookup(self, name):
        return self.env.get(name)


def run_ops(ctx):
    """Execute (trace) every op of ctx.block over ctx.env."""
    for op in ctx.block.ops:
        run_op(ctx, op)


def run_op(ctx, op):
    opdef = get_op_def(op.type)
    ins = {}
    for slot, names in op.inputs.items():
        ins[slot] = [ctx.env[n] if n in ctx.env else _missing(ctx, n, op)
                     for n in names]
    raw = opdef.lower(ctx, ins, op.attrs)
    if raw is None:
        return
    outs = normalize_outs(op.outputs, raw)
    for slot, names in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        for n, v in zip(names, vals):
            if v is not None:
                ctx.env[n] = v


def _missing(ctx, name, op):
    raise KeyError(
        f"var {name!r} (input of op {op.type!r}) has no value: it was neither "
        f"fed, produced by an earlier op, nor found in the scope")


def analyze_block_io(program, block_idx, feed_names):
    """Which vars a block reads from outside (scope state) and which
    persistable vars it writes (state to store back).

    Mirrors the reference's unused-var/GC analysis role
    (framework/executor_gc_helper.cc) but for functional state threading.
    """
    block = program.blocks[block_idx]
    defined = set(feed_names)
    reads = []
    reads_set = set()
    writes = []
    writes_set = set()

    def visit_block(bidx, local_defined):
        blk = program.blocks[bidx]
        for op in blk.ops:
            for n in op.input_arg_names:
                if n not in local_defined and n not in reads_set:
                    reads_set.add(n)
                    reads.append(n)
            for sub_attr in ("sub_block", "sub_block_true", "sub_block_false"):
                sb = op.attrs.get(sub_attr)
                if sb is not None:
                    # names the op itself binds inside the sub-block (scan
                    # slices, loop memories, branch operands) are defined
                    # there, not read from the scope
                    bound = set(op.attrs.get("step_input_vars", ()))
                    bound.update(m[0] for m in op.attrs.get("memories", ()))
                    bound.update(op.attrs.get("x_names", ()))
                    if "x_name" in op.attrs:        # pipeline stage input
                        bound.add(op.attrs["x_name"])
                    visit_block(sb, set(local_defined) | bound)
            for n in op.output_arg_names:
                local_defined.add(n)
                if n not in writes_set:
                    try:
                        var = blk.var(n)
                        persistable = var.persistable
                    except ValueError:
                        persistable = False
                    if persistable:
                        writes_set.add(n)
                        writes.append(n)

    visit_block(block_idx, defined)
    return reads, writes


def build_block_fn(program, block_idx, feed_names, fetch_names, state_in,
                   state_out, mesh=None):
    """Return fn(state_mut, state_ro, feed, base_key) ->
    (fetches, new_state, new_key).

    `state_mut` (read-and-updated vars: params, optimizer moments, BN stats)
    is safe to buffer-donate; `state_ro` is read-only scope state.
    """
    feed_names = list(feed_names)
    fetch_names = list(fetch_names)

    def fn(state_mut, state_ro, feed, base_key):
        env = dict(state_ro)
        env.update(state_mut)
        env.update(feed)
        ctx = LowerCtx(program, program.blocks[block_idx], env, base_key,
                       mesh=mesh)
        run_ops(ctx)
        fetches = []
        for n in fetch_names:
            if n not in env:
                raise KeyError(f"fetch target {n!r} was never computed")
            fetches.append(env[n])
        new_state = {n: env[n] for n in state_out if n in env}
        new_key = jax.random.split(base_key, 1)[0]
        return fetches, new_state, new_key

    return fn


# ---------------------------------------------------------------------------
# Flattened-concat machinery for the fused multi-tensor optimizer kernels
# (framework/passes.py FuseOptimizerPass -> ops/optimizer_ops.py fused_*).
# A bucket of N per-param updates lowers as ONE elementwise update over
# the concatenation of the flattened params; because every op involved is
# elementwise, each element sees exactly the arithmetic the per-param op
# would apply — the fused path is bitwise-identical, just 1 kernel
# instead of N.
# ---------------------------------------------------------------------------

def flatten_concat(arrs, mesh=None):
    """Concatenate arrays into one flat vector; returns
    (flat, shapes) where `shapes` undoes the concat via
    :func:`split_unflatten`. Under a mesh the result is pinned
    REPLICATED: the fusion pass only buckets unsharded params, but
    GSPMD's propagation through a concat of values derived from
    tp-sharded activations must not be left to choose a partitioning
    the split would mis-slice."""
    shapes = [tuple(a.shape) for a in arrs]
    flat = jnp.concatenate([jnp.reshape(a, (-1,)) for a in arrs])
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        flat = jax.lax.with_sharding_constraint(
            flat, NamedSharding(mesh, P()))
    return flat, shapes


def split_unflatten(flat, shapes):
    """Inverse of :func:`flatten_concat`: split `flat` back into arrays
    of the given shapes (static sizes — XLA lowers this to slices)."""
    sizes = [math.prod(s) for s in shapes]
    offsets = []
    acc = 0
    for n in sizes[:-1]:
        acc += n
        offsets.append(acc)
    parts = jnp.split(flat, offsets) if offsets else [flat]
    return [jnp.reshape(p, s) for p, s in zip(parts, shapes)]


def broadcast_segments(scalars, shapes, dtype):
    """Per-segment scalar broadcast over a flattened concat: segment i
    (of size prod(shapes[i])) is filled with scalars[i]. Used for
    per-param scalars (adam's bias-corrected step size) so each element
    is multiplied by exactly the scalar its per-param op would use."""
    return jnp.concatenate([
        jnp.full((math.prod(s),), jnp.reshape(sc, ()).astype(dtype))
        for sc, s in zip(scalars, shapes)])


def _nonfinite_leaf(x):
    """Per-array non-finite element count as an in-graph int32 scalar.
    Integer/bool arrays are always finite and contribute a constant 0 (they
    stay in the slot list so slot indices line up with slot names)."""
    if jnp.issubdtype(x.dtype, jnp.floating) or \
            jnp.issubdtype(x.dtype, jnp.complexfloating):
        return (~jnp.isfinite(x)).sum(dtype=jnp.int32)
    return jnp.int32(0)


def build_multi_step_fn(program, block_idx, feed_names, fetch_names,
                        state_in, state_out, mut_names,
                        mesh=None, guard=False, skip_nonfinite=False,
                        unroll=1, viol_axes=()):
    """Return fn(state_mut, state_ro, feed_slab, base_key) ->
    (stacked_fetches, final_state, final_key, viol_counts, viol_slots):
    K training steps fused into one ``lax.scan`` over feeds stacked on a
    leading K axis.

    Per-step semantics are bitwise those of K sequential
    ``build_block_fn`` calls: the scan body IS the single-step fn, state
    rebinds through the carry and the RNG key advances by the same
    ``split(key, 1)[0]`` chain, so per-op ``fold_in`` streams match the
    unfused executor exactly.

    `mut_names` is the read-and-updated subset of `state_in`; the passed
    `state_mut` dict must ALSO carry an initial value for every
    write-only persistable output (callers seed it from the scope, or
    zeros when absent) — those live in the scan carry so the LAST
    step's value survives and a rolled-back step restores what the
    scope held, matching the sequential executor's skip path.

    With `guard` (FLAGS_check_nan_inf) the body also emits a per-step
    int32 violation count plus the index of the first offending slot
    (ordered: fetches, then updated state) — the whole non-finite check
    stays on device and costs one tiny readback instead of a device->host
    transfer of every updated parameter. With `skip_nonfinite` the carry
    update becomes a ``lax.cond`` select between pre- and post-step state
    (and pre/post RNG key): a poisoned step rolls back IN-GRAPH, with no
    host backup copies — this also works for mesh-sharded state where a
    host-side ``np.asarray`` snapshot would gather.

    `unroll` feeds through to ``lax.scan``: the loop form (1) keeps
    compile time K-independent; full unroll (K) restores straight-line
    code on backends whose while-loop bodies pessimize (XLA CPU drops
    intra-op threading inside loops). Both forms run the identical
    per-step computation.

    `viol_axes` (hierarchical multi-slice path): mapped axis names the
    per-step violation count is psum'd over INSIDE the scan body, so the
    ``skip_nonfinite`` rollback ``cond`` takes the same branch on every
    device — a NaN seen by one slice's local batch must roll the step
    back everywhere, not fork the replicas. Per-axis psums, innermost
    first, so the cross-slice hop of this int32 rides only the
    designated DCN axis."""
    step_fn = build_block_fn(program, block_idx, feed_names, fetch_names,
                             state_in, state_out, mesh=mesh)
    mut_names = list(mut_names)

    def fn(state_mut, state_ro, feed_slab, base_key):
        carry_state = dict(state_mut)

        def body(carry, feed_k):
            cstate, key = carry
            smut = {n: cstate[n] for n in mut_names}
            fetches, new_state, new_key = step_fn(smut, state_ro, feed_k,
                                                  key)
            out_state = dict(cstate)
            out_state.update(new_state)
            viol = jnp.int32(0)
            slot = jnp.int32(0)
            if guard or skip_nonfinite:
                leaves = list(fetches) + list(new_state.values())
                counts = (jnp.stack([_nonfinite_leaf(v) for v in leaves])
                          if leaves else jnp.zeros((1,), jnp.int32))
                viol = counts.sum(dtype=jnp.int32)
                slot = jnp.argmax(counts > 0).astype(jnp.int32)
                for a in reversed(tuple(viol_axes)):
                    viol = jax.lax.psum(viol, a)
                    slot = jax.lax.pmax(slot, a)
            if skip_nonfinite:
                out_state, new_key = jax.lax.cond(
                    viol > 0,
                    lambda: (cstate, key),
                    lambda: (out_state, new_key))
            return (out_state, new_key), (tuple(fetches), viol, slot)

        (final_state, final_key), (ys, viols, slots) = jax.lax.scan(
            body, (carry_state, base_key), feed_slab,
            unroll=max(int(unroll), 1))
        return list(ys), final_state, final_key, viols, slots

    return fn


# ---------------------------------------------------------------------------
# Multi-slice hierarchical data parallelism (ROADMAP item 5, MegaScale
# NSDI'24 shape): a mesh whose outermost axis is ``dcn_dp`` spans TPU
# slices over DCN. Left to GSPMD, the gradient sync would be ONE flat
# all-reduce over (dcn_dp x dp) — the full gradient payload crossing the
# slow fabric. Instead the executor runs the fused step fn under
# shard_map over the whole mesh, which binds the axis names so the
# ``hier_allreduce`` ops the hier_grad_sync pass inserted decompose per
# fabric: reduce-scatter@dp (ICI), all-reduce@dcn_dp on the owned 1/dp
# shard (DCN), all-gather@dp (ICI).
# ---------------------------------------------------------------------------

def hier_dp_axes(mesh):
    """The batch-sharding axes of a multi-slice mesh, outermost first
    (``("dcn_dp", "dp")`` / ``("dcn_dp",)``), or ``()`` when the mesh
    has no cross-slice axis (the hierarchical path does not apply)."""
    if mesh is None or "dcn_dp" not in mesh.axis_names:
        return ()
    return tuple(a for a in ("dcn_dp", "dp") if a in mesh.axis_names)


def _hier_fetch_reduce(y, axes):
    """Cross-replica mean of a fetched value, one pmean per axis
    (inner/ICI first) so the cross-slice hop reduces an already
    slice-reduced value and DCN traffic stays on the designated axis.
    Non-float fetches pass through (per-device value)."""
    if not jnp.issubdtype(jnp.result_type(y), jnp.inexact):
        return y
    for a in reversed(axes):
        y = jax.lax.pmean(y, a)
    return y


def wrap_hier_dp_steps(fn, mesh, feed_slab):
    """shard_map a ``build_multi_step_fn`` product over a dcn_dp mesh.

    Per-device semantics: each device traces the SAME program over its
    local batch shard (feed slabs shard dim 1 jointly over
    (dcn_dp, dp); state and the RNG key replicate), and the
    hier_allreduce ops make the updated state identical everywhere —
    ``out_specs=P()`` with the replication check off, since the
    compiler cannot prove what the sync guarantees. Fetches are
    pmean'd hierarchically before leaving the region (losses/metrics
    become their global-batch means, matching the GSPMD path's
    mean-over-global-batch up to summation order).

    The global batch must divide by the total data-parallel degree;
    feed arrays whose dim 1 does not divide (per-step scalars,
    K-leading aux feeds) replicate instead.
    """
    from jax.sharding import PartitionSpec as P
    from ..ops._shard_compat import shard_map

    axes = hier_dp_axes(mesh)
    denom = 1
    for a in axes:
        denom *= int(mesh.shape[a])
    batch_spec = axes if len(axes) > 1 else axes[0]
    feed_specs = {}
    for n, a in feed_slab.items():
        shape = tuple(getattr(a, "shape", ()) or ())
        if len(shape) >= 2 and denom > 1 and shape[1] % denom == 0:
            feed_specs[n] = P(None, batch_spec)
        else:
            feed_specs[n] = P()

    def body(state_mut, state_ro, feed_slab, base_key):
        ys, final_state, final_key, viols, slots = fn(
            state_mut, state_ro, feed_slab, base_key)
        ys = [_hier_fetch_reduce(y, axes) for y in ys]
        return ys, final_state, final_key, viols, slots

    return shard_map(body, mesh=mesh,
                     in_specs=(P(), P(), feed_specs, P()),
                     out_specs=P(), check_vma=False)
