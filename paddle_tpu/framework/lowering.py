"""Program -> JAX function lowering.

TPU-native replacement for the reference's executors: instead of an op-by-op
interpreter loop (/root/reference/paddle/fluid/framework/executor.cc:471) or an
SSA-graph thread pool (details/fast_threaded_ssa_graph_executor.cc:54), a Block
lowers to ONE pure function over an environment of named arrays, jit-compiled
by XLA. Sequential in-place semantics of the reference (optimizer writes, BN
running stats) are recovered by name rebinding in the env; persistable writes
flow back to the Scope.
"""
import jax
import jax.numpy as jnp

from .registry import get_op_def, normalize_outs


class LowerCtx:
    """State threaded through op lowerings: the env, rng base key, mesh."""

    def __init__(self, program, block, env, base_key, mesh=None,
                 abstract=False):
        self.program = program
        self.block = block
        self.env = env
        self.base_key = base_key
        self.mesh = mesh
        self.abstract = abstract

    def op_key(self, attrs):
        """Deterministic per-op PRNG key: fold the op's build-time seed into
        the run key. Forward and vjp-recomputed forward fold the same seed, so
        stochastic ops (dropout) reuse identical masks in backward."""
        seed = attrs.get("__rng_seed__", 0)
        user_seed = attrs.get("seed", 0)
        if self.abstract or self.base_key is None:
            base = jax.random.PRNGKey(user_seed or 0)
        elif user_seed:
            base = jax.random.PRNGKey(user_seed)
        else:
            base = self.base_key
        return jax.random.fold_in(base, seed)

    def sub_ctx(self, block_idx, env):
        return LowerCtx(self.program, self.program.blocks[block_idx], env,
                        self.base_key, mesh=self.mesh, abstract=self.abstract)

    def lower_block_ops(self, block_idx, env):
        """Run a sub-block's ops over `env` (control-flow op support)."""
        ctx = self.sub_ctx(block_idx, env)
        run_ops(ctx)
        return env

    def lookup(self, name):
        return self.env.get(name)


def run_ops(ctx):
    """Execute (trace) every op of ctx.block over ctx.env."""
    for op in ctx.block.ops:
        run_op(ctx, op)


def run_op(ctx, op):
    opdef = get_op_def(op.type)
    ins = {}
    for slot, names in op.inputs.items():
        ins[slot] = [ctx.env[n] if n in ctx.env else _missing(ctx, n, op)
                     for n in names]
    raw = opdef.lower(ctx, ins, op.attrs)
    if raw is None:
        return
    outs = normalize_outs(op.outputs, raw)
    for slot, names in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        for n, v in zip(names, vals):
            if v is not None:
                ctx.env[n] = v


def _missing(ctx, name, op):
    raise KeyError(
        f"var {name!r} (input of op {op.type!r}) has no value: it was neither "
        f"fed, produced by an earlier op, nor found in the scope")


def analyze_block_io(program, block_idx, feed_names):
    """Which vars a block reads from outside (scope state) and which
    persistable vars it writes (state to store back).

    Mirrors the reference's unused-var/GC analysis role
    (framework/executor_gc_helper.cc) but for functional state threading.
    """
    block = program.blocks[block_idx]
    defined = set(feed_names)
    reads = []
    reads_set = set()
    writes = []
    writes_set = set()

    def visit_block(bidx, local_defined):
        blk = program.blocks[bidx]
        for op in blk.ops:
            for n in op.input_arg_names:
                if n not in local_defined and n not in reads_set:
                    reads_set.add(n)
                    reads.append(n)
            for sub_attr in ("sub_block", "sub_block_true", "sub_block_false"):
                sb = op.attrs.get(sub_attr)
                if sb is not None:
                    # names the op itself binds inside the sub-block (scan
                    # slices, loop memories, branch operands) are defined
                    # there, not read from the scope
                    bound = set(op.attrs.get("step_input_vars", ()))
                    bound.update(m[0] for m in op.attrs.get("memories", ()))
                    bound.update(op.attrs.get("x_names", ()))
                    if "x_name" in op.attrs:        # pipeline stage input
                        bound.add(op.attrs["x_name"])
                    visit_block(sb, set(local_defined) | bound)
            for n in op.output_arg_names:
                local_defined.add(n)
                if n not in writes_set:
                    try:
                        var = blk.var(n)
                        persistable = var.persistable
                    except ValueError:
                        persistable = False
                    if persistable:
                        writes_set.add(n)
                        writes.append(n)

    visit_block(block_idx, defined)
    return reads, writes


def build_block_fn(program, block_idx, feed_names, fetch_names, state_in,
                   state_out, mesh=None):
    """Return fn(state_mut, state_ro, feed, base_key) ->
    (fetches, new_state, new_key).

    `state_mut` (read-and-updated vars: params, optimizer moments, BN stats)
    is safe to buffer-donate; `state_ro` is read-only scope state.
    """
    feed_names = list(feed_names)
    fetch_names = list(fetch_names)

    def fn(state_mut, state_ro, feed, base_key):
        env = dict(state_ro)
        env.update(state_mut)
        env.update(feed)
        ctx = LowerCtx(program, program.blocks[block_idx], env, base_key,
                       mesh=mesh)
        run_ops(ctx)
        fetches = []
        for n in fetch_names:
            if n not in env:
                raise KeyError(f"fetch target {n!r} was never computed")
            fetches.append(env[n])
        new_state = {n: env[n] for n in state_out if n in env}
        new_key = jax.random.split(base_key, 1)[0]
        return fetches, new_state, new_key

    return fn
