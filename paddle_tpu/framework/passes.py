"""Program-pass framework: registry + ordered application + the
pre-lowering optimization pipeline.

Capability parity with the reference's IR pass infrastructure
(/root/reference/paddle/fluid/framework/ir/pass.h — Pass::Apply over a
Graph, REGISTER_PASS, and PassBuilder ordering in
paddle/fluid/framework/details/build_strategy.cc). The reference's passes
mutate a C++ graph; here a pass is a callable over the Program IR
(framework/core.py), the same structure every existing rewrite (AMP cast
insertion, QAT instrumentation, sync-BN substitution) already walks by
hand. Registering them gives users the reference's extension point: write
a Pass subclass, `register_pass` it, and `apply_passes(program, [...])`
runs an ordered pipeline.

The DEFAULT pipeline (``FLAGS_program_passes``, on by default) runs on
every Executor compile-cache miss, over a CLONE of the user's program —
the original Program is never mutated, so program versions (and with
them the compile-cache keys) stay stable:

- ``dce``   — dead code elimination: drop ops whose outputs are
  unreachable from the fetch targets, persistable writes, or
  side-effecting ops (the reference traces fetch-pruned programs
  op-by-op; here dead branches cost trace/compile time even though XLA
  would DCE them later).
- ``cse``   — common subexpression elimination: dedupe identical
  (type, inputs-at-version, attrs) pure ops within the global block
  (duplicate casts/fill_constants from AMP and grad-merge rewrites).
- ``fuse_optimizer`` — multi-tensor optimizer fusion: per-param
  sgd/momentum/adam/adamw update ops group into byte-capped buckets,
  each lowered as ONE flattened-concat update (NVIDIA-Apex-style
  multi_tensor_apply; the reference's fuse_adam_op_pass). Elementwise
  math on the concatenation is bitwise-identical to the per-param ops.

Every pass records op/byte deltas and wall time — ``stats()`` reports
the last pipeline run, and profiler events (``pass/<name>``) feed the
summary table.
"""
import time

import numpy as np

from ..flags import flag as _flag
# underscore-aliased: this namespace is part of the frozen public API
# surface (tools/api_signatures.txt) — only the pass registry is public
from .analysis import SIDE_EFFECT_OPS  # noqa: F401  (compat re-export)
from .analysis import has_sub_block as _has_sub_block
from .analysis import is_side_effect_type as _is_side_effect_type  # noqa: F401,E501  (compat re-export)
from .analysis import needs_rng as _needs_rng  # noqa: F401  (compat re-export)
from .analysis import writes_persistable as _writes_persistable  # noqa: F401,E501  (compat re-export)
from .core import OP_ROLE_KEY
from .core import Operator as _Operator
from .core import OpRole as _OpRole
from .core import VarType as _VarType
from .dtype import np_dtype as _np_dtype


class UnknownPassError(KeyError):
    """Raised for a pass name that is not in the registry; the message
    names the registered passes (a typo'd name used to surface as a bare
    KeyError with no context)."""

    def __init__(self, name):
        self.pass_name = name
        super().__init__(name)

    def __str__(self):
        return (f"pass {self.pass_name!r} is not registered; "
                f"known passes: {list_passes()}")


class Pass:
    """Base pass: override apply(program) and mutate in place (return
    the program for chaining). `name` defaults to the registration name;
    attrs passed at construction are available on self.
    ``pipeline_order`` ranks the pass in canonical pipeline order
    (lower runs earlier; None = no canonical position, ordered by
    registration)."""

    name = None
    pipeline_order = None

    def __init__(self, **attrs):
        for k, v in attrs.items():
            setattr(self, k, v)

    def apply(self, program):
        raise NotImplementedError

    def __call__(self, program):
        out = self.apply(program)
        out = program if out is None else out
        # the executor caches compiled programs on (uid, version): a
        # mutation-only pass must invalidate that cache or it silently
        # no-ops on an already-executed program
        bump = getattr(out, "_bump_version", None)
        if bump is not None:
            bump()
        return out


_PASSES = {}
_REG_SEQ = {}          # name -> registration index (ordering tiebreak)
_REG_GEN = [0]         # bumped per registration: pass IDENTITY version
_sig_memo = {}         # (flag values, reg gen) -> pipeline_signature()


def register_pass(name):
    """Decorator: register a Pass subclass (or factory) under `name`
    (reference REGISTER_PASS(name, class)). Re-registering a name
    overrides the previous entry (the extension point for swapping a
    built-in pass with a custom one); the registration generation feeds
    :func:`pipeline_signature`, so executables compiled under the old
    pass can never be replayed for the new one."""
    def deco(cls):
        _PASSES[name] = cls
        _REG_SEQ.setdefault(name, len(_REG_SEQ))
        _REG_GEN[0] += 1
        try:
            cls._reg_serial = _REG_GEN[0]
        except (AttributeError, TypeError):
            pass
        _sig_memo.clear()
        if getattr(cls, "name", None) is None:
            try:
                cls.name = name
            except (AttributeError, TypeError):
                pass
        return cls
    return deco


def get_pass(name, **attrs):
    cls = _PASSES.get(name)
    if cls is None:
        raise UnknownPassError(name)
    return cls(**attrs)


def has_pass(name):
    return name in _PASSES


def list_passes():
    return sorted(_PASSES)


def canonical_order(names):
    """Deterministic pipeline order for a collection of pass names:
    by ``pipeline_order`` (dce < cse < fuse_optimizer), then by
    registration sequence for passes without a canonical position."""
    def rank(n):
        cls = _PASSES.get(n)
        order = getattr(cls, "pipeline_order", None) if cls else None
        return (0, order, "") if order is not None \
            else (1, _REG_SEQ.get(n, len(_REG_SEQ)), n)
    return sorted(names, key=rank)


# ---------------------------------------------------------------------------
# Pipeline application + stats
# ---------------------------------------------------------------------------

_last_stats = {"passes": [], "total_ms": 0.0, "verify_ms": 0.0}

# cumulative per-pass telemetry (stats() stays "the LAST run"; these
# feed the process metrics registry / Prometheus exposition)
from ..observability.metrics import default_registry as _registry  # noqa: E402

_M_PASS_RUNS = _registry().counter(
    "program_pass_runs_total", "pipeline pass applications",
    labels=("pass",), max_series=32)
_M_PASS_MS = _registry().counter(
    "program_pass_ms_total", "wall ms spent inside each pass",
    labels=("pass",), max_series=32)
_M_PASS_OPS_REMOVED = _registry().counter(
    "program_pass_ops_removed_total",
    "ops removed by each pass (net, clamped at 0 per run)",
    labels=("pass",), max_series=32)


def stats():
    """Report of the LAST apply_passes run: per-pass
    {pass, ops_before, ops_after, bytes_before, bytes_after, ms, detail}
    plus the pipeline total and, when ``FLAGS_verify_passes`` ran the
    per-pass translation validation, its wall time (``verify_ms``,
    also per row)."""
    return {"passes": [dict(r) for r in _last_stats["passes"]],
            "total_ms": _last_stats["total_ms"],
            "verify_ms": _last_stats.get("verify_ms", 0.0)}


def _program_op_count(program):
    return sum(len(blk.ops) for blk in program.blocks)


def _program_bytes(program):
    """Static-size estimate of every var the program's ops touch (dims
    of -1 and unknown shapes contribute 0 — a telemetry measure, not an
    allocator)."""
    seen = set()
    total = 0
    for blk in program.blocks:
        for op in blk.ops:
            for n in op.input_arg_names + op.output_arg_names:
                if n in seen:
                    continue
                seen.add(n)
                try:
                    var = blk.var(n)
                except ValueError:
                    continue
                shape = getattr(var, "shape", None)
                if shape is None or any(int(s) < 0 for s in shape):
                    continue
                try:
                    itemsize = np.dtype(_np_dtype(var.dtype)).itemsize
                except (TypeError, ValueError):
                    continue
                total += int(np.prod(shape, dtype=np.int64)) * itemsize
    return total


def apply_passes(program, names, _validate=None, **common_attrs):
    """Run passes over `program` (reference PassBuilder::Build).
    `names` entries are either registered names or instantiated
    Pass/callables. Lists/tuples run in the GIVEN order; unordered
    collections (set/frozenset/dict keys) are canonicalized with
    :func:`canonical_order` so the pipeline is deterministic. An unknown
    name raises :class:`UnknownPassError` naming the registry contents.
    Per-pass op/byte deltas and wall time land in :func:`stats` and the
    profiler event table (``pass/<name>``).

    ``_validate`` (an :class:`analysis.PipelineValidator`) runs
    translation validation after every pass — a pass whose output fails
    well-formedness or breaks a preservation invariant raises
    :class:`analysis.ProgramVerifyError` naming the pass; validation
    wall time lands in each row's ``verify_ms`` and the pipeline
    ``verify_ms`` total."""
    from .. import profiler as _prof
    if isinstance(names, (set, frozenset)) or (
            isinstance(names, dict) or type(names).__name__ == "dict_keys"):
        names = canonical_order(list(names))
    rows = []
    t_pipeline = time.perf_counter()
    ops = _program_op_count(program)
    nbytes = _program_bytes(program)
    for n in names:
        p = get_pass(n, **common_attrs) if isinstance(n, str) else n
        pname = getattr(p, "name", None) or type(p).__name__
        t0 = time.perf_counter()
        program = p(program) or program
        dt = time.perf_counter() - t0
        ops_after = _program_op_count(program)
        bytes_after = _program_bytes(program)
        row = {"pass": pname, "ops_before": ops, "ops_after": ops_after,
               "bytes_before": nbytes, "bytes_after": bytes_after,
               "ms": dt * 1e3}
        detail = getattr(p, "_report", None)
        if detail:
            row["detail"] = dict(detail)
        if _validate is not None:
            _validate.after_pass(program, pname)
            row["verify_ms"] = _validate.last_pass_ms
        rows.append(row)
        _prof.record_duration(f"pass/{pname}", dt)
        _M_PASS_RUNS.inc(labels=(pname,))
        _M_PASS_MS.inc(dt * 1e3, labels=(pname,))
        _M_PASS_OPS_REMOVED.inc(max(ops - ops_after, 0),
                                labels=(pname,))
        ops, nbytes = ops_after, bytes_after
    _last_stats["passes"] = rows
    _last_stats["total_ms"] = (time.perf_counter() - t_pipeline) * 1e3
    _last_stats["verify_ms"] = (_validate.verify_ms
                                if _validate is not None else 0.0)
    return program


# The executor's default pipeline (canonical order).
DEFAULT_PIPELINE = ("dce", "cse", "fuse_optimizer")


def resolve_pipeline(spec=None):
    """FLAGS_program_passes -> ordered tuple of pass names. "0"/"off"
    disables the pipeline entirely (the executor then lowers the user's
    program untouched — bitwise today's behavior); "1"/"default" is
    DEFAULT_PIPELINE; anything else is a comma-separated pass list run
    in canonical order."""
    if spec is None:
        spec = _flag("program_passes")
    s = str(spec).strip().lower()
    if s in ("0", "", "off", "false", "none"):
        return ()
    if s in ("1", "on", "true", "default"):
        names = list(DEFAULT_PIPELINE)
    else:
        names = [t.strip() for t in str(spec).split(",") if t.strip()]
    for n in names:
        if n not in _PASSES:
            raise UnknownPassError(n)
    return tuple(canonical_order(names))


def pipeline_signature(spec=None):
    """Hashable identity of the active pass configuration — the flag's
    resolved pipeline, each pass's registration serial (re-registering a
    pass changes its serial, so executables compiled under the old
    implementation can't replay), and every attr that changes a pass's
    output. Part of the executor's compile-cache key so toggling passes
    can never serve a stale executable. Memoized on the flag values +
    registry generation: this sits on the per-step dispatch path, so
    the parse/sort must not recur."""
    raw = (_flag("program_passes") if spec is None else spec,
           _flag("fuse_optimizer_bucket_mb"), _REG_GEN[0])
    sig = _sig_memo.get(raw)
    if sig is not None:
        return sig
    names = resolve_pipeline(raw[0])
    if not names:
        sig = ()
    else:
        extras = []
        if "fuse_optimizer" in names:
            extras.append(("fuse_optimizer_bucket_mb", int(raw[1])))
        sig = (tuple((n, getattr(_PASSES[n], "_reg_serial", 0))
                     for n in names), tuple(extras))
    if len(_sig_memo) < 64:        # flags take few distinct values
        _sig_memo[raw] = sig
    return sig


def optimize_program(program, fetch_names=(), spec=None):
    """Run the configured pipeline over a CLONE of `program` and return
    it (the caller's program is never mutated, keeping its version — and
    the executor cache keys derived from it — stable). With the pipeline
    disabled the original program is returned as-is.

    Under ``FLAGS_verify_passes`` every pass's output is translation-
    validated (framework/analysis.py): a buggy rewrite raises a typed
    ``ProgramVerifyError`` naming the pass and op instead of surfacing
    as a deep lowering KeyError — or worse, silently wrong numerics
    behind a compile-cache hit."""
    names = resolve_pipeline(spec)
    if not names:
        return program
    if isinstance(fetch_names, str):
        # a bare string must mean ONE fetch target; tuple() would
        # char-split it into nonsense DCE roots that drop the program
        fetch_names = (fetch_names,)
    opt = program.clone()
    pipeline = [get_pass(n, fetch_names=tuple(fetch_names)) for n in names]
    validator = None
    if _flag("verify_passes"):
        from .analysis import PipelineValidator
        validator = PipelineValidator(
            opt, fetch_names,
            # failure-path attribution: replay the pipeline over a fresh
            # clone, verifying after each pass, to name the guilty one
            replay=lambda: (program.clone(),
                            [get_pass(n, fetch_names=tuple(fetch_names))
                             for n in names]))
    apply_passes(opt, pipeline, _validate=validator)
    if validator is not None:
        validator.finalize(opt, last_pass_name=names[-1])
        _last_stats["verify_ms"] = validator.verify_ms
    return opt


# ---------------------------------------------------------------------------
# Built-in passes wrapping the existing hand-rolled program rewrites, so
# the standard transforms are discoverable/orderable through the registry
# like the reference's default pass pipeline (build_strategy.cc).
# ---------------------------------------------------------------------------

@register_pass("amp_bf16")
class AmpBf16Pass(Pass):
    """bf16 mixed-precision cast insertion (contrib.mixed_precision.
    fp16_utils.rewrite_program; reference ir/fp16 pass family). attrs:
    amp_lists (AutoMixedPrecisionLists), dest_dtype."""

    amp_lists = None
    dest_dtype = "bfloat16"

    def apply(self, program):
        from ..contrib.mixed_precision.fp16_lists import (
            AutoMixedPrecisionLists)
        from ..contrib.mixed_precision.fp16_utils import rewrite_program
        rewrite_program(program,
                        self.amp_lists or AutoMixedPrecisionLists(),
                        dest_dtype=self.dest_dtype)


@register_pass("sync_batch_norm")
class SyncBatchNormPass(Pass):
    """batch_norm -> sync_batch_norm substitution (reference
    framework/ir/sync_batch_norm_pass.cc; the CompiledProgram build
    strategy applies it via this registry)."""

    def apply(self, program):
        for block in program.blocks:
            for op in block.ops:
                if op.type == "batch_norm":
                    op.type = "sync_batch_norm"


@register_pass("hier_grad_sync")
class HierGradSyncPass(Pass):
    """Insert an explicit ``hier_allreduce`` after every parameter
    gradient's producer — the multi-slice gradient-sync pass
    (CompiledProgram applies it when the mesh has a ``dcn_dp`` axis).

    Under the executor's shard_map hier path each device computes its
    LOCAL-batch gradient; the inserted op makes it the global mean via
    reduce-scatter in-slice (ICI) / all-reduce across slices (DCN, on
    the 1/dp shard) / all-gather in-slice. Insertion happens directly
    AFTER the raw ``<param>@GRAD`` producer — not batched at the end of
    backward — so XLA can overlap layer k's cross-slice hop against
    layer k-1's backward compute. All downstream readers (gradient
    clipping, regularization, the optimize op) are rewired to the
    synced value, so grad transformations see the same global gradient
    the flat-GSPMD path gives them. Outside a mapped axis the op is an
    identity: applying this pass never changes single-mesh numerics,
    which is what makes FLAGS_dcn_hierarchical a pure runtime A/B
    switch on ONE program.

    Idempotent: a grad whose ``@HIER`` twin already exists is skipped.
    """

    inner_axis = "dp"
    outer_axis = "dcn_dp"
    GRAD_SUFFIX = "@GRAD"
    SYNC_SUFFIX = "@HIER"

    def apply(self, program):
        for block in program.blocks:
            self._apply_block(block)

    def _grad_names(self, block):
        """Gradient vars to sync, preferring the raw ``<param>@GRAD``
        over the optimize op's (possibly clipped/regularized) Grad
        input so upstream grad transforms also see the synced value."""
        out, seen = [], set()
        for op in block.ops:
            if op.attrs.get(OP_ROLE_KEY) != _OpRole.Optimize:
                continue
            params = op.input("Param")
            fed = op.input("Grad")
            for i, g in enumerate(fed):
                if i < len(params):
                    raw = params[i] + self.GRAD_SUFFIX
                    if raw in block.vars:
                        g = raw
                if g not in seen:
                    seen.add(g)
                    out.append(g)
        return out

    def _apply_block(self, block):
        for g in self._grad_names(block):
            synced = g + self.SYNC_SUFFIX
            if synced in block.vars:
                continue
            writers = [i for i, op in enumerate(block.ops)
                       if g in op.output_arg_names
                       and op.type != "hier_allreduce"]
            if not writers:
                continue
            idx = writers[-1]
            v = block.vars.get(g)
            block.create_var(name=synced,
                             shape=getattr(v, "shape", None),
                             dtype=getattr(v, "dtype", "float32"))
            block._insert_op(
                idx + 1, "hier_allreduce",
                inputs={"X": [g]}, outputs={"Out": [synced]},
                attrs={"inner_axis": self.inner_axis,
                       "outer_axis": self.outer_axis,
                       "mean": True,
                       OP_ROLE_KEY: _OpRole.Backward})
            for op in block.ops[idx + 2:]:
                for slot, names in op.inputs.items():
                    op.inputs[slot] = [synced if n == g else n
                                       for n in names]


@register_pass("quant_aware")
class QuantAwarePass(Pass):
    """QAT fake-quant instrumentation (reference contrib/slim
    QuantizationTransformPass, exposed here as a registered program
    pass). attrs forwarded to the slim implementation."""

    weight_bits = 8
    activation_bits = 8

    def apply(self, program):
        from ..contrib.slim.quantization.quantization_pass import (
            QuantizationTransformPass)
        QuantizationTransformPass(
            weight_bits=self.weight_bits,
            activation_bits=self.activation_bits).apply(program)


# ---------------------------------------------------------------------------
# The pre-lowering optimization pipeline: DCE / CSE / optimizer fusion.
#
# The purity/side-effect classifier and the def-use/liveness machinery
# live in framework/analysis.py — ONE authoritative implementation shared
# by the passes, the program verifier, and future passes (ZeRO bucket
# sharding, fuse_embedding). The original names stay importable from
# here (SIDE_EFFECT_OPS, _is_side_effect_type, ... aliased at the top).
# ---------------------------------------------------------------------------


def _freeze(v):
    """Stable hashable form of an op attr value (nested dicts from grad
    ops' __fwd_op__, numpy arrays, lists)."""
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, np.ndarray):
        return ("__ndarray__", v.shape, str(v.dtype), v.tobytes())
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, (set, frozenset)):
        return tuple(sorted((_freeze(x) for x in v), key=repr))
    return v


@register_pass("dce")
class DeadCodeEliminationPass(Pass):
    """Drop global-block ops whose outputs are unreachable from the
    fetch targets, any persistable write, or any side-effecting op
    (reference: the executor GC/pruning role of
    framework/executor_gc_helper.cc + Program._prune, but run
    automatically before lowering). Control-flow ops keep their whole
    sub-block; only block 0 is pruned. attrs: fetch_names."""

    pipeline_order = 10
    fetch_names = ()

    def apply(self, program):
        from .analysis import live_op_ids
        block = program.global_block()
        live = live_op_ids(program, self.fetch_names or ())
        kept = [op for op in block.ops if id(op) in live]
        self._report = {"removed_ops": len(block.ops) - len(kept)}
        block.ops = kept


@register_pass("cse")
class CommonSubexpressionEliminationPass(Pass):
    """Dedupe identical pure ops in the global block: two ops with the
    same (type, attrs, input names at the same binding version) compute
    the same values, so the second is dropped and later readers are
    renamed to the first's outputs. Never merges RNG-consuming ops
    (each carries a unique __rng_seed__ and must keep its own stream),
    side-effecting ops, control-flow ops, or ops whose outputs are
    persistable, fetched, rebound elsewhere, or read inside a
    sub-block (those reads cannot be renamed). attrs: fetch_names."""

    pipeline_order = 20
    fetch_names = ()

    def _pinned_names(self, program):
        from .analysis import sub_block_pinned_reads
        fetches = self.fetch_names or ()
        if isinstance(fetches, str):
            fetches = (fetches,)
        # renames don't descend into sub-blocks, so anything a
        # control-flow op (transitively) reads stays fixed
        return set(fetches) | sub_block_pinned_reads(program)

    def _eligible(self, block, op, pinned, def_count, version):
        from .analysis import is_pure_op
        if not is_pure_op(op):
            return False
        outs = op.output_arg_names
        if not outs:
            return False
        for n in outs:
            if n in pinned or n in version or def_count.get(n, 0) != 1:
                return False       # only fresh, single-def outputs
            try:
                if block.var(n).persistable:
                    return False
            except ValueError:
                pass
        return True

    @staticmethod
    def _key(op, version):
        attrs = tuple(sorted((k, _freeze(v)) for k, v in op.attrs.items()
                             if k != OP_ROLE_KEY))
        ins = tuple(sorted(
            (slot, tuple((n, version.get(n, 0)) for n in names))
            for slot, names in op.inputs.items()))
        out_shape = tuple(sorted((slot, len(names))
                                 for slot, names in op.outputs.items()))
        return (op.type, attrs, ins, out_shape)

    def apply(self, program):
        from .analysis import block_def_use
        block = program.global_block()
        pinned = self._pinned_names(program)
        def_count = block_def_use(program).def_count
        version = {}       # name -> rebind count (value identity)
        rename = {}        # dropped output -> canonical output
        seen = {}          # value key -> canonical op
        kept = []
        merged = 0
        for op in block.ops:
            for slot, names in op.inputs.items():
                op.inputs[slot] = [rename.get(n, n) for n in names]
            if self._eligible(block, op, pinned, def_count, version):
                key = self._key(op, version)
                prior = seen.get(key)
                if prior is not None:
                    for slot, names in op.outputs.items():
                        for mine, theirs in zip(names,
                                                prior.outputs.get(slot,
                                                                  ())):
                            rename[mine] = theirs
                    merged += 1
                    continue       # drop the duplicate
                seen[key] = op
            kept.append(op)
            for n in op.output_arg_names:
                version[n] = version.get(n, 0) + 1
        block.ops = kept
        self._report = {"merged_ops": merged}


# Fusable per-param optimizer updates: state slots riding along with
# Param/Grad/LearningRate. LARS/LAMB are excluded on purpose — their
# per-PARAM norm reductions would change meaning over a concatenation.
_FUSABLE_OPTIMIZERS = {
    "sgd": (),
    "momentum": ("Velocity",),
    "adam": ("Moment1", "Moment2", "Beta1Pow", "Beta2Pow"),
    "adamw": ("Moment1", "Moment2", "Beta1Pow", "Beta2Pow"),
}
# scalar-broadcast state (per-param scalars, NOT concatenated)
_SCALAR_STATE = frozenset({"Beta1Pow", "Beta2Pow"})
_STATE_OUT = {"Velocity": "VelocityOut", "Moment1": "Moment1Out",
              "Moment2": "Moment2Out", "Beta1Pow": "Beta1PowOut",
              "Beta2Pow": "Beta2PowOut"}


@register_pass("fuse_optimizer")
class FuseOptimizerPass(Pass):
    """Multi-tensor optimizer fusion (reference
    ir/fuse_optimizer_ops_pass/fuse_adam_op_pass.cc; NVIDIA Apex
    multi_tensor_apply): per-param sgd/momentum/adam/adamw update ops
    with the same (op type, param dtype, hyperparameters, LR var) fuse
    into bucketed ``fused_<type>`` ops, each lowered as ONE
    flattened-concat elementwise update (framework/lowering.py
    fused_flat_apply) — bitwise-identical per element to the per-param
    ops, but hundreds of tiny kernels become a handful. Buckets cap at
    ``max_bucket_bytes`` (default FLAGS_fuse_optimizer_bucket_mb).
    Sparse (SelectedRows) grads, lazy-mode adam, sharded (dist_attr)
    params, and param-shaped beta-pow accumulators stay unfused."""

    pipeline_order = 30
    fetch_names = ()
    max_bucket_bytes = None

    # -- eligibility ------------------------------------------------------
    @staticmethod
    def _maybe_sparse_names(block):
        """Var names that may hold a SelectedRows VALUE at run time
        (sparsity is a value property here, not an IR var type): outputs
        of sparse-grad emitters, propagated through any op they feed."""
        sparse = set()
        for op in block.ops:
            t = op.type
            src = t in ("split_selected_rows", "merge_selected_rows")
            if not src and t.endswith("_grad"):
                fwd = op.attrs.get("__fwd_op__")
                src = bool(op.attrs.get("is_sparse")) or (
                    isinstance(fwd, dict)
                    and fwd.get("attrs", {}).get("is_sparse"))
            if src or any(n in sparse for n in op.input_arg_names):
                sparse.update(op.output_arg_names)
        return sparse

    def _candidate(self, block, op, sparse_names):
        """(group_key, param_bytes) when `op` is a fusable per-param
        update, else None."""
        state_slots = _FUSABLE_OPTIMIZERS.get(op.type)
        if state_slots is None:
            return None
        if op.attrs.get("lazy_mode"):
            return None
        needed = ("Param", "Grad", "LearningRate") + state_slots
        if any(len(op.inputs.get(s, ())) != 1 for s in needed):
            return None
        pname = op.inputs["Param"][0]
        if op.outputs.get("ParamOut", [None])[0] != pname:
            return None            # only the in-place update form
        for slot in state_slots:   # state must be in-place too: the
            if op.outputs.get(_STATE_OUT[slot], [None])[0] != \
                    op.inputs[slot][0]:
                return None        # fused op rebinds the input names
        gname = op.inputs["Grad"][0]
        if gname in sparse_names:
            return None            # SelectedRows grad: keep sparse path
        try:
            pvar = block.var(pname)
            gvar = block.var(gname)
        except ValueError:
            return None
        if getattr(pvar, "dist_attr", None) is not None:
            return None            # sharded param: keep natural layout
        if getattr(gvar, "type", _VarType.LOD_TENSOR) != _VarType.LOD_TENSOR:
            return None            # sparse grad
        shape = getattr(pvar, "shape", None)
        if shape is None or any(int(s) < 0 for s in shape):
            return None
        # beta-pow accumulators come scalar-shaped OR param-shaped (both
        # are elementwise in the update); a bucket must be homogeneous so
        # the fused kernel picks ONE broadcast strategy
        pow_mode = ""
        for slot in state_slots:
            try:
                svar = block.var(op.inputs[slot][0])
            except ValueError:
                return None
            if slot in _SCALAR_STATE:
                sshape = getattr(svar, "shape", None)
                if sshape is None:
                    return None
                if tuple(sshape) == tuple(shape):
                    mode = "dense"     # wins ties for ()/(1,)-params
                elif tuple(sshape) in ((), (1,)):
                    mode = "scalar"
                else:
                    return None
                if pow_mode and mode != pow_mode:
                    return None
                pow_mode = mode
        attrs = tuple(sorted(
            (k, _freeze(v)) for k, v in op.attrs.items()
            if k not in (OP_ROLE_KEY, "op_device", "lazy_mode")))
        try:
            itemsize = np.dtype(_np_dtype(pvar.dtype)).itemsize
        except (TypeError, ValueError):
            return None
        nbytes = int(np.prod(shape, dtype=np.int64)) * itemsize
        key = (op.type, str(pvar.dtype), op.inputs["LearningRate"][0],
               attrs, pow_mode)
        return key, nbytes

    @staticmethod
    def _op_names(block, op):
        # sub-block reads AND writes count: a control-flow op that
        # touches an updated param only inside its sub_block must still
        # close the bucket, or the fused update would move past it
        from .analysis import op_reads, op_writes
        if _has_sub_block(op):
            return (set(op_reads(block.program, op)),
                    set(op_writes(block.program, op)))
        return set(op.input_arg_names), set(op.output_arg_names)

    def _build_fused(self, block, ops):
        first = ops[0]
        state_slots = _FUSABLE_OPTIMIZERS[first.type]
        inputs = {"Param": [o.inputs["Param"][0] for o in ops],
                  "Grad": [o.inputs["Grad"][0] for o in ops],
                  "LearningRate": [first.inputs["LearningRate"][0]]}
        outputs = {"ParamOut": [o.inputs["Param"][0] for o in ops]}
        for slot in state_slots:
            inputs[slot] = [o.inputs[slot][0] for o in ops]
            outputs[_STATE_OUT[slot]] = [o.inputs[slot][0] for o in ops]
        attrs = {k: v for k, v in first.attrs.items()
                 if k not in (OP_ROLE_KEY, "op_device")}
        attrs[OP_ROLE_KEY] = _OpRole.Optimize
        return _Operator(block, "fused_" + first.type, inputs=inputs,
                        outputs=outputs, attrs=attrs)

    def apply(self, program):
        block = program.global_block()
        cap = self.max_bucket_bytes
        if not cap:
            cap = int(_flag("fuse_optimizer_bucket_mb")) * (1 << 20)
        # One forward walk. Fusable ops join the open bucket for their
        # group key; the bucket's fused op is emitted where the bucket
        # CLOSES — i.e. members only ever move LATER, to the point just
        # before the first op that observes them. An op that reads or
        # rebinds any var a member already wrote, or rebinds a var a
        # member read, closes the bucket first, so every such observer
        # still sees exactly the values it saw under per-param order.
        new_ops = []
        open_buckets = {}       # key -> {"ops", "bytes", reads, writes}
        report = {"fused_buckets": 0, "fused_params": 0}
        sparse_names = self._maybe_sparse_names(block)

        def close(key):
            b = open_buckets.pop(key, None)
            if b is None:
                return
            if len(b["ops"]) == 1:
                new_ops.append(b["ops"][0])
            else:
                new_ops.append(self._build_fused(block, b["ops"]))
                report["fused_buckets"] += 1
                report["fused_params"] += len(b["ops"])

        def conflicts(reads, writes, bucket):
            return (writes & bucket["writes"] or reads & bucket["writes"]
                    or writes & bucket["reads"])

        for op in block.ops:
            reads, writes = self._op_names(block, op)
            cand = self._candidate(block, op, sparse_names)
            key = cand[0] if cand else None
            for k in [k for k, b in open_buckets.items()
                      if k != key and conflicts(reads, writes, b)]:
                close(k)
            if cand is None:
                new_ops.append(op)
                continue
            _, nbytes = cand
            bucket = open_buckets.get(key)
            if bucket is not None and (
                    conflicts(reads, writes, bucket)
                    or bucket["bytes"] + nbytes > cap):
                close(key)
                bucket = None
            if bucket is None:
                bucket = {"ops": [], "bytes": 0, "reads": set(),
                          "writes": set()}
                open_buckets[key] = bucket
            bucket["ops"].append(op)
            bucket["bytes"] += nbytes
            bucket["reads"] |= reads
            bucket["writes"] |= writes
        for k in list(open_buckets):
            close(k)
        block.ops = new_ops
        self._report = report
