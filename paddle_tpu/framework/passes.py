"""Program-pass framework: registry + ordered application.

Capability parity with the reference's IR pass infrastructure
(/root/reference/paddle/fluid/framework/ir/pass.h — Pass::Apply over a
Graph, REGISTER_PASS, and PassBuilder ordering in
paddle/fluid/framework/details/build_strategy.cc). The reference's passes
mutate a C++ graph; here a pass is a callable over the Program IR
(framework/core.py), the same structure every existing rewrite (AMP cast
insertion, QAT instrumentation, sync-BN substitution) already walks by
hand. Registering them gives users the reference's extension point: write
a Pass subclass, `register_pass` it, and `apply_passes(program, [...])`
runs an ordered pipeline.
"""


class Pass:
    """Base pass: override apply(program) and mutate in place (return
    the program for chaining). `name` defaults to the class name
    de-camelized; attrs passed at construction are available on self."""

    name = None

    def __init__(self, **attrs):
        for k, v in attrs.items():
            setattr(self, k, v)

    def apply(self, program):
        raise NotImplementedError

    def __call__(self, program):
        out = self.apply(program)
        out = program if out is None else out
        # the executor caches compiled programs on (uid, version): a
        # mutation-only pass must invalidate that cache or it silently
        # no-ops on an already-executed program
        bump = getattr(out, "_bump_version", None)
        if bump is not None:
            bump()
        return out


_PASSES = {}


def register_pass(name):
    """Decorator: register a Pass subclass (or factory) under `name`
    (reference REGISTER_PASS(name, class))."""
    def deco(cls):
        _PASSES[name] = cls
        if getattr(cls, "name", None) is None:
            try:
                cls.name = name
            except (AttributeError, TypeError):
                pass
        return cls
    return deco


def get_pass(name, **attrs):
    cls = _PASSES.get(name)
    if cls is None:
        raise KeyError(
            f"pass {name!r} is not registered; known: {sorted(_PASSES)}")
    return cls(**attrs)


def has_pass(name):
    return name in _PASSES


def list_passes():
    return sorted(_PASSES)


def apply_passes(program, names, **common_attrs):
    """Run passes in the given order (reference PassBuilder::Build).
    `names` entries are either a registered name or an instantiated
    Pass/callable."""
    for n in names:
        p = get_pass(n, **common_attrs) if isinstance(n, str) else n
        program = p(program) or program
    return program


# ---------------------------------------------------------------------------
# Built-in passes wrapping the existing hand-rolled program rewrites, so
# the standard transforms are discoverable/orderable through the registry
# like the reference's default pass pipeline (build_strategy.cc).
# ---------------------------------------------------------------------------

@register_pass("amp_bf16")
class AmpBf16Pass(Pass):
    """bf16 mixed-precision cast insertion (contrib.mixed_precision.
    fp16_utils.rewrite_program; reference ir/fp16 pass family). attrs:
    amp_lists (AutoMixedPrecisionLists), dest_dtype."""

    amp_lists = None
    dest_dtype = "bfloat16"

    def apply(self, program):
        from ..contrib.mixed_precision.fp16_lists import (
            AutoMixedPrecisionLists)
        from ..contrib.mixed_precision.fp16_utils import rewrite_program
        rewrite_program(program,
                        self.amp_lists or AutoMixedPrecisionLists(),
                        dest_dtype=self.dest_dtype)


@register_pass("sync_batch_norm")
class SyncBatchNormPass(Pass):
    """batch_norm -> sync_batch_norm substitution (reference
    framework/ir/sync_batch_norm_pass.cc; the CompiledProgram build
    strategy applies it via this registry)."""

    def apply(self, program):
        for block in program.blocks:
            for op in block.ops:
                if op.type == "batch_norm":
                    op.type = "sync_batch_norm"


@register_pass("quant_aware")
class QuantAwarePass(Pass):
    """QAT fake-quant instrumentation (reference contrib/slim
    QuantizationTransformPass, exposed here as a registered program
    pass). attrs forwarded to the slim implementation."""

    weight_bits = 8
    activation_bits = 8

    def apply(self, program):
        from ..contrib.slim.quantization.quantization_pass import (
            QuantizationTransformPass)
        QuantizationTransformPass(
            weight_bits=self.weight_bits,
            activation_bits=self.activation_bits).apply(program)
