"""SelectedRows: sparse row-set gradients.

Capability parity with the reference's SelectedRows
(/root/reference/paddle/fluid/framework/selected_rows.h:32): the sparse
(id -> row) tensor used for embedding gradients so a [vocab, dim] dense
grad never materializes (lookup_table_op.h emits SelectedRows when
is_sparse=True; optimizer kernels have *_sparse variants over it).

TPU-first mapping: a SelectedRows value is a host-side pytree
`SelectedRows(rows=int32[N], values=f32[N, ...])` flowing through the SAME
functional env slots as dense arrays — XLA traces it as two arrays.
Gradient accumulation concatenates (duplicate ids are fine: scatter-adds
coalesce them), and optimizer lowerings apply row-wise updates via
`.at[rows].add` (a fused TPU scatter) instead of a dense [vocab, dim] op.
"""
from typing import NamedTuple

import jax
import jax.numpy as jnp


class SelectedRows(NamedTuple):
    rows: jax.Array       # int32 [N] row ids (duplicates allowed)
    values: jax.Array     # [N, ...] per-row gradient values

    @property
    def dtype(self):      # duck-type as an array where it matters
        return self.values.dtype

    @property
    def shape(self):
        return self.values.shape


# NamedTuples are pytrees automatically — SelectedRows nests transparently
# into jit arguments/results.


def is_selected_rows(v):
    return isinstance(v, SelectedRows)


def merge(grads):
    """Accumulate partial sparse grads: concat rows/values (scatter-adds
    coalesce duplicates at apply time) — reference
    merge_selected_rows_op semantics without the sort."""
    rows = jnp.concatenate([g.rows for g in grads])
    values = jnp.concatenate([g.values for g in grads])
    return SelectedRows(rows, values)


def to_dense(sr, dense_shape, dtype=None):
    """Materialize (for parity checks / fallbacks)."""
    out = jnp.zeros(dense_shape, dtype or sr.values.dtype)
    return out.at[sr.rows].add(sr.values)


def coalesce(sr):
    """Merge duplicate row ids so each unique row appears once (the
    reference's scatter::MergeAdd before sparse optimizer updates).
    Static-shape form: values of later duplicates fold into the FIRST
    occurrence's slot; duplicate slots get an out-of-range row id so
    .at[rows] scatters with mode='drop' skip them (N stays fixed)."""
    rows = sr.rows
    n = rows.shape[0]
    eq = rows[None, :] == rows[:, None]               # [N, N]
    first = jnp.argmax(eq, axis=1).astype(jnp.int32)  # first occurrence idx
    merged = jnp.zeros_like(sr.values).at[first].add(sr.values)
    is_first = jnp.arange(n, dtype=jnp.int32) == first
    big = jnp.asarray(2_147_483_647, rows.dtype)      # dropped by scatters
    rows_eff = jnp.where(is_first, rows, big)
    return SelectedRows(rows_eff, merged)
