#!/bin/sh
# Build the native datafeed engine (no deps beyond libstdc++/pthread).
cd "$(dirname "$0")"
exec g++ -std=c++17 -O2 -shared -fPIC -pthread datafeed.cc \
    -o libpaddle_datafeed.so
