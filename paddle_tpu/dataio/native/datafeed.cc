// Native multi-threaded slot-data feed engine.
//
// TPU-native equivalent of the reference's C++ DataFeed/Dataset ingestion
// runtime (/root/reference/paddle/fluid/framework/data_feed.h MultiSlot*,
// framework/data_set.h DatasetImpl, framework/channel.h): N reader threads
// pull files off a shared list, parse slot-formatted text lines
// ("name:v1,v2,... name2:...") into contiguous per-slot buffers, batch
// them, and push batches through a bounded producer/consumer channel the
// Python DataLoader drains. The GIL-free parse + batch assembly is the
// point — the reference burns whole host cores on exactly this work per
// trainer (hogwild_worker.cc TrainFiles' feed->Next()).
//
// C ABI only (consumed via ctypes from dataio/native_feed.py; this repo
// deliberately has no pybind dependency). Build: see build.sh next to
// this file (g++ -O2 -shared -fPIC -pthread).
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <memory>
#include <mutex>
#include <queue>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Slot {
  std::string name;
  bool is_int = false;         // int64 vs float32
  std::atomic<int> width{-1};  // values per sample; -1 until inferred

  Slot() = default;
  Slot(const Slot& o)
      : name(o.name), is_int(o.is_int), width(o.width.load()) {}
};

struct Batch {
  int rows = 0;
  // per-slot contiguous data, rows * width elements each
  std::vector<std::vector<float>> fdata;
  std::vector<std::vector<int64_t>> idata;
};

struct Feed {
  std::vector<Slot> slots;
  std::vector<std::string> files;
  int batch_size = 1;
  size_t capacity = 8;

  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  std::queue<Batch*> channel;
  std::atomic<size_t> next_file{0};
  std::atomic<int> live_readers{0};
  std::atomic<long long> dropped{0};   // malformed/ragged lines skipped
  std::mutex width_mu;                 // serializes first-width inference
  std::atomic<bool> stopping{false};
  std::vector<std::thread> threads;
  std::string error;

  ~Feed() { stop(); }

  void stop() {
    {
      // the flag flip must be ordered with waiters' predicate checks:
      // an unlocked store+notify can fire between a waiter's check and
      // its wait(), losing the wakeup forever
      std::lock_guard<std::mutex> g(mu);
      stopping = true;
    }
    cv_push.notify_all();
    cv_pop.notify_all();
    for (auto& t : threads)
      if (t.joinable()) t.join();
    threads.clear();
    std::lock_guard<std::mutex> g(mu);
    while (!channel.empty()) {
      delete channel.front();
      channel.pop();
    }
  }

  void fail(const std::string& msg) {
    {
      std::lock_guard<std::mutex> g(mu);
      if (error.empty()) error = msg;
      stopping = true;
    }
    cv_push.notify_all();
    cv_pop.notify_all();
  }

  void push(Batch* b) {
    std::unique_lock<std::mutex> g(mu);
    cv_push.wait(g, [&] { return channel.size() < capacity || stopping; });
    if (stopping) {
      delete b;
      return;
    }
    channel.push(b);
    cv_pop.notify_one();
  }

  // nullptr => end of data (all readers done, channel drained) or error
  Batch* pop() {
    std::unique_lock<std::mutex> g(mu);
    cv_pop.wait(g, [&] {
      return !channel.empty() || live_readers.load() == 0 || stopping;
    });
    if (!channel.empty()) {
      Batch* b = channel.front();
      channel.pop();
      cv_push.notify_one();
      return b;
    }
    return nullptr;
  }

  bool parse_line(const std::string& line, Batch* batch) {
    // find each slot's "name:" group; groups may appear in any order
    size_t nslots = slots.size();
    std::vector<const char*> starts(nslots, nullptr);
    std::vector<size_t> lens(nslots, 0);
    const char* p = line.c_str();
    while (*p) {
      while (*p == ' ' || *p == '\t') ++p;
      if (!*p) break;
      const char* tok = p;
      while (*p && *p != ' ' && *p != '\t') ++p;
      const char* colon =
          static_cast<const char*>(memchr(tok, ':', p - tok));
      if (!colon) continue;
      for (size_t s = 0; s < nslots; ++s) {
        if (slots[s].name.size() == static_cast<size_t>(colon - tok) &&
            memcmp(slots[s].name.data(), tok, colon - tok) == 0) {
          starts[s] = colon + 1;
          lens[s] = p - colon - 1;
          break;
        }
      }
    }
    for (size_t s = 0; s < nslots; ++s) {
      if (!starts[s]) return false;  // missing slot -> drop line
      // count values
      int n = 1;
      for (size_t i = 0; i < lens[s]; ++i)
        if (starts[s][i] == ',') ++n;
      int w = slots[s].width.load(std::memory_order_acquire);
      if (w < 0) {
        // first width observation: serialize so every thread/batch agrees
        std::lock_guard<std::mutex> g(width_mu);
        w = slots[s].width.load(std::memory_order_relaxed);
        if (w < 0) {
          slots[s].width.store(n, std::memory_order_release);
          w = n;
        }
      }
      if (n != w) return false;  // ragged -> drop line
    }
    // parse with rollback: a malformed token (non-numeric, trailing
    // comma) must not leave a partial row behind — the buffers would
    // silently misalign every following sample in the batch
    for (size_t s = 0; s < nslots; ++s) {
      const char* q = starts[s];
      const char* end = starts[s] + lens[s];
      int w = slots[s].width.load(std::memory_order_relaxed);
      size_t before =
          slots[s].is_int ? batch->idata[s].size() : batch->fdata[s].size();
      bool bad = false;
      while (q < end && !bad) {
        char* next;
        if (slots[s].is_int)
          batch->idata[s].push_back(strtoll(q, &next, 10));
        else
          batch->fdata[s].push_back(strtof(q, &next));
        if (next == q) bad = true;          // no progress: garbage token
        q = (*next == ',') ? next + 1 : next;
      }
      size_t added = (slots[s].is_int ? batch->idata[s].size()
                                      : batch->fdata[s].size()) - before;
      if (bad || added != static_cast<size_t>(w)) {
        for (size_t r = 0; r <= s; ++r) {   // roll back this line fully
          auto trim = [&](auto& vec) {
            int wr = slots[r].width.load(std::memory_order_relaxed);
            size_t keep = static_cast<size_t>(batch->rows) *
                          (wr < 0 ? 0 : wr);
            if (vec.size() > keep) vec.resize(keep);
          };
          if (slots[r].is_int) trim(batch->idata[r]);
          else trim(batch->fdata[r]);
        }
        return false;
      }
    }
    batch->rows += 1;
    return true;
  }

  Batch* new_batch() {
    Batch* b = new Batch();
    b->fdata.resize(slots.size());
    b->idata.resize(slots.size());
    return b;
  }

  void reader_main() {
    Batch* batch = new_batch();
    while (!stopping) {
      size_t fi = next_file.fetch_add(1);
      if (fi >= files.size()) break;
      std::ifstream in(files[fi]);
      if (!in) {
        delete batch;
        fail("datafeed: cannot open file " + files[fi]);
        {
          std::lock_guard<std::mutex> g(mu);
          live_readers.fetch_sub(1);
        }
        cv_pop.notify_all();
        return;
      }
      std::string line;
      while (!stopping && std::getline(in, line)) {
        if (line.empty()) continue;
        if (!parse_line(line, batch)) dropped.fetch_add(1);
        if (batch->rows == batch_size) {
          push(batch);
          batch = new_batch();
        }
      }
    }
    if (batch->rows > 0 && !stopping)
      push(batch);
    else
      delete batch;
    {
      std::lock_guard<std::mutex> g(mu);
      live_readers.fetch_sub(1);
    }
    cv_pop.notify_all();
  }
};

}  // namespace

extern "C" {

void* df_create(const char* slot_names, const char* slot_is_int,
                int batch_size, int capacity) {
  auto* f = new Feed();
  std::stringstream names(slot_names), kinds(slot_is_int);
  std::string n, k;
  while (std::getline(names, n, ',') && std::getline(kinds, k, ',')) {
    Slot s;
    s.name = n;
    s.is_int = (k == "1");
    f->slots.push_back(s);
  }
  f->batch_size = batch_size > 0 ? batch_size : 1;
  f->capacity = capacity > 0 ? capacity : 8;
  return f;
}

int df_set_filelist(void* h, const char** paths, int n) {
  auto* f = static_cast<Feed*>(h);
  f->files.assign(paths, paths + n);
  return 0;
}

int df_start(void* h, int threads) {
  auto* f = static_cast<Feed*>(h);
  if (threads < 1) threads = 1;
  f->stopping = false;
  f->next_file = 0;
  f->live_readers = threads;
  for (int i = 0; i < threads; ++i)
    f->threads.emplace_back([f] { f->reader_main(); });
  return 0;
}

// Returns a batch handle, or NULL at end-of-data / error.
void* df_next(void* h) { return static_cast<Feed*>(h)->pop(); }

const char* df_error(void* h) {
  auto* f = static_cast<Feed*>(h);
  std::lock_guard<std::mutex> g(f->mu);
  return f->error.empty() ? nullptr : f->error.c_str();
}

int df_batch_rows(void* b) { return static_cast<Batch*>(b)->rows; }

// Slot width as inferred from data (valid once any batch was produced).
int df_slot_width(void* h, int slot) {
  return static_cast<Feed*>(h)->slots[slot].width.load();
}

long long df_dropped(void* h) {
  return static_cast<Feed*>(h)->dropped.load();
}

const float* df_batch_fdata(void* b, int slot) {
  return static_cast<Batch*>(b)->fdata[slot].data();
}

const int64_t* df_batch_idata(void* b, int slot) {
  return static_cast<Batch*>(b)->idata[slot].data();
}

void df_batch_free(void* b) { delete static_cast<Batch*>(b); }

void df_stop(void* h) { static_cast<Feed*>(h)->stop(); }

void df_free(void* h) { delete static_cast<Feed*>(h); }

}  // extern "C"
