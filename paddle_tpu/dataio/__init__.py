"""Data pipeline: readers, loaders, datasets (reference:
python/paddle/fluid/reader.py, data_feeder.py, dataset.py,
python/paddle/reader/decorator.py)."""
from .reader import DataLoader, PyReader, DataFeeder  # noqa: F401
from .feed_desc import DataFeedDesc  # noqa: F401
from .dataset import (  # noqa: F401
    DatasetFactory, DatasetBase, QueueDataset, InMemoryDataset,
)
from . import decorator  # noqa: F401
from .decorator import (  # noqa: F401
    batch, shuffle, buffered, cache, chain, compose, map_readers,
    xmap_readers, firstn,
)
