"""ctypes front for the native C++ datafeed engine (native/datafeed.cc).

The reference parses slot data with C++ DataFeed threads per trainer
(framework/data_feed.h, hogwild_worker.cc feed->Next()); the Python
dataset's pure-python parser is the portable fallback. This wrapper
builds/loads the shared library on demand and exposes the batches as the
same {name: np.ndarray} dicts the Python path yields, so Dataset can swap
engines transparently (dataset.py use_native)."""
import ctypes
import os
import subprocess

import numpy as np

_LIB = None
_LIB_ERR = None


def _lib():
    global _LIB, _LIB_ERR
    if _LIB is not None or _LIB_ERR is not None:
        return _LIB
    here = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "native")
    so = os.path.join(here, "libpaddle_datafeed.so")
    src = os.path.join(here, "datafeed.cc")
    try:
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(src)):
            # build.sh is the single source of truth for compile flags
            subprocess.run(["sh", os.path.join(here, "build.sh")],
                           check=True, capture_output=True)
        lib = ctypes.CDLL(so)
    except Exception as e:  # no compiler / load failure -> python path
        _LIB_ERR = e
        return None
    lib.df_create.restype = ctypes.c_void_p
    lib.df_create.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                              ctypes.c_int, ctypes.c_int]
    lib.df_set_filelist.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p), ctypes.c_int]
    lib.df_start.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.df_next.restype = ctypes.c_void_p
    lib.df_next.argtypes = [ctypes.c_void_p]
    lib.df_error.restype = ctypes.c_char_p
    lib.df_error.argtypes = [ctypes.c_void_p]
    lib.df_batch_rows.restype = ctypes.c_int
    lib.df_batch_rows.argtypes = [ctypes.c_void_p]
    lib.df_slot_width.restype = ctypes.c_int
    lib.df_slot_width.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.df_batch_fdata.restype = ctypes.POINTER(ctypes.c_float)
    lib.df_batch_fdata.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.df_batch_idata.restype = ctypes.POINTER(ctypes.c_int64)
    lib.df_batch_idata.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.df_batch_free.argtypes = [ctypes.c_void_p]
    lib.df_dropped.restype = ctypes.c_longlong
    lib.df_dropped.argtypes = [ctypes.c_void_p]
    lib.df_stop.argtypes = [ctypes.c_void_p]
    lib.df_free.argtypes = [ctypes.c_void_p]
    _LIB = lib
    return lib


def available():
    return _lib() is not None


class NativeDataFeed:
    """Iterate {slot_name: array[batch, width]} batches parsed by the C++
    engine. slots: [(name, dtype)] with dtype 'int64'/'float32'."""

    def __init__(self, slots, files, batch_size, threads=2, capacity=8,
                 allow_malformed=False):
        lib = _lib()
        if lib is None:
            raise RuntimeError(f"native datafeed unavailable: {_LIB_ERR}")
        self._lib = lib
        self._slots = [(n, np.dtype(d)) for n, d in slots]
        names = ",".join(n for n, _ in self._slots).encode()
        kinds = ",".join(
            "1" if np.issubdtype(d, np.integer) else "0"
            for _, d in self._slots).encode()
        self._h = lib.df_create(names, kinds, batch_size, capacity)
        arr = (ctypes.c_char_p * len(files))(
            *[str(f).encode() for f in files])
        lib.df_set_filelist(self._h, arr, len(files))
        self._threads = threads
        self._started = False
        self._allow_malformed = allow_malformed

    def __iter__(self):
        lib = self._lib
        if self._started:
            raise RuntimeError("NativeDataFeed is single-pass; build a "
                               "new one per epoch")
        self._started = True
        lib.df_start(self._h, self._threads)
        try:
            while True:
                b = lib.df_next(self._h)
                if not b:
                    err = lib.df_error(self._h)
                    if err:
                        raise RuntimeError(err.decode())
                    n_drop = lib.df_dropped(self._h)
                    if n_drop and not self._allow_malformed:
                        # the pure-python parser raises on the same input;
                        # a silent sample-count difference between engines
                        # would corrupt experiments invisibly
                        raise RuntimeError(
                            f"native datafeed dropped {n_drop} malformed/"
                            f"ragged lines (missing slot, bad token, or "
                            f"inconsistent width); fix the data or pass "
                            f"allow_malformed=True")
                    return
                rows = lib.df_batch_rows(b)
                out = {}
                for i, (name, dt) in enumerate(self._slots):
                    w = lib.df_slot_width(self._h, i)
                    n = rows * w
                    if np.issubdtype(dt, np.integer):
                        ptr = lib.df_batch_idata(b, i)
                        a = np.ctypeslib.as_array(ptr, (n,)).copy()
                    else:
                        ptr = lib.df_batch_fdata(b, i)
                        a = np.ctypeslib.as_array(ptr, (n,)).copy()
                    out[name] = a.astype(dt).reshape(rows, w)
                lib.df_batch_free(b)
                yield out
        finally:
            lib.df_stop(self._h)

    def close(self):
        if getattr(self, "_h", None):
            self._lib.df_stop(self._h)
            self._lib.df_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
