"""Dataset — out-of-core file ingestion (reference:
/root/reference/paddle/fluid/framework/data_set.h:43 DatasetImpl,
python/paddle/fluid/dataset.py InMemoryDataset/QueueDataset; slot schema
framework/data_feed.proto). TPU-first: the C++ channel/DataFeed machinery
becomes a host-side parser + filelist sharding; global shuffle shards by
process index over jax.distributed instead of an RPC ring
(data_set.h:110 GlobalShuffle).

Text line format (slot-based, like the reference's MultiSlotDataFeed):
whitespace-separated `name:v1,v2,...` groups, or a custom line_parser.
"""
import random

import numpy as np

from ..resilience import maybe_fail as _maybe_fail


class PositionedBatchIterator:
    """Batch/slab iterator with a resumable cursor — the position API
    behind preemption-aware training (train.TrainingSupervisor).

    Wraps a raw batch stream; ``position()`` reports exactly how much of
    the stream the consumer has RECEIVED (batches land in the count only
    when the batch — or the completed slab holding it — is yielded, so a
    slab buffered half-full at kill time is not counted):

    - ``epoch``: the epoch index this iterator was created for
    - ``batches``: batches consumed so far, INCLUDING the replay-skipped
      prefix — feed it back as ``position={"batches": n}`` to resume
    - ``slabs``: slabs (or batches when unslabbed) yielded this epoch
    - ``skipped``: the buffered-reader skip count — how many batches this
      iterator re-parsed and dropped to reach its resume point
    - ``shuffle_seed``: the dataset's shuffle seed at creation (None when
      the dataset has none), so a resumed run can replay the same
      permutation before skipping
    """

    def __init__(self, raw_batches, slab=None, epoch=0, skip_batches=0,
                 shuffle_seed=None):
        # slab=1 (unlike the legacy positionless path) still SLABS: the
        # consumer asked for run_steps-shaped dicts with a leading step
        # axis, and a [batch, ...] dict would be misread as a 1-sample
        # K=batch slab
        self._slab = int(slab) if slab else 0
        self._epoch = int(epoch)
        self._shuffle_seed = shuffle_seed
        self._skipped = 0
        for _ in range(int(skip_batches)):
            if next(raw_batches, None) is None:
                break
            self._skipped += 1
        self._batches = self._skipped
        self._slabs = 0
        self._it = (DatasetBase._slab_batches(raw_batches, self._slab)
                    if self._slab >= 1 else raw_batches)

    def __iter__(self):
        return self

    def __next__(self):
        out = next(self._it)
        if self._slab >= 1:
            # the slab's leading axis IS its batch count (shape-change
            # flushes and the tail yield short slabs)
            self._batches += int(np.shape(next(iter(out.values())))[0])
        else:
            self._batches += 1
        self._slabs += 1
        return out

    def position(self):
        return {"epoch": self._epoch, "batches": self._batches,
                "slabs": self._slabs, "skipped": self._skipped,
                "shuffle_seed": self._shuffle_seed}


class DatasetFactory:
    def create_dataset(self, datafeed_class="QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        return QueueDataset()


class DatasetBase:
    def __init__(self):
        self.filelist = []
        self.batch_size = 1
        self.thread_num = 1
        self.use_vars = []
        self.pipe_command = None
        self.line_parser = None
        self._seed = 0

    # ---- config surface (reference dataset.py) ----
    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    def set_batch_size(self, batch_size):
        self.batch_size = batch_size

    def set_thread(self, thread_num):
        self.thread_num = thread_num

    def set_use_var(self, var_list):
        self.use_vars = list(var_list)

    def set_pipe_command(self, cmd):
        # the reference pipes lines through an external binary; here a
        # python line_parser covers the capability
        self.pipe_command = cmd

    def set_line_parser(self, fn):
        """fn(line) -> tuple of per-var numpy values (sample)."""
        self.line_parser = fn

    def set_hdfs_config(self, fs_name, fs_ugi):
        pass  # no HDFS in this environment; local/NFS paths only

    # ---- parsing ----
    def _parse_line(self, line):
        if self.line_parser is not None:
            return self.line_parser(line)
        sample = []
        groups = dict(g.split(":", 1) for g in line.split())
        for var in self.use_vars:
            vals = groups[var.name].split(",")
            dt = np.int64 if "int" in var.dtype else np.float32
            sample.append(np.asarray([dt(v) for v in vals], dtype=dt))
        return tuple(sample)

    def _iter_files(self, files):
        for path in files:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield self._parse_line(line)

    def _shard_files(self):
        import jax
        n, idx = jax.process_count(), jax.process_index()
        return self.filelist[idx::n] if n > 1 else list(self.filelist)

    def _batches(self, samples):
        names = [v.name for v in self.use_vars]
        buf = []
        for s in samples:
            buf.append(s)
            if len(buf) == self.batch_size:
                _maybe_fail("dataio.producer")
                yield self._collate(names, buf)
                buf = []
        if buf:
            _maybe_fail("dataio.producer")
            yield self._collate(names, buf)

    def _positioned(self, it, slab, position):
        """Shared batch_iterator tail: with ``position`` the stream is
        wrapped in a :class:`PositionedBatchIterator` (skipping the
        already-consumed prefix); without it the legacy plain iterator
        comes back unchanged."""
        if position is not None:
            return PositionedBatchIterator(
                iter(it), slab=slab,
                epoch=position.get("epoch", 0),
                skip_batches=position.get("batches", 0),
                shuffle_seed=position.get("shuffle_seed",
                                          getattr(self, "_seed", None)))
        if slab and slab > 1:
            return self._slab_batches(it, int(slab))
        return it

    @staticmethod
    def _collate(names, buf):
        out = {}
        for i, n in enumerate(names):
            out[n] = np.stack([s[i] for s in buf])
        return out

    @staticmethod
    def _slab_batches(batches, k):
        """Group consecutive same-shape batches into slabs: dicts with a
        new leading axis of up to `k` steps, the feed format of
        Executor.run_steps. EVERY shape change flushes the open slab
        early so slabs stay homogeneous — for a fixed-shape stream only
        the tail is short, but variable-shape streams (bucketed
        sequence lengths) flush at each bucket switch and those short
        slabs run unfused (train_from_dataset falls back to per-step
        run() for them); pad/bucket to a stable shape to keep fusion."""
        buf, sig = [], None
        for b in batches:
            # np.shape/getattr: batch values may be plain lists/scalars
            # (run() feeds accept them, so the collator must too)
            s = {n: (np.shape(a), str(getattr(a, "dtype", "")))
                 for n, a in b.items()}
            if buf and s != sig:
                yield DatasetBase._stack_slab(buf)
                buf = []
            sig = s
            buf.append(b)
            if len(buf) == k:
                yield DatasetBase._stack_slab(buf)
                buf = []
        if buf:
            yield DatasetBase._stack_slab(buf)

    @staticmethod
    def _stack_slab(buf):
        return {n: np.stack([np.asarray(b[n]) for b in buf])
                for n in buf[0]}


class QueueDataset(DatasetBase):
    """Streaming: parse + batch on the fly (reference QueueDataset). When
    the native C++ feed engine is buildable and the default slot parser is
    in use, parsing/batching runs GIL-free on `thread_num` reader threads
    (native/datafeed.cc — the reference's MultiSlotDataFeed runtime);
    otherwise the pure-python path is used. set_use_native(False) forces
    python."""

    def __init__(self):
        super().__init__()
        self._use_native = True

    def set_use_native(self, flag):
        self._use_native = bool(flag)

    def _native_ok(self):
        from . import native_feed
        return (self._use_native and self.line_parser is None
                and self.pipe_command is None and self.use_vars
                and native_feed.available())

    def batch_iterator(self, slab=None, position=None):
        if self._native_ok():
            from .native_feed import NativeDataFeed
            slots = [(v.name, "int64" if "int" in v.dtype else "float32")
                     for v in self.use_vars]
            it = iter(NativeDataFeed(
                slots, self._shard_files(), self.batch_size,
                threads=max(self.thread_num, 1)))
        else:
            it = self._batches(self._iter_files(self._shard_files()))
        return self._positioned(it, slab, position)


class InMemoryDataset(DatasetBase):
    """Load once, shuffle in memory (reference InMemoryDataset:
    LoadIntoMemory data_set.h:198, LocalShuffle :108, GlobalShuffle :110)."""

    def __init__(self):
        super().__init__()
        self._samples = []

    def load_into_memory(self):
        self._samples = list(self._iter_files(self._shard_files()))

    def local_shuffle(self):
        random.Random(self._seed).shuffle(self._samples)
        self._seed += 1

    def global_shuffle(self, fleet=None, thread_num=None, spool_dir=None):
        """Cross-process sample redistribution (reference GlobalShuffle,
        data_set.h:110, shuffles over an RPC ring). With `spool_dir` (a
        shared filesystem path) samples really MOVE between processes:
        each worker spools its samples into per-destination files keyed by
        a seeded hash, barriers on marker files, then loads its own
        bucket. Without spool_dir (or single-process), a seeded local
        shuffle of the disjoint filelist shards is the fallback — a valid
        global permutation of assignments in which samples never cross
        processes."""
        import jax

        n, idx = jax.process_count(), jax.process_index()
        if spool_dir is None or n <= 1:
            self.local_shuffle()
            return
        import os
        import pickle
        import time

        os.makedirs(spool_dir, exist_ok=True)
        # files are namespaced by a RUN TOKEN all processes agree on (one
        # broadcast from process 0) + a round counter — stale files from a
        # crashed previous run sharing the spool dir can never satisfy
        # this run's barrier
        if not hasattr(self, "_shuffle_token"):
            import secrets
            try:
                from jax.experimental import multihost_utils
                tok = np.asarray(secrets.randbits(31), np.int32)
                self._shuffle_token = int(
                    multihost_utils.broadcast_one_to_all(tok))
            except Exception:
                # backends without multiprocess collectives (jaxlib's CPU
                # backend raises XlaRuntimeError): agree through the spool
                # dir itself. Process 0 ALWAYS rewrites the token file
                # with a fresh random value (temp + atomic replace) — a
                # token left by a crashed previous run is overwritten,
                # never reused, so that run's shard/done files (named by
                # the old token) can never satisfy this run's barrier.
                # Other ranks only accept a token file written at/after
                # their own arrival (small slack for clock fuzz); a stale
                # file is ignored until rank 0 replaces it.
                tfile = os.path.join(spool_dir, "_run_token")
                if idx == 0:
                    tok0 = secrets.randbits(31)
                    tmp = tfile + ".tmp"
                    with open(tmp, "w") as f:
                        f.write(str(tok0))
                        f.flush()
                        os.fsync(f.fileno())
                    os.replace(tmp, tfile)
                    self._shuffle_token = tok0
                else:
                    fresh_after = time.time() - 120.0
                    deadline0 = time.monotonic() + 300
                    while True:
                        try:
                            if os.stat(tfile).st_mtime >= fresh_after:
                                with open(tfile) as f:
                                    txt = f.read().strip()
                                if txt:
                                    self._shuffle_token = int(txt)
                                    break
                        except OSError:
                            pass
                        if time.monotonic() > deadline0:
                            raise TimeoutError(
                                "global_shuffle: rank 0 never wrote a "
                                "fresh run token to the spool dir")
                        time.sleep(0.02)
        tok = self._shuffle_token
        r = getattr(self, "_shuffle_round", 0)
        rng = random.Random(self._seed)
        buckets = [[] for _ in range(n)]
        for s in self._samples:
            buckets[rng.randrange(n)].append(s)
        for dst, bucket in enumerate(buckets):
            with open(os.path.join(
                    spool_dir, f"t{tok}_r{r}_shard_{idx}_to_{dst}.pkl"),
                    "wb") as f:
                pickle.dump(bucket, f)
        open(os.path.join(spool_dir, f"t{tok}_r{r}_done_{idx}"), "w").close()
        deadline = time.monotonic() + 300
        while any(not os.path.exists(
                os.path.join(spool_dir, f"t{tok}_r{r}_done_{i}"))
                for i in range(n)):
            if time.monotonic() > deadline:
                raise TimeoutError("global_shuffle: peers never spooled")
            time.sleep(0.05)
        merged = []
        for src in range(n):
            with open(os.path.join(
                    spool_dir, f"t{tok}_r{r}_shard_{src}_to_{idx}.pkl"),
                    "rb") as f:
                merged.extend(pickle.load(f))
        random.Random(self._seed + idx + 1).shuffle(merged)
        self._samples = merged
        self._seed += 1
        self._shuffle_round = r + 1
        # best-effort cleanup of the PREVIOUS round's files this process
        # owns (every peer has passed that barrier by now)
        if r > 0:
            for dst in range(n):
                try:
                    os.remove(os.path.join(
                        spool_dir, f"t{tok}_r{r-1}_shard_{idx}_to_{dst}.pkl"))
                except OSError:
                    pass

    def release_memory(self):
        self._samples = []

    def get_memory_data_size(self, fleet=None):
        return len(self._samples)

    def get_shuffle_data_size(self, fleet=None):
        return len(self._samples)

    def batch_iterator(self, slab=None, position=None):
        it = self._batches(iter(self._samples))
        return self._positioned(it, slab, position)
