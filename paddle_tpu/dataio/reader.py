"""DataLoader / PyReader / DataFeeder.

Capability parity with the reference's data-feeding stack
(/root/reference/python/paddle/fluid/reader.py:100 DataLoader,
:360 from_generator, :951 GeneratorLoader, :1224 PyReader;
data_feeder.py DataFeeder; C++ double buffering
operators/reader/buffered_reader.cc). TPU-first: the C++ blocking queue +
read-op machinery collapses into a host prefetch thread handing numpy
batches to the Executor, with an async jax.device_put overlapping H2D
against the previous step's compute (jax dispatch is async, so one batch of
lookahead achieves the reference's double buffering).
"""
import queue
import threading
import time

import numpy as np

from ..framework.core import Variable
from ..framework.dtype import np_dtype


class DataFeeder:
    """Converts a batch of samples to a feed dict
    (reference python/paddle/fluid/data_feeder.py)."""

    def __init__(self, feed_list, place=None, program=None):
        self.feed_vars = feed_list
        self.place = place

    def feed(self, iterable):
        batch = list(iterable)
        out = {}
        for i, var in enumerate(self.feed_vars):
            name = var.name if isinstance(var, Variable) else str(var)
            vals = [np.asarray(sample[i]) for sample in batch]
            arr = np.stack(vals)
            if isinstance(var, Variable) and var.shape is not None:
                want = tuple(s for s in var.shape)
                # fluid convention: sample may omit trailing dims of size 1
                if len(want) == arr.ndim + 1 and want[-1] == 1:
                    arr = arr[..., None]
                arr = arr.astype(np_dtype(var.dtype), copy=False)
            out[name] = arr
        return out


class _QueueIterator:
    _END = object()

    def __init__(self, gen_fn, capacity, prefetch_to_device):
        from ..observability.inputstall import StallTracker
        self.q = queue.Queue(maxsize=capacity)
        self.err = []
        self.prefetch = prefetch_to_device
        self._pending = None
        self._closed = threading.Event()
        # input-pipeline stall profiler: producer/consumer wait
        # histograms + occupancy gauge + data_stall flight events
        self._tracker = StallTracker("dataloader", capacity)
        self.thread = threading.Thread(target=self._fill, args=(gen_fn,),
                                       daemon=True)
        self.thread.start()

    def _fill(self, gen_fn):
        from .decorator import put_until_closed
        try:
            for item in gen_fn():
                if not put_until_closed(self.q, item, self._closed,
                                        on_wait=self._tracker.producer_wait):
                    return
        except BaseException as e:
            self.err.append(e)
        finally:
            put_until_closed(self.q, self._END, self._closed)

    def close(self):
        """Stop the producer and drop queued batches (early-exit path).
        Joins the producer thread with a bounded timeout so early-exiting
        loops (and pytest teardown) don't accumulate live threads — the
        drain above guarantees its timeout-put unblocks within a tick."""
        self._closed.set()
        try:
            while True:
                self.q.get_nowait()
        except queue.Empty:
            pass
        self._pending = None
        t = getattr(self, "thread", None)
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            try:
                t.join(timeout=1.0)
            except RuntimeError:
                pass  # interpreter shutdown

    __del__ = close

    def _device_put(self, feed):
        import jax
        return {k: jax.device_put(v) for k, v in feed.items()}

    def _take(self):
        """Next raw item; terminal state is sticky."""
        if self._closed.is_set():
            return self._END
        self._tracker.sample_occupancy(self.q.qsize())
        try:
            item = self.q.get_nowait()
        except queue.Empty:
            # consumer blocked on an empty queue: the producer is
            # behind — the stall profiler's consumer-wait signal
            t0 = time.perf_counter()
            item = self.q.get()
            self._tracker.consumer_wait(time.perf_counter() - t0)
        if item is self._END:
            self.q.put(self._END)  # stay terminal for any further call
            return self._END
        return self._device_put(item) if self.prefetch else item

    def __iter__(self):
        return self

    def __next__(self):
        # one batch of lookahead already on device = double buffering
        if self._pending is None:
            self._pending = self._take()
        out = self._pending
        if out is self._END:
            if self.err:
                raise self.err[0]
            raise StopIteration
        self._pending = self._take()
        return out


class DataLoader:
    """fluid.io.DataLoader.from_generator parity."""

    def __init__(self, feed_list, capacity=8, use_double_buffer=True,
                 iterable=True, return_list=False):
        self.feed_list = feed_list or []
        self.capacity = capacity
        self.use_double_buffer = use_double_buffer
        self.iterable = iterable
        self.return_list = return_list
        self._gen = None
        self._it = None       # last _QueueIterator, for cleanup
        self._started = None  # non-iterable (start/reset) mode

    @staticmethod
    def from_generator(feed_list=None, capacity=8, use_double_buffer=True,
                       iterable=True, return_list=False,
                       use_multiprocess=False, drop_last=True):
        return DataLoader(feed_list, capacity, use_double_buffer, iterable,
                          return_list)

    # ---- generator flavors (reference reader.py:430-520) ----
    def set_sample_generator(self, generator, batch_size, drop_last=True,
                             places=None):
        from .decorator import batch as batch_dec
        reader = batch_dec(generator, batch_size, drop_last=drop_last)
        return self.set_sample_list_generator(reader, places)

    def set_sample_list_generator(self, generator, places=None):
        feeder = DataFeeder(self.feed_list)

        def gen():
            for samples in generator():
                yield feeder.feed(samples)
        self._gen = gen
        return self

    def set_batch_generator(self, generator, places=None):
        names = [v.name if isinstance(v, Variable) else str(v)
                 for v in self.feed_list]

        def gen():
            for b in generator():
                if isinstance(b, dict):
                    yield b
                else:
                    arrs = b if isinstance(b, (list, tuple)) else [b]
                    yield {n: np.asarray(a) for n, a in zip(names, arrs)}
        self._gen = gen
        return self

    def __call__(self):
        return self.__iter__()

    def __iter__(self):
        assert self._gen is not None, \
            "call set_sample_generator / set_sample_list_generator / " \
            "set_batch_generator first"
        if self._it is not None:
            self._it.close()  # release a previous (possibly early-exited)
        self._it = _QueueIterator(self._gen, self.capacity,
                                  self.use_double_buffer)
        if not self.return_list:
            return self._it
        names = [v.name if isinstance(v, Variable) else str(v)
                 for v in self.feed_list]
        it = self._it
        return ([d[n] for n in names] for d in it)

    # non-iterable (start/reset) mode parity
    def start(self):
        self._started = iter(self)

    def reset(self):
        if self._it is not None:
            self._it.close()
            self._it = None
        self._started = None

    def next(self):
        if self._started is None:
            raise RuntimeError(
                "DataLoader is not started — call loader.start() before "
                "next(), or iterate it directly")
        return next(self._started)


class PyReader(DataLoader):
    """Legacy alias (reference reader.py:1224)."""

    def __init__(self, feed_list=None, capacity=8, use_double_buffer=True,
                 iterable=True, return_list=False):
        super().__init__(feed_list, capacity, use_double_buffer, iterable,
                         return_list)

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        return self.set_sample_generator(sample_generator, batch_size,
                                         drop_last, places)

    def decorate_sample_list_generator(self, reader, places=None):
        return self.set_sample_list_generator(reader, places)

    def decorate_batch_generator(self, reader, places=None):
        return self.set_batch_generator(reader, places)
