"""Reader decorators (reference: python/paddle/reader/decorator.py —
batch, shuffle, buffered, cache, chain, compose, map_readers, xmap_readers,
firstn). A "reader" is a zero-arg callable returning an iterator of samples.
"""
import itertools
import queue
import random
import threading
import time


def put_until_closed(q, item, closed, tick=0.05, on_wait=None):
    """Blocking queue put that gives up once `closed` is set — the
    closeable timeout-put shared by buffered() and reader._QueueIterator
    so an abandoned consumer never strands a producer thread mid-put.
    Returns True when the item was enqueued. ``on_wait(seconds)``, if
    given, reports the time spent BLOCKED on a full queue (the stall
    profiler's producer-wait signal); the non-blocking fast path never
    calls it."""
    if closed.is_set():
        return False
    try:
        q.put_nowait(item)
        return True
    except queue.Full:
        pass
    t0 = time.perf_counter() if on_wait is not None else 0.0
    try:
        while not closed.is_set():
            try:
                q.put(item, timeout=tick)
                return True
            except queue.Full:
                continue
        return False
    finally:
        if on_wait is not None:
            on_wait(time.perf_counter() - t0)


def batch(reader, batch_size, drop_last=False):
    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batch_reader


def shuffle(reader, buf_size, seed=None):
    def shuffled_reader():
        rng = random.Random(seed)
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        rng.shuffle(buf)
        yield from buf
    return shuffled_reader


def buffered(reader, size):
    """Background-thread prefetch of up to `size` samples (reference
    decorator.py buffered — the host-side half of double buffering).

    The producer uses a closeable timeout-put: when the consumer
    abandons the generator early (break / GeneratorExit), the close
    event is set, the producer drains out of its blocked put within one
    timeout tick and exits — no daemon thread leaks per abandoned
    reader, and the source reader's own generator is closed too.

    Both sides feed the input-pipeline stall profiler
    (observability/inputstall): producer/consumer wait histograms when
    a put/get actually blocks, a queue-occupancy gauge, and a
    ``data_stall`` flight event when consumer waits dominate a window."""
    end = object()

    def buffered_reader():
        from ..observability.inputstall import StallTracker
        q = queue.Queue(maxsize=size)
        err = []
        closed = threading.Event()
        tracker = StallTracker("buffered", size)

        def fill():
            from ..resilience import maybe_fail
            it = reader()
            try:
                for sample in it:
                    # chaos point for the dataset-producer stage: a
                    # fault here propagates through `err` into the
                    # consuming training loop like a real parse crash
                    maybe_fail("dataio.producer")
                    if not put_until_closed(q, sample, closed,
                                            on_wait=tracker.producer_wait):
                        return
            except BaseException as e:  # propagate into the consumer
                err.append(e)
            finally:
                close_fn = getattr(it, "close", None)
                if close_fn is not None:
                    try:
                        close_fn()
                    except BaseException as e:
                        # a raising cleanup must not swallow the end
                        # sentinel (the consumer would block forever)
                        err.append(e)
                put_until_closed(q, end, closed)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        try:
            while True:
                tracker.sample_occupancy(q.qsize())
                try:
                    s = q.get_nowait()
                except queue.Empty:
                    # the consumer is about to block: the producer is
                    # behind — this wait IS the input-pipeline stall
                    t0 = time.perf_counter()
                    s = q.get()
                    tracker.consumer_wait(time.perf_counter() - t0)
                if s is end:
                    if err:
                        raise err[0]
                    return
                yield s
        finally:
            closed.set()
            try:  # unblock a producer mid-put; drop whatever it queued
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=1.0)
    return buffered_reader


def cache(reader):
    memo = []
    done = []

    def cached_reader():
        if done:
            yield from memo
            return
        for s in reader():
            memo.append(s)
            yield s
        done.append(True)
    return cached_reader


def chain(*readers):
    def chained_reader():
        for r in readers:
            yield from r()
    return chained_reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, check_alignment=True):
    _end = object()

    def composed_reader():
        for outputs in itertools.zip_longest(*[r() for r in readers],
                                             fillvalue=_end):
            if any(o is _end for o in outputs):
                if check_alignment:
                    raise ComposeNotAligned(
                        "composed readers have different lengths")
                return
            out = []
            for o in outputs:
                out.extend(o if isinstance(o, tuple) else (o,))
            yield tuple(out)
    return composed_reader


def map_readers(func, *readers):
    def mapped_reader():
        for args in zip(*[r() for r in readers]):
            yield func(*args)
    return mapped_reader


def xmap_readers(mapper, reader, process_num, buffer_size,
                 order=False):
    """Thread-pool sample mapper (reference decorator.py xmap_readers)."""
    end = object()

    def xreader():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)
        errors = []

        def feed():
            try:
                for i, s in enumerate(reader()):
                    in_q.put((i, s))
            except BaseException as e:
                errors.append(e)
            finally:
                for _ in range(process_num):
                    in_q.put(end)

        def work():
            try:
                while True:
                    item = in_q.get()
                    if item is end:
                        return
                    i, s = item
                    out_q.put((i, mapper(s)))
            except BaseException as e:
                errors.append(e)
            finally:
                out_q.put(end)

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        pending = {}
        next_i = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if not order:
                yield item[1]
                continue
            pending[item[0]] = item[1]
            while next_i in pending:
                yield pending.pop(next_i)
                next_i += 1
        if errors:
            raise errors[0]
        if order:
            for i in sorted(pending):
                yield pending[i]
    return xreader


def firstn(reader, n):
    def firstn_reader():
        yield from itertools.islice(reader(), n)
    return firstn_reader
