"""DataFeedDesc (reference python/paddle/fluid/data_feed_desc.py:21):
describes the on-disk slot format for the file-list Dataset /
train_from_dataset path. The reference wraps a DataFeedDesc protobuf
parsed from a prototxt file; here the descriptor is a plain config
parsed from the same prototxt-style text (name/type/is_dense/is_used
per slot + batch_size), consumable by DatasetFactory datasets and the
native C++ datafeed engine's slot schema."""
import re


class DataFeedDesc:
    def __init__(self, proto_file):
        self._batch_size = 32
        self._slots = []        # [{name, type, is_dense, is_used}]
        with open(proto_file) as f:
            text = f.read()
        self._parse(text)

    def _parse(self, text):
        m = re.search(r"batch_size\s*:\s*(\d+)", text)
        if m:
            self._batch_size = int(m.group(1))
        for block in re.finditer(r"slots?\s*\{([^}]*)\}", text):
            body = block.group(1)

            def field(key, default=None):
                fm = re.search(rf"{key}\s*:\s*\"?([\w.]+)\"?", body)
                return fm.group(1) if fm else default

            self._slots.append({
                "name": field("name"),
                "type": field("type", "uint64"),
                "is_dense": field("is_dense", "false") == "true",
                "is_used": field("is_used", "false") == "true",
            })

    # ---- reference data_feed_desc.py API ----
    def set_batch_size(self, batch_size):
        self._batch_size = int(batch_size)

    def _check_known(self, names):
        known = {s["name"] for s in self._slots}
        unknown = [n for n in names if n not in known]
        if unknown:
            # reference data_feed_desc.py indexes a name->slot dict and
            # raises on unknown names; a typo must not be a silent no-op
            raise ValueError(
                f"unknown slot name(s) {unknown}; declared slots: "
                f"{sorted(known)}")

    def set_dense_slots(self, dense_slots_name):
        names = set(dense_slots_name)
        self._check_known(names)
        for s in self._slots:
            if s["name"] in names:
                s["is_dense"] = True

    def set_use_slots(self, use_slots_name):
        names = set(use_slots_name)
        self._check_known(names)
        for s in self._slots:
            if s["name"] in names:
                s["is_used"] = True

    def desc(self):
        lines = [f"batch_size: {self._batch_size}"]
        for s in self._slots:
            lines.append("slots {")
            lines.append(f"  name: \"{s['name']}\"")
            lines.append(f"  type: \"{s['type']}\"")
            lines.append(f"  is_dense: {str(s['is_dense']).lower()}")
            lines.append(f"  is_used: {str(s['is_used']).lower()}")
            lines.append("}")
        return "\n".join(lines) + "\n"
