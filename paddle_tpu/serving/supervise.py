"""Supervised serving loops: crash/hang detection and restart.

The MicroBatcher and DecodeBatcher each run ONE loop thread; before this
module, a crashed loop silently stopped serving (clients waited out
their own timeouts against a healthy-looking socket) and a hung loop
was indistinguishable from a slow one. The :class:`LoopSupervisor`
closes that gap with the classic supervision-tree contract:

- every loop stamps ``batcher.heartbeat`` once per iteration; the
  supervisor polls it. A dead thread (crash) or a stale heartbeat
  beyond the ``FLAGS_serving_loop_watchdog_s``-derived threshold (hang
  somewhere the per-execute watchdog doesn't reach, e.g. a wedged
  prefill compile) triggers a restart.
- restart = ``batcher.restart()``: the old thread is deposed (epoch
  bump — it can never touch shared state again), every in-flight
  request fails with a TYPED error, and a fresh loop thread starts.
  Restarts back off exponentially (capped) so a crash-looping engine
  can't melt the host.
- repeated restarts (or sustained engine-failure streaks inside a live
  loop) feed a ``resilience.CircuitBreaker``; when it opens the server
  is notified (``on_degraded``) and enters the DEGRADED state —
  generation admission sheds while ping/health/stats keep answering.
  A sustained healthy period closes the breaker again
  (``on_recovered``).

Restart counts and per-loop liveness are exported through
``server.stats()`` / the ``health`` wire op.
"""
import threading
import time

from ..observability.recorder import flight_recorder as _flightrec
from ..resilience import CircuitBreaker


class LoopSupervisor:
    """Watches named batcher loops (anything with ``heartbeat``,
    ``alive()``, ``restart(reason)`` and ``consecutive_failures``) and
    restarts the dead or hung ones. Single daemon thread; poll cadence
    derives from the watchdog budget."""

    def __init__(self, stats=None, watchdog_s=None, poll_s=None,
                 restart_threshold=3, reset_secs=5.0,
                 restart_backoff=0.05, max_backoff=2.0,
                 on_degraded=None, on_recovered=None):
        if watchdog_s is None:
            from ..flags import flag
            watchdog_s = flag("serving_loop_watchdog_s")
        self.watchdog_s = float(watchdog_s)
        # a loop whose heartbeat is older than this is hung. 2x the
        # per-execute watchdog: a watchdogged execute stalls the
        # heartbeat for at most ~watchdog_s before the loop reclaims it
        self.hung_after_s = 2.0 * self.watchdog_s
        if poll_s is None:
            poll_s = (max(0.02, min(0.5, self.watchdog_s / 10.0))
                      if self.watchdog_s > 0 else 0.1)
        self.poll_s = float(poll_s)
        self.restart_backoff = float(restart_backoff)
        self.max_backoff = float(max_backoff)
        self.reset_secs = float(reset_secs)
        self.stats = stats
        self.on_degraded = on_degraded
        self.on_recovered = on_recovered
        self.breaker = CircuitBreaker(endpoint="serving-loops",
                                      failure_threshold=restart_threshold,
                                      reset_timeout=reset_secs)
        self._loops = {}       # name -> bookkeeping dict
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._degraded = False
        self._last_failure = 0.0

    # -- registration / lifecycle -----------------------------------------
    def add(self, name, batcher):
        with self._lock:
            self._loops[name] = {
                "batcher": batcher, "restarts": 0,
                "backoff": self.restart_backoff, "next_restart_at": 0.0,
                "last_restart": 0.0,
            }
        return self

    def start(self):
        if not self._loops:
            return self
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serving-supervisor")
        self._thread.start()
        return self

    def stop(self, timeout=2):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    @property
    def degraded(self):
        return self._degraded

    def restarts(self):
        with self._lock:
            return sum(ent["restarts"] for ent in self._loops.values())

    def snapshot(self):
        """Per-loop liveness for the ``health`` op."""
        now = time.monotonic()
        out = {}
        with self._lock:
            loops = dict(self._loops)
        for name, ent in loops.items():
            b = ent["batcher"]
            out[name] = {
                "alive": b.alive(),
                "heartbeat_age_s": round(now - b.heartbeat, 3),
                "restarts": ent["restarts"],
                "consecutive_failures": b.consecutive_failures,
            }
        return out

    # -- supervision loop --------------------------------------------------
    def _run(self):
        while not self._stop.wait(self.poll_s):
            try:
                self._tick(time.monotonic())
            except Exception:  # noqa: BLE001 — the supervisor never dies
                pass

    def _tick(self, now):
        with self._lock:
            loops = list(self._loops.items())
        all_healthy = True
        for name, ent in loops:
            b = ent["batcher"]
            dead = not b.alive()
            hung = (not dead and self.watchdog_s > 0
                    and now - b.heartbeat > self.hung_after_s)
            streak = (b.consecutive_failures
                      >= self.breaker.failure_threshold)
            if dead or hung:
                all_healthy = False
                if now >= ent["next_restart_at"]:
                    self._restart(name, ent, now,
                                  "loop thread died" if dead else
                                  f"heartbeat stale "
                                  f"{now - b.heartbeat:.1f}s")
            elif streak:
                # the loop is alive but the engine fails every batch:
                # count it against the breaker without a restart (the
                # loop itself is fine; the chip path is not)
                all_healthy = False
                b.consecutive_failures = 0
                self._record_failure(now)
            elif b.consecutive_failures:
                all_healthy = False
            elif now - ent["last_restart"] > self.reset_secs:
                ent["backoff"] = self.restart_backoff
        if all_healthy and self._degraded \
                and now - self._last_failure > self.reset_secs:
            self.breaker.record_success()
            self._degraded = False
            _flightrec().record("recovered")
            if self.on_recovered:
                self.on_recovered()

    def _restart(self, name, ent, now, reason):
        _flightrec().record("loop_restart", loop=name, reason=reason,
                            restarts=ent["restarts"] + 1)
        ent["batcher"].restart(reason=reason)
        ent["restarts"] += 1
        ent["last_restart"] = now
        ent["next_restart_at"] = now + ent["backoff"]
        ent["backoff"] = min(ent["backoff"] * 2.0, self.max_backoff)
        if self.stats:
            self.stats.bump("loop_restarts")
        self._record_failure(now)

    def _record_failure(self, now):
        self._last_failure = now
        self.breaker.record_failure()
        if self.breaker.state != "closed" and not self._degraded:
            self._degraded = True
            _flightrec().record("degraded",
                                breaker=self.breaker.state)
            if self.on_degraded:
                self.on_degraded()
