"""Thread-based prediction service over the PS wire framing.

``InferenceServer`` turns a saved inference model into a multi-client
service: connection threads speak the length-prefixed, HMAC-optional
frame protocol from ``distributed/wire.py`` (so ``WireTruncationError``
and the PR-1 retry semantics apply unchanged), admission happens on the
connection thread (backpressure is refused in O(1), never queued), and
one MicroBatcher thread feeds the chip padded batches.

Wire protocol (all values inside the typed wire universe):

    request  {"op": "infer", "feed": {name: ndarray},
              "deadline_ms": float|None}
    reply    {"ok": True, "fetch": (ndarray, ...), "batched": int}
           | {"ok": False, "etype": "DeadlineExceeded"|"Overloaded"
                                    |"BadRequest"|"Internal",
              "error": str}
    request  {"op": "stats"}   -> {"ok": True, "stats": {...}}
    request  {"op": "ping"}    -> {"ok": True}

Deadline semantics: ``deadline_ms`` is a budget measured from ADMISSION
at the server (transit time is the client's problem; clocks never need
agreement). It is checked at admission, when the batch forms, and the
expiry reply carries how long the request actually waited. A request
that expires mid-execution still completes and returns its result — the
chip's work is never thrown away.
"""
import socket
import threading

import numpy as np

from .batching import (DeadlineExceededError, DecodeBatcher,
                       GenerationRequest, MicroBatcher, Request,
                       RequestQueue, ServerOverloadedError)
from .engine import GenerationEngine, ServingEngine
from .metrics import ServingStats
from ..distributed.wire import (WireError, default_key, recv_frame,
                                send_frame)


class ServingConfig:
    """Knobs, defaulting from ``FLAGS_serving_*`` (env-overridable like
    every other flag): batching shape, queue depth, deadlines, cache
    caps, load-shed breaker tuning."""

    _FLAG_FIELDS = {
        "max_batch_size": "serving_max_batch_size",
        "batch_timeout_ms": "serving_batch_timeout_ms",
        "queue_depth": "serving_queue_depth",
        "default_deadline_ms": "serving_default_deadline_ms",
        "cache_entries": "serving_cache_entries",
        "cache_bytes": "serving_cache_bytes",
        "shed_failures": "serving_shed_failures",
        "shed_reset_secs": "serving_shed_reset_secs",
    }

    def __init__(self, **overrides):
        from ..flags import flag
        for field, fname in self._FLAG_FIELDS.items():
            setattr(self, field, overrides.pop(field, None)
                    if field in overrides else flag(fname))
            if getattr(self, field) is None:
                setattr(self, field, flag(fname))
        if overrides:
            raise TypeError(f"unknown ServingConfig fields: "
                            f"{sorted(overrides)}")


class InferenceServer:
    """Multi-client serving front-end. In-process use:

        server = InferenceServer(model_dir).start()
        out = server.infer({"x": batch})          # or submit() for async

    Network use: ``start()`` also binds a socket (default loopback,
    OS-assigned port) and ``Client(server.endpoint)`` speaks the wire
    protocol. Authentication mirrors the PS transport: set
    ``PADDLE_PS_AUTH_KEY`` on both ends (required for non-loopback binds
    unless ``allow_insecure=True``)."""

    def __init__(self, model_dir=None, *, engine=None, generator=None,
                 decode_slots=None, config=None,
                 host="127.0.0.1", port=0, auth_key=None,
                 allow_insecure=False, **config_overrides):
        self.config = config or ServingConfig(**config_overrides)
        self.stats_sink = ServingStats()
        if engine is None and (model_dir is not None
                               or generator is None):
            from .cache import ExecutableCache
            cache = ExecutableCache(max_entries=self.config.cache_entries,
                                    max_bytes=self.config.cache_bytes)
            engine = ServingEngine(model_dir, cache=cache,
                                   stats=self.stats_sink)
        elif engine is not None:
            engine.stats = engine.stats or self.stats_sink
        self.engine = engine          # None for a generation-only server
        self.queue = self.batcher = None
        if engine is not None:
            self.queue = RequestQueue(max_depth=self.config.queue_depth,
                                      stats=self.stats_sink)
            self.batcher = MicroBatcher(
                self.queue, self.engine.execute,
                max_batch_size=self.config.max_batch_size,
                batch_timeout_ms=self.config.batch_timeout_ms,
                stats=self.stats_sink)
        # generation endpoint: a models.generation.GPTGenerator turns
        # the server into a token service — requests join a fixed bank
        # of decode slots (continuous batching, slot reuse on finish)
        self.gen_engine = self.gen_queue = self.decode_batcher = None
        if generator is not None:
            self.gen_engine = GenerationEngine(generator,
                                               slots=decode_slots,
                                               stats=self.stats_sink)
            self.gen_queue = RequestQueue(
                max_depth=self.config.queue_depth, stats=self.stats_sink)
            self.decode_batcher = DecodeBatcher(
                self.gen_queue, self.gen_engine, stats=self.stats_sink)
        self.host = host
        self.port = int(port)
        self._key = auth_key if auth_key is not None else default_key()
        self._allow_insecure = allow_insecure
        self._sock = None
        self._stop = threading.Event()
        self._threads = []
        self._conns = set()
        self._conns_lock = threading.Lock()

    # -- lifecycle --------------------------------------------------------
    @property
    def endpoint(self):
        return f"{self.host}:{self.port}"

    def start(self, serve_network=True, warmup_batch_sizes=None,
              warmup_signature_file=None):
        """Start the batcher (always) and the socket front-end (unless
        ``serve_network=False`` for purely in-process serving). Optional
        warmup precompiles before the first byte of traffic."""
        if (warmup_batch_sizes or warmup_signature_file) \
                and self.engine is not None:
            self.engine.warmup(batch_sizes=warmup_batch_sizes or (),
                               signature_file=warmup_signature_file)
        if self.batcher is not None:
            self.batcher.start()
        if self.decode_batcher is not None:
            self.decode_batcher.start()
        if serve_network:
            loopback = (self.host.startswith("127.")
                        or self.host in ("localhost", "::1"))
            if not loopback and self._key is None \
                    and not self._allow_insecure:
                raise PermissionError(
                    f"refusing to bind the inference server on "
                    f"non-loopback {self.host}:{self.port} without "
                    f"authentication — set PADDLE_PS_AUTH_KEY (both "
                    f"ends) or pass allow_insecure=True")
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind((self.host, self.port))
            self.port = self._sock.getsockname()[1]
            self._sock.listen(128)
            t = threading.Thread(target=self._accept_loop, daemon=True,
                                 name="serving-accept")
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        # close accepted connections too: a keep-alive client blocked in
        # recv_frame on the other end holds its handler thread forever
        # otherwise (the _stop flag is only re-checked between frames)
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self.queue is not None:
            self.queue.close()
        if self.batcher is not None:
            self.batcher.stop()
        if self.gen_queue is not None:
            self.gen_queue.close()
        if self.decode_batcher is not None:
            self.decode_batcher.stop()
        for t in self._threads:
            t.join(timeout=2)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- in-process client path -------------------------------------------
    def submit(self, feeds, deadline_ms=None):
        """Admit a request (raises ServerOverloadedError /
        DeadlineExceededError at the door); returns the Request — call
        ``.wait()`` for the fetch list."""
        if self.queue is None:
            raise ValueError("no inference model loaded — this server "
                             "only serves 'generate'")
        if deadline_ms is None and self.config.default_deadline_ms > 0:
            deadline_ms = self.config.default_deadline_ms
        return self.queue.put(Request(feeds, deadline_ms=deadline_ms))

    def infer(self, feeds, deadline_ms=None, timeout=None):
        return self.submit(feeds, deadline_ms=deadline_ms).wait(
            timeout=timeout)

    def submit_generate(self, tokens, max_new_tokens=32, temperature=0.0,
                        top_k=0, eos_id=None, deadline_ms=None):
        """Admit a generation request into the decode bank (admission
        control applies: queue depth, breaker, deadline). Returns the
        GenerationRequest — ``.wait()`` yields ``[np int32 tokens]``.

        ``FLAGS_serving_default_deadline_ms`` is NOT inherited here: it
        is a per-infer-batch budget, and a whole generation (prefill +
        up to max_new_tokens decode steps) lives on a different time
        scale — generation deadlines are per-request opt-in."""
        if self.gen_queue is None:
            raise ValueError("no generator loaded — pass generator= to "
                             "InferenceServer to serve 'generate'")
        return self.gen_queue.put(GenerationRequest(
            tokens, max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k, eos_id=eos_id,
            deadline_ms=deadline_ms))

    def generate(self, tokens, max_new_tokens=32, temperature=0.0,
                 top_k=0, eos_id=None, deadline_ms=None, timeout=None):
        """Generate new tokens for one prompt; returns a 1-D np.int32
        array (EOS excluded)."""
        req = self.submit_generate(tokens, max_new_tokens=max_new_tokens,
                                   temperature=temperature, top_k=top_k,
                                   eos_id=eos_id, deadline_ms=deadline_ms)
        return req.wait(timeout=timeout)[0]

    def stats(self):
        """One snapshot across every stage: admission counters, stage
        latency histograms, batch occupancy, executable-cache hit/miss/
        evict, queue depth."""
        extra = {}
        if self.queue is not None:
            extra["queue_depth"] = len(self.queue)
            extra["breaker_state"] = self.queue.breaker.state
        if self.engine is not None:
            for k, v in self.engine.cache.stats().items():
                extra[f"cache_{k}"] = v
        if self.gen_queue is not None:
            extra["decode_queue_depth"] = len(self.gen_queue)
            extra["decode_free_slots"] = len(self.decode_batcher._free)
            for k, v in self.gen_engine.gen.cache.stats().items():
                extra[f"decode_cache_{k}"] = v
        return self.stats_sink.snapshot(extra=extra)

    def record_signatures(self, path=None):
        if self.engine is None:
            raise ValueError("no inference model loaded — this server "
                             "only serves 'generate'")
        return self.engine.record_signatures(path)

    # -- network front-end ------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.2)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True, name="serving-conn")
            t.start()
            # prune finished connection threads so a long-lived server
            # doesn't accumulate one dead handle per past client
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _serve_conn(self, conn):
        with self._conns_lock:
            self._conns.add(conn)
        try:
            while not self._stop.is_set():
                try:
                    msg = recv_frame(conn, self._key)
                except (ConnectionError, EOFError, OSError):
                    return
                except WireError:
                    # unauthenticated/malformed frame: drop the
                    # connection (same policy as the PS server)
                    return
                reply = self._handle(msg)
                try:
                    send_frame(conn, reply, self._key)
                except (ConnectionError, OSError):
                    return
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, msg):
        if not isinstance(msg, dict) or "op" not in msg:
            return {"ok": False, "etype": "BadRequest",
                    "error": "expected a dict with an 'op' field"}
        op = msg["op"]
        if op == "ping":
            return {"ok": True}
        if op == "stats":
            return {"ok": True, "stats": self.stats()}
        if op == "generate":
            return self._handle_generate(msg)
        if op != "infer":
            return {"ok": False, "etype": "BadRequest",
                    "error": f"unknown op {op!r}"}
        if self.engine is None:
            return {"ok": False, "etype": "BadRequest",
                    "error": "no inference model loaded — this server "
                             "only serves 'generate'"}
        try:
            feed = msg.get("feed")
            if not isinstance(feed, dict) or not feed:
                raise ValueError("'feed' must be a non-empty dict of "
                                 "arrays")
            missing = [n for n in self.engine.feed_names if n not in feed]
            if missing:
                raise ValueError(f"missing feeds: {missing}")
            feed = {n: np.asarray(feed[n])
                    for n in self.engine.feed_names}
            req = self.submit(feed, deadline_ms=msg.get("deadline_ms"))
        except ServerOverloadedError as e:
            return {"ok": False, "etype": "Overloaded", "error": str(e)}
        except DeadlineExceededError as e:
            return {"ok": False, "etype": "DeadlineExceeded",
                    "error": str(e)}
        except (ValueError, TypeError) as e:
            return {"ok": False, "etype": "BadRequest", "error": str(e)}
        # bound the wait: the deadline (if any) plus compile/execute
        # headroom, else a hard server-side cap
        budget = msg.get("deadline_ms")
        wait_s = (budget / 1e3 + 60.0) if budget else 300.0
        try:
            outs = req.wait(timeout=wait_s)
            return {"ok": True, "fetch": tuple(outs),
                    "batched": int(req.rows)}
        except DeadlineExceededError as e:
            return {"ok": False, "etype": "DeadlineExceeded",
                    "error": str(e)}
        except ServerOverloadedError as e:
            return {"ok": False, "etype": "Overloaded", "error": str(e)}
        except Exception as e:  # noqa: BLE001 — surface, don't die
            return {"ok": False, "etype": "Internal",
                    "error": f"{type(e).__name__}: {e}"}

    def _handle_generate(self, msg):
        if self.gen_queue is None:
            return {"ok": False, "etype": "BadRequest",
                    "error": "this server has no generator — pass "
                             "generator= to InferenceServer"}
        try:
            tokens = msg.get("tokens")
            if tokens is None:
                raise ValueError("'tokens' (1-D int prompt) is required")
            req = self.submit_generate(
                np.asarray(tokens),
                max_new_tokens=int(msg.get("max_new_tokens", 32)),
                temperature=float(msg.get("temperature", 0.0)),
                top_k=int(msg.get("top_k", 0)),
                eos_id=msg.get("eos_id"),
                deadline_ms=msg.get("deadline_ms"))
        except ServerOverloadedError as e:
            return {"ok": False, "etype": "Overloaded", "error": str(e)}
        except DeadlineExceededError as e:
            return {"ok": False, "etype": "DeadlineExceeded",
                    "error": str(e)}
        except (ValueError, TypeError) as e:
            return {"ok": False, "etype": "BadRequest", "error": str(e)}
        # generation budget: prompt prefill + one step per token, plus
        # compile headroom on the first request of a shape
        budget = msg.get("deadline_ms")
        wait_s = (budget / 1e3 + 120.0) if budget else 600.0
        try:
            out, = req.wait(timeout=wait_s)
            return {"ok": True, "tokens": np.asarray(out, np.int32),
                    "generated": int(np.asarray(out).size)}
        except TimeoutError:
            # abandon the request properly: marking it done lets the
            # DecodeBatcher reclaim its slot instead of decoding tokens
            # nobody will read, and the client gets a typed, retryable
            # error instead of a generic Internal
            err = DeadlineExceededError(
                f"server-side wait budget of {wait_s:.0f}s exceeded; "
                f"the request was abandoned")
            req.set_error(err)
            return {"ok": False, "etype": "DeadlineExceeded",
                    "error": str(err)}
        except DeadlineExceededError as e:
            return {"ok": False, "etype": "DeadlineExceeded",
                    "error": str(e)}
        except ServerOverloadedError as e:
            return {"ok": False, "etype": "Overloaded", "error": str(e)}
        except Exception as e:  # noqa: BLE001 — surface, don't die
            return {"ok": False, "etype": "Internal",
                    "error": f"{type(e).__name__}: {e}"}


_ETYPES = {"DeadlineExceeded": DeadlineExceededError,
           "Overloaded": ServerOverloadedError}


class Client:
    """Wire-protocol client. One socket, serial request/reply (run one
    Client per concurrent caller — sockets are cheap; the server batches
    across them). Transport failures surface as ConnectionError
    subclasses (``WireTruncationError`` included), so callers can wrap
    ``infer`` in ``resilience.retry_call`` — inference is idempotent."""

    def __init__(self, endpoint, auth_key=None, timeout=None,
                 connect_retries=20):
        host, port = endpoint.rsplit(":", 1)
        self.endpoint = endpoint
        self._addr = (host, int(port))
        self._key = auth_key if auth_key is not None else default_key()
        self._timeout = timeout
        self._connect_retries = connect_retries
        self._sock = None

    def _ensure(self):
        if self._sock is None:
            from ..resilience import retry_call
            self._sock = retry_call(
                lambda: socket.create_connection(
                    self._addr, timeout=self._timeout),
                deadline=10.0, retries=self._connect_retries,
                what="serving connect", endpoint=self.endpoint)
        return self._sock

    def _call(self, msg):
        sock = self._ensure()
        try:
            send_frame(sock, msg, self._key, timeout=self._timeout)
            reply = recv_frame(sock, self._key, timeout=self._timeout)
        except (ConnectionError, OSError):
            self.close()
            raise
        if not isinstance(reply, dict):
            raise WireError(f"malformed serving reply: {type(reply)}")
        if reply.get("ok"):
            return reply
        etype = _ETYPES.get(reply.get("etype"), RuntimeError)
        raise etype(reply.get("error", "serving request failed"))

    def infer(self, feeds, deadline_ms=None):
        """Returns the fetch list (numpy arrays). Raises
        DeadlineExceededError / ServerOverloadedError mapped from the
        server's reply, ConnectionError on transport failure."""
        reply = self._call({"op": "infer", "feed": dict(feeds),
                            "deadline_ms": deadline_ms})
        return [np.asarray(a) for a in reply["fetch"]]

    def generate(self, tokens, max_new_tokens=32, temperature=0.0,
                 top_k=0, eos_id=None, deadline_ms=None):
        """Autoregressive generation for one prompt (1-D int tokens).
        Returns the NEW tokens as a 1-D np.int32 array (EOS excluded).
        Same error mapping as ``infer``; ``deadline_ms`` is token-level
        (checked between decode steps server-side)."""
        reply = self._call({
            "op": "generate",
            "tokens": np.asarray(tokens, dtype=np.int32).ravel(),
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "top_k": int(top_k),
            "eos_id": None if eos_id is None else int(eos_id),
            "deadline_ms": deadline_ms,
        })
        return np.asarray(reply["tokens"], dtype=np.int32)

    def stats(self):
        return self._call({"op": "stats"})["stats"]

    def ping(self):
        return bool(self._call({"op": "ping"}).get("ok"))

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
