"""Thread-based prediction service over the PS wire framing.

``InferenceServer`` turns a saved inference model into a multi-client
service: connection threads speak the length-prefixed, HMAC-optional
frame protocol from ``distributed/wire.py`` (so ``WireTruncationError``
and the PR-1 retry semantics apply unchanged), admission happens on the
connection thread (backpressure is refused in O(1), never queued), and
one MicroBatcher thread feeds the chip padded batches.

Wire protocol (all values inside the typed wire universe):

    request  {"op": "infer", "feed": {name: ndarray},
              "deadline_ms": float|None}
    reply    {"ok": True, "fetch": (ndarray, ...), "batched": int}
           | {"ok": False, "etype": "DeadlineExceeded"|"Overloaded"
                                    |"Shutdown"|"Cancelled"|"Watchdog"
                                    |"BadRequest"|"Internal",
              "error": str}
    request  {"op": "stats"}   -> {"ok": True, "stats": {...}}
    request  {"op": "metrics"} -> {"ok": True, "metrics": str}
                                  (Prometheus text exposition of the
                                   process metrics registry)
    request  {"op": "debug_dump", "write": bool} -> {"ok": True,
                                  "events": [...], "path": str|None}
                                  (flight-recorder snapshot / dump)
    request  {"op": "ping"}    -> {"ok": True}
    request  {"op": "health"}  -> {"ok": True, "health": {state, queue
                                   depths, loop liveness, weights_version,
                                   kvpool_occupancy (paged)}}
    request  {"op": "cancel", "rid": str} -> {"ok": True, "cancelled": bool}
    request  {"op": "prefill", "tokens": ...} -> {"ok": True, "kv": {...}}
                                  (disaggregated split, prefill half:
                                   the prompt's KV blocks serialized out
                                   of the paged pool, first_token and
                                   prompt_tokens riding inside)
    request  {"op": "generate", ..., "kv": {...}, "first_token": int}
                                  (decode half: stream migrated blocks
                                   into this replica's pool and decode
                                   from first_token — no prefill runs)
    request  {"op": "reload_weights", "path": str} -> {"ok": True,
                                  "weights_version": int,
                                  "swap_pause_ms": float}

Deadline semantics: ``deadline_ms`` is a budget measured from ADMISSION
at the server (transit time is the client's problem; clocks never need
agreement). It is checked at admission, when the batch forms, and the
expiry reply carries how long the request actually waited. A request
that expires mid-execution still completes and returns its result — the
chip's work is never thrown away.

Tracing: ``infer``/``generate`` requests may carry a ``"trace"`` dict
(``{"tid", "sid"}``, minted client-side at ``FLAGS_trace_sample_rate``)
next to the existing ``rid``; the server threads a child context through
admission -> queue -> pad/compile/execute (and prefill/decode in the
slot bank), recording spans into the profiler's unified span table so
``tools/timeline.py`` renders one Chrome/Perfetto trace per request.

Resilience layer: the server walks a lifecycle state machine (warming ->
serving -> draining -> stopped, plus degraded while the loop supervisor's
breaker is open), ``drain()`` is the graceful half of shutdown (stop
admission, let in-flight work finish, then stop), ``reload_weights()``
swaps a manifest-verified checkpoint in without dropping traffic, and
``infer``/``generate`` requests may carry a client ``rid`` — a hedged
pair (Dean & Barroso, "The Tail at Scale") dedups onto ONE in-flight
execution and the loser is cancelled by rid.
"""
import contextlib
import socket
import threading
import time
import uuid
from collections import OrderedDict, deque

import numpy as np

from .batching import (BadRequestError, DeadlineExceededError,
                       DecodeBatcher, GenerationRequest,
                       InternalServerError, MicroBatcher, Request,
                       RequestCancelledError, RequestQueue,
                       ServerOverloadedError, ServerShutdownError,
                       priority_rank, remaining_budget_ms)
from .brownout import BrownoutController
from .engine import GenerationEngine, ServingEngine
from .metrics import ServingStats, record_class_shed
from .supervise import LoopSupervisor
from ..distributed.wire import (WireError, default_key, recv_frame,
                                send_frame)
from ..observability import tracing as _trace
from ..observability.metrics import render_metrics
from ..observability.recorder import flight_recorder as _flightrec
from ..resilience import (WatchdogTimeout, default_retry_budget,
                          retry_call)


class ServingConfig:
    """Knobs, defaulting from ``FLAGS_serving_*`` (env-overridable like
    every other flag): batching shape, queue depth, deadlines, cache
    caps, load-shed breaker tuning."""

    _FLAG_FIELDS = {
        "max_batch_size": "serving_max_batch_size",
        "batch_timeout_ms": "serving_batch_timeout_ms",
        "queue_depth": "serving_queue_depth",
        "default_deadline_ms": "serving_default_deadline_ms",
        "cache_entries": "serving_cache_entries",
        "cache_bytes": "serving_cache_bytes",
        "shed_failures": "serving_shed_failures",
        "shed_reset_secs": "serving_shed_reset_secs",
        "loop_watchdog_s": "serving_loop_watchdog_s",
    }

    def __init__(self, **overrides):
        from ..flags import flag
        for field, fname in self._FLAG_FIELDS.items():
            setattr(self, field, overrides.pop(field, None)
                    if field in overrides else flag(fname))
            if getattr(self, field) is None:
                setattr(self, field, flag(fname))
        if overrides:
            raise TypeError(f"unknown ServingConfig fields: "
                            f"{sorted(overrides)}")


class InferenceServer:
    """Multi-client serving front-end. In-process use:

        server = InferenceServer(model_dir).start()
        out = server.infer({"x": batch})          # or submit() for async

    Network use: ``start()`` also binds a socket (default loopback,
    OS-assigned port) and ``Client(server.endpoint)`` speaks the wire
    protocol. Authentication mirrors the PS transport: set
    ``PADDLE_PS_AUTH_KEY`` on both ends (required for non-loopback binds
    unless ``allow_insecure=True``)."""

    def __init__(self, model_dir=None, *, engine=None, generator=None,
                 decode_slots=None, config=None,
                 host="127.0.0.1", port=0, auth_key=None,
                 allow_insecure=False, kv_paged=None,
                 kv_pool_name="serving", slo_rules=None,
                 **config_overrides):
        self.config = config or ServingConfig(**config_overrides)
        self.stats_sink = ServingStats()
        if engine is None and (model_dir is not None
                               or generator is None):
            from .cache import ExecutableCache
            cache = ExecutableCache(max_entries=self.config.cache_entries,
                                    max_bytes=self.config.cache_bytes)
            engine = ServingEngine(model_dir, cache=cache,
                                   stats=self.stats_sink)
        elif engine is not None:
            engine.stats = engine.stats or self.stats_sink
        self.engine = engine          # None for a generation-only server
        self.queue = self.batcher = None
        if engine is not None:
            self.queue = RequestQueue(max_depth=self.config.queue_depth,
                                      stats=self.stats_sink)
            self.batcher = MicroBatcher(
                self.queue, self.engine.execute,
                max_batch_size=self.config.max_batch_size,
                batch_timeout_ms=self.config.batch_timeout_ms,
                stats=self.stats_sink,
                watchdog_s=self.config.loop_watchdog_s)
        # generation endpoint: a models.generation.GPTGenerator turns
        # the server into a token service — requests join a fixed bank
        # of decode slots (continuous batching, slot reuse on finish)
        self.gen_engine = self.gen_queue = self.decode_batcher = None
        if generator is not None:
            self.gen_engine = GenerationEngine(generator,
                                               slots=decode_slots,
                                               stats=self.stats_sink,
                                               paged=kv_paged,
                                               pool_name=kv_pool_name)
            self.gen_queue = RequestQueue(
                max_depth=self.config.queue_depth, stats=self.stats_sink)
            self.decode_batcher = DecodeBatcher(
                self.gen_queue, self.gen_engine, stats=self.stats_sink,
                watchdog_s=self.config.loop_watchdog_s)
        # supervision: dead/hung loop threads are restarted with backoff;
        # repeated restarts open the breaker -> DEGRADED state (generate
        # sheds, ping/health/stats keep answering)
        self.supervisor = LoopSupervisor(
            stats=self.stats_sink,
            watchdog_s=self.config.loop_watchdog_s,
            on_degraded=lambda: self._set_state("degraded",
                                               only_from=("serving",)),
            on_recovered=lambda: self._set_state("serving",
                                                 only_from=("degraded",)))
        if self.batcher is not None:
            self.supervisor.add("microbatcher", self.batcher)
        if self.decode_batcher is not None:
            self.supervisor.add("decode", self.decode_batcher)
        # SLO guardrails: declarative rules (default: p99 inter-token
        # latency, queue-depth ratios, kvpool occupancy, optional MFU
        # floor) evaluated on a supervised loop; breach state rides
        # health() so the fleet Router penalizes a breached replica's
        # dispatch score. Built in start() (FLAGS_slo_monitor) so the
        # default rules bind the final queue/engine wiring.
        self._slo_rules = slo_rules
        self.slo_monitor = None
        # brownout ladder (FLAGS_serving_brownout): an SLO breach
        # degrades best_effort, then batch traffic (shed / capped
        # max_new_tokens / shrunken admission) BEFORE interactive; the
        # getter reads the live monitor so the ladder follows breaches
        # the moment start() wires the rules
        self.brownout = BrownoutController(
            lambda: (len(self.slo_monitor.breached())
                     if self.slo_monitor is not None else 0),
            scope=f"server-{id(self) & 0xffffff:x}")
        if self.decode_batcher is not None:
            # the ladder is also the speculative-decoding load knob:
            # the batcher shrinks degraded classes' draft depth per row
            self.decode_batcher.brownout = self.brownout
        self.host = host
        self.port = int(port)
        self._key = auth_key if auth_key is not None else default_key()
        self._allow_insecure = allow_insecure
        self._sock = None
        self._stop = threading.Event()
        self._threads = []
        self._conns = set()
        self._conns_lock = threading.Lock()
        self._started_at = time.monotonic()
        self._state_lock = threading.Lock()
        self._lifecycle = "created"
        self._weights_version = 1
        # request-id dedup (hedged pairs attach to ONE in-flight
        # execution); LRU-capped like the PS push-dedup table
        self._rids = OrderedDict()
        self._rids_lock = threading.Lock()
        self._rid_cap = 2048

    # -- lifecycle --------------------------------------------------------
    @property
    def endpoint(self):
        return f"{self.host}:{self.port}"

    @property
    def state(self):
        """Lifecycle state: created -> warming -> serving -> draining ->
        stopped, with serving <-> degraded while the supervisor breaker
        is open."""
        with self._state_lock:
            return self._lifecycle

    def _set_state(self, new, only_from=None):
        with self._state_lock:
            if self._lifecycle == "stopped":      # terminal
                return False
            if only_from is not None \
                    and self._lifecycle not in only_from:
                return False
            self._lifecycle = new
            return True

    def start(self, serve_network=True, warmup_batch_sizes=None,
              warmup_signature_file=None):
        """Start the batcher (always) and the socket front-end (unless
        ``serve_network=False`` for purely in-process serving). Optional
        warmup precompiles before the first byte of traffic."""
        self._set_state("warming")
        if (warmup_batch_sizes or warmup_signature_file) \
                and self.engine is not None:
            self.engine.warmup(batch_sizes=warmup_batch_sizes or (),
                               signature_file=warmup_signature_file)
        if self.batcher is not None:
            self.batcher.start()
        if self.decode_batcher is not None:
            self.decode_batcher.start()
        self.supervisor.start()
        if serve_network:
            loopback = (self.host.startswith("127.")
                        or self.host in ("localhost", "::1"))
            if not loopback and self._key is None \
                    and not self._allow_insecure:
                raise PermissionError(
                    f"refusing to bind the inference server on "
                    f"non-loopback {self.host}:{self.port} without "
                    f"authentication — set PADDLE_PS_AUTH_KEY (both "
                    f"ends) or pass allow_insecure=True")
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind((self.host, self.port))
            self.port = self._sock.getsockname()[1]
            self._sock.listen(128)
            t = threading.Thread(target=self._accept_loop, daemon=True,
                                 name="serving-accept")
            t.start()
            self._threads.append(t)
        from ..flags import flag as _flag
        if _flag("slo_monitor") and self.slo_monitor is None:
            from ..observability import slo as _slo
            if callable(self._slo_rules):
                rules = self._slo_rules(self)   # rules need live wiring
            elif self._slo_rules is not None:
                rules = self._slo_rules         # [] = monitor off
            else:
                rules = _slo.default_server_rules(self)
            if rules:
                scope = self.endpoint if serve_network \
                    else f"server-{id(self) & 0xffffff:x}"
                self.slo_monitor = _slo.SloMonitor(rules,
                                                   scope=scope).start()
        self._set_state("serving", only_from=("warming", "created"))
        return self

    def drain(self, timeout=30.0):
        """Graceful shutdown: stop ADMISSION (new requests are refused
        with the typed ``ServerShutdownError``), let every in-flight
        micro-batch and decode row finish — token-level deadlines stay
        enforced, so the wait is bounded — then ``stop()``. ``ping``/
        ``stats``/``health`` keep answering throughout. Returns
        ``{"drained": bool, "remaining": n}`` (``remaining`` counts the
        requests abandoned to the hard stop when ``timeout`` ran out)."""
        self._set_state("draining")
        for q in (self.queue, self.gen_queue):
            if q is not None:
                q.quiesce()

        def _inflight():
            n = 0
            if self.queue is not None:
                n += len(self.queue)
            if self.batcher is not None:
                n += self.batcher.inflight()
            if self.gen_queue is not None:
                n += len(self.gen_queue)
            if self.decode_batcher is not None:
                n += self.decode_batcher.inflight()
            return n

        deadline = time.monotonic() + float(timeout)
        zero_streak = 0
        while time.monotonic() < deadline:
            if _inflight() == 0:
                # require consecutive zero reads: a request can sit
                # BETWEEN the queue and the batcher's pending dict for
                # an instant (popped, not yet admitted to a batch)
                zero_streak += 1
                if zero_streak >= 3:
                    break
            else:
                zero_streak = 0
            time.sleep(0.005)
        remaining = _inflight()
        self.stop()
        return {"drained": remaining == 0, "remaining": remaining}

    def stop(self):
        self._set_state("stopped")
        if self.slo_monitor is not None:
            self.slo_monitor.stop()
            self.slo_monitor = None
        self.supervisor.stop()
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        # close accepted connections too: a keep-alive client blocked in
        # recv_frame on the other end holds its handler thread forever
        # otherwise (the _stop flag is only re-checked between frames)
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self.queue is not None:
            self.queue.close()
        if self.batcher is not None:
            self.batcher.stop()
        if self.gen_queue is not None:
            self.gen_queue.close()
        if self.decode_batcher is not None:
            self.decode_batcher.stop()
        for t in self._threads:
            t.join(timeout=2)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- in-process client path -------------------------------------------
    def submit(self, feeds, deadline_ms=None, priority=None):
        """Admit a request (raises ServerOverloadedError /
        DeadlineExceededError at the door); returns the Request — call
        ``.wait()`` for the fetch list. ``priority`` is the admission
        class (interactive/batch/best_effort): lower classes shed first
        under backpressure and brownout."""
        if self.queue is None:
            raise ValueError("no inference model loaded — this server "
                             "only serves 'generate'")
        if deadline_ms is None and self.config.default_deadline_ms > 0:
            deadline_ms = self.config.default_deadline_ms
        _mnt, depth_cap = self._brownout_gate(priority)
        return self.queue.put(
            Request(feeds, deadline_ms=deadline_ms, priority=priority),
            max_depth=depth_cap)

    def _brownout_gate(self, priority, max_new_tokens=None):
        """The one copy of the brownout admission verdict for the
        infer and generate doors: raises the typed shed for degraded
        classes, else returns ``(max_new_tokens, depth_cap)`` with the
        class's cap/shrink applied."""
        shed, mnt, depth_cap = self.brownout.admission(
            priority_rank(priority), max_new_tokens=max_new_tokens,
            queue_depth=self.config.queue_depth)
        if shed:
            if self.stats_sink:
                self.stats_sink.bump("shed_overload")
            record_class_shed(priority)
            raise ServerOverloadedError(
                f"brownout level {self.brownout.level()}: "
                f"{priority} traffic is shed while the server works "
                f"off its SLO breach — retry later or upgrade the "
                f"request's class")
        return mnt, depth_cap

    def infer(self, feeds, deadline_ms=None, timeout=None,
              priority=None):
        return self.submit(feeds, deadline_ms=deadline_ms,
                           priority=priority).wait(timeout=timeout)

    def submit_generate(self, tokens, max_new_tokens=32, temperature=0.0,
                        top_k=0, eos_id=None, deadline_ms=None,
                        export_kv=False, kv=None, first_token=None,
                        priority=None):
        """Admit a generation request into the decode bank (admission
        control applies: queue depth, breaker, deadline). Returns the
        GenerationRequest — ``.wait()`` yields ``[np int32 tokens]``.

        ``FLAGS_serving_default_deadline_ms`` is NOT inherited here: it
        is a per-infer-batch budget, and a whole generation (prefill +
        up to max_new_tokens decode steps) lives on a different time
        scale — generation deadlines are per-request opt-in.

        Requests that could NEVER run are refused typed AT THE DOOR,
        before any queue wait or prefill compile: an overlong prompt
        (prompt + max_new_tokens > the decode cache length) and, in
        paged mode, a request bigger than the whole KV pool both raise
        :class:`BadRequestError` (wire ``etype: "BadRequest"`` —
        retrying cannot help)."""
        if self.gen_queue is None:
            raise ValueError("no generator loaded — pass generator= to "
                             "InferenceServer to serve 'generate'")
        ntokens = np.asarray(tokens).size
        self.gen_engine.admission_check(
            ntokens, max_new_tokens, static_only=True)
        if (export_kv or kv is not None) \
                and self.gen_engine.pool is None:
            raise BadRequestError(
                "disaggregated prefill/decode requires the paged KV "
                "pool (FLAGS_kv_paged / kv_paged=True) — the dense "
                "bank's rows are not migratable")
        if kv is not None:
            # door check: the migrated payload must describe exactly
            # this prompt's prefill (position arithmetic depends on it)
            claimed = kv.get("tokens") if isinstance(kv, dict) else None
            if claimed != ntokens:
                raise BadRequestError(
                    f"migrated KV payload covers {claimed!r} tokens but "
                    f"the prompt has {ntokens} — prefill and decode "
                    f"halves disagree")
        if self.state == "degraded":
            if self.stats_sink:
                self.stats_sink.bump("shed_overload")
            raise ServerOverloadedError(
                "server is degraded (supervisor breaker open after "
                "repeated loop failures) — generation is shed; "
                "ping/health/stats still answer")
        # brownout ladder: a breached-SLO server sheds best_effort
        # (then batch) typed at the door, caps batch token budgets and
        # shrinks batch admission — interactive traffic degrades LAST
        max_new_tokens, depth_cap = self._brownout_gate(
            priority, max_new_tokens=int(max_new_tokens))
        return self.gen_queue.put(GenerationRequest(
            tokens, max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k, eos_id=eos_id,
            deadline_ms=deadline_ms, export_kv=export_kv, kv=kv,
            first_token=first_token, priority=priority),
            max_depth=depth_cap)

    def generate(self, tokens, max_new_tokens=32, temperature=0.0,
                 top_k=0, eos_id=None, deadline_ms=None, timeout=None,
                 priority=None):
        """Generate new tokens for one prompt; returns a 1-D np.int32
        array (EOS excluded)."""
        req = self.submit_generate(tokens, max_new_tokens=max_new_tokens,
                                   temperature=temperature, top_k=top_k,
                                   eos_id=eos_id, deadline_ms=deadline_ms,
                                   priority=priority)
        return req.wait(timeout=timeout)[0]

    def stats(self):
        """One snapshot across every stage: admission counters, stage
        latency histograms, batch occupancy, executable-cache hit/miss/
        evict, queue depth."""
        extra = {}
        if self.queue is not None:
            extra["queue_depth"] = len(self.queue)
            extra["breaker_state"] = self.queue.breaker.state
        if self.engine is not None:
            for k, v in self.engine.cache.stats().items():
                extra[f"cache_{k}"] = v
        if self.gen_queue is not None:
            extra["decode_queue_depth"] = len(self.gen_queue)
            extra["decode_free_slots"] = len(self.decode_batcher._free)
            for k, v in self.gen_engine.gen.cache.stats().items():
                extra[f"decode_cache_{k}"] = v
            if self.gen_engine.pool is not None:
                for k, v in self.gen_engine.pool.stats().items():
                    extra[f"kvpool_{k}"] = v
        extra["state"] = self.state
        extra["weights_version"] = self._weights_version
        # level() (not snapshot's cached value): the ladder is
        # evaluated lazily, and a server whose traffic stopped at
        # level 2 must report recovery once its breaches clear
        extra["brownout_level"] = self.brownout.level()
        extra["brownout_shed"] = self.brownout.snapshot()["shed"]
        for q, key in ((self.queue, "expired_in_queue"),
                       (self.gen_queue, "decode_expired_in_queue")):
            if q is not None:
                extra[key] = q.expired_in_queue
                extra[key.replace("expired_in_queue",
                                  "priority_evictions")] = \
                    q.priority_evictions
        return self.stats_sink.snapshot(extra=extra)

    def health(self):
        """Liveness/readiness snapshot, cheap enough for a poller: the
        lifecycle state, queue depths, per-loop thread liveness +
        heartbeat age + restart counts, the supervisor breaker, and the
        current weights version."""
        h = {
            "state": self.state,
            "weights_version": self._weights_version,
            "uptime_s": round(time.monotonic() - self._started_at, 3),
            "loops": self.supervisor.snapshot(),
            "breaker": self.supervisor.breaker.state,
            # the autoscaler's queue-ratio signal and the router's
            # hedge policy read these: degradation state + the depth
            # cap that turns probed queue depths into a ratio
            "brownout_level": self.brownout.level(),
            "queue_capacity": int(self.config.queue_depth),
        }
        if self.slo_monitor is not None:
            # the Router's dispatch-score penalty reads this: current
            # SLO breach state next to the load signals, one cheap probe
            breached = self.slo_monitor.breached()
            h["slo_breached"] = len(breached)
            if breached:
                h["slo_breached_rules"] = ",".join(sorted(breached))
        if self.queue is not None:
            h["queue_depth"] = len(self.queue)
        if self.gen_queue is not None:
            h["decode_queue_depth"] = len(self.gen_queue)
            h["decode_active_rows"] = self.decode_batcher.inflight()
            if self.decode_batcher.spec_k > 0:
                # the speculative load knob's observable state: depth +
                # windowed acceptance next to the load signals
                h.update(self.decode_batcher.spec_snapshot())
            pool = self.gen_engine.pool
            if pool is not None:
                # the router's least-loaded dispatch reads this: live
                # kvpool occupancy next to the queue depths, one cheap
                # probe instead of a full stats()/metrics scrape
                cap = pool.capacity_blocks
                # blocks_in_use excludes cache-only blocks: a pool full
                # of EVICTABLE prefix blocks reads as empty to the
                # dispatch score (those blocks are reclaimable capacity
                # that doubles as cache value), with the evictable
                # count alongside for the affinity-aware observer
                h["kvpool_occupancy"] = round(
                    pool.blocks_in_use() / cap, 4) if cap else 0.0
                h["kvpool_evictable_blocks"] = pool.cached_blocks()
        return h

    def reload_weights(self, path, timeout=120.0):
        """Hot weight reload (CheckFreq-style atomic swap, zero dropped
        traffic): verify + load a manifest-carrying checkpoint dir,
        build the new DEVICE snapshot off the serving loops, then swap —
        the infer engine swaps atomically between micro-batches, and the
        decode bank pauses ADMISSION (requests queue, nothing is failed)
        while in-flight generations FINISH ON THE OLD WEIGHTS, applying
        the swap between decode steps once the bank is empty.

        A corrupt/incomplete checkpoint raises
        ``CheckpointCorruptError`` (or ``ValueError`` on a shape/dtype
        mismatch) with the old snapshot untouched. Returns
        ``{"weights_version", "swap_pause_ms"}``."""
        if self.state == "stopped":
            raise ServerShutdownError("cannot reload weights on a "
                                      "stopped server")
        # load + verify EVERYTHING first: a failure in either engine's
        # checkpoint must leave both snapshots untouched
        new_state = staged = None
        if self.engine is not None:
            new_state = self.engine.load_state_snapshot(path)
        if self.gen_engine is not None:
            host = self.gen_engine.load_param_snapshot(path)
            staged = self.gen_engine.stage_params(host)
        pause_ms = 0.0
        if new_state is not None:
            self.engine.swap_state(new_state)
        if staged is not None:
            if self.decode_batcher is not None \
                    and self.decode_batcher.alive():
                handle = self.decode_batcher.request_swap(
                    lambda: self.gen_engine.apply_params(staged))
                pause_ms = handle.wait(timeout)
            else:
                self.gen_engine.apply_params(staged)
        with self._state_lock:
            self._weights_version += 1
            version = self._weights_version
        self.stats_sink.bump("weight_reloads")
        _flightrec().record("weight_reload", path=str(path),
                            weights_version=version,
                            swap_pause_ms=round(float(pause_ms or 0.0),
                                                3))
        return {"weights_version": version,
                "swap_pause_ms": round(float(pause_ms or 0.0), 3)}

    def record_signatures(self, path=None):
        if self.engine is None:
            raise ValueError("no inference model loaded — this server "
                             "only serves 'generate'")
        return self.engine.record_signatures(path)

    # -- network front-end ------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.2)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True, name="serving-conn")
            t.start()
            # prune finished connection threads so a long-lived server
            # doesn't accumulate one dead handle per past client
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _serve_conn(self, conn):
        with self._conns_lock:
            self._conns.add(conn)
        try:
            while not self._stop.is_set():
                try:
                    msg = recv_frame(conn, self._key)
                except (ConnectionError, EOFError, OSError):
                    return
                except WireError:
                    # unauthenticated/malformed frame: drop the
                    # connection (same policy as the PS server)
                    return
                try:
                    # chaos point: a stalled/killed connection handler
                    # (the hedged-client scenario — the request made it
                    # onto the wire but its reply never comes)
                    from ..resilience import maybe_fail
                    maybe_fail("serving.handle")
                except Exception as e:  # noqa: BLE001 — typed reply
                    reply = _error_reply(e)
                else:
                    reply = self._handle(msg)
                tr = msg.get("trace") if isinstance(msg, dict) else None
                t_r0 = time.perf_counter() if tr is not None else 0.0
                try:
                    send_frame(conn, reply, self._key)
                except (ConnectionError, OSError):
                    return
                if tr is not None:
                    _trace.record_child("serving/reply", t_r0,
                                        time.perf_counter(),
                                        _trace.from_wire(tr))
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _dedup(self, rid, admit):
        """Request-id dedup: the second half of a hedged pair ATTACHES
        to the first's in-flight request instead of admitting a second
        execution. ``admit`` runs under the table lock (it is the O(1)
        non-blocking queue put), so racing twins cannot double-admit.
        Returns ``(request, joined)``."""
        if not rid:
            return admit(), False
        with self._rids_lock:
            req = self._rids.get(rid)
            if req is not None:
                self._rids.move_to_end(rid)
                return req, True
            req = admit()
            self._rids[rid] = req
            while len(self._rids) > self._rid_cap:
                self._rids.popitem(last=False)
            return req, False

    def metrics(self):
        """Prometheus text exposition of the process metrics registry
        (serving counters/histograms, executor cache, pass pipeline,
        breaker states, training, utilization gauges — everything that
        reports into ``observability.default_registry()``)."""
        return render_metrics()

    def _handle(self, msg):
        if not isinstance(msg, dict) or "op" not in msg:
            return {"ok": False, "etype": "BadRequest",
                    "error": "expected a dict with an 'op' field"}
        op = msg["op"]
        if op == "ping":
            return {"ok": True}
        if op in ("stats", "metrics", "health", "cancel"):
            # probe/control ops carry the trace context too (a router's
            # health-probe latency belongs on the Perfetto timeline next
            # to the requests it gates); span() with a None parent is
            # free, so untraced probes pay nothing
            with _trace.span(f"serving/{op}",
                             parent=_trace.from_wire(msg.get("trace"))):
                if op == "stats":
                    return {"ok": True, "stats": self.stats()}
                if op == "metrics":
                    return {"ok": True, "metrics": self.metrics()}
                if op == "health":
                    return {"ok": True, "health": self.health()}
                return self._handle_cancel(msg)
        if op == "debug_dump":
            return self._handle_debug_dump(msg)
        if op == "generate":
            return self._handle_generate(msg)
        if op == "prefill":
            return self._handle_prefill(msg)
        if op == "reload_weights":
            return self._handle_reload(msg)
        if op != "infer":
            return {"ok": False, "etype": "BadRequest",
                    "error": f"unknown op {op!r}"}
        return self._handle_infer(msg)

    def _handle_debug_dump(self, msg):
        """Flight-recorder snapshot over the wire; ``"write": True``
        also dumps it to a JSON file server-side and returns the
        path."""
        rec = _flightrec()
        path = None
        if msg.get("write"):
            try:
                path = rec.dump(reason="debug_dump wire op")
            except OSError as e:
                return _error_reply(e)
        return {"ok": True, "events": rec.snapshot(), "path": path}

    def _handle_infer(self, msg):
        if self.engine is None:
            return {"ok": False, "etype": "BadRequest",
                    "error": "no inference model loaded — this server "
                             "only serves 'generate'"}
        # the handler span is ambient for the whole body, so the
        # Request minted inside parents its stage spans under it
        with _trace.span("serving/handle",
                         parent=_trace.from_wire(msg.get("trace"))):
            try:
                feed = msg.get("feed")
                if not isinstance(feed, dict) or not feed:
                    raise ValueError("'feed' must be a non-empty dict "
                                     "of arrays")
                missing = [n for n in self.engine.feed_names
                           if n not in feed]
                if missing:
                    raise ValueError(f"missing feeds: {missing}")
                feed = {n: np.asarray(feed[n])
                        for n in self.engine.feed_names}
                req, joined = self._dedup(
                    msg.get("rid"),
                    lambda: self.submit(
                        feed, deadline_ms=msg.get("deadline_ms"),
                        priority=msg.get("priority")))
                if joined and self.stats_sink:
                    self.stats_sink.bump("hedge_dedup_hits")
            except Exception as e:  # noqa: BLE001 — typed refusal reply
                return _error_reply(e)
            # bound the wait: the deadline (if any) plus compile/execute
            # headroom, else a hard server-side cap
            budget = msg.get("deadline_ms")
            wait_s = (budget / 1e3 + 60.0) if budget else 300.0
            try:
                outs = req.wait(timeout=wait_s)
                return {"ok": True, "fetch": tuple(outs),
                        "batched": int(req.rows)}
            except Exception as e:  # noqa: BLE001 — surface, don't die
                return _error_reply(e)

    def _handle_cancel(self, msg):
        """Cancel a request by client request id (the hedge loser): a
        still-in-flight request is failed with the typed cancellation
        error (the batchers skip done requests), a finished one is left
        alone."""
        rid = msg.get("rid")
        req = None
        if rid:
            with self._rids_lock:
                req = self._rids.get(rid)
        cancelled = False
        if req is not None and not req.done():
            req.set_error(RequestCancelledError(
                f"cancelled by the client (request id {rid})"))
            cancelled = True
            if self.stats_sink:
                self.stats_sink.bump("requests_cancelled")
        return {"ok": True, "cancelled": cancelled}

    def _handle_generate(self, msg):
        if self.gen_queue is None:
            return {"ok": False, "etype": "BadRequest",
                    "error": "this server has no generator — pass "
                             "generator= to InferenceServer"}
        with _trace.span("serving/handle",
                         parent=_trace.from_wire(msg.get("trace"))):
            return self._handle_generate_inner(msg)

    def _handle_generate_inner(self, msg):
        try:
            tokens = msg.get("tokens")
            if tokens is None:
                raise ValueError("'tokens' (1-D int prompt) is required")
            first_token = msg.get("first_token")
            req, joined = self._dedup(
                msg.get("rid"),
                lambda: self.submit_generate(
                    np.asarray(tokens),
                    max_new_tokens=int(msg.get("max_new_tokens", 32)),
                    temperature=float(msg.get("temperature", 0.0)),
                    top_k=int(msg.get("top_k", 0)),
                    eos_id=msg.get("eos_id"),
                    deadline_ms=msg.get("deadline_ms"),
                    kv=msg.get("kv"),
                    first_token=None if first_token is None
                    else int(first_token),
                    priority=msg.get("priority")))
            if joined and self.stats_sink:
                self.stats_sink.bump("hedge_dedup_hits")
        except Exception as e:  # noqa: BLE001 — typed refusal reply
            return _error_reply(e)
        # generation budget: prompt prefill + one step per token, plus
        # compile headroom on the first request of a shape
        budget = msg.get("deadline_ms")
        wait_s = (budget / 1e3 + 120.0) if budget else 600.0
        try:
            out, = req.wait(timeout=wait_s)
            return {"ok": True, "tokens": np.asarray(out, np.int32),
                    "generated": int(np.asarray(out).size)}
        except TimeoutError:
            # abandon the request properly: marking it done lets the
            # DecodeBatcher reclaim its slot instead of decoding tokens
            # nobody will read, and the client gets a typed, retryable
            # error instead of a generic Internal
            err = DeadlineExceededError(
                f"server-side wait budget of {wait_s:.0f}s exceeded; "
                f"the request was abandoned")
            req.set_error(err)
            return _error_reply(err)
        except Exception as e:  # noqa: BLE001 — surface, don't die
            return _error_reply(e)

    def _handle_prefill(self, msg):
        """The compute-bound half of the disaggregated split: prefill
        the prompt, sample its first token, then serialize the slot's
        KV blocks out of the paged pool instead of decoding. Reply
        ``{"ok": True, "kv": payload}`` where the payload carries
        ``first_token``/``prompt_tokens`` plus the block arrays —
        ready to stream into a decode replica via ``generate``'s
        ``kv=`` field."""
        if self.gen_queue is None:
            return {"ok": False, "etype": "BadRequest",
                    "error": "this server has no generator — pass "
                             "generator= to InferenceServer"}
        with _trace.span("serving/handle",
                         parent=_trace.from_wire(msg.get("trace"))):
            try:
                tokens = msg.get("tokens")
                if tokens is None:
                    raise ValueError(
                        "'tokens' (1-D int prompt) is required")
                req, joined = self._dedup(
                    msg.get("rid"),
                    lambda: self.submit_generate(
                        np.asarray(tokens),
                        max_new_tokens=int(msg.get("max_new_tokens",
                                                    32)),
                        temperature=float(msg.get("temperature", 0.0)),
                        top_k=int(msg.get("top_k", 0)),
                        deadline_ms=msg.get("deadline_ms"),
                        export_kv=True,
                        priority=msg.get("priority")))
                if joined and self.stats_sink:
                    self.stats_sink.bump("hedge_dedup_hits")
            except Exception as e:  # noqa: BLE001 — typed refusal
                return _error_reply(e)
            budget = msg.get("deadline_ms")
            wait_s = (budget / 1e3 + 120.0) if budget else 600.0
            try:
                payload, = req.wait(timeout=wait_s)
                return {"ok": True, "kv": payload}
            except TimeoutError:
                err = DeadlineExceededError(
                    f"server-side wait budget of {wait_s:.0f}s "
                    f"exceeded; the prefill was abandoned")
                req.set_error(err)
                return _error_reply(err)
            except Exception as e:  # noqa: BLE001 — surface, don't die
                return _error_reply(e)

    def _handle_reload(self, msg):
        """Hot weight reload over the wire (the router's rolling-reload
        building block): same contract as :meth:`reload_weights`."""
        path = msg.get("path")
        if not isinstance(path, str) or not path:
            return {"ok": False, "etype": "BadRequest",
                    "error": "'path' (checkpoint dir) is required"}
        try:
            out = self.reload_weights(
                path, timeout=float(msg.get("timeout", 120.0)))
        except Exception as e:  # noqa: BLE001 — typed reply
            return _error_reply(e)
        return {"ok": True, **out}


# reply etype <-> exception mapping. Order matters server-side:
# subclasses (Cancelled/Shutdown before their bases) must match first
_ETYPE_MAP = (
    ("Cancelled", RequestCancelledError),
    ("Shutdown", ServerShutdownError),
    ("DeadlineExceeded", DeadlineExceededError),
    ("Overloaded", ServerOverloadedError),
    ("Watchdog", WatchdogTimeout),
    ("BadRequest", (BadRequestError, ValueError, TypeError)),
)
# client-side reply mapping: server-side BadRequest detection matches
# (ValueError, TypeError), but the CLIENT raises the typed ServingError
# subclass so input refusals stay distinguishable from server faults
_ETYPES = {etype: cls for etype, cls in _ETYPE_MAP
           if isinstance(cls, type)}
_ETYPES["BadRequest"] = BadRequestError


_ierr_lock = threading.Lock()
_ierr_counts = {}       # exception type name -> cumulative count


def _record_internal_error(exc):
    """Flight-record an internal error crossing the server boundary,
    SAMPLED per exception type (first, then every 64th, cumulative
    count riding each sampled event — the RequestQueue admission
    discipline): a wedged engine failing every request at production
    QPS must not churn the ring and evict the restart/chaos/non-finite
    events that explain WHY it wedged."""
    key = type(exc).__name__
    with _ierr_lock:
        n = _ierr_counts.get(key, 0) + 1
        _ierr_counts[key] = n
    if n == 1 or n % 64 == 0:
        _flightrec().record("internal_error", etype=key, n=n,
                            error=str(exc)[:200])


def _error_reply(exc):
    """Map an exception to its typed wire reply. Internal/Watchdog
    faults crossing the server boundary trigger an automatic
    flight-recorder dump (rate-limited; only when
    ``FLAGS_flight_recorder_dir`` is set) — the chaos-soak postmortem
    artifact."""
    for etype, cls in _ETYPE_MAP:
        if isinstance(exc, cls):
            if etype == "Watchdog":
                _flightrec().auto_dump(
                    f"Watchdog error crossed the server boundary: {exc}")
            return {"ok": False, "etype": etype, "error": str(exc)}
    _record_internal_error(exc)
    _flightrec().auto_dump(
        f"Internal error crossed the server boundary: "
        f"{type(exc).__name__}: {exc}")
    return {"ok": False, "etype": "Internal",
            "error": f"{type(exc).__name__}: {exc}"}


# "argument not given" sentinel for per-call timeout overrides (None is
# a meaningful value: block forever). The stable repr keeps
# tools/api_signatures.txt reproducible across processes (a bare
# object()'s repr embeds its address).
class _Unset:
    def __repr__(self):
        return "<unset>"


_UNSET = _Unset()


class Client:
    """Wire-protocol client. One socket, serial request/reply (run one
    Client per concurrent caller — sockets are cheap; the server batches
    across them). Transport failures surface as ConnectionError
    subclasses (``WireTruncationError`` included).

    Resilience: a dead cached socket is detected on send/recv failure
    and reconnected ONCE transparently before any error surfaces (a
    bounced server does not strand old clients), ``ping``/``stats``/
    ``health`` retry with backoff via ``resilience.retry_call`` (they
    are idempotent), every ``infer``/``generate`` carries a request id
    (the server dedups, so a retried or hedged pair executes once), and
    ``infer`` can HEDGE: if no reply lands within a p99-derived delay
    (``hedge_ms``, default ``FLAGS_serving_hedge_ms``; the observed p99
    takes over once enough latencies are banked), a twin request races
    on a second connection, the first reply wins and the loser is
    cancelled by request id."""

    def __init__(self, endpoint, auth_key=None, timeout=None,
                 connect_retries=20, hedge_ms=None, retry_budget=None):
        from ..flags import flag
        host, port = endpoint.rsplit(":", 1)
        self.endpoint = endpoint
        self._addr = (host, int(port))
        self._key = auth_key if auth_key is not None else default_key()
        self._timeout = timeout
        self._connect_retries = connect_retries
        # None = the process-global retry budget. Infrastructure
        # callers (the router's health-probe clients) pass their own —
        # a dead replica probed every interval must not drain the
        # shared bucket and suppress hedges/failovers for healthy
        # user traffic
        self._retry_budget = retry_budget
        self._sock = None
        self._hedge_ms = float(hedge_ms if hedge_ms is not None
                               else flag("serving_hedge_ms"))
        self._lat_s = deque(maxlen=256)     # winning infer latencies
        self._hedges = 0
        self._hedge_wins = 0
        self._hedges_suppressed = 0     # refused by the retry budget

    def _budget(self):
        return (self._retry_budget if self._retry_budget is not None
                else default_retry_budget())

    @staticmethod
    def _remaining_ms(budget_ms, t0):
        """Deadline budget still unspent at THIS moment — what actually
        goes on the wire, so a hop (or a delayed retry/hedge) never
        grants itself the caller's full original budget again. Raises
        the typed expiry when nothing is left: no tier should burn
        compute on a request its caller has already abandoned."""
        if budget_ms is None:
            return None
        rem = remaining_budget_ms(budget_ms, t0)
        if rem <= 0:
            raise DeadlineExceededError(
                f"deadline budget of {float(budget_ms):.1f}ms spent "
                f"client-side before the request reached a server",
                deadline_ms=float(budget_ms),
                waited_ms=(time.monotonic() - t0) * 1e3)
        return rem

    def _ensure(self, timeout=_UNSET):
        if self._sock is None:
            t = self._timeout if timeout is _UNSET else timeout
            # an explicit per-call timeout also bounds the CONNECT
            # retries: a router probing a dead replica must fail fast,
            # not ride out the 10s reconnect discipline
            deadline = 10.0 if timeout is _UNSET or timeout is None \
                else max(float(timeout), 0.05)
            self._sock = retry_call(
                lambda: socket.create_connection(self._addr, timeout=t),
                deadline=deadline, retries=self._connect_retries,
                what="serving connect", endpoint=self.endpoint,
                budget=self._budget())
        return self._sock

    def _transact(self, sock, msg, timeout=_UNSET):
        """One request/reply exchange on ``sock``; maps error replies to
        their typed exceptions. No reconnect logic here. ANY failure
        inside the exchange (transport error, timeout, injected fault)
        poisons the socket — a half-done exchange can leave the reply in
        the buffer, and reusing the socket would pair the NEXT request
        with this one's stale reply — so the cached socket is dropped
        and the next call reconnects. ``timeout`` overrides the client
        default for THIS exchange (health probes against a hung replica
        fail fast instead of inheriting the long socket default)."""
        t = self._timeout if timeout is _UNSET else timeout
        try:
            send_frame(sock, msg, self._key, timeout=t)
            reply = recv_frame(sock, self._key, timeout=t)
        except BaseException:
            if sock is self._sock:
                self.close()
            raise
        # past here the exchange is COMPLETE — reply-decode errors are
        # typed results, not transport damage; the socket stays cached
        if not isinstance(reply, dict):
            raise WireError(f"malformed serving reply: {type(reply)}")
        if reply.get("ok"):
            return reply
        etype = _ETYPES.get(reply.get("etype"), InternalServerError)
        raise etype(reply.get("error", "serving request failed"))

    def _call(self, msg, timeout=_UNSET, budget_ms=None, t0=None):
        """Exchange with reconnect-once: a send/recv failure on the
        cached socket (typically a bounced server) closes it and retries
        the exchange on a fresh connection before surfacing anything.
        Safe because infer/generate carry a request id the server
        dedups, and the other ops are idempotent.

        ``budget_ms``/``t0`` arm deadline propagation: before every
        attempt the wire ``deadline_ms`` is rewritten to the REMAINING
        budget (raising typed expiry when none is left), and the
        reconnect retry itself withdraws from the process retry budget
        — a saturated fleet turns a reconnect storm into fast typed
        sheds instead of doubled offered load."""
        for attempt in (0, 1):
            if budget_ms is not None:
                msg["deadline_ms"] = self._remaining_ms(budget_ms, t0)
            sock = self._ensure(timeout=timeout)
            try:
                return self._transact(sock, msg, timeout=timeout)
            except (ConnectionError, OSError) as e:
                self.close()
                # an explicit per-call timeout expiring is the answer
                # (replica hung), not a stale-socket symptom — retrying
                # would double the caller's deadline
                if attempt or (timeout is not _UNSET
                               and isinstance(e, socket.timeout)):
                    raise
                self._budget().acquire(what="client-reconnect")
        raise AssertionError("unreachable")

    # -- hedging -----------------------------------------------------------
    def _hedge_delay_s(self, hedge_ms):
        """Effective hedge trigger: the observed p99 infer latency once
        >= 16 samples are banked (floored at 1 ms so a microsecond p99
        cannot hedge every call), else the configured cold-start
        delay."""
        base = self._hedge_ms if hedge_ms is None else float(hedge_ms)
        if base <= 0:
            return 0.0
        if len(self._lat_s) >= 16:
            p99 = float(np.percentile(np.asarray(self._lat_s), 99)) * 1e3
            return max(p99, 1.0) / 1e3
        return base / 1e3

    def hedge_stats(self):
        return {"hedges": self._hedges, "hedge_wins": self._hedge_wins,
                "budget_suppressed": self._hedges_suppressed,
                "observed": len(self._lat_s)}

    def _call_hedged(self, msg, delay_s, budget_ms=None, t0=None):
        """Race the primary exchange against a delayed twin on a fresh
        connection; first reply wins, the loser is cancelled by request
        id (the server's dedup table guarantees the pair executed at
        most once). The twin withdraws from the process retry budget
        first: when the bucket is dry the hedge is SUPPRESSED (counted
        in :meth:`hedge_stats`) and the call rides the primary alone —
        hedging is optional tail-fighting work, the first thing a
        saturated fleet must stop doing."""
        state = {"reply": None, "who": None, "errors": [], "done": 0}
        cv = threading.Condition()

        def attempt(tag, fn):
            try:
                r = fn()
            except Exception as e:  # noqa: BLE001 — judged by the racer
                r = None
                err = e
            with cv:
                if r is not None and state["reply"] is None:
                    state["reply"], state["who"] = r, tag
                elif r is None:
                    state["errors"].append(err)
                state["done"] += 1
                cv.notify_all()

        if budget_ms is not None:
            msg["deadline_ms"] = self._remaining_ms(budget_ms, t0)
        sock = self._ensure()
        threading.Thread(
            target=attempt, args=("primary",
                                  lambda: self._transact(sock, msg)),
            daemon=True, name="serving-client-primary").start()
        launched = 1
        with cv:
            cv.wait_for(lambda: state["reply"] is not None
                        or state["done"] >= launched, timeout=delay_s)
            fire_hedge = state["reply"] is None and state["done"] < 1

        # the twin owns its COPY of the message (the primary thread may
        # still be serializing the original) and fires LATER than the
        # primary: it carries the budget remaining NOW, not the
        # primary's stale copy — a spent budget means no twin (the
        # primary is still the caller's best hope), checked BEFORE the
        # budget withdrawal so a deadline-cancelled hedge doesn't leak
        # a token
        hmsg = dict(msg) if fire_hedge else None
        if fire_hedge and budget_ms is not None:
            try:
                hmsg["deadline_ms"] = self._remaining_ms(budget_ms, t0)
            except DeadlineExceededError:
                fire_hedge = False
        if fire_hedge and not self._budget().try_acquire(
                what="client-hedge"):
            self._hedges_suppressed += 1
            fire_hedge = False
        if fire_hedge:
            self._hedges += 1

            def hedge_fn():
                hs = socket.create_connection(self._addr,
                                              timeout=self._timeout)
                try:
                    return self._transact(hs, hmsg)
                finally:
                    try:
                        hs.close()
                    except OSError:
                        pass

            threading.Thread(target=attempt, args=("hedge", hedge_fn),
                             daemon=True,
                             name="serving-client-hedge").start()
            launched = 2
        with cv:
            cv.wait_for(lambda: state["reply"] is not None
                        or state["done"] >= launched)
            reply, who = state["reply"], state["who"]
            errors = list(state["errors"])
        if reply is None:
            if all(isinstance(e, (ConnectionError, OSError))
                   for e in errors):
                # both attempts died on transport: the reconnect-once
                # contract still applies — one fresh-socket retry (the
                # request id makes the replay exactly-once server-side)
                self.close()
                self._budget().acquire(what="client-reconnect")
                return self._call(msg, budget_ms=budget_ms, t0=t0)
            raise errors[0]
        if who == "hedge":
            self._hedge_wins += 1
            # the primary worker is still blocked on the cached socket:
            # drop it so the NEXT call gets a fresh connection instead
            # of interleaving frames with the abandoned exchange
            self.close()
        if launched == 2:
            try:
                self._call({"op": "cancel", "rid": msg["rid"]})
            except Exception:  # noqa: BLE001 — cancel is best-effort
                pass
        return reply

    @contextlib.contextmanager
    def _traced(self, msg):
        """Attach the sampled/ambient trace context to an outgoing
        request and record the client/send span around the call — the
        one copy of the trace-attach arithmetic for infer/generate."""
        ctx = _trace.maybe_trace()
        if ctx is not None:
            msg["trace"] = _trace.to_wire(ctx)
        t0p = time.perf_counter() if ctx is not None else 0.0
        try:
            yield
        finally:
            if ctx is not None:
                _trace.record_span("client/send", t0p,
                                   time.perf_counter(), ctx)

    # -- ops ---------------------------------------------------------------
    def infer(self, feeds, deadline_ms=None, hedge_ms=None,
              priority=None):
        """Returns the fetch list (numpy arrays). Raises
        DeadlineExceededError / ServerOverloadedError /
        ServerShutdownError mapped from the server's reply,
        ConnectionError on transport failure. ``hedge_ms`` overrides the
        client's hedging delay for this call (0 disables); ``priority``
        is the admission class (interactive/batch/best_effort).
        ``deadline_ms`` is a BUDGET: what goes on the wire is the part
        still unspent at send time, so a retried/hedged attempt never
        re-grants itself the full original allowance. At
        ``FLAGS_trace_sample_rate`` (or inside an ambient
        ``tracing.span``) the request carries a trace context the
        server's stages parent under."""
        msg = {"op": "infer", "feed": dict(feeds),
               "deadline_ms": deadline_ms, "rid": uuid.uuid4().hex}
        if priority is not None:
            msg["priority"] = str(priority)
        delay_s = self._hedge_delay_s(hedge_ms)
        t0 = time.monotonic()
        self._budget().record_request()
        with self._traced(msg):
            if delay_s <= 0:
                reply = self._call(msg, budget_ms=deadline_ms, t0=t0)
            else:
                reply = self._call_hedged(msg, delay_s,
                                          budget_ms=deadline_ms, t0=t0)
        self._lat_s.append(time.monotonic() - t0)
        return [np.asarray(a) for a in reply["fetch"]]

    def generate(self, tokens, max_new_tokens=32, temperature=0.0,
                 top_k=0, eos_id=None, deadline_ms=None, priority=None):
        """Autoregressive generation for one prompt (1-D int tokens).
        Returns the NEW tokens as a 1-D np.int32 array (EOS excluded).
        Same error mapping as ``infer``; ``deadline_ms`` is token-level
        (checked between decode steps server-side) and propagates as a
        REMAINING budget across retries; ``priority`` is the admission
        class (interactive/batch/best_effort — lower classes shed
        first under overload and brownout)."""
        msg = {
            "op": "generate",
            "tokens": np.asarray(tokens, dtype=np.int32).ravel(),
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "top_k": int(top_k),
            "eos_id": None if eos_id is None else int(eos_id),
            "deadline_ms": deadline_ms,
            "rid": uuid.uuid4().hex,
        }
        if priority is not None:
            msg["priority"] = str(priority)
        t0 = time.monotonic()
        self._budget().record_request()
        with self._traced(msg):
            reply = self._call(msg, budget_ms=deadline_ms, t0=t0)
        return np.asarray(reply["tokens"], dtype=np.int32)

    def prefill(self, tokens, max_new_tokens=32, temperature=0.0,
                top_k=0, deadline_ms=None):
        """The compute-bound half of the disaggregated split: prefill
        the prompt on this (prefill) replica and return the serialized
        KV payload — ``first_token``/``prompt_tokens`` plus the slot's
        block arrays — ready to pass to another replica's
        :meth:`generate` as ``kv=``. Requires the server's paged pool."""
        msg = {
            "op": "prefill",
            "tokens": np.asarray(tokens, dtype=np.int32).ravel(),
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "top_k": int(top_k),
            "deadline_ms": deadline_ms,
            "rid": uuid.uuid4().hex,
        }
        with self._traced(msg):
            return self._call(msg)["kv"]

    def generate_from_kv(self, tokens, kv, max_new_tokens=32,
                         temperature=0.0, top_k=0, eos_id=None,
                         deadline_ms=None):
        """The bandwidth-bound half: stream a migrated ``kv`` payload
        (from :meth:`prefill`) into this (decode) replica's pool and
        continue decoding from its ``first_token``. Returns ALL new
        tokens (the prefill-side first token included) as np.int32."""
        msg = {
            "op": "generate",
            "tokens": np.asarray(tokens, dtype=np.int32).ravel(),
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "top_k": int(top_k),
            "eos_id": None if eos_id is None else int(eos_id),
            "deadline_ms": deadline_ms,
            "kv": dict(kv),
            "first_token": int(kv["first_token"]),
            "rid": uuid.uuid4().hex,
        }
        with self._traced(msg):
            reply = self._call(msg)
        return np.asarray(reply["tokens"], dtype=np.int32)

    def reload_weights(self, path, timeout=120.0):
        """Hot weight reload on the server (manifest-verified atomic
        swap; the router's rolling-reload building block). Returns
        ``{"weights_version", "swap_pause_ms"}``."""
        msg = {"op": "reload_weights", "path": str(path),
               "timeout": float(timeout)}
        reply = self._call(msg)
        return {"weights_version": reply["weights_version"],
                "swap_pause_ms": reply["swap_pause_ms"]}

    def cancel(self, rid):
        """Cancel an in-flight request by its id (hedge losers; also
        usable after abandoning a slow call). Returns True if the server
        actually cancelled something."""
        msg = {"op": "cancel", "rid": str(rid)}
        with self._traced(msg):
            return bool(self._call(msg).get("cancelled"))

    def _idempotent(self, msg, timeout=_UNSET):
        deadline = 10.0 if timeout is _UNSET or timeout is None \
            else max(float(timeout), 0.05)
        return retry_call(lambda: self._call(msg, timeout=timeout),
                          deadline=deadline,
                          retries=2, what=f"serving {msg['op']}",
                          endpoint=self.endpoint, budget=self._budget())

    def stats(self, timeout=_UNSET):
        """One server-stage stats snapshot. ``timeout`` (seconds)
        overrides the client's socket default for this call — probe
        loops against a hung replica fail fast."""
        msg = {"op": "stats"}
        with self._traced(msg):
            return self._idempotent(msg, timeout=timeout)["stats"]

    def metrics(self, timeout=_UNSET):
        """Prometheus text exposition of the server process's metrics
        registry (the scrape endpoint: pipe it to a pushgateway or the
        node-exporter textfile collector via
        ``tools/export_metrics.py``). ``timeout`` is per-call."""
        msg = {"op": "metrics"}
        with self._traced(msg):
            return self._idempotent(msg, timeout=timeout)["metrics"]

    def debug_dump(self, write=False):
        """The server's flight-recorder snapshot:
        ``{"ok", "events", "path"}`` with ``events`` the structured
        event dicts, oldest first. ``write=True`` also dumps them to a
        JSON file server-side; ``path`` is then its location (None
        otherwise)."""
        msg = {"op": "debug_dump", "write": bool(write)}
        if write:
            # the server-side file write is NOT idempotent: a retry
            # after a dropped reply would leave orphan dump files that
            # disagree about the incident window — one shot only
            return self._call(msg)
        return self._idempotent(msg)

    def health(self, timeout=_UNSET):
        """The server's lifecycle/liveness snapshot (state, queue
        depths, loop heartbeats + restarts, weights_version, kvpool
        occupancy when paged). ``timeout`` (seconds) overrides the
        client's socket default for this one call — the router's
        health probes pass ``FLAGS_router_probe_timeout_s`` so a hung
        replica (stalled accept loop included) fails the probe fast
        instead of inheriting the long execute-path default."""
        msg = {"op": "health"}
        with self._traced(msg):
            return self._idempotent(msg, timeout=timeout)["health"]

    def ping(self, timeout=_UNSET):
        return bool(self._idempotent({"op": "ping"},
                                     timeout=timeout).get("ok"))

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
