"""ServingEngine: saved inference model -> padded-batch executor with an
AOT executable cache.

Reuses the framework's lowering exactly as ``inference.AnalysisPredictor``
does (one XLA module per program), but compiles through an explicit
``jit.lower(...).compile()`` pipeline so the compiled executables live in
the serving ``ExecutableCache`` — byte/entry-capped, counted, recordable
— instead of jax's invisible internal cache. Model state (params) is
device-resident and shared by every executable; feeds are the only
per-call traffic.
"""
import os
import time

import numpy as np

from .batching import next_bucket
from .cache import ExecutableCache, feed_signature
from ..resilience import maybe_fail

SIGNATURE_FILE = "_serving_signatures.json"


class ServingEngine:
    """Loads a saved inference model once and executes padded batches.

    ``execute(requests)`` is the MicroBatcher flush target: concatenates
    request rows, pads to the power-of-two bucket, runs the cached
    executable for that signature (compiling on miss), splits the rows
    back per request and delivers results. Also usable stand-alone via
    ``run(feeds)`` for single-shot prediction.
    """

    def __init__(self, model_dir=None, *, program=None, scope=None,
                 feed_names=None, fetch_targets=None, model_filename=None,
                 params_filename=None, cache=None, stats=None):
        from ..framework.executor import Executor, Scope, scope_guard
        from ..framework.lowering import analyze_block_io, build_block_fn
        import jax

        if program is None:
            if model_dir is None:
                raise ValueError("ServingEngine needs model_dir= or a "
                                 "loaded program=")
            from .. import io as fluid_io
            scope = scope or Scope()
            with scope_guard(scope):
                program, feed_names, fetch_targets = \
                    fluid_io.load_inference_model(
                        model_dir, Executor(),
                        model_filename=model_filename,
                        params_filename=params_filename)
        self.model_dir = model_dir
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = [t.name if hasattr(t, "name") else str(t)
                            for t in fetch_targets]
        self.stats = stats

        state_in, _ = analyze_block_io(program, 0, list(self.feed_names))
        fn = build_block_fn(program, 0, list(self.feed_names),
                            list(self.fetch_names), state_in, [])
        key = jax.random.PRNGKey(0)

        def infer(state, feed):
            fetches, _, _ = fn({}, state, feed, key)
            return fetches

        self._infer = jax.jit(infer)
        self._state = {}
        for n in state_in:
            v = scope.find_var(n) if scope is not None else None
            if v is None:
                raise RuntimeError(
                    f"inference model state var {n!r} is not in the "
                    f"scope — load_inference_model must run first")
            self._state[n] = jax.device_put(np.asarray(v))
        self.cache = cache if cache is not None else ExecutableCache()
        gb = program.global_block()
        # batching across requests is only sound when every feed's
        # leading dim is dynamic (-1): a static-batch model is executed
        # request-by-request at its natural shape instead
        self.batchable = all(
            (gb.vars.get(n) is None
             or not getattr(gb.vars[n], "shape", None)
             or int(gb.vars[n].shape[0]) < 0)
            for n in self.feed_names)
        # which fetches are per-row, decided STATICALLY from the program
        # IR: a dynamic (-1) leading dim means the output scales with the
        # batch and is sliced back per request; anything else (scalar,
        # fixed-size table) is batch-global and replicated. None = shape
        # unknown in the IR, fall back to a runtime dim check.
        self._row_aligned = []
        for n in self.fetch_names:
            var = gb.vars.get(n)
            shape = getattr(var, "shape", None) if var is not None else None
            self._row_aligned.append(
                None if not shape else int(shape[0]) < 0)

    # -- compilation ------------------------------------------------------
    def _compile(self, feed):
        """AOT-compile the module for this feed signature and cache it."""
        from .. import profiler as _prof
        t0 = time.perf_counter()
        with _prof.record_event("serving/compile_inner"):
            lowered = self._infer.lower(self._state, feed)
            compiled = lowered.compile()
        dt = time.perf_counter() - t0
        nbytes = self._executable_bytes(compiled, feed)
        sig = feed_signature(feed)
        self.cache.put(sig, compiled, nbytes=nbytes)
        if self.stats:
            self.stats.bump("compiles")
            self.stats.hist["compile"].observe(dt)
        else:
            _prof.record_duration("serving/compile", dt)
        return compiled

    @staticmethod
    def _executable_bytes(compiled, feed):
        """Byte cost of a cache entry: XLA's own generated-code +
        temp-buffer sizes when the backend reports them, else the feed
        buffer size as a proportional lower bound."""
        try:
            ma = compiled.memory_analysis()
            n = int(getattr(ma, "generated_code_size_in_bytes", 0)
                    + getattr(ma, "temp_size_in_bytes", 0)
                    + getattr(ma, "output_size_in_bytes", 0))
            if n > 0:
                return n
        except Exception:  # noqa: BLE001 — backend-dependent surface
            pass
        return sum(a.nbytes for a in feed.values())

    def _executable_for(self, feed):
        sig = feed_signature(feed)
        compiled = self.cache.get(sig)
        if compiled is None:
            compiled = self._compile(feed)
        return compiled

    # -- single-shot ------------------------------------------------------
    def run(self, feeds):
        """Run one feed dict as-is (no cross-request batching, still
        cached): returns the fetch list as numpy arrays."""
        feed = {n: np.ascontiguousarray(feeds[n]) for n in self.feed_names}
        compiled = self._executable_for(feed)
        outs = compiled(self._state, feed)
        return [np.asarray(o) for o in outs]

    # -- batched path (MicroBatcher flush target) -------------------------
    def execute(self, requests):
        """Execute a same-signature group of requests as one padded
        batch. Delivers per-request results/errors; never raises for a
        single bad request (the batch-level failure path is handled by
        the MicroBatcher)."""
        maybe_fail("serving.execute")
        now = time.monotonic()
        live = [r for r in requests if not r.done()]
        if not live:
            return
        if not self.batchable:
            # static-batch model: request-by-request at natural shape
            for req in live:
                try:
                    outs = self.run(req.feeds)
                    if self.stats:
                        self.stats.observe_batch(req.rows, req.rows)
                        self.stats.bump("requests_completed")
                        self.stats.hist["total"].observe(
                            time.monotonic() - req.t_enqueue)
                    req.set_result(outs)
                except Exception as exc:  # noqa: BLE001
                    req.set_error(exc)
                    if self.stats:
                        self.stats.bump("requests_failed")
            return

        t_pad0 = time.perf_counter()
        total = sum(r.rows for r in live)
        bucket = next_bucket(total)
        feed = {}
        for name in self.feed_names:
            parts = [r.feeds[name] for r in live]
            arr = parts[0] if len(parts) == 1 else np.concatenate(parts)
            if bucket > total:
                pad = np.zeros((bucket - total,) + arr.shape[1:],
                               dtype=arr.dtype)
                arr = np.concatenate([arr, pad])
            feed[name] = np.ascontiguousarray(arr)
        t_pad = time.perf_counter() - t_pad0
        if self.stats:
            self.stats.hist["pad"].observe(t_pad)

        compiled = self._executable_for(feed)
        t_exec0 = time.perf_counter()
        outs = compiled(self._state, feed)
        outs = [np.asarray(o) for o in outs]
        t_exec = time.perf_counter() - t_exec0
        if self.stats:
            self.stats.hist["execute"].observe(t_exec)
            self.stats.observe_batch(total, bucket)

        off = 0
        done_t = time.monotonic()
        for req in live:
            res = []
            for o, aligned in zip(outs, self._row_aligned):
                if aligned is None:
                    aligned = bool(o.ndim) and o.shape[0] == bucket
                if aligned:
                    res.append(o[off:off + req.rows])
                else:
                    # batch-global output (scalar, fixed table): the
                    # full tensor is replicated to every request
                    res.append(o)
            off += req.rows
            req.set_result(res)
            if self.stats:
                self.stats.bump("requests_completed")
                self.stats.hist["total"].observe(done_t - req.t_enqueue)

    # -- warmup -----------------------------------------------------------
    def feed_specs(self, batch_size=None):
        """{name: (shape, dtype)} for warmup feeds; dynamic dims become
        ``batch_size`` (leading) / 1 (others). Prefers the save-time
        ``feed_specs`` record ``save_inference_model`` writes into
        ``__model__`` (attached as ``program._feed_specs`` on load);
        falls back to the program's feed vars for pre-upgrade saves."""
        from ..framework.dtype import np_dtype
        gb = self.program.global_block()
        recorded = getattr(self.program, "_feed_specs", None) or {}
        specs = {}
        for n in self.feed_names:
            rec = recorded.get(n)
            if rec and rec.get("shape"):
                shape = [int(d) for d in rec["shape"]]
                dt = np_dtype(rec.get("dtype") or "float32")
            else:
                var = gb.vars.get(n)
                shape = [int(d)
                         for d in getattr(var, "shape", None) or (1,)]
                dt = np_dtype(getattr(var, "dtype", "float32")
                              or "float32")
            for i, d in enumerate(shape):
                if d < 0:
                    shape[i] = int(batch_size or 1) if i == 0 else 1
            specs[n] = (tuple(shape), np.dtype(dt).name)
        return specs

    def warmup(self, batch_sizes=(1,), signature_file=None):
        """Precompile executables before taking traffic: one per bucket
        size in ``batch_sizes`` (from the model's feed specs), plus every
        signature in ``signature_file`` (a recorded-traffic file written
        by ``record_signatures``; missing file is not an error — warmup
        is best-effort by design). Returns the number of compiles."""
        sigs = []
        for b in batch_sizes or ():
            sigs.append(self.feed_specs(batch_size=next_bucket(b)))
        if signature_file:
            path = signature_file
            if path is True and self.model_dir:
                path = os.path.join(self.model_dir, SIGNATURE_FILE)
            if isinstance(path, str) and os.path.exists(path):
                sigs.extend(ExecutableCache.load_signatures(path))
        n = 0
        for spec in sigs:
            try:
                feed = {name: np.zeros(shape, dtype=dtype)
                        for name, (shape, dtype) in spec.items()}
                if feed_signature(feed) not in self.cache:
                    self._compile(feed)
                    n += 1
            except Exception as e:  # noqa: BLE001 — warmup is best-effort
                import warnings
                warnings.warn(f"serving warmup skipped signature {spec}: "
                              f"{type(e).__name__}: {e}", stacklevel=2)
        return n

    def record_signatures(self, path=None):
        """Persist the cache's observed signatures for next launch's
        warmup. Default path: ``<model_dir>/_serving_signatures.json``."""
        if path is None:
            if not self.model_dir:
                raise ValueError("record_signatures needs a path when the "
                                 "engine was not loaded from a model_dir")
            path = os.path.join(self.model_dir, SIGNATURE_FILE)
        self.cache.record(path)
        return path
