"""ServingEngine: saved inference model -> padded-batch executor with an
AOT executable cache.

Reuses the framework's lowering exactly as ``inference.AnalysisPredictor``
does (one XLA module per program), but compiles through an explicit
``jit.lower(...).compile()`` pipeline so the compiled executables live in
the serving ``ExecutableCache`` — byte/entry-capped, counted, recordable
— instead of jax's invisible internal cache. Model state (params) is
device-resident and shared by every executable; feeds are the only
per-call traffic.
"""
import json
import os
import time

import numpy as np

from .batching import next_bucket
from .cache import ExecutableCache, feed_signature
from .metrics import record_class_done
from ..flags import flag
from ..observability import tracing as _trace
from ..observability import utilization as _util
from ..resilience import (CheckpointCorruptError, maybe_fail,
                          run_with_watchdog)
from ..utils.lru import LRUCache

SIGNATURE_FILE = "_serving_signatures.json"


def load_param_snapshot(dirname, current):
    """Load + integrity-check new values for ``current``'s parameters
    from a ``save_params``-layout checkpoint dir (per-var ``.npy`` files
    + ``_manifest.json``) — the hot-weight-reload loader.

    Every file is verified against the manifest BEFORE anything is
    returned (CheckFreq-style atomic swap discipline: a corrupt or
    incomplete checkpoint raises :class:`CheckpointCorruptError` and the
    serving snapshot is never touched), and each array must match the
    live parameter's shape and dtype. Returns {name: host ndarray}.
    """
    from .. import io as fluid_io
    manifest = fluid_io._read_manifest(dirname)
    if manifest is None:
        raise CheckpointCorruptError(
            f"checkpoint dir {dirname!r} has no _manifest.json — "
            f"reload_weights only trusts manifest-verified checkpoints "
            f"(save with io.save_params / save_persistables)",
            path=dirname)
    meta = {"vars": {}}
    meta_path = os.path.join(dirname, fluid_io._META_FILE)
    if os.path.exists(meta_path):
        fluid_io._verify_against_manifest(dirname, fluid_io._META_FILE,
                                          manifest)
        with open(meta_path) as f:
            meta = json.load(f)
    out, missing = {}, []
    for name, cur in current.items():
        rel = fluid_io._escape(name) + ".npy"
        path = os.path.join(dirname, rel)
        if not os.path.exists(path):
            missing.append(name)
            continue
        fluid_io._verify_against_manifest(dirname, rel, manifest)
        try:
            arr = np.load(path, allow_pickle=False)
        except (OSError, ValueError) as e:
            raise CheckpointCorruptError(
                f"checkpoint file {rel!r} in {dirname!r} is unreadable: "
                f"{type(e).__name__}: {e}", path=path)
        tag = meta["vars"].get(name, {}).get("dtype", str(arr.dtype))
        arr = fluid_io._restore(arr, tag)
        cur_np = cur if hasattr(cur, "shape") else np.asarray(cur)
        if tuple(arr.shape) != tuple(cur_np.shape) \
                or str(arr.dtype) != str(np.dtype(cur_np.dtype)):
            raise ValueError(
                f"checkpoint param {name!r} is {arr.shape}/{arr.dtype}, "
                f"the serving snapshot holds "
                f"{tuple(cur_np.shape)}/{np.dtype(cur_np.dtype)} — "
                f"reload_weights only swaps like-for-like weights")
        out[name] = arr
    if missing:
        raise CheckpointCorruptError(
            f"checkpoint at {dirname!r} is missing {len(missing)} "
            f"serving parameter(s): {', '.join(sorted(missing))} — "
            f"the old snapshot was left untouched", path=dirname)
    return out


class ServingEngine:
    """Loads a saved inference model once and executes padded batches.

    ``execute(requests)`` is the MicroBatcher flush target: concatenates
    request rows, pads to the power-of-two bucket, runs the cached
    executable for that signature (compiling on miss), splits the rows
    back per request and delivers results. Also usable stand-alone via
    ``run(feeds)`` for single-shot prediction.
    """

    def __init__(self, model_dir=None, *, program=None, scope=None,
                 feed_names=None, fetch_targets=None, model_filename=None,
                 params_filename=None, cache=None, stats=None):
        from ..framework.executor import Executor, Scope, scope_guard
        from ..framework.lowering import analyze_block_io, build_block_fn
        import jax

        if program is None:
            if model_dir is None:
                raise ValueError("ServingEngine needs model_dir= or a "
                                 "loaded program=")
            from .. import io as fluid_io
            scope = scope or Scope()
            with scope_guard(scope):
                program, feed_names, fetch_targets = \
                    fluid_io.load_inference_model(
                        model_dir, Executor(),
                        model_filename=model_filename,
                        params_filename=params_filename)
        self.model_dir = model_dir
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = [t.name if hasattr(t, "name") else str(t)
                            for t in fetch_targets]
        self.stats = stats

        state_in, _ = analyze_block_io(program, 0, list(self.feed_names))
        fn = build_block_fn(program, 0, list(self.feed_names),
                            list(self.fetch_names), state_in, [])
        key = jax.random.PRNGKey(0)

        def infer(state, feed):
            fetches, _, _ = fn({}, state, feed, key)
            return fetches

        self._infer = jax.jit(infer)
        self._state = {}
        for n in state_in:
            v = scope.find_var(n) if scope is not None else None
            if v is None:
                raise RuntimeError(
                    f"inference model state var {n!r} is not in the "
                    f"scope — load_inference_model must run first")
            self._state[n] = jax.device_put(np.asarray(v))
        self.cache = cache if cache is not None else ExecutableCache()
        # feed signature -> cost_analysis dict|False (LRU: misses for
        # still-cached executables recompute via _util.cost_for)
        self._costs = LRUCache(max_entries=256)
        gb = program.global_block()
        # batching across requests is only sound when every feed's
        # leading dim is dynamic (-1): a static-batch model is executed
        # request-by-request at its natural shape instead
        self.batchable = all(
            (gb.vars.get(n) is None
             or not getattr(gb.vars[n], "shape", None)
             or int(gb.vars[n].shape[0]) < 0)
            for n in self.feed_names)
        # which fetches are per-row, decided STATICALLY from the program
        # IR: a dynamic (-1) leading dim means the output scales with the
        # batch and is sliced back per request; anything else (scalar,
        # fixed-size table) is batch-global and replicated. None = shape
        # unknown in the IR, fall back to a runtime dim check.
        self._row_aligned = []
        for n in self.fetch_names:
            var = gb.vars.get(n)
            shape = getattr(var, "shape", None) if var is not None else None
            self._row_aligned.append(
                None if not shape else int(shape[0]) < 0)

    # -- compilation ------------------------------------------------------
    def _compile(self, feed):
        """AOT-compile the module for this feed signature and cache it."""
        from .. import profiler as _prof
        maybe_fail("serving.compile")
        t0 = time.perf_counter()
        with _prof.record_event("serving/compile_inner"):
            lowered = self._infer.lower(self._state, feed)
            compiled = lowered.compile()
        dt = time.perf_counter() - t0
        nbytes = self._executable_bytes(compiled, feed)
        sig = feed_signature(feed)
        self.cache.put(sig, compiled, nbytes=nbytes)
        # cost_analysis read once per executable: the live MFU/HBM
        # gauges attach it to every later execute() timing
        cost = _util.cost_for(self._costs, sig, compiled)
        # sharding audit + collective ledger on newly compiled serving
        # executables (flag-gated shared front door, mesh runs only —
        # the tensor-parallel serving PR this instruments)
        from ..observability.sharding import maybe_observe
        from ..parallel.mesh import get_mesh
        maybe_observe("infer", compiled, get_mesh(),
                      program=self.program,
                      feed_names=self.feed_names, cost=cost,
                      tag="serving_infer")
        if self.stats:
            self.stats.bump("compiles")
            self.stats.hist["compile"].observe(dt)
        else:
            _prof.record_duration("serving/compile", dt)
        return compiled

    @staticmethod
    def _executable_bytes(compiled, feed):
        """Byte cost of a cache entry: XLA's own generated-code +
        temp-buffer sizes when the backend reports them, else the feed
        buffer size as a proportional lower bound."""
        try:
            ma = compiled.memory_analysis()
            n = int(getattr(ma, "generated_code_size_in_bytes", 0)
                    + getattr(ma, "temp_size_in_bytes", 0)
                    + getattr(ma, "output_size_in_bytes", 0))
            if n > 0:
                return n
        except Exception:  # noqa: BLE001 — backend-dependent surface
            pass
        return sum(a.nbytes for a in feed.values())

    def _executable_for(self, feed):
        """(signature, executable, compile_seconds) for ``feed`` —
        ``compile_seconds`` is None on a cache hit, so callers can
        attribute a compile span without re-implementing the miss
        path."""
        sig = feed_signature(feed)
        compiled = self.cache.get(sig)
        if compiled is None:
            t0 = time.perf_counter()
            compiled = self._compile(feed)
            return sig, compiled, time.perf_counter() - t0
        return sig, compiled, None

    # -- hot weight reload ------------------------------------------------
    def load_state_snapshot(self, dirname):
        """Verify + load a new device snapshot of every model state var
        from a manifest-carrying checkpoint dir. Raises
        CheckpointCorruptError / ValueError without touching the live
        snapshot; the result is ready for :meth:`swap_state`."""
        import jax
        host = load_param_snapshot(dirname, self._state)
        return {n: jax.device_put(a) for n, a in host.items()}

    def swap_state(self, new_state):
        """Atomically swap the device param snapshot between
        micro-batches: ``execute``/``run`` capture ``self._state`` once
        at entry, so an in-flight batch finishes on the old weights and
        every later batch reads the new ones."""
        missing = [n for n in self._state if n not in new_state]
        if missing:
            raise ValueError(f"swap_state snapshot is missing state "
                             f"vars: {sorted(missing)}")
        self._state = {n: new_state[n] for n in self._state}

    # -- single-shot ------------------------------------------------------
    def run(self, feeds):
        """Run one feed dict as-is (no cross-request batching, still
        cached): returns the fetch list as numpy arrays."""
        state = self._state          # one snapshot for the whole call
        feed = {n: np.ascontiguousarray(feeds[n]) for n in self.feed_names}
        _sig, compiled, _dt = self._executable_for(feed)
        outs = compiled(state, feed)
        return [np.asarray(o) for o in outs]

    # -- batched path (MicroBatcher flush target) -------------------------
    def execute(self, requests):
        """Execute a same-signature group of requests as one padded
        batch. Delivers per-request results/errors; never raises for a
        single bad request (the batch-level failure path is handled by
        the MicroBatcher)."""
        maybe_fail("serving.execute")
        state = self._state          # one snapshot for the whole batch:
        now = time.monotonic()       # a reload swaps BETWEEN batches
        live = [r for r in requests if not r.done()]
        if not live:
            return
        if not self.batchable:
            # static-batch model: request-by-request at natural shape
            for req in live:
                try:
                    outs = self.run(req.feeds)
                    if self.stats:
                        self.stats.observe_batch(req.rows, req.rows)
                        self.stats.bump("requests_completed")
                        self.stats.hist["total"].observe(
                            time.monotonic() - req.t_enqueue)
                    req.set_result(outs)
                    record_class_done(req.priority,
                                      time.monotonic() - req.t_enqueue)
                except Exception as exc:  # noqa: BLE001
                    req.set_error(exc)
                    if self.stats:
                        self.stats.bump("requests_failed")
            return

        t_pad0 = time.perf_counter()
        total = sum(r.rows for r in live)
        bucket = next_bucket(total)
        feed = {}
        for name in self.feed_names:
            parts = [r.feeds[name] for r in live]
            arr = parts[0] if len(parts) == 1 else np.concatenate(parts)
            if bucket > total:
                pad = np.zeros((bucket - total,) + arr.shape[1:],
                               dtype=arr.dtype)
                arr = np.concatenate([arr, pad])
            feed[name] = np.ascontiguousarray(arr)
        t_pad = time.perf_counter() - t_pad0
        if self.stats:
            self.stats.hist["pad"].observe(t_pad)
        traced = [r for r in live if r.trace is not None]
        for req in traced:
            _trace.record_child("serving/pad", t_pad0, t_pad0 + t_pad,
                                req.trace)

        sig, compiled, compile_s = self._executable_for(feed)
        if compile_s is not None:
            t_c1 = time.perf_counter()
            for req in traced:
                _trace.record_child("serving/compile", t_c1 - compile_s,
                                    t_c1, req.trace)
        t_exec0 = time.perf_counter()
        outs = compiled(state, feed)
        outs = [np.asarray(o) for o in outs]
        t_exec = time.perf_counter() - t_exec0
        for req in traced:
            _trace.record_child("serving/execute", t_exec0,
                                t_exec0 + t_exec, req.trace)
        cost = _util.cost_for(self._costs, sig, compiled)
        if cost:
            _util.observe_execution("infer", cost, t_exec)
        if self.stats:
            self.stats.hist["execute"].observe(t_exec)
            self.stats.observe_batch(total, bucket)

        off = 0
        done_t = time.monotonic()
        for req in live:
            res = []
            for o, aligned in zip(outs, self._row_aligned):
                if aligned is None:
                    aligned = bool(o.ndim) and o.shape[0] == bucket
                if aligned:
                    res.append(o[off:off + req.rows])
                else:
                    # batch-global output (scalar, fixed table): the
                    # full tensor is replicated to every request
                    res.append(o)
            off += req.rows
            req.set_result(res)
            record_class_done(req.priority, done_t - req.t_enqueue)
            if self.stats:
                self.stats.bump("requests_completed")
                self.stats.hist["total"].observe(done_t - req.t_enqueue)

    # -- warmup -----------------------------------------------------------
    def feed_specs(self, batch_size=None):
        """{name: (shape, dtype)} for warmup feeds; dynamic dims become
        ``batch_size`` (leading) / 1 (others). Prefers the save-time
        ``feed_specs`` record ``save_inference_model`` writes into
        ``__model__`` (attached as ``program._feed_specs`` on load);
        falls back to the program's feed vars for pre-upgrade saves."""
        from ..framework.dtype import np_dtype
        gb = self.program.global_block()
        recorded = getattr(self.program, "_feed_specs", None) or {}
        specs = {}
        for n in self.feed_names:
            rec = recorded.get(n)
            if rec and rec.get("shape"):
                shape = [int(d) for d in rec["shape"]]
                dt = np_dtype(rec.get("dtype") or "float32")
            else:
                var = gb.vars.get(n)
                shape = [int(d)
                         for d in getattr(var, "shape", None) or (1,)]
                dt = np_dtype(getattr(var, "dtype", "float32")
                              or "float32")
            for i, d in enumerate(shape):
                if d < 0:
                    shape[i] = int(batch_size or 1) if i == 0 else 1
            specs[n] = (tuple(shape), np.dtype(dt).name)
        return specs

    def warmup(self, batch_sizes=(1,), signature_file=None):
        """Precompile executables before taking traffic: one per bucket
        size in ``batch_sizes`` (from the model's feed specs), plus every
        signature in ``signature_file`` (a recorded-traffic file written
        by ``record_signatures``; missing file is not an error — warmup
        is best-effort by design). Returns the number of compiles."""
        sigs = []
        for b in batch_sizes or ():
            sigs.append(self.feed_specs(batch_size=next_bucket(b)))
        if signature_file:
            path = signature_file
            if path is True and self.model_dir:
                path = os.path.join(self.model_dir, SIGNATURE_FILE)
            if isinstance(path, str) and os.path.exists(path):
                sigs.extend(ExecutableCache.load_signatures(path))
        n = 0
        for spec in sigs:
            try:
                feed = {name: np.zeros(shape, dtype=dtype)
                        for name, (shape, dtype) in spec.items()}
                if feed_signature(feed) not in self.cache:
                    self._compile(feed)
                    n += 1
            except Exception as e:  # noqa: BLE001 — warmup is best-effort
                import warnings
                warnings.warn(f"serving warmup skipped signature {spec}: "
                              f"{type(e).__name__}: {e}", stacklevel=2)
        return n

    def record_signatures(self, path=None):
        """Persist the cache's observed signatures for next launch's
        warmup. Default path: ``<model_dir>/_serving_signatures.json``."""
        if path is None:
            if not self.model_dir:
                raise ValueError("record_signatures needs a path when the "
                                 "engine was not loaded from a model_dir")
            path = os.path.join(self.model_dir, SIGNATURE_FILE)
        self.cache.record(path)
        return path


class GenerationEngine:
    """Slot-batched autoregressive decoding primitives for the serving
    runtime, over a ``models.generation.GPTGenerator``.

    The engine owns a fixed bank of ``slots`` generation rows whose KV
    caches live on the device as ONE ``[slots, H, max_len, D]`` buffer
    per layer, stepped by a single compiled decode executable
    (``FLAGS_decode_slots``). The ``DecodeBatcher`` drives it:

    - ``admit(requests, slot_ids)``: bucketed prefill over the new
      prompts, per-row sampling of their first tokens, and a jitted
      scatter of the fresh row caches into the slot bank (slot reuse —
      a finished row's stale cache is simply overwritten).
    - ``step(tokens, pos, temperature, top_k)``: one decode + sample
      over the whole bank; rows at different positions (and with
      different sampling configs) share the executable.

    All methods are single-caller by design — the DecodeBatcher thread
    is the only driver (the chip is the bottleneck resource; concurrency
    lives in the connection threads, exactly like the infer path).
    """

    def __init__(self, generator, *, slots=None, stats=None, seed=0,
                 paged=None, kv_dtype=None, kv_block_size=None,
                 kv_pool_blocks=None, pool_name="serving",
                 prefix_cache=None):
        import jax
        self.gen = generator
        self.slots = int(slots or flag("decode_slots"))
        self.stats = stats if stats is not None else generator.stats
        # block-paged decode memory (FLAGS_kv_paged / paged=True): the
        # slot bank becomes a shared KVBlockPool with per-slot block
        # tables — concurrency bounded by actual tokens, not
        # slots * max_len. None/False keeps the dense bank (the parity
        # baseline). ``pool_name`` labels the pool's kvpool_* gauge
        # series — fleet replicas sharing one process must not clobber
        # each other's occupancy. ``prefix_cache`` (None ->
        # FLAGS_kv_prefix_cache) turns on block-granular prompt-prefix
        # reuse across requests.
        self.paged = bool(flag("kv_paged") if paged is None else paged)
        self.pool = None
        if self.paged:
            from .kvpool import KVBlockPool
            cfg = generator.cfg
            self.pool = KVBlockPool(
                slots=self.slots, num_layers=cfg.num_layers,
                num_heads=cfg.num_heads,
                d_head=cfg.hidden_size // cfg.num_heads,
                max_seq_len=generator.max_len,
                block_size=kv_block_size, num_blocks=kv_pool_blocks,
                dtype=kv_dtype, name=pool_name,
                prefix_cache=prefix_cache)
            if getattr(generator, "mesh", None) is not None:
                # tensor-parallel serving: the pool's block arrays live
                # sharded on the head axis of the generator's tp mesh
                generator.apply_pool_sharding(self.pool)
        # a generator WITHOUT its own sink adopts the server's (stage
        # histograms land in server.stats()), and a sink a PREVIOUS
        # engine bound is rebound to the live server (else a reused
        # generator reports into a dead server's sink). A sink the USER
        # set stays put — rebinding it would make unrelated offline
        # generate() calls pollute the served-traffic counters.
        if generator.stats is None or getattr(generator,
                                              "_stats_adopted", False):
            generator.stats = self.stats
            generator._stats_adopted = True
        self.max_len = generator.max_len
        self._key = jax.random.PRNGKey(int(seed))
        self._caches = None        # lazy: zeros [slots, H, L, D] per layer
        self._insert_fn = None
        self.bank_lost = False     # see _drop_bank

    def _ensure_caches(self):
        self.bank_lost = False
        if self.pool is not None:
            self.pool.arrays()       # lazy device-side pool build
            return
        if self._caches is not None:
            return
        import jax.numpy as jnp
        cfg = self.gen.cfg
        d_head = cfg.hidden_size // cfg.num_heads
        shape = (self.slots, cfg.num_heads, self.max_len, d_head)
        self._caches = {}
        for i in range(cfg.num_layers):
            self._caches[f"cache_k_{i}"] = jnp.zeros(shape, jnp.float32)
            self._caches[f"cache_v_{i}"] = jnp.zeros(shape, jnp.float32)

    def _insert(self, row_caches, slot_ids):
        """Scatter freshly prefilled row caches into the slot bank (one
        jitted executable; jax's shape cache handles the (n, bucket)
        universe)."""
        import jax
        import jax.numpy as jnp
        maybe_fail("serving.slot_insert")
        if self._insert_fn is None:
            def ins(dst, src, idx):
                return {name: dst[name].at[idx].set(src[name][:idx.shape[0]])
                        for name in dst}
            self._insert_fn = jax.jit(ins, donate_argnums=(0,))
        idx = jnp.asarray(slot_ids, jnp.int32)
        try:
            self._caches = self._insert_fn(self._caches, row_caches, idx)
        except Exception:
            self._drop_bank()
            raise

    def _drop_bank(self):
        """A failed donated call may have invalidated the slot bank's
        buffers: drop it (the next admission rebuilds zeros) and flag
        the loss so the DecodeBatcher fails every active row instead of
        letting them silently decode against a fresh zero cache. Paged
        mode drops the pool's DEVICE arrays only — the host block
        accounting survives, and the failed rows return their blocks
        through the batcher's release path."""
        self._caches = None
        if self.pool is not None:
            self.pool.drop_device()
        self.bank_lost = True

    def reset(self):
        """Forget the slot bank without flagging a loss — the restart
        path: a replaced decode loop starts from an empty bank (its rows
        were already failed by the supervisor), so the stale caches are
        garbage, not state. Paged mode frees every block too."""
        self._caches = None
        if self.pool is not None:
            self.pool.reset()
        self.bank_lost = False

    # -- paged-pool admission / lifecycle hooks ---------------------------
    def admission_check(self, prompt_len, max_new_tokens,
                        pending_tokens=(), static_only=False):
        """Typed admission gate, callable BEFORE any queue wait or
        prefill compile: an overlong request raises
        :class:`batching.BadRequestError` (the wire maps it to
        ``etype: "BadRequest"`` — retrying without fixing the input
        cannot help), and in paged mode so does a request the pool
        could NEVER hold even empty; a request whose prompt blocks are
        merely not free RIGHT NOW (unless ``static_only``) raises the
        retryable :class:`kvpool.KVPoolExhaustedError` instead,
        counting requests already accepted this admission round via
        ``pending_tokens`` (their prompt lengths)."""
        from .batching import BadRequestError
        prompt_len, max_new_tokens = int(prompt_len), int(max_new_tokens)
        if prompt_len + max_new_tokens > self.max_len:
            raise BadRequestError(
                f"prompt ({prompt_len} tokens) + max_new_tokens "
                f"({max_new_tokens}) exceeds the decode cache length "
                f"{self.max_len}")
        if self.pool is not None:
            self.pool.check_fits(prompt_len + max_new_tokens)
            if not static_only:
                # +1: the first decode append may open a fresh block
                self.pool.admission_check(
                    prompt_len + 1, [int(t) + 1 for t in pending_tokens])

    def release_slot(self, slot):
        """Return a finished slot's KV blocks to the pool (EOS /
        deadline / cancel / error — the continuous-batching reclaim).
        Dense mode: no-op (the bank row is simply overwritten)."""
        if self.pool is not None:
            self.pool.free_slot(slot)

    def prepare_step(self, active_pos, widths=None):
        """Allocation-on-append before a decode step: grow each live
        row's blocks to cover the slot its next token writes
        (``active_pos`` maps slot -> position). ``widths`` (slot ->
        token count, default 1 everywhere) covers a speculative verify
        span instead: the row writes ``[pos, pos + width)`` in one
        step, so allocation AND the COW barrier extend over the whole
        span — a shared prefix block must be duplicated BEFORE the
        speculative write lands, even for draft positions that may be
        rejected. Returns ``{slot: exc}`` for rows the pool could not
        grow — the batcher sheds exactly those rows (typed) while the
        rest of the bank keeps decoding. Dense mode returns ``{}``."""
        if self.pool is None:
            return {}
        shed = {}
        for slot, p in active_pos.items():
            w = max(int(widths.get(slot, 1)) if widths else 1, 1)
            try:
                self.pool.ensure(slot, int(p) + w - 1)
                if self.pool.prefix_enabled:
                    # COW barrier: any block this span lands in may be
                    # co-owned by the prefix cache (or another slot
                    # that adopted it) — duplicate before writing
                    self.pool.prepare_write(slot, int(p), int(p) + w)
            except Exception as exc:  # noqa: BLE001 — per-row shed
                shed[slot] = exc
        return shed

    def reclaim_leaks(self, live_slots):
        """Leak sweep: free blocks held by slots not in ``live_slots``
        (flight-recorded per leaking slot). Dense mode: 0."""
        if self.pool is None:
            return 0
        return self.pool.reclaim_leaks(live_slots)

    # -- hot weight reload ------------------------------------------------
    def load_param_snapshot(self, dirname):
        """Verify + load new HOST values for every generator parameter
        (building the parameter-bearing programs first if no traffic
        has). Raises without touching the live snapshot."""
        for kind in ("prefill", "decode", "logits"):
            self.gen._ensure_fn(kind)
        return load_param_snapshot(dirname, self.gen._params)

    def stage_params(self, host_params):
        """Device-put the verified host arrays — run OFF the decode loop
        so the swap itself (apply_params) is a dict rebind, not a
        transfer."""
        import jax
        return {n: jax.device_put(a) for n, a in host_params.items()}

    def apply_params(self, device_params):
        """The atomic swap half: rebind the generator's parameter
        snapshot. Scheduled between decode steps via
        DecodeBatcher.request_swap so in-flight generations finish on
        the old weights."""
        self.gen.swap_params(device_params)

    def admit(self, requests, slot_ids):
        """Prefill the new requests' prompts (one bucketed batch), sample
        their first tokens, write their caches into ``slot_ids``.
        Returns the first tokens as np int32 [len(requests)]."""
        maybe_fail("serving.prefill")
        self._ensure_caches()
        t0 = time.perf_counter()
        n = len(requests)
        tokens, pos_ids, last = self.gen._pack_prompts(
            [req.prompt for req in requests])
        bb = tokens.shape[0]
        temp = np.zeros((bb,), np.float32)
        topk = np.zeros((bb,), np.int32)
        for r, req in enumerate(requests):
            temp[r] = req.temperature
            topk[r] = req.top_k

        if self.pool is not None:
            # allocate each row's prompt blocks BEFORE the prefill (the
            # scatter routes through the tables); a mid-batch failure
            # rolls this batch's allocations back untouched
            allocated = []
            try:
                for req, slot in zip(requests, slot_ids):
                    self.pool.free_slot(slot)   # stale holder (if any)
                    self.pool.alloc(slot, int(req.prompt.size))
                    allocated.append(slot)
            except Exception:
                for sl in allocated:
                    self.pool.free_slot(sl)
                raise
        logits, row_caches, self._key = self.gen._run_prefill(
            tokens, pos_ids, last, self._key)
        toks, self._key = self.gen._run_sample(logits, temp, topk,
                                               self._key)
        if self.pool is not None:
            try:
                self.pool.scatter_prefill(list(slot_ids), row_caches,
                                          tokens.shape[1])
            except Exception:
                # the donated device pool is lost (scatter dropped it);
                # this batch's blocks go back, the batcher fails the
                # other active rows via bank_lost
                for sl in slot_ids:
                    self.pool.free_slot(sl)
                self.bank_lost = True
                raise
        else:
            self._insert(row_caches, list(slot_ids))
        if self.pool is not None and self.pool.prefix_enabled:
            # deposit the freshly prefilled prompt blocks into the
            # prefix index (refcounted co-ownership — they outlive the
            # slot's EOS until evicted LRU); later requests sharing the
            # prompt prefix adopt them instead of recomputing
            for req, slot in zip(requests, slot_ids):
                self.pool.prefix_insert(req.prompt, slot)
        out = np.asarray(toks)[:n]
        t1 = time.perf_counter()
        for req in requests:
            if getattr(req, "trace", None) is not None:
                _trace.record_child("serving/prefill", t0, t1, req.trace)
        return out

    # -- chunked (incremental) prefill ------------------------------------
    def incremental_prefill_enabled(self):
        """Chunked prompt ingestion (Orca/Sarathi-style): on when the
        paged pool exists AND either ``FLAGS_prefill_chunk_tokens``
        bounds the per-round prompt slice (long prompts stop stalling
        the decode bank's token cadence) or the prefix cache is on (the
        incremental path is what turns a cached-prefix hit into skipped
        prefill compute)."""
        return self.pool is not None and (
            int(flag("prefill_chunk_tokens")) > 0
            or self.pool.prefix_enabled)

    def start_prefill(self, req, slot):
        """Begin incremental prefill of ``req`` into ``slot``: reclaim
        the stale holder, adopt the longest cached prompt prefix (block
        references only — no compute), and return the prefill state the
        batcher advances one :meth:`prefill_chunk` per decode round. A
        FULL exact-prompt hit still replays the final token as a
        1-token chunk (COWing the shared tail block): that chunk's
        logits ARE the first-token distribution, so a repeat prompt
        pays one token of prefill instead of the whole prompt."""
        self._ensure_caches()
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        L = int(prompt.size)
        self.pool.free_slot(slot)       # stale holder (if any)
        reused = 0
        if self.pool.prefix_enabled:
            m = self.pool.match_prefix(prompt)
            if m is not None:
                self.pool.adopt_prefix(slot, m)
                reused = int(m["tokens"])
        return {"req": req, "slot": int(slot), "prompt": prompt,
                "next": min(reused, L - 1), "reused": reused,
                "chunk": int(flag("prefill_chunk_tokens")),
                "first_logits": None, "t0": time.perf_counter()}

    def prefill_chunk(self, state):
        """Ingest ONE chunk of ``state``'s prompt into its slot's
        blocks (at most the chunk budget; everything left when only the
        prefix cache turned the incremental path on). Typed pool
        pressure (alloc/COW) raises BEFORE any device call — the slot's
        accounting is intact and the batcher sheds just this row; a
        failure of the chunk executable itself loses the donated pool
        arrays, so the slot is released and ``bank_lost`` set, exactly
        like a failed monolithic scatter. Returns True when the prompt
        is fully ingested (sample via :meth:`finish_prefill`)."""
        slot, prompt = state["slot"], state["prompt"]
        L = int(prompt.size)
        s = int(state["next"])
        take = min(state["chunk"] or (L - s), L - s)
        # fixed chunk width under a budget, bucketed width otherwise —
        # either way a bounded universe of compiled chunk shapes
        C = state["chunk"] or min(
            next_bucket(take, min_bucket=self.gen.bucket_min),
            self.max_len)
        toks = np.zeros((1, C), np.int32)
        toks[0, :take] = prompt[s:s + take]
        pos_ids = np.clip(np.arange(s, s + C, dtype=np.int32),
                          0, L - 1)[None, :]
        self.pool.alloc(slot, s + take)
        if self.pool.prefix_enabled:
            self.pool.prepare_write(slot, s, s + take)
        try:
            logits, self._key = self.gen._run_prefill_chunk(
                toks, pos_ids, np.array([s], np.int32),
                np.array([take], np.int32),
                np.array([take - 1], np.int32), self.pool, self._key,
                rows=[slot])
        except Exception:
            # the donated device pool is lost; this row's blocks go
            # back, the batcher fails the other active rows via
            # bank_lost
            self.pool.free_slot(slot)
            self.bank_lost = True
            raise
        state["next"] = s + take
        if state["next"] >= L:
            state["first_logits"] = np.asarray(logits)[:1]
            return True
        return False

    def finish_prefill(self, state):
        """Sample the first token from the final chunk's logits, deposit
        the now-complete prompt blocks into the prefix index, and return
        the token (int). The per-request analogue of :meth:`admit`'s
        tail."""
        req, slot = state["req"], state["slot"]
        temp = np.array([req.temperature], np.float32)
        topk = np.array([req.top_k], np.int32)
        toks, self._key = self.gen._run_sample(
            state["first_logits"], temp, topk, self._key)
        if self.pool.prefix_enabled:
            self.pool.prefix_insert(state["prompt"], slot)
        if getattr(req, "trace", None) is not None:
            _trace.record_child("serving/prefill_chunked", state["t0"],
                                time.perf_counter(), req.trace)
        return int(np.asarray(toks)[0])

    # -- disaggregated prefill/decode (KV-block migration) ----------------
    def export_slot(self, slot):
        """Serialize ``slot``'s KV blocks for cross-replica migration
        (the prefill half of the disaggregated split). Paged mode only:
        the block table is what makes in-flight KV state a well-defined,
        movable unit — the dense bank has no such boundary."""
        from .batching import BadRequestError
        if self.pool is None:
            raise BadRequestError(
                "KV export requires the paged pool (FLAGS_kv_paged / "
                "paged=True) — the dense bank's rows are not migratable")
        return self.pool.export_slot(slot)

    def admit_imported(self, requests, slot_ids):
        """Admit requests whose prefill ran on ANOTHER replica: stream
        each request's ``kv`` payload into its slot's blocks instead of
        running a prefill. Mirrors :meth:`admit`'s contract — returns
        the first tokens (carried in the payloads, sampled prefill-side)
        as np int32 [len(requests)]; on failure nothing stays allocated
        and a donated-array loss flags ``bank_lost``."""
        from .batching import BadRequestError
        if self.pool is None:
            raise BadRequestError(
                "KV import requires the paged pool (FLAGS_kv_paged / "
                "paged=True) on the decode replica")
        self._ensure_caches()
        t0 = time.perf_counter()
        imported = []
        try:
            for req, slot in zip(requests, slot_ids):
                self.pool.free_slot(slot)     # stale holder (if any)
                self.pool.import_slot(slot, req.kv)
                imported.append(slot)
        except Exception:
            for sl in imported:
                self.pool.free_slot(sl)
            # a scatter failure dropped the donated device arrays
            # (import_slot already forgot them); the other active rows'
            # caches died with them
            if self.pool._arrays is None:
                self.bank_lost = True
            raise
        t1 = time.perf_counter()
        first = np.asarray([int(req.first_token) for req in requests],
                           np.int32)
        for req in requests:
            if getattr(req, "trace", None) is not None:
                _trace.record_child("serving/kv_import", t0, t1,
                                    req.trace)
            # the device pool owns the blocks now: drop the host-side
            # payload — the server's rid-dedup table retains completed
            # request objects, and a pinned multi-MB payload per entry
            # would accumulate into real host-memory growth
            req.kv = None
        return first

    def step(self, tokens, pos, temperature, top_k, budget=None):
        """One decode + sample over the whole slot bank. ``tokens``/
        ``pos``/``temperature``/``top_k`` are np arrays of length
        ``slots`` (free slots carry harmless stale values — their rows
        are never read). Returns sampled np int32 tokens [slots].

        ``budget`` (seconds) runs the decode call under
        ``resilience.run_with_watchdog``: a hung chip call raises
        WatchdogTimeout instead of wedging the decode loop. The worker
        only COMPUTES — state (caches, RNG key) is assigned on this
        thread after it returns, so an abandoned overbudget worker can
        never resurrect a bank this thread already dropped."""
        maybe_fail("serving.decode_step")
        self._ensure_caches()
        tok = np.ascontiguousarray(tokens, dtype=np.int32)
        posc = np.ascontiguousarray(pos, dtype=np.int32)
        key = self._key

        if self.pool is not None:
            # paged decode: the worker only COMPUTES (feed built here,
            # pool state adopted on this thread after it returns), so an
            # abandoned overbudget worker can never resurrect a pool
            # this thread already dropped — mirroring the dense path
            from .kvpool import adopt_decode_fetches, decode_feed
            feed = decode_feed(self.pool, tok, posc)
            kind = f"decode_paged_{self.pool.dtype}"

            def _decode_paged():
                return self.gen._invoke(kind, "decode", feed, key)

            try:
                if budget:
                    fetches, new_key = run_with_watchdog(
                        _decode_paged, budget,
                        what="serving decode step")
                else:
                    fetches, new_key = _decode_paged()
            except Exception:
                self._drop_bank()  # pool arrays were donated in
                raise
            logits = adopt_decode_fetches(self.pool, fetches)
            self._key = new_key
        else:
            caches = self._caches

            def _decode():
                return self.gen._run_decode(tok, posc, caches, key)

            try:
                if budget:
                    logits, new_caches, new_key = run_with_watchdog(
                        _decode, budget, what="serving decode step")
                else:
                    logits, new_caches, new_key = _decode()
            except Exception:
                self._drop_bank()  # caches were donated into the call
                raise
            self._caches, self._key = new_caches, new_key
        toks, self._key = self.gen._run_sample(
            logits, np.ascontiguousarray(temperature, dtype=np.float32),
            np.ascontiguousarray(top_k, dtype=np.int32), self._key)
        return np.asarray(toks)

    def spec_step(self, tokens, pos, temperature, top_k, drafts,
                  num_draft, live, budget=None):
        """One speculative verify + accept step over the whole slot
        bank (paged pool only — the dense bank's fixed-span cache write
        clamps near the row end, so the batcher never routes it here).

        ``drafts`` is np int32 [slots, K] (drafter proposals per row),
        ``num_draft`` np int32 [slots] counts the real drafts per row
        (0 = the row takes a plain 1-token step through the same
        verify executable), ``live`` marks occupied slots — free rows
        get ``limit`` 0 so every one of their span writes routes to the
        pool's trash block. Returns ``(out [slots, K+1], accepted
        [slots])``: row ``s`` emits ``out[s, :accepted[s] + 1]`` tokens
        (accepted drafts, then the correction/bonus token), all drawn
        from the target distribution by rejection sampling.

        Same watchdog discipline as :meth:`step`: the worker only
        computes; pool adoption and key assignment happen on this
        thread after it returns."""
        if self.pool is None:
            raise ValueError(
                "speculative decoding requires the paged KV pool "
                "(FLAGS_kv_paged / paged=True) — the dense bank has no "
                "trash-routed multi-token write")
        maybe_fail("serving.decode_step")
        self._ensure_caches()
        tok = np.ascontiguousarray(tokens, dtype=np.int32)
        posc = np.ascontiguousarray(pos, dtype=np.int32)
        drafts = np.ascontiguousarray(drafts, dtype=np.int32)
        nd = np.ascontiguousarray(num_draft, dtype=np.int32)
        S = drafts.shape[1] + 1
        cfg = self.gen.cfg
        feed = dict(self.pool.arrays())
        feed["tokens"] = np.concatenate([tok[:, None], drafts], axis=1)
        feed["pos_ids"] = np.clip(
            posc[:, None] + np.arange(S, dtype=np.int32)[None, :],
            0, cfg.max_position - 1)
        feed["start_pos"] = posc
        feed["limit"] = np.where(np.asarray(live, bool), nd + 1,
                                 0).astype(np.int32)
        feed["block_tables"] = np.ascontiguousarray(self.pool.tables)
        kind = f"verify_paged_{self.pool.dtype}"
        key = self._key

        def _verify():
            return self.gen._invoke(kind, "decode", feed, key)

        try:
            if budget:
                fetches, new_key = run_with_watchdog(
                    _verify, budget, what="serving spec verify step")
            else:
                fetches, new_key = _verify()
        except Exception:
            self._drop_bank()  # pool arrays were donated in
            raise
        from .kvpool import adopt_decode_fetches
        logits = adopt_decode_fetches(self.pool, fetches)
        self._key = new_key
        out, acc, self._key = self.gen._run_spec_accept(
            logits, drafts,
            np.ascontiguousarray(temperature, dtype=np.float32),
            np.ascontiguousarray(top_k, dtype=np.int32), nd, self._key)
        return np.asarray(out), np.asarray(acc)
