"""Block-paged KV-cache pool: decode memory priced by ACTUAL tokens.

The dense decode bank (``GenerationEngine``'s ``[slots, H, max_len, D]``
buffer per layer) charges every slot ``max_len`` HBM whatever its real
length — BENCHMARKS.md shows decode is bandwidth-bound against exactly
that buffer. This module is the vLLM/PagedAttention alternative (Kwon et
al. 2023): one device-resident pool of fixed-size blocks
(``[num_blocks, H, block_size, D]`` per layer, K and V) shared across
slots, a per-slot block table, blocks allocated on append and returned
on EOS/deadline/cancel, so concurrent generations are bounded by the
pool's token capacity — not ``slots * max_len``.

Host side (this file): a free-list allocator with occupancy /
internal-fragmentation accounting, typed
:class:`KVPoolExhaustedError` admission backpressure (a
``ServerOverloadedError`` subclass — the wire maps it to
``etype: "Overloaded"`` and clients back off), ``kvpool_*`` metrics in
the process registry, flight-recorder events for exhaustion and block
leaks, and the ``serving.kv_alloc`` chaos point through every
allocation. Block 0 is the reserved TRASH block: padded block-table
entries point at it, so bucket-padded prefill scatters and stale free
slots write garbage somewhere harmless that position masks never read.

Device side: lazily-built jnp pool arrays (float32 / bfloat16 / int8
with per-(block, head, slot) float32 scales — ``FLAGS_kv_cache_dtype``;
at bandwidth-bound decode, halving cache bytes is ~2x tokens/s), a
jitted bucketed prefill scatter (dense prefill row caches reshaped to
blocks and scattered through the table in one donated call), and the
paged decode programs' feed dict. The fused read path is
``kernels/paged_attention.py``.
"""
import hashlib
import math
import threading
from collections import OrderedDict

import numpy as np

from ..flags import flag
from ..observability.metrics import default_registry
from ..observability.recorder import flight_recorder as _flightrec
from ..resilience import maybe_fail
from .batching import BadRequestError, ServerOverloadedError, next_bucket

# -- typed backpressure ----------------------------------------------------


class KVPoolExhaustedError(ServerOverloadedError):
    """The pool has no free blocks for the allocation. Subclasses
    :class:`ServerOverloadedError`, so admission surfaces it as
    backpressure (wire ``etype: "Overloaded"``) — the client backs off
    and retries, by which time finished rows have returned blocks.
    Carries ``needed``/``free``/``capacity`` block counts."""

    def __init__(self, message, needed=None, free=None, capacity=None):
        super().__init__(message)
        self.needed = needed
        self.free = free
        self.capacity = capacity


# -- metrics (native families; ``pool`` label keeps a serving pool and
#    transient offline pools from clobbering each other's gauges) --------

_BLOCKS_IN_USE = default_registry().gauge(
    "kvpool_blocks_in_use_count",
    "KV-pool blocks currently allocated to live slots",
    labels=("pool",), max_series=64)
_CAPACITY = default_registry().gauge(
    "kvpool_capacity_blocks_count",
    "KV-pool allocatable block capacity (trash block excluded)",
    labels=("pool",), max_series=64)
_OCCUPANCY = default_registry().gauge(
    "kvpool_occupancy_ratio",
    "allocated / allocatable KV-pool blocks",
    labels=("pool",), max_series=64)
_SAVED = default_registry().gauge(
    "kvpool_saved_vs_dense_bytes",
    "device bytes a dense [slots, H, max_len, D] fp32 bank would hold "
    "minus the pool bytes actually allocated",
    labels=("pool",), max_series=64)
_ALLOC_FAIL = default_registry().counter(
    "kvpool_alloc_failures_total",
    "block allocations refused with KVPoolExhaustedError",
    labels=("pool",), max_series=64)
_ALLOCATED = default_registry().counter(
    "kvpool_blocks_allocated_total",
    "KV-pool blocks handed out by the free-list allocator",
    labels=("pool",), max_series=64)
_FREED = default_registry().counter(
    "kvpool_blocks_freed_total",
    "KV-pool blocks returned to the free list",
    labels=("pool",), max_series=64)
_LEAKED = default_registry().counter(
    "kvpool_leaked_blocks_total",
    "blocks found still held by finished slots and reclaimed by the "
    "leak sweep",
    labels=("pool",), max_series=64)
_EXPORTED = default_registry().counter(
    "kvpool_blocks_exported_total",
    "KV blocks serialized out of the pool for cross-replica migration",
    labels=("pool",), max_series=64)
_IMPORTED = default_registry().counter(
    "kvpool_blocks_imported_total",
    "migrated KV blocks deserialized into the pool",
    labels=("pool",), max_series=64)
_PREFIX_ENTRIES = default_registry().gauge(
    "kvpool_prefix_entries_count",
    "prompt-prefix cache entries currently indexed",
    labels=("pool",), max_series=64)
_PREFIX_BLOCKS = default_registry().gauge(
    "kvpool_prefix_cached_blocks_count",
    "KV blocks held ONLY by the prefix cache (evictable under "
    "pressure; not counted as slot load)",
    labels=("pool",), max_series=64)
_PREFIX_HITS = default_registry().counter(
    "kvpool_prefix_hits_total",
    "prompt admissions that adopted cached prefix blocks",
    labels=("pool",), max_series=64)
_PREFIX_MISSES = default_registry().counter(
    "kvpool_prefix_misses_total",
    "prompt admissions that found no cached prefix",
    labels=("pool",), max_series=64)
_PREFIX_TOKENS_REUSED = default_registry().counter(
    "kvpool_prefix_tokens_reused_total",
    "prompt tokens whose prefill was skipped by adopting cached "
    "prefix blocks",
    labels=("pool",), max_series=64)
_PREFIX_EVICTIONS = default_registry().counter(
    "kvpool_prefix_evictions_total",
    "prefix-cache entries evicted LRU under pool pressure",
    labels=("pool",), max_series=64)
_PREFIX_COW = default_registry().counter(
    "kvpool_prefix_cow_copies_total",
    "shared KV blocks copy-on-write duplicated before a divergent "
    "write",
    labels=("pool",), max_series=64)

_DTYPES = ("fp32", "bf16", "int8")
_ELEM_BYTES = {"fp32": 4, "bf16": 2, "int8": 1}

# migration payload format tag (bump on any layout change: an importer
# must never guess at a frame written by a different code revision)
KV_WIRE_FMT = "kvblocks1"


def _np_pool_dtype(kv_dtype):
    import jax.numpy as jnp
    return {"fp32": jnp.float32, "bf16": jnp.bfloat16,
            "int8": jnp.int8}[kv_dtype]


def pool_feed_names(num_layers, quantized):
    """Feed/fetch names of the paged decode program's pool arrays, in
    the ONE canonical order the graph builder, the generator's unpack
    and this pool all share: k pools, v pools, then (int8 only) k/v
    scale pools. The ``cache_`` prefix keeps them in the generator's
    donated-argument group — XLA aliases the append in place."""
    names = [f"cache_pk_{i}" for i in range(num_layers)] \
        + [f"cache_pv_{i}" for i in range(num_layers)]
    if quantized:
        names += [f"cache_pks_{i}" for i in range(num_layers)] \
            + [f"cache_pvs_{i}" for i in range(num_layers)]
    return names


def prompt_prefix_key(tokens, length=None):
    """Content hash of the first ``length`` tokens of a prompt (the
    whole prompt when ``length`` is None) — the ONE prefix key the
    pool's block index and the router's affinity map share, so 'the
    replica that cached this prefix' is a well-defined address
    fleet-wide. int32 token bytes hashed, so the key is independent of
    list/array input type."""
    a = np.ascontiguousarray(np.asarray(tokens, np.int32).reshape(-1))
    if length is not None:
        a = a[:int(length)]
    return hashlib.blake2b(a.tobytes(), digest_size=16).hexdigest()


def decode_feed(pool, token, pos):
    """ONE paged decode step's feed dict: the pool's device arrays
    (donated into the call — XLA appends in place), this step's
    token/pos vectors, and the host block tables. The one builder both
    the offline generator loop and the serving engine use."""
    feed = dict(pool.arrays())
    feed["token"] = token
    feed["pos"] = pos
    feed["block_tables"] = np.ascontiguousarray(pool.tables)
    return feed


def adopt_decode_fetches(pool, fetches):
    """Adopt a paged decode step's fetched (donated-in-place) pool
    arrays back into ``pool`` and return the logits — the fetch-order
    contract (logits first, then :func:`pool_feed_names` order) lives
    HERE, next to the feed-order contract, so the two callers cannot
    drift."""
    names = pool_feed_names(pool.num_layers, pool.quantized)
    pool.update_arrays({n: fetches[1 + i] for i, n in enumerate(names)})
    return fetches[0]


class KVBlockPool:
    """Device block pool + host free-list allocator + per-slot tables.

    Single-driver by design, like the ``GenerationEngine`` it backs: the
    decode loop is the only caller of alloc/free/scatter/update (a lock
    still guards the accounting so stats()/metrics scrapes from other
    threads read consistent state).

    ``num_blocks`` counts the trash block: the allocatable capacity is
    ``num_blocks - 1``. Default sizing is HBM-equivalent to the dense
    bank it replaces (``slots * ceil(max_seq_len/block_size) + 1``) —
    the paged win is that short generations leave most of it free for
    MORE concurrent slots, where dense burned it on padding.
    """

    def __init__(self, *, slots, num_layers, num_heads, d_head,
                 max_seq_len, block_size=None, num_blocks=None,
                 dtype=None, name="serving", prefix_cache=None):
        self.slots = int(slots)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.d_head = int(d_head)
        self.max_seq_len = int(max_seq_len)
        self.block_size = int(block_size or flag("kv_block_size"))
        if self.block_size < 1:
            raise ValueError("kv_block_size must be >= 1")
        self.dtype = dtype or flag("kv_cache_dtype")
        if self.dtype not in _DTYPES:
            raise ValueError(
                f"kv_cache_dtype must be one of {_DTYPES}, "
                f"got {self.dtype!r}")
        self.blocks_per_row = _ceil_div(self.max_seq_len, self.block_size)
        if num_blocks is None:
            num_blocks = int(flag("kv_pool_blocks")) or \
                self.slots * self.blocks_per_row + 1
        self.num_blocks = int(num_blocks)
        if self.num_blocks < 2:
            raise ValueError("KVBlockPool needs >= 2 blocks (block 0 is "
                             "the reserved trash block)")
        self.name = str(name)
        self.quantized = self.dtype == "int8"

        # host accounting (block 0 = trash, never allocated). LIFO free
        # list: recently-freed blocks are re-used first, which keeps the
        # working set of hot blocks small.
        self._lock = threading.Lock()
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._slot_nblocks = {}        # slot -> blocks held
        self._slot_tokens = {}         # slot -> tokens accounted
        self.tables = np.zeros((self.slots, self.blocks_per_row),
                               np.int32)
        # refcounted sharing (prefix cache / COW): every handed-out
        # block carries a refcount; a block returns to the free list
        # only when its LAST owner (slot table entry or prefix-cache
        # entry) releases it
        self._refs = {}                # block -> total owners
        self._cache_ref = {}           # block -> prefix-entry owners
        # hash(prompt prefix) -> {"blocks", "tokens", "hits"}; insertion
        # order IS the LRU order (move_to_end on hit, popitem(False)
        # under pressure)
        self._prefix = OrderedDict()
        self.prefix_enabled = bool(flag("kv_prefix_cache")
                                   if prefix_cache is None
                                   else prefix_cache)
        self.array_sharding = None     # NamedSharding under a tp mesh
        self._arrays = None            # lazy device pool
        self._scatter_fn = None
        self._import_fn = None         # migration scatter (import_slot)
        self._copy_fn = None           # COW block duplication
        self._update_gauges()

    # -- sizing helpers ---------------------------------------------------
    def blocks_for_tokens(self, ntokens):
        return _ceil_div(max(int(ntokens), 0), self.block_size)

    @property
    def capacity_blocks(self):
        """Allocatable blocks (trash excluded)."""
        return self.num_blocks - 1

    def block_bytes(self):
        """Device bytes per block across layers, K+V, scales included."""
        elem = _ELEM_BYTES[self.dtype]
        n = 2 * self.num_layers * self.num_heads * self.block_size \
            * self.d_head * elem
        if self.quantized:
            n += 2 * self.num_layers * self.num_heads * self.block_size \
                * 4
        return n

    def dense_slot_bytes(self):
        """Device bytes ONE dense bank slot costs (fp32, max_seq_len)."""
        return 2 * self.num_layers * self.num_heads * self.max_seq_len \
            * self.d_head * 4

    # -- allocator --------------------------------------------------------
    def check_fits(self, ntokens):
        """Raise :class:`~.batching.BadRequestError` when a request of
        ``ntokens`` could NEVER be satisfied by this pool — even empty.
        The submit-time door check: refusing it early costs nothing,
        and the error is TERMINAL (wire ``etype: "BadRequest"``), not
        the retryable ``Overloaded`` backpressure — backing off cannot
        make an impossible request fit."""
        need = self.blocks_for_tokens(ntokens)
        if need > self.capacity_blocks:
            raise BadRequestError(
                f"request needs {need} KV blocks "
                f"({ntokens} tokens at block_size={self.block_size}) "
                f"but the pool's total capacity is "
                f"{self.capacity_blocks} blocks — it can never be "
                f"admitted; raise FLAGS_kv_pool_blocks")

    def admission_check(self, ntokens, pending_tokens=()):
        """The admission-time capacity gate: blocks for ``ntokens``,
        PLUS blocks for every entry of ``pending_tokens`` (requests
        already accepted this admission round but not yet allocated),
        must be free right now — else a counted, flight-recorded
        :class:`KVPoolExhaustedError` (the typed shed half of
        backpressure: the client backs off; blocks return as rows
        finish)."""
        need = self.blocks_for_tokens(ntokens)
        pending = sum(self.blocks_for_tokens(t) for t in pending_tokens)
        with self._lock:
            if need + pending > len(self._free):
                self._evict_cold_locked(need + pending)
            free = len(self._free)
        if need + pending > free:
            _ALLOC_FAIL.inc(labels=(self.name,))
            _flightrec().record(
                "kv_pool_exhausted", pool=self.name, slot=None,
                needed_blocks=need + pending, free_blocks=free,
                capacity_blocks=self.capacity_blocks)
            raise KVPoolExhaustedError(
                f"KV pool {self.name!r} cannot admit a request of "
                f"{ntokens} tokens right now: {need} block(s) needed "
                f"(+{pending} pending this round), {free} free of "
                f"{self.capacity_blocks} — back off and retry",
                needed=need + pending, free=free,
                capacity=self.capacity_blocks)

    def alloc(self, slot, ntokens):
        """Grow ``slot``'s allocation to cover ``ntokens`` tokens
        (no-op when it already does). Raises
        :class:`KVPoolExhaustedError` with nothing changed when the
        free list cannot cover the growth."""
        maybe_fail("serving.kv_alloc")
        slot = int(slot)
        need = self.blocks_for_tokens(ntokens)
        with self._lock:
            have = self._slot_nblocks.get(slot, 0)
            add = need - have
            if add <= 0:
                self._slot_tokens[slot] = max(
                    self._slot_tokens.get(slot, 0), int(ntokens))
                return 0
            if add > len(self._free):
                self._evict_cold_locked(add)
            if add > len(self._free):
                free_now = len(self._free)
            else:
                for j in range(have, need):
                    b = self._free.pop()
                    self._refs[b] = 1
                    self.tables[slot, j] = b
                self._slot_nblocks[slot] = need
                self._slot_tokens[slot] = max(
                    self._slot_tokens.get(slot, 0), int(ntokens))
                self._update_gauges_locked()
                free_now = None
        if free_now is not None:
            _ALLOC_FAIL.inc(labels=(self.name,))
            _flightrec().record(
                "kv_pool_exhausted", pool=self.name, slot=slot,
                needed_blocks=add, free_blocks=free_now,
                capacity_blocks=self.capacity_blocks)
            raise KVPoolExhaustedError(
                f"KV pool {self.name!r} exhausted: slot {slot} needs "
                f"{add} more block(s) for {ntokens} tokens, "
                f"{free_now} free of {self.capacity_blocks}",
                needed=add, free=free_now, capacity=self.capacity_blocks)
        _ALLOCATED.inc(add, labels=(self.name,))
        return add

    def ensure(self, slot, pos):
        """Allocation-on-append: make sure the block holding cache slot
        ``pos`` exists before the decode step writes there."""
        return self.alloc(slot, int(pos) + 1)

    def free_slot(self, slot):
        """Release every block ``slot`` holds (EOS / deadline / cancel /
        error — the continuous-batching reclaim). A refcounted block
        (shared with the prefix cache or another slot) only returns to
        the free list when its LAST owner releases it. Idempotent;
        returns the number of blocks physically freed."""
        slot = int(slot)
        with self._lock:
            n = self._slot_nblocks.pop(slot, 0)
            self._slot_tokens.pop(slot, None)
            freed = self._release_blocks_locked(
                int(self.tables[slot, j]) for j in range(n))
            self.tables[slot, :] = 0
            self._update_gauges_locked()
        if freed:
            _FREED.inc(freed, labels=(self.name,))
        return freed

    def _release_blocks_locked(self, block_ids):
        """Drop one reference per block; append to the free list at
        refcount 0. Returns blocks physically freed."""
        freed = 0
        for b in block_ids:
            left = self._refs.get(b, 1) - 1
            if left <= 0:
                self._refs.pop(b, None)
                self._free.append(b)
                freed += 1
            else:
                self._refs[b] = left
        return freed

    def blocks_in_use(self):
        """Blocks allocated to live slots. Blocks held ONLY by the
        prefix cache are working capital, not load — they report under
        :meth:`cached_blocks` / ``kvpool_prefix_cached_blocks_count``
        and evict LRU under pressure."""
        with self._lock:
            return self.capacity_blocks - len(self._free) \
                - self._cached_only_locked()

    def cached_blocks(self):
        """Blocks held only by the prefix cache (evictable)."""
        with self._lock:
            return self._cached_only_locked()

    def _cached_only_locked(self):
        return sum(1 for b, c in self._cache_ref.items()
                   if c > 0 and self._refs.get(b, 0) == c)

    def holders(self):
        """{slot: blocks_held} for every slot holding blocks."""
        with self._lock:
            return dict(self._slot_nblocks)

    def reclaim_leaks(self, live_slots):
        """Free blocks held by slots NOT in ``live_slots`` — the leak
        sweep (a finished slot should have freed on its way out; blocks
        it still holds are a leak). Records a flight-recorder event per
        leaking slot so ``debug_dump`` explains shed admissions.
        Returns blocks reclaimed."""
        live = set(int(s) for s in live_slots)
        with self._lock:
            leaked = [(s, n) for s, n in self._slot_nblocks.items()
                      if s not in live and n > 0]
        total = 0
        for slot, held in leaked:
            n = self.free_slot(slot)
            total += n
            _LEAKED.inc(n, labels=(self.name,))
            # shared = table entries whose blocks stayed alive under a
            # remaining reference (prefix cache / another slot) — the
            # sweep released the leaking slot's claim either way
            _flightrec().record("kv_block_leak", pool=self.name,
                                slot=slot, blocks=n,
                                shared=held - n)
        return total

    # -- device arrays ----------------------------------------------------
    def arrays(self):
        """The paged decode program's pool feed dict (lazily built
        zeros): ``{cache_pk_i, cache_pv_i[, cache_pks_i, cache_pvs_i]}``
        — see :func:`pool_feed_names` for the order contract."""
        if self._arrays is None:
            import jax.numpy as jnp
            shape = (self.num_blocks, self.num_heads, self.block_size,
                     self.d_head)
            dt = _np_pool_dtype(self.dtype)
            arrs = {}
            for i in range(self.num_layers):
                arrs[f"cache_pk_{i}"] = jnp.zeros(shape, dt)
                arrs[f"cache_pv_{i}"] = jnp.zeros(shape, dt)
            if self.quantized:
                sshape = shape[:3]
                for i in range(self.num_layers):
                    # scale 1.0, not 0: a read of a never-written slot
                    # dequantizes 0 * 1.0 instead of hitting a 0-scale
                    arrs[f"cache_pks_{i}"] = jnp.ones(sshape, jnp.float32)
                    arrs[f"cache_pvs_{i}"] = jnp.ones(sshape, jnp.float32)
            if self.array_sharding is not None:
                # tp-mesh placement: blocks sharded on the head axis
                # (dim 1), matching gpt.apply_tp_sharding's qkv split —
                # each chip holds its own heads' cache bytes. Scale
                # pools share the same head-axis split.
                import jax
                arrs = {n: jax.device_put(a, self.array_sharding[n])
                        for n, a in arrs.items()}
            self._arrays = arrs
        return self._arrays

    def update_arrays(self, new_arrays):
        """Adopt the decode step's fetched (donated-in-place) pool
        arrays."""
        self._arrays = dict(new_arrays)

    def drop_device(self):
        """Forget the device arrays (a failed donated call may have
        invalidated them); the next :meth:`arrays` rebuilds zeros. Host
        accounting is NOT touched — callers that also lost the logical
        contents call :meth:`reset`."""
        self._arrays = None

    def reset(self):
        """Free everything and drop the device pool — the engine
        restart / bank-lost path."""
        with self._lock:
            freed = self.capacity_blocks - len(self._free)
            self._free = list(range(self.num_blocks - 1, 0, -1))
            self._slot_nblocks.clear()
            self._slot_tokens.clear()
            self._refs.clear()
            self._cache_ref.clear()
            self._prefix.clear()
            self.tables[:] = 0
            self._arrays = None
            self._update_gauges_locked()
        if freed:
            _FREED.inc(freed, labels=(self.name,))

    # -- block-granular prefix cache (refcounted sharing + COW) -----------
    # A completed prompt's blocks are deposited into a hash-keyed index
    # (exact length AND block-aligned length, so both a full repeat and
    # a longer prompt sharing whole blocks can hit). A hit adopts the
    # cached blocks by reference — the adopting slot only prefills the
    # tail. Any write into a block with >1 owner is preceded by a
    # copy-on-write duplication (prepare_write), so cached content is
    # immutable while shared and per-prompt outputs stay bitwise
    # correct after divergence.

    def match_prefix(self, prompt):
        """Longest cached prefix of ``prompt``: the exact prompt first
        (full-repeat fast path), then block-aligned lengths descending.
        Returns ``{"key", "tokens", "blocks"}`` or None. A hit
        refreshes the entry's LRU position."""
        if not self.prefix_enabled:
            return None
        toks = np.asarray(prompt, np.int32).reshape(-1)
        L = int(toks.size)
        if L < 1:
            return None
        bs = self.block_size
        lengths = [L] + [n for n in range((L // bs) * bs, 0, -bs)
                         if n != L]
        with self._lock:
            for n in lengths:
                key = prompt_prefix_key(toks, n)
                e = self._prefix.get(key)
                if e is None or e["tokens"] != n:
                    continue
                self._prefix.move_to_end(key)
                e["hits"] += 1
                _PREFIX_HITS.inc(labels=(self.name,))
                return {"key": key, "tokens": n,
                        "blocks": list(e["blocks"])}
        _PREFIX_MISSES.inc(labels=(self.name,))
        return None

    def adopt_prefix(self, slot, match):
        """Attach a :meth:`match_prefix` hit's blocks to ``slot`` by
        reference (refcount +1 per block; the slot must hold nothing).
        The adopter owes a :meth:`prepare_write` before any write into
        the adopted range — COW duplicates on first divergence."""
        slot = int(slot)
        blocks = [int(b) for b in match["blocks"]]
        tokens = int(match["tokens"])
        with self._lock:
            if self._slot_nblocks.get(slot, 0):
                raise ValueError(
                    f"KV pool {self.name!r} slot {slot} already holds "
                    f"blocks — free it before adopting a cached prefix")
            for j, b in enumerate(blocks):
                self.tables[slot, j] = b
                self._refs[b] = self._refs.get(b, 0) + 1
            self._slot_nblocks[slot] = len(blocks)
            self._slot_tokens[slot] = tokens
            self._update_gauges_locked()
        _PREFIX_TOKENS_REUSED.inc(tokens, labels=(self.name,))
        return len(blocks)

    def prefix_insert(self, prompt, slot):
        """Deposit ``slot``'s freshly prefilled prompt blocks into the
        prefix index (refcount +1 per block — the cache co-owns them,
        so they survive the slot's EOS until evicted LRU). Inserts the
        exact-length entry and, when distinct, the block-aligned one.
        No-op per entry already indexed. Returns entries inserted."""
        if not self.prefix_enabled:
            return 0
        toks = np.asarray(prompt, np.int32).reshape(-1)
        L = int(toks.size)
        slot = int(slot)
        if L < 1:
            return 0
        bs = self.block_size
        lengths = [L]
        aligned = (L // bs) * bs
        if aligned and aligned != L:
            lengths.append(aligned)
        inserted = 0
        with self._lock:
            held = self._slot_nblocks.get(slot, 0)
            for n in lengths:
                nb = _ceil_div(n, bs)
                if nb < 1 or nb > held:
                    continue
                key = prompt_prefix_key(toks, n)
                if key in self._prefix:
                    self._prefix.move_to_end(key)
                    continue
                blocks = [int(self.tables[slot, j]) for j in range(nb)]
                if 0 in blocks:
                    continue
                for b in blocks:
                    self._refs[b] = self._refs.get(b, 0) + 1
                    self._cache_ref[b] = self._cache_ref.get(b, 0) + 1
                self._prefix[key] = {"blocks": blocks, "tokens": n,
                                     "hits": 0}
                inserted += 1
            if inserted:
                self._update_gauges_locked()
        return inserted

    def prepare_write(self, slot, start_pos, end_pos):
        """Copy-on-write barrier: make every block covering cache
        positions ``[start_pos, end_pos)`` of ``slot`` exclusively
        owned before a write lands there. Shared blocks are duplicated
        into fresh ones (one donated jitted device copy for the batch
        of them) and the slot's table re-pointed; the cache/other-slot
        owners keep the originals. Raises :class:`KVPoolExhaustedError`
        (after LRU eviction of cold prefixes) when no block can be
        found for a copy — with the slot's table unchanged. Returns
        blocks duplicated."""
        slot = int(slot)
        start, end = int(start_pos), int(end_pos)
        if end <= start:
            return 0
        bs = self.block_size
        j0, j1 = start // bs, _ceil_div(end, bs)
        copies = []
        with self._lock:
            def shared():
                out = []
                for j in range(j0, j1):
                    b = int(self.tables[slot, j])
                    if b != 0 and self._refs.get(b, 1) > 1:
                        out.append(j)
                return out
            js = shared()
            if len(js) > len(self._free):
                # eviction can also UNSHARE a block (the cache drops
                # its reference), so re-scan after
                self._evict_cold_locked(len(js))
                js = shared()
            if len(js) > len(self._free):
                free_now = len(self._free)
            else:
                free_now = None
                for j in js:
                    b = int(self.tables[slot, j])
                    nb = self._free.pop()
                    self._refs[b] -= 1
                    self._refs[nb] = 1
                    self.tables[slot, j] = nb
                    copies.append((b, nb))
                if copies:
                    self._update_gauges_locked()
        if free_now is not None:
            _ALLOC_FAIL.inc(labels=(self.name,))
            _flightrec().record(
                "kv_pool_exhausted", pool=self.name, slot=slot,
                needed_blocks=len(js), free_blocks=free_now,
                capacity_blocks=self.capacity_blocks)
            raise KVPoolExhaustedError(
                f"KV pool {self.name!r} cannot copy-on-write {len(js)} "
                f"shared block(s) for slot {slot}: {free_now} free of "
                f"{self.capacity_blocks}",
                needed=len(js), free=free_now,
                capacity=self.capacity_blocks)
        if not copies:
            return 0
        _PREFIX_COW.inc(len(copies), labels=(self.name,))
        self._copy_blocks([s for s, _ in copies],
                          [d for _, d in copies])
        return len(copies)

    def _evict_cold_locked(self, need):
        """Evict LRU prefix entries until at least ``need`` blocks are
        free (or the index is empty). Cold cached prefixes are working
        capital, not load — LRU eviction here is what keeps affinity
        routing from pinning a replica's pool full of them."""
        evicted = 0
        while self._prefix and len(self._free) < need:
            key, e = self._prefix.popitem(last=False)
            for b in e["blocks"]:
                c = self._cache_ref.get(b, 0) - 1
                if c <= 0:
                    self._cache_ref.pop(b, None)
                else:
                    self._cache_ref[b] = c
            freed = self._release_blocks_locked(e["blocks"])
            if freed:
                _FREED.inc(freed, labels=(self.name,))
            _PREFIX_EVICTIONS.inc(labels=(self.name,))
            _flightrec().record(
                "kv_prefix_evicted", pool=self.name, tokens=e["tokens"],
                blocks=len(e["blocks"]), freed=freed, hits=e["hits"])
            evicted += 1
        return evicted

    def _copy_blocks(self, src_ids, dst_ids):
        """Device-side block duplication (COW): one donated jitted call
        copies every pool array's ``src`` rows into ``dst``. On failure
        the donated arrays must be presumed lost (drop_device
        semantics) — the caller's bank-lost path applies."""
        import jax
        import jax.numpy as jnp
        if self._copy_fn is None:
            def cp(pool, src, dst):
                return {n: a.at[dst].set(a[src])
                        for n, a in pool.items()}
            self._copy_fn = jax.jit(cp, donate_argnums=(0,))
        try:
            self._arrays = self._copy_fn(
                self.arrays(), jnp.asarray(src_ids, jnp.int32),
                jnp.asarray(dst_ids, jnp.int32))
        except Exception:
            self._arrays = None
            raise

    # -- prefill scatter --------------------------------------------------
    def scatter_prefill(self, slot_ids, row_caches, bucket_len):
        """Move freshly-prefilled dense row caches into the pool: rows
        ``slot_ids`` of the tables receive the first ``bucket_len``
        positions of ``row_caches[cache_{k,v}_i][:len(slot_ids)]``
        (shape ``[bb, H, max_len, D]``), reshaped into blocks and
        scattered through the block table in ONE donated jitted call.
        Table entries past a row's allocation point at the trash block,
        so bucket padding lands there. Quantizes on the way in for an
        int8 pool. On ANY failure the donated pool arrays must be
        presumed lost — callers reset the pool."""
        import jax
        import jax.numpy as jnp

        n = len(slot_ids)
        nblk = self.blocks_for_tokens(bucket_len)
        tables = np.ascontiguousarray(
            self.tables[np.asarray(slot_ids, np.int32), :nblk]
        ).reshape(-1)                                     # [n*nblk]

        if self._scatter_fn is None:
            from ..kernels.paged_attention import quantize_kv
            bs, quant = self.block_size, self.quantized
            nl = self.num_layers

            def scatter(pool, rows, tables_flat):
                out = dict(pool)
                m = tables_flat.shape[0]
                for i in range(nl):
                    for kind in ("k", "v"):
                        src = rows[f"cache_{kind}_{i}"]    # [n,H,L,D]
                        n_rows = src.shape[0]
                        # the covered length is shape-determined (the
                        # jit retraces per (n, m) pair): m//n blocks of
                        # bs slots per row, zero-padded past max_len
                        cover = (m // n_rows) * bs
                        take = min(cover, src.shape[2])
                        vals = src[:, :, :take]
                        if take < cover:
                            pad = jnp.zeros(
                                src.shape[:2] + (cover - take,
                                                 src.shape[3]),
                                src.dtype)
                            vals = jnp.concatenate([vals, pad], axis=2)
                        vals = vals.reshape(n_rows, vals.shape[1],
                                            cover // bs, bs,
                                            vals.shape[3])
                        vals = vals.transpose(0, 2, 1, 3, 4).reshape(
                            m, vals.shape[1], bs, vals.shape[4])
                        dst = out[f"cache_p{kind}_{i}"]
                        if quant:
                            q, sc = quantize_kv(vals)
                            out[f"cache_p{kind}_{i}"] = \
                                dst.at[tables_flat].set(q)
                            skey = f"cache_p{kind}s_{i}"
                            out[skey] = out[skey].at[tables_flat].set(sc)
                        else:
                            out[f"cache_p{kind}_{i}"] = \
                                dst.at[tables_flat].set(
                                    vals.astype(dst.dtype))
                return out

            self._scatter_fn = jax.jit(scatter, donate_argnums=(0,))
        rows = {name: a[:n] for name, a in row_caches.items()}
        try:
            self._arrays = self._scatter_fn(
                self.arrays(), rows, jnp.asarray(tables, jnp.int32))
        except Exception:
            self._arrays = None
            raise

    # -- cross-replica block migration ------------------------------------
    # A finished prefill's KV state is a well-defined unit: the slot's
    # allocated blocks (in table order) plus the geometry needed to
    # validate them on the far side. export_slot/import_slot are the two
    # halves of the disaggregated prefill/decode split: a compute-bound
    # prefill replica serializes the finished slot out of its pool and a
    # bandwidth-bound decode replica streams it into its own. Payloads
    # stay inside the typed wire universe (bf16 travels as its uint16
    # bit pattern — numpy's bfloat16 is a void-kind dtype the wire
    # refuses; the bitcast round-trips exactly).

    def export_slot(self, slot):
        """Serialize ``slot``'s allocated blocks into a wire-safe dict:
        geometry fields + per-layer ``k_i``/``v_i`` arrays of shape
        ``[nblocks, H, block_size, D]`` (plus ``ks_i``/``vs_i`` float32
        scales for an int8 pool). Raises ``ValueError`` when the slot
        holds nothing. Single-driver like alloc/free — the decode loop
        is the only caller."""
        maybe_fail("serving.kv_export")
        slot = int(slot)
        with self._lock:
            n = int(self._slot_nblocks.get(slot, 0))
            tokens = int(self._slot_tokens.get(slot, 0))
            ids = self.tables[slot, :n].copy()
        if n == 0:
            raise ValueError(
                f"KV pool {self.name!r} slot {slot} holds no blocks — "
                f"nothing to export")
        import jax.numpy as jnp
        arrs = self.arrays()
        idx = jnp.asarray(ids, jnp.int32)
        payload = {
            "fmt": KV_WIRE_FMT, "pool_dtype": self.dtype,
            "block_size": self.block_size, "num_layers": self.num_layers,
            "num_heads": self.num_heads, "d_head": self.d_head,
            "tokens": tokens, "nblocks": n,
        }
        for i in range(self.num_layers):
            for kind in ("k", "v"):
                a = np.asarray(arrs[f"cache_p{kind}_{i}"][idx])
                if self.dtype == "bf16":
                    a = a.view(np.uint16)
                payload[f"{kind}_{i}"] = a
                if self.quantized:
                    payload[f"{kind}s_{i}"] = np.asarray(
                        arrs[f"cache_p{kind}s_{i}"][idx])
        _EXPORTED.inc(n, labels=(self.name,))
        return payload

    @staticmethod
    def payload_bytes(payload):
        """Total array bytes a migration payload carries (the wire-cost
        number the router's fleet_kv_migrated_bytes_total counts)."""
        return int(sum(a.nbytes for a in payload.values()
                       if isinstance(a, np.ndarray)))

    def import_slot(self, slot, payload):
        """Deserialize a migrated payload into ``slot``: validates the
        geometry against this pool (mismatch -> typed
        :class:`~.batching.BadRequestError` — retrying cannot help),
        allocates the blocks (typed :class:`KVPoolExhaustedError`
        backpressure with nothing changed), then scatters the arrays
        through the fresh table entries in one donated jitted call. On a
        scatter failure the blocks are returned and the device arrays
        presumed lost (the caller's bank-lost path applies)."""
        maybe_fail("serving.kv_import")
        slot = int(slot)
        geom = self._validate_payload(payload)
        tokens, n = geom["tokens"], geom["nblocks"]
        self.alloc(slot, tokens)        # typed exhaustion, nothing held
        # the scatter's operand shapes are [nblocks, ...]: pad the
        # block count up to a power of two (the prefill bucketing
        # policy) so the jitted import compiles per BUCKET, not per
        # distinct prompt length — padded rows scatter into the trash
        # block, which nothing ever reads
        n_pad = next_bucket(n)
        with self._lock:
            ids = np.zeros(n_pad, np.int32)        # trash-block padding
            ids[:n] = self.tables[slot, :n]
        import jax
        import jax.numpy as jnp
        vals = {}
        try:
            pool_np = _np_pool_dtype(self.dtype)

            def padded(a):
                if n_pad == n:
                    return a
                return np.concatenate(
                    [a, np.zeros((n_pad - n,) + a.shape[1:], a.dtype)])

            for i in range(self.num_layers):
                for kind in ("k", "v"):
                    a = np.ascontiguousarray(payload[f"{kind}_{i}"])
                    if self.dtype == "bf16":
                        a = a.view(pool_np)
                    vals[f"cache_p{kind}_{i}"] = jnp.asarray(padded(a))
                    if self.quantized:
                        vals[f"cache_p{kind}s_{i}"] = jnp.asarray(
                            padded(np.ascontiguousarray(
                                payload[f"{kind}s_{i}"],
                                dtype=np.float32)))
            if self._import_fn is None:
                def imp(pool, new_vals, idx):
                    out = dict(pool)
                    for name, v in new_vals.items():
                        out[name] = out[name].at[idx].set(v)
                    return out
                self._import_fn = jax.jit(imp, donate_argnums=(0,))
            self._arrays = self._import_fn(self.arrays(), vals,
                                           jnp.asarray(ids, jnp.int32))
        except Exception:
            # the donated pool arrays must be presumed lost; the blocks
            # just allocated go straight back
            self._arrays = None
            self.free_slot(slot)
            raise
        _IMPORTED.inc(n, labels=(self.name,))
        return n

    def _validate_payload(self, payload):
        """Geometry/shape checks for a migration payload; returns
        ``{"tokens", "nblocks"}``. Every refusal is a
        :class:`~.batching.BadRequestError` (terminal, not retryable)."""
        if not isinstance(payload, dict) \
                or payload.get("fmt") != KV_WIRE_FMT:
            raise BadRequestError(
                f"KV payload format {payload.get('fmt') if isinstance(payload, dict) else type(payload).__name__!r} "
                f"is not {KV_WIRE_FMT!r}")
        for field, mine in (("pool_dtype", self.dtype),
                            ("block_size", self.block_size),
                            ("num_layers", self.num_layers),
                            ("num_heads", self.num_heads),
                            ("d_head", self.d_head)):
            got = payload.get(field)
            if got != mine:
                raise BadRequestError(
                    f"KV payload {field}={got!r} does not match the "
                    f"receiving pool's {mine!r} — prefill and decode "
                    f"replicas must share the cache geometry")
        try:
            tokens = int(payload["tokens"])
            n = int(payload["nblocks"])
        except (KeyError, TypeError, ValueError):
            raise BadRequestError("KV payload lacks integer "
                                  "tokens/nblocks fields")
        if tokens < 1 or n != self.blocks_for_tokens(tokens):
            raise BadRequestError(
                f"KV payload claims {tokens} tokens in {n} blocks; "
                f"{self.blocks_for_tokens(tokens)} blocks expected at "
                f"block_size={self.block_size}")
        if tokens > self.max_seq_len:
            raise BadRequestError(
                f"KV payload holds {tokens} tokens but the receiving "
                f"pool's rows cap at max_seq_len={self.max_seq_len}")
        shape = (n, self.num_heads, self.block_size, self.d_head)
        for i in range(self.num_layers):
            for kind in ("k", "v"):
                a = payload.get(f"{kind}_{i}")
                if not isinstance(a, np.ndarray) \
                        or tuple(a.shape) != shape:
                    raise BadRequestError(
                        f"KV payload array {kind}_{i} is "
                        f"{getattr(a, 'shape', None)}, expected {shape}")
                if self.quantized:
                    s = payload.get(f"{kind}s_{i}")
                    if not isinstance(s, np.ndarray) \
                            or tuple(s.shape) != shape[:3]:
                        raise BadRequestError(
                            f"int8 KV payload scale array {kind}s_{i} "
                            f"is {getattr(s, 'shape', None)}, expected "
                            f"{shape[:3]}")
        return {"tokens": tokens, "nblocks": n}

    # -- reporting --------------------------------------------------------
    def _update_gauges_locked(self):
        lab = (self.name,)
        cached = self._cached_only_locked()
        in_use = self.capacity_blocks - len(self._free) - cached
        _BLOCKS_IN_USE.set(in_use, labels=lab)
        _CAPACITY.set(self.capacity_blocks, labels=lab)
        # occupancy counts SLOT load only: blocks held just by the
        # prefix cache are evictable working capital, and the router's
        # load score must not shun the replica that cached the most
        _OCCUPANCY.set(in_use / self.capacity_blocks
                       if self.capacity_blocks else 0.0, labels=lab)
        _SAVED.set(self.slots * self.dense_slot_bytes()
                   - (in_use + cached) * self.block_bytes(), labels=lab)
        _PREFIX_ENTRIES.set(len(self._prefix), labels=lab)
        _PREFIX_BLOCKS.set(cached, labels=lab)

    def _update_gauges(self):
        with self._lock:
            self._update_gauges_locked()

    def stats(self):
        """Occupancy / fragmentation snapshot (plain ints/floats — wire
        safe, merged into ``server.stats()`` under ``kvpool_*``)."""
        with self._lock:
            cached = self._cached_only_locked()
            in_use = self.capacity_blocks - len(self._free) - cached
            tokens = sum(self._slot_tokens.values())
            slots_held = sum(1 for n in self._slot_nblocks.values()
                             if n > 0)
            prefix_entries = len(self._prefix)
        cap_tokens = in_use * self.block_size
        return {
            "blocks": self.num_blocks,
            "block_size": self.block_size,
            "dtype": self.dtype,
            "capacity_blocks": self.capacity_blocks,
            "blocks_in_use": in_use,
            "blocks_free": self.capacity_blocks - in_use,
            "occupancy": round(in_use / self.capacity_blocks, 4)
            if self.capacity_blocks else 0.0,
            # internal fragmentation: allocated capacity the held
            # tokens don't fill (last-block slack per slot)
            "fragmentation": round(1.0 - tokens / cap_tokens, 4)
            if cap_tokens else 0.0,
            "tokens_held": tokens,
            "slots_holding_blocks": slots_held,
            # prefix cache: entries indexed and blocks held ONLY by the
            # cache — evictable on demand, so the router's load scoring
            # discounts them (satellite: cold prefixes must not read as
            # load)
            "prefix_entries": prefix_entries,
            "evictable_blocks": cached,
            "bytes_in_use": (in_use + cached) * self.block_bytes(),
            "bytes_capacity": self.capacity_blocks * self.block_bytes(),
            "saved_vs_dense_bytes": self.slots * self.dense_slot_bytes()
            - (in_use + cached) * self.block_bytes(),
        }


def _ceil_div(a, b):
    return -(-int(a) // int(b))
