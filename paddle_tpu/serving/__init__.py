"""TPU serving runtime: dynamic micro-batching, compiled-executable
cache, admission control.

The reference ships a standalone inference engine (AnalysisPredictor +
zero-copy tensors) for single callers; this package is the multi-client
layer above it — the TPU-native analog of a serving stack in the
clipper/ORCA adaptive-batching tradition:

- ``RequestQueue`` + ``MicroBatcher`` coalesce single requests into
  padded power-of-two batches per feed-shape signature
  (``FLAGS_serving_max_batch_size`` / ``FLAGS_serving_batch_timeout_ms``)
- ``ExecutableCache`` holds AOT-compiled XLA executables — LRU, byte- and
  entry-capped, hit/miss/evict counters, warmup from a recorded
  signature file
- admission control: queue-depth backpressure
  (``ServerOverloadedError``), per-request deadlines
  (``DeadlineExceededError``), load shedding via
  ``resilience.CircuitBreaker``
- ``InferenceServer`` speaks the ``distributed/wire.py`` length-prefixed
  framing (HMAC-optional, same retry semantics as the PS transport);
  ``Client`` is the matching caller; both also work purely in-process
- ``server.stats()`` snapshots per-stage latency histograms
  (queue/pad/compile/execute), throughput and batch occupancy; the same
  spans land in ``paddle_tpu.profiler`` event tables while profiling

- generation: pass a ``models.generation.GPTGenerator`` as
  ``InferenceServer(generator=...)`` and the server also speaks
  ``op: "generate"`` — requests join a fixed bank of decode slots
  (``FLAGS_decode_slots``) stepped one token at a time by a single
  compiled KV-cached decode executable (ORCA-style continuous
  batching: per-row position counters, token-level deadlines, slot
  reuse the moment a row finishes); ``stats()`` adds prefill/decode/
  sample histograms, ``tokens_per_s`` and ``decode_occupancy``

- paged KV cache (``FLAGS_kv_paged``): the dense per-slot decode bank
  becomes a shared block-paged ``kvpool.KVBlockPool``
  (vLLM/PagedAttention) — per-slot block tables, allocation on append,
  frees on EOS/deadline/cancel, typed
  ``KVPoolExhaustedError`` backpressure, optional bf16/int8 cache
  (``FLAGS_kv_cache_dtype``) read by the fused
  ``kernels.paged_attention`` decode kernel; ``stats()`` adds
  ``kvpool_*`` occupancy/fragmentation and the registry exports
  ``kvpool_*`` gauges

- telemetry: the ``metrics`` wire op (``Client.metrics()``) returns the
  Prometheus text exposition of the process metrics registry
  (``paddle_tpu.observability``); ``debug_dump`` returns the flight
  recorder's recent structured events; ``infer``/``generate`` frames
  may carry a ``trace`` context (sampled client-side at
  ``FLAGS_trace_sample_rate``) that the server threads through every
  stage into the profiler's unified span table for
  ``tools/timeline.py``

- fleet (``serving.fleet``): a ``Router`` tier fronts N replicas over
  the same wire protocol — telemetry-driven least-loaded dispatch
  (probed ``health`` snapshots: queue depths + kvpool occupancy),
  replica eviction/readmission, cross-replica failover + hedging with
  request-id dedup, drain-aware rolling weight reloads, and a
  DISAGGREGATED prefill/decode split that streams finished KV blocks
  from compute-bound prefill replicas into bandwidth-bound decode
  replicas' pools (``op: "prefill"`` + ``generate``'s ``kv=`` import)

- overload control: every request carries a priority class
  (``interactive``/``batch``/``best_effort``) — the queue serves
  higher classes first and sheds the lowest first under backpressure,
  deadline-expired queue entries are evicted typed, ``deadline_ms``
  propagates as the REMAINING budget across client -> router ->
  replica hops, one process-global ``resilience.RetryBudget`` bounds
  every retry/hedge/failover (``FLAGS_retry_budget_ratio``), a
  breached-SLO server walks the brownout ladder
  (``serving.brownout``, best_effort then batch degrade before
  interactive), and ``fleet.Autoscaler`` scales the replica pool on
  the probed telemetry with hysteresis + cooldown

- resilience: the server runs a lifecycle state machine (warming ->
  serving -> draining -> stopped, degraded while the loop supervisor's
  breaker is open), a ``health`` wire op, ``drain()`` graceful shutdown,
  ``reload_weights()`` hot checkpoint swap (manifest-verified; in-flight
  generations finish on the old weights), supervised batcher loops
  (heartbeats, watchdogged executes, capped-backoff restarts), and a
  hedging/reconnecting ``Client`` with server-side request-id dedup.
  ``resilience.chaos()`` arms seeded fault points through every serving
  stage for deterministic failure testing.

Quick start::

    import paddle_tpu.serving as serving
    server = serving.InferenceServer("/path/to/saved_model").start()
    with serving.Client(server.endpoint) as c:
        probs, = c.infer({"x": batch}, deadline_ms=50.0)
    print(server.stats()["mean_batch_size"])
    server.stop()

Generation quick start::

    gen = paddle_tpu.models.GPTGenerator(cfg, scope, max_len=512)
    server = serving.InferenceServer(generator=gen).start()
    with serving.Client(server.endpoint) as c:
        new_tokens = c.generate(prompt_ids, max_new_tokens=64,
                                temperature=0.8, top_k=40)
    server.stop()
"""
from .batching import (  # noqa: F401
    PRIORITIES, BadRequestError, DeadlineExceededError, DecodeBatcher,
    GenerationRequest, InternalServerError, MicroBatcher, Request,
    RequestCancelledError, RequestQueue, ServerOverloadedError,
    ServerShutdownError, ServingError, SwapHandle, next_bucket,
    priority_rank,
)
from .brownout import BrownoutController  # noqa: F401
from .cache import ExecutableCache, LRUCache, feed_signature  # noqa: F401
from .engine import (  # noqa: F401
    SIGNATURE_FILE, GenerationEngine, ServingEngine,
    load_param_snapshot,
)
from .kvpool import KVBlockPool, KVPoolExhaustedError  # noqa: F401
from .metrics import LatencyHistogram, ServingStats  # noqa: F401
from .server import Client, InferenceServer, ServingConfig  # noqa: F401
from .supervise import LoopSupervisor  # noqa: F401
from . import fleet  # noqa: F401  — Router/ReplicaRegistry (serving.fleet)
