"""Request queue + dynamic micro-batcher.

Clipper/ORCA-style adaptive batching for the TPU serving runtime: single
requests (each carrying a small leading-dim batch of examples) are
coalesced into padded device batches under ``max_batch_size`` /
``batch_timeout_ms``. Requests only share a batch when their PER-EXAMPLE
signature (trailing dims + dtype per feed) matches; total rows are
padded up to the next power-of-two bucket so at most 2x padding waste
and a bounded set of compiled shapes.

Admission control lives in ``RequestQueue.put``: a hard queue-depth
limit (backpressure -> ``ServerOverloadedError``), per-request deadlines
(``DeadlineExceededError`` — checked at admission, again when the batch
is formed, and a third time right before execution), and load-shedding
through a ``resilience.CircuitBreaker``: sustained overload/engine
failures open the breaker, and while it is open requests are refused in
O(1) without touching the queue.

Priority admission: every request carries a priority CLASS —
``interactive`` (the default), ``batch``, ``best_effort`` — and the
queue serves higher classes first (FIFO within a class). Under
backpressure the LOWEST class sheds first: a full queue evicts its
youngest lowest-class entry (typed ``ServerOverloadedError``) to admit
a strictly-higher-class arrival, and entries whose deadline expired
WHILE QUEUED are failed typed immediately instead of dequeuing into a
doomed micro-batch (``serving_expired_in_queue_total``).
"""
import threading
import time
from collections import deque

import numpy as np

from .metrics import (record_class_shed, record_class_done,
                      record_expired_in_queue, record_spec_accept_ratio)
from ..observability import tracing as _trace
from ..observability.recorder import flight_recorder as _flightrec
from ..resilience import (CircuitBreaker, CircuitOpenError, WatchdogTimeout,
                          maybe_fail, run_with_watchdog)

# priority classes, highest first: under overload the server sheds
# best_effort, then batch, and protects interactive (the brownout
# ladder follows the same order)
PRIORITIES = ("interactive", "batch", "best_effort")
_PRIORITY_RANK = {p: i for i, p in enumerate(PRIORITIES)}


def priority_rank(priority):
    """Validated rank (0 = highest) for a priority-class name; None
    means the default class."""
    if priority is None:
        return 0
    try:
        return _PRIORITY_RANK[priority]
    except KeyError:
        raise ValueError(
            f"unknown priority class {priority!r} — one of "
            f"{PRIORITIES}") from None


def remaining_budget_ms(budget_ms, t0, now=None):
    """Deadline budget still unspent at ``now`` in ms (may be <= 0 =
    spent) — the ONE copy of the propagation arithmetic shared by the
    client's re-send/hedge rewrites and the router's hop forwarding,
    so the two tiers' accounting can never drift."""
    return float(budget_ms) \
        - ((time.monotonic() if now is None else now) - t0) * 1e3


class ServingError(RuntimeError):
    """Base class for serving-runtime request failures."""


class DeadlineExceededError(ServingError):
    """The request's deadline passed before it reached the chip. Carries
    ``deadline_ms`` (the budget) and ``waited_ms`` (time actually spent
    queued when the expiry was detected)."""

    def __init__(self, message, deadline_ms=None, waited_ms=None):
        super().__init__(message)
        self.deadline_ms = deadline_ms
        self.waited_ms = waited_ms


class ServerOverloadedError(ServingError):
    """Admission refused: queue at depth limit or load-shed breaker open.
    Clients should back off (the wire server maps this to an
    ``etype: "Overloaded"`` reply)."""


class ServerShutdownError(ServerOverloadedError):
    """The server is draining or stopping: admission is closed, and
    requests still queued at ``stop()`` are failed with this
    immediately rather than left to ride out their own timeouts.
    Subclasses :class:`ServerOverloadedError` so pre-existing overload
    handlers (back off, try another replica) keep working; the wire
    server maps it to ``etype: "Shutdown"``."""


class RequestCancelledError(ServingError):
    """The request was cancelled by its client (hedged-request loser:
    the twin that lost the race is cancelled by request id so a hedged
    pair never executes twice)."""


class InternalServerError(ServingError):
    """Client-side face of an ``etype: "Internal"`` (or unrecognized)
    error reply: the server deliberately answered with a failure the
    wire protocol does not map to a more specific class. Still a
    ServingError — a caller catching the typed serving surface sees
    every reply-borne failure."""


class BadRequestError(ServingError):
    """Client-side face of an ``etype: "BadRequest"`` reply: the server
    validated the request and refused it (missing feeds, malformed
    prompt). Distinguishable from server faults — retrying without
    fixing the input will not help."""


def _record_queue_span(req, now):
    """One copy of the queue-span arithmetic for both batchers: the
    span ends NOW and covers the monotonic time since enqueue, re-based
    onto the profiler's perf_counter clock."""
    if req.trace is None:
        return
    pc = time.perf_counter()
    _trace.record_child("serving/queue", pc - (now - req.t_enqueue), pc,
                        req.trace)


class Request:
    """One in-flight prediction request.

    ``feeds``: {name: np.ndarray}, every array with a leading example
    dim (shape ``(rows, *example_shape)``); all feeds must agree on
    ``rows``. The response is delivered through ``wait()`` ->
    ``result`` (list of np arrays, one per fetch target) or raises the
    recorded error.
    """

    __slots__ = ("feeds", "rows", "example_sig", "deadline_at",
                 "deadline_ms", "t_enqueue", "t_flush", "result", "error",
                 "_done", "trace", "priority", "rank")

    def __init__(self, feeds, deadline_ms=None, priority=None):
        self.feeds = {n: np.ascontiguousarray(a) for n, a in feeds.items()}
        if not self.feeds:
            raise ValueError("request has no feeds")
        rows = {a.shape[0] if a.ndim else 1 for a in self.feeds.values()}
        if len(rows) != 1:
            raise ValueError(
                f"feeds disagree on the leading example dim: "
                f"{ {n: a.shape for n, a in self.feeds.items()} }")
        self.rows = rows.pop()
        if self.rows < 1:
            raise ValueError("request carries zero examples")
        self.example_sig = tuple(sorted(
            (n, tuple(a.shape[1:]), str(a.dtype))
            for n, a in self.feeds.items()))
        self._init_lifecycle(deadline_ms, priority)

    def _init_lifecycle(self, deadline_ms, priority=None):
        """Deadline/event/result bookkeeping shared with subclasses that
        don't carry an infer feeds dict (GenerationRequest)."""
        self.rank = priority_rank(priority)
        self.priority = PRIORITIES[self.rank]
        self.deadline_ms = deadline_ms
        now = time.monotonic()
        self.t_enqueue = now
        self.t_flush = None
        self.deadline_at = (now + deadline_ms / 1e3
                            if deadline_ms else None)
        self.result = None
        self.error = None
        self._done = threading.Event()
        # request-scoped trace context: the server's connection handler
        # (or any caller) installs one via tracing.ambient() before
        # admission; stage spans (queue/pad/execute/decode) parent here
        self.trace = _trace.current()

    # -- lifecycle --------------------------------------------------------
    def expired(self, now=None):
        return (self.deadline_at is not None
                and (now or time.monotonic()) > self.deadline_at)

    def expire(self, now=None, where="queue"):
        now = now or time.monotonic()
        waited = (now - self.t_enqueue) * 1e3
        self.set_error(DeadlineExceededError(
            f"request deadline of {self.deadline_ms:.1f}ms exceeded in "
            f"{where} after {waited:.1f}ms",
            deadline_ms=self.deadline_ms, waited_ms=waited))

    def set_result(self, result):
        self.result = result
        self._done.set()

    def set_error(self, exc):
        self.error = exc
        self._done.set()

    def done(self):
        return self._done.is_set()

    def wait(self, timeout=None):
        """Block until the reply is in; returns the fetch list or raises
        the recorded error. ``timeout`` None waits forever."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"no reply within {timeout}s (request still in flight)")
        if self.error is not None:
            raise self.error
        return self.result


class RequestQueue:
    """Bounded priority queue with admission control. ``put`` is the
    single gate every request passes: breaker check (load shed), depth
    check (backpressure, lowest priority class shed first),
    deadline-already-passed check. ``get`` is consumed by the batchers
    only; it serves the highest class first (FIFO within a class) and
    evicts entries whose deadline expired while queued — they fail
    typed immediately instead of riding into a doomed batch."""

    def __init__(self, max_depth=None, breaker=None, stats=None):
        if max_depth is None:
            from ..flags import flag
            max_depth = flag("serving_queue_depth")
        self.max_depth = int(max_depth)
        # one FIFO per priority rank; depth/backpressure span all three
        self._items = {r: [] for r in range(len(PRIORITIES))}
        self._cv = threading.Condition()
        self._closed = False
        self._draining = False
        # flight-recorder admission sampling: per-outcome counters
        self._adm_lock = threading.Lock()
        self._adm_counts = {}
        self.stats = stats
        self.expired_in_queue = 0
        self.priority_evictions = 0
        if breaker is None:
            from ..flags import flag
            breaker = CircuitBreaker(
                endpoint="serving-admission",
                failure_threshold=flag("serving_shed_failures"),
                reset_timeout=flag("serving_shed_reset_secs"))
        self.breaker = breaker

    def __len__(self):
        with self._cv:
            return sum(len(q) for q in self._items.values())

    def _depth_locked(self):
        return sum(len(q) for q in self._items.values())

    def _sweep_expired_locked(self, now):
        """Drop every queued entry whose deadline already passed;
        returns them (the caller fails them OUTSIDE the lock — a
        waiter's callback must not run under ``_cv``)."""
        dead = []
        for q in self._items.values():
            live = []
            for req in q:
                if req.done():
                    continue           # abandoned while queued
                if req.expired(now):
                    dead.append(req)
                else:
                    live.append(req)
            q[:] = live
        return dead

    def _fail_expired(self, dead):
        if not dead:
            return
        self.expired_in_queue += len(dead)
        record_expired_in_queue(len(dead))
        for req in dead:
            if self.stats:
                self.stats.bump("shed_deadline")
            req.expire(where="queue")

    def _record_admission(self, outcome, **fields):
        """Flight-record one admission outcome, SAMPLED per outcome
        (first, then every 64th): at production QPS — shed storms
        included — a per-request event would turn the ring over in
        under a second and evict exactly the rare events (restarts,
        chaos, non-finite) the black box exists to keep. The cumulative
        per-outcome count rides every sampled event, so the dump still
        quantifies a storm it didn't record request-by-request."""
        with self._adm_lock:
            n = self._adm_counts.get(outcome, 0) + 1
            self._adm_counts[outcome] = n
        if n == 1 or n % 64 == 0:
            _flightrec().record("admission", outcome=outcome, n=n,
                                **fields)

    def put(self, req, max_depth=None):
        """Admit ``req`` or raise ServerOverloadedError /
        DeadlineExceededError. Never blocks — backpressure is a fast
        refusal, not a slow accept (the client owns retry policy).

        Under backpressure the lowest class sheds first: expired
        entries are swept out, then — if the queue is still full — the
        youngest entry of a strictly LOWER class than ``req`` is
        evicted (typed) to make room; only when no lower-class victim
        exists is ``req`` itself refused. ``max_depth`` overrides the
        queue's depth limit for this one admission (the brownout ladder
        shrinks admission for degraded classes without touching
        interactive traffic)."""
        maybe_fail("serving.admit")
        depth_cap = self.max_depth if max_depth is None \
            else min(int(max_depth), self.max_depth)
        try:
            self.breaker.before_call()
        except CircuitOpenError as e:
            if self.stats:
                self.stats.bump("shed_overload")
            record_class_shed(req.priority)
            self._record_admission("shed_breaker")
            raise ServerOverloadedError(
                f"load shedding: {e}") from e
        if req.expired():
            self.breaker.release_probe()    # not the server's fault
            if self.stats:
                self.stats.bump("shed_deadline")
            self._record_admission("shed_deadline",
                                   deadline_ms=req.deadline_ms)
            req.expire(where="admission")
            raise req.error
        dead, victim = [], None
        genuinely_full = False
        with self._cv:
            if self._closed or self._draining:
                self.breaker.release_probe()
                self._record_admission("shutdown")
                raise ServerShutdownError(
                    "server is draining — admission closed"
                    if self._draining and not self._closed
                    else "server is shutting down")
            if self._depth_locked() >= depth_cap:
                # expired entries must not hold a slot against live
                # traffic: sweep before judging the depth
                dead = self._sweep_expired_locked(time.monotonic())
            if self._depth_locked() >= depth_cap:
                genuinely_full = self._depth_locked() >= self.max_depth
                # victim eviction only for UN-capped admissions at a
                # genuinely full queue: a request admitted under a
                # shrunken per-call cap (the brownout ladder halving a
                # degraded class's admission) is refused outright — a
                # degraded class must never evict lower-class work the
                # queue already admitted, full or not
                if max_depth is None and genuinely_full:
                    # shed the lowest class first: evict the YOUNGEST
                    # entry of the lowest populated class strictly
                    # below req's (the youngest has waited least —
                    # least sunk cost to throw away)
                    for r in range(len(PRIORITIES) - 1, req.rank, -1):
                        if self._items[r]:
                            victim = self._items[r].pop()
                            self.priority_evictions += 1
                            break
                overloaded = victim is None
            else:
                overloaded = False
            if not overloaded:
                self._items[req.rank].append(req)
                self._cv.notify()
        self._fail_expired(dead)
        if victim is not None:
            if self.stats:
                self.stats.bump("shed_overload")
            record_class_shed(victim.priority)
            self._record_admission("shed_evicted",
                                   victim=victim.priority)
            victim.set_error(ServerOverloadedError(
                f"queued {victim.priority} request shed to admit "
                f"{req.priority} traffic under backpressure — back off "
                f"and retry"))
        if overloaded:
            if genuinely_full:
                self.breaker.record_failure()
            else:
                # refused by an ARTIFICIAL per-call cap (brownout
                # shrinking a degraded class) with global capacity to
                # spare: not the server's fault — the load-shed
                # breaker must not open and start refusing the
                # interactive traffic the ladder exists to protect
                self.breaker.release_probe()
            if self.stats:
                self.stats.bump("shed_overload")
            record_class_shed(req.priority)
            self._record_admission("shed_overload", depth=depth_cap)
            raise ServerOverloadedError(
                f"request queue at depth limit ({depth_cap}); "
                f"retry with backoff")
        self.breaker.record_success()
        if self.stats:
            self.stats.bump("requests_admitted")
        self._record_admission("admitted", rows=req.rows)
        return req

    def get(self, timeout=None):
        """Pop the oldest request of the HIGHEST populated class, or
        None on timeout/close. Entries whose deadline expired (or were
        abandoned) while queued are failed typed as they reach the
        front — a doomed request must not burn a micro-batch slot —
        and the pop continues to the next live entry. Cost is
        amortized O(1): only entries actually removed are examined
        (the full sweep runs on the put-when-full path, where the
        depth scan is already being paid)."""
        maybe_fail("serving.queue")
        dead, out = [], None
        with self._cv:
            if not self._depth_locked():
                self._cv.wait(timeout)
            now = time.monotonic()
            for r in range(len(PRIORITIES)):
                q = self._items[r]
                while q:
                    req = q.pop(0)
                    if req.done():          # abandoned while queued
                        continue
                    if req.expired(now):
                        dead.append(req)
                        continue
                    out = req
                    break
                if out is not None:
                    break
        self._fail_expired(dead)
        return out

    def quiesce(self):
        """Stop admitting (``put`` raises :class:`ServerShutdownError`)
        but keep everything already queued flowing to the batcher — the
        drain() half of shutdown. Idempotent."""
        with self._cv:
            self._draining = True

    def close(self):
        """Stop admitting; fail whatever is still queued IMMEDIATELY
        with the typed shutdown error (a queued request must never be
        left to ride out its own timeout against a dead server)."""
        with self._cv:
            self._closed = True
            drained = [req for r in range(len(PRIORITIES))
                       for req in self._items[r]]
            for q in self._items.values():
                q.clear()
            self._cv.notify_all()
        for req in drained:
            req.set_error(ServerShutdownError(
                "server shut down with the request still queued"))


class GenerationRequest(Request):
    """One in-flight autoregressive generation request: a 1-D int prompt
    plus sampling knobs. Admission control (queue depth, deadline,
    breaker) is inherited from :class:`Request` — ``deadline_ms`` is
    token-level: it is re-checked between decode steps, so a request
    whose budget runs out mid-generation fails fast instead of holding
    its slot for the full ``max_new_tokens``.

    Disaggregated prefill/decode split (serving/fleet): with
    ``export_kv=True`` the request is prefill-ONLY — the prompt is
    prefilled and its first token sampled as usual, then the slot's KV
    blocks are serialized and delivered as the result instead of the
    row joining the decode bank. With ``kv=`` (a
    ``kvpool.export_slot`` payload) plus ``first_token=``, the request
    is the other half: it skips prefill entirely, streaming the
    migrated blocks into its slot and decoding from ``first_token``."""

    __slots__ = ("prompt", "max_new_tokens", "temperature", "top_k",
                 "eos_id", "out_tokens", "slot", "export_kv", "kv",
                 "first_token")

    def __init__(self, prompt, max_new_tokens=32, temperature=0.0,
                 top_k=0, eos_id=None, deadline_ms=None,
                 export_kv=False, kv=None, first_token=None,
                 priority=None):
        prompt = np.asarray(prompt, dtype=np.int32).ravel()
        if prompt.size < 1:
            raise ValueError("generation request has an empty prompt")
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if kv is not None and export_kv:
            raise ValueError("a request cannot both import (kv=) and "
                             "export (export_kv=True) KV state")
        if (kv is None) != (first_token is None):
            raise ValueError("kv= and first_token= come together: the "
                             "migrated payload is decoded FROM the "
                             "prefill-side sampled token")
        # no infer feeds dict: the prompt is the payload (feeds/
        # example_sig are MicroBatcher concepts; the DecodeBatcher
        # groups by slot, not signature)
        self.feeds = None
        self.rows = 1
        self.example_sig = None
        self._init_lifecycle(deadline_ms, priority)
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.out_tokens = []
        self.slot = None
        self.export_kv = bool(export_kv)
        self.kv = kv
        self.first_token = None if first_token is None \
            else int(first_token)


class SwapHandle:
    """Future for a hot weight swap scheduled onto the decode loop
    (:meth:`DecodeBatcher.request_swap`): ``wait()`` blocks until the
    loop applied the swap between decode steps (or failed); carries the
    measured admission pause in ``pause_ms``."""

    def __init__(self, apply_fn):
        self.apply_fn = apply_fn
        self.requested_at = time.monotonic()
        self.pause_ms = None
        self.error = None
        self._done = threading.Event()

    def apply(self):
        try:
            self.apply_fn()
            self.pause_ms = (time.monotonic() - self.requested_at) * 1e3
        except Exception as exc:  # noqa: BLE001 — relayed to the waiter
            self.error = exc
        self._done.set()

    def fail(self, exc):
        self.error = exc
        self._done.set()

    def wait(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"weight swap not applied within {timeout}s (decode "
                f"rows still draining)")
        if self.error is not None:
            raise self.error
        return self.pause_ms


class DecodeBatcher:
    """Continuous batching over a fixed bank of decode slots
    (ORCA-style iteration-level scheduling): one thread pulls
    GenerationRequests off the queue, prefills them into free slots,
    then steps the WHOLE bank one token at a time — new requests join
    between steps, finished rows (EOS / max_new_tokens / deadline) free
    their slot immediately for the next admission. Per-row state
    (position counter, current token, sampling config, done) lives
    here; the device-side slot caches live in the GenerationEngine."""

    def __init__(self, queue, engine, stats=None, watchdog_s=None,
                 spec_k=None, drafter=None, brownout=None):
        from ..flags import flag
        if watchdog_s is None:
            watchdog_s = flag("serving_loop_watchdog_s")
        self.queue = queue
        self.engine = engine
        self.slots = engine.slots
        self.stats = stats
        self.watchdog_s = float(watchdog_s)
        # speculative decoding (FLAGS_decode_spec_k > 0, paged pool
        # only): between steps each live row proposes up to spec_k
        # draft tokens (drafter; FLAGS_decode_spec_mode picks the
        # default) verified in ONE span pass through the pool —
        # rejection sampling keeps the output distribution exact. The
        # draft depth is a LOAD knob: a windowed acceptance rate adapts
        # it globally (low acceptance = wasted verify compute) and the
        # brownout ladder shrinks it per-row for degraded classes
        # before their admission degrades.
        if spec_k is None:
            spec_k = flag("decode_spec_k")
        self.spec_k = int(spec_k) \
            if getattr(engine, "pool", None) is not None else 0
        self._drafter = drafter         # lazy: make_drafter on first use
        self.brownout = brownout
        self._accept_window = deque(maxlen=64)   # (accepted, proposed)
        self._spec_scope = f"decode-{id(self) & 0xffffff:x}"
        self._stop = threading.Event()
        self._thread = None
        self._free = list(range(self.slots))
        self._active = {}                       # slot -> request
        self._tok = np.zeros((self.slots,), np.int32)
        self._pos = np.zeros((self.slots,), np.int32)
        self._temp = np.zeros((self.slots,), np.float32)
        self._topk = np.zeros((self.slots,), np.int32)
        # supervision handles: the loop stamps `heartbeat` every
        # iteration; `_epoch` deposes a hung thread on restart (the old
        # loop notices the bump and exits without touching shared state)
        self.heartbeat = time.monotonic()
        self._epoch = 0
        self.consecutive_failures = 0
        self._swap = None                       # pending SwapHandle
        self._swap_lock = threading.Lock()
        self._admitting = 0     # popped from the queue, not yet in a slot
        self._admitting_reqs = []
        self._steps_since_sweep = 0             # paged-pool leak sweep
        # chunked-prefill states (engine.start_prefill dicts): rows
        # whose prompt is being ingested one chunk per decode round —
        # they hold a slot but are not yet in _active
        self._prefilling = []

    # -- lifecycle --------------------------------------------------------
    def start(self):
        self.heartbeat = time.monotonic()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serving-decode-batcher")
        self._thread.start()
        return self

    def alive(self):
        return self._thread is not None and self._thread.is_alive()

    def inflight(self):
        """Rows being decoded PLUS requests mid-admission (popped from
        the queue but not yet in a slot — prefill compile can hold them
        there for seconds; drain() polls this to zero) PLUS rows mid
        chunked-prefill (slot held, prompt still ingesting)."""
        return len(self._active) + self._admitting \
            + len(self._prefilling)

    def spec_snapshot(self):
        """Speculative-decoding state for health()/dashboards: the
        configured depth, the window-adapted effective depth, and the
        windowed acceptance rate (None until any drafting happened)."""
        win = list(self._accept_window)
        proposed = sum(p for _, p in win)
        return {
            "spec_k": self.spec_k,
            "spec_k_effective": (self._adaptive_spec_k(self.spec_k)
                                 if self.spec_k > 0 else 0),
            "spec_accept_ratio": (
                round(sum(a for a, _ in win) / proposed, 4)
                if proposed else None),
        }

    def stop(self, timeout=5):
        self._stop.set()
        with self.queue._cv:
            self.queue._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                # loop thread owns the row state and is still inside a
                # long step (e.g. a first-shape compile); it fails the
                # in-flight requests itself on exit (_loop's finally),
                # so no client hangs even though we stop waiting here
                return
        release = getattr(self.engine, "release_slot", None)
        for slot, req in list(self._active.items()):
            if not req.done():
                req.set_error(ServerShutdownError(
                    "server stopped while the request was decoding"))
            if release is not None:
                release(slot)
        self._active.clear()
        for st in self._prefilling:
            if not st["req"].done():
                st["req"].set_error(ServerShutdownError(
                    "server stopped while the request was prefilling"))
            if release is not None:
                release(st["slot"])
        self._prefilling = []

    def restart(self, reason="supervisor restart"):
        """Replace a dead/hung loop thread: depose the old thread (epoch
        bump), fail every in-flight row with a typed error, reset the
        slot bank (row caches died with the old loop's state), start a
        fresh loop. Called by the LoopSupervisor only."""
        self._epoch += 1
        err = ServingError(f"decode loop restarted ({reason}); the "
                           f"request's decode state was lost")
        for req in list(self._active.values()):
            if not req.done():
                req.set_error(err)
                if self.stats:
                    self.stats.bump("requests_failed")
        for st in self._prefilling:
            if not st["req"].done():
                st["req"].set_error(err)
                if self.stats:
                    self.stats.bump("requests_failed")
        self._active.clear()
        self._prefilling = []
        self._free = list(range(self.slots))
        self._admitting = 0
        self.engine.reset()
        with self._swap_lock:
            sw, self._swap = self._swap, None
        if sw is not None:
            sw.fail(ServingError(f"weight swap abandoned: {reason}"))
        self.consecutive_failures = 0
        self.start()

    # -- row lifecycle ----------------------------------------------------
    def _finish(self, req, error=None):
        slot = req.slot
        if slot is not None and slot in self._active:
            del self._active[slot]
            self._free.append(slot)
            # reset the freed slot's sampling config: a stale
            # temperature > 0 would force the full sampler program on
            # an otherwise all-greedy bank (the engine picks the argmax
            # fast path only when every row's temperature is <= 0)
            self._temp[slot] = 0.0
            self._topk[slot] = 0
            # paged pool: EOS/deadline/cancel/error all land here — the
            # row's KV blocks go back to the free list immediately
            release = getattr(self.engine, "release_slot", None)
            if release is not None:
                release(slot)
        if req.done():
            # abandoned request (e.g. the wire handler's wait budget
            # expired and set an error): the slot is reclaimed above,
            # nothing to deliver
            return
        if error is not None:
            req.set_error(error)
            if self.stats:
                self.stats.bump("requests_failed")
            return
        req.set_result([np.asarray(req.out_tokens, np.int32)])
        record_class_done(req.priority, time.monotonic() - req.t_enqueue)
        if self.stats:
            self.stats.bump("requests_completed")
            self.stats.hist["total"].observe(
                time.monotonic() - req.t_enqueue)

    def _deliver_token(self, req, tok):
        """Record one sampled token; finish the row on EOS or budget.
        Returns True while the row stays live."""
        if req.eos_id is not None and tok == req.eos_id:
            self._finish(req)
            return False
        req.out_tokens.append(tok)
        if self.stats:
            self.stats.bump("tokens_generated")
        if len(req.out_tokens) >= req.max_new_tokens:
            self._finish(req)
            return False
        return True

    # -- speculative decoding ---------------------------------------------
    def _get_drafter(self):
        if self._drafter is None:
            from ..models.generation import make_drafter
            self._drafter = make_drafter(generator=self.engine.gen)
        return self._drafter

    def _adaptive_spec_k(self, k):
        """Effective draft depth from the windowed acceptance rate —
        the speculative analogue of the client's observed-p99 hedge
        delay: a measured signal replaces the configured constant once
        there is enough of it. Low acceptance means most of the verify
        span is wasted compute, so the depth backs off (never below 1:
        the window must keep refilling to observe recovery)."""
        proposed = sum(p for _, p in self._accept_window)
        if proposed < 32:
            return k            # not enough signal yet: trust the flag
        rate = sum(a for a, _ in self._accept_window) / proposed
        if rate >= 0.5:
            return k
        if rate >= 0.25:
            return max(k // 2, 1)
        return 1

    def _propose_drafts(self, k):
        """Draft proposals for every live row: np int32
        ``(drafts [slots, k], num_draft [slots])``. Per-row depth =
        the window-adapted global depth, shrunk by the brownout ladder
        for degraded priority classes, capped to the row's remaining
        token budget minus one (the verify step always emits at least
        one real token)."""
        drafts = np.zeros((self.slots, k), np.int32)
        nd = np.zeros((self.slots,), np.int32)
        k_eff = self._adaptive_spec_k(k)
        for slot, req in self._active.items():
            kr = k_eff
            if self.brownout is not None:
                kr = self.brownout.draft_depth(
                    priority_rank(req.priority), kr)
            kr = min(int(kr),
                     int(req.max_new_tokens) - len(req.out_tokens) - 1)
            if kr <= 0:
                continue
            ctx = np.concatenate([
                np.asarray(req.prompt, np.int32).reshape(-1),
                np.asarray(req.out_tokens, np.int32)])
            d = np.asarray(self._get_drafter().draft(ctx, kr),
                           np.int32).reshape(-1)[:kr]
            if d.size:
                drafts[slot, :d.size] = d
                nd[slot] = d.size
        return drafts, nd

    def _deliver_spec(self, out, acc, nd):
        """Deliver one verify step's emitted runs: row ``slot`` takes
        ``acc[slot]`` accepted drafts plus the correction/bonus token,
        stopping early on EOS/budget (later tokens of the run are
        dropped — their KV is garbage past the row's new position and
        is overwritten before it is ever attended). Updates the
        acceptance window, gauge, counters and flight events."""
        accepted = proposed = rejected = 0
        for slot in list(self._active):
            req = self._active[slot]
            if req.done():      # abandoned by its waiter
                self._finish(req)
                continue
            a, n = int(acc[slot]), int(nd[slot])
            accepted += a
            proposed += n
            if a < n:
                rejected += 1
                _flightrec().record("spec_rejected", slot=slot,
                                    proposed=n, accepted=a)
            alive = True
            for j in range(a + 1):
                alive = self._deliver_token(req, int(out[slot, j]))
                if not alive:
                    break
            if alive:
                self._pos[slot] += a + 1
                self._tok[slot] = int(out[slot, a])
        if self.stats:
            self.stats.bump("spec_steps")
            if proposed:
                self.stats.bump("spec_drafted", proposed)
            if accepted:
                self.stats.bump("spec_accepted", accepted)
            if rejected:
                self.stats.bump("spec_rejected", rejected)
        self._accept_window.append((accepted, proposed))
        win_p = sum(p for _, p in self._accept_window)
        if win_p:
            record_spec_accept_ratio(
                self._spec_scope,
                sum(a for a, _ in self._accept_window) / win_p)

    def _fail_active_if_bank_lost(self, exc):
        """After an engine failure, a donated-call loss of the slot bank
        takes every ACTIVE row's caches with it — fail those rows too
        rather than letting them silently decode against a rebuilt zero
        bank."""
        if getattr(self.engine, "bank_lost", False) and self._active:
            for req in list(self._active.values()):
                self._finish(req, ServingError(
                    f"decode slot bank lost to an engine failure "
                    f"({type(exc).__name__}: {exc}); the row's cache "
                    f"is unrecoverable"))

    def _check_deadlines(self, now):
        for slot in list(self._active):
            req = self._active[slot]
            if req.expired(now):
                waited = (now - req.t_enqueue) * 1e3
                if self.stats:
                    self.stats.bump("shed_deadline")
                self._finish(req, DeadlineExceededError(
                    f"token-level deadline of {req.deadline_ms:.1f}ms "
                    f"exceeded after {waited:.1f}ms with "
                    f"{len(req.out_tokens)} tokens generated",
                    deadline_ms=req.deadline_ms, waited_ms=waited))
        still = []
        for st in self._prefilling:
            req = st["req"]
            if not (req.done() or req.expired(now)):
                still.append(st)
                continue
            if not req.done():
                waited = (now - req.t_enqueue) * 1e3
                if self.stats:
                    self.stats.bump("shed_deadline")
                req.set_error(DeadlineExceededError(
                    f"deadline of {req.deadline_ms:.1f}ms exceeded "
                    f"after {waited:.1f}ms mid chunked prefill",
                    deadline_ms=req.deadline_ms, waited_ms=waited))
            self.engine.release_slot(st["slot"])
            self._free.append(st["slot"])
        self._prefilling[:] = still

    # -- admission --------------------------------------------------------
    def _admit(self, epoch=None):
        try:
            self._admit_inner(self._epoch if epoch is None else epoch)
        except BaseException:
            # a crash mid-collection (e.g. an injected queue fault on
            # the SECOND pop) must not silently drop the requests
            # already taken off the queue — _admit_inner parks them in
            # _admitting_reqs until they reach a slot
            for req in self._admitting_reqs:
                if not req.done():
                    req.set_error(ServingError(
                        "decode loop crashed during admission"))
                    if self.stats:
                        self.stats.bump("requests_failed")
            raise
        finally:
            self._admitting_reqs = []
            self._admitting = 0

    def _admit_inner(self, epoch):
        take = self._admitting_reqs
        while self._free and len(take) < len(self._free) \
                and not self._stop.is_set() and self._epoch == epoch:
            # block briefly only when the bank is idle and nothing was
            # taken yet; once rows are decoding, admission must not
            # stall the step loop
            timeout = 0.05 if not (self._active or take) else 0
            req = self.queue.get(timeout=timeout)
            if req is None:
                break
            now = time.monotonic()
            if req.done():              # abandoned while queued
                continue
            if req.expired(now):
                if self.stats:
                    self.stats.bump("shed_deadline")
                req.expire(now, where="decode-queue")
                continue
            try:
                check = getattr(self.engine, "admission_check", None)
                if check is not None:
                    # pending_tokens: prompts already accepted this
                    # round hold free blocks hostage — admission must
                    # not promise the same blocks twice
                    check(req.prompt.size, req.max_new_tokens,
                          pending_tokens=[r.prompt.size for r in take])
                elif req.prompt.size + req.max_new_tokens \
                        > self.engine.max_len:
                    raise BadRequestError(
                        f"prompt ({req.prompt.size} tokens) + "
                        f"max_new_tokens ({req.max_new_tokens}) exceeds "
                        f"the decode cache length {self.engine.max_len}")
            except ServerOverloadedError as exc:
                # paged pool exhausted: typed shed — the client backs
                # off and retries once finished rows return blocks
                req.set_error(exc)
                if self.stats:
                    self.stats.bump("shed_overload")
                continue
            except Exception as exc:  # noqa: BLE001 — BadRequest etc.
                req.set_error(exc)
                if self.stats:
                    self.stats.bump("requests_failed")
                continue
            _record_queue_span(req, now)
            take.append(req)
            self._admitting = len(take)
        if not take:
            return
        if self._epoch != epoch:
            for req in take:
                if not req.done():
                    req.set_error(ServingError(
                        "decode loop restarted during admission"))
                    if self.stats:
                        self.stats.bump("requests_failed")
            return
        # migrated requests (kv=) admit through the KV-import path,
        # everything else prefills; failures are ISOLATED — the fresh
        # prefills admit as one batch, but each migrated payload admits
        # ALONE (validation is per-payload), so one poisoned migration
        # neither takes down the round's prefills nor its sibling
        # imports
        fresh = [r for r in take if getattr(r, "kv", None) is None]
        imported = [r for r in take if getattr(r, "kv", None) is not None]
        inc = getattr(self.engine, "incremental_prefill_enabled", None)
        if fresh and inc is not None and inc():
            # chunked-prefill admission (Orca/Sarathi): each prompt
            # claims a slot now but ingests one chunk per decode round,
            # interleaved with the bank's steps — a 2048-token prompt
            # no longer freezes every active row's token cadence for a
            # monolithic prefill
            for req in fresh:
                slot = self._free.pop()
                try:
                    st = self.engine.start_prefill(req, slot)
                except Exception as exc:  # noqa: BLE001 — typed
                    self._free.append(slot)
                    if not req.done():
                        req.set_error(exc)
                    if self.stats:
                        self.stats.bump("requests_failed")
                    continue
                req.slot = slot
                self._prefilling.append(st)
            fresh = []
        admit_imported = getattr(self.engine, "admit_imported", None)
        if imported and admit_imported is None:
            for req in imported:
                req.set_error(BadRequestError(
                    "this engine cannot admit migrated KV state"))
                if self.stats:
                    self.stats.bump("requests_failed")
            imported = []
        batches = ([(fresh, self.engine.admit)] if fresh else []) \
            + [([r], admit_imported) for r in imported]
        for group, admit in batches:
            slots = [self._free.pop() for _ in group]
            try:
                first = admit(group, slots)
            except Exception as exc:  # noqa: BLE001 — reach the clients
                for req in group:
                    req.set_error(exc)
                    if self.stats:
                        self.stats.bump("requests_failed")
                if self._epoch != epoch:
                    # deposed: _free/_active belong to the new loop
                    # thread — and the round's remaining taken requests
                    # will never be admitted; fail them all
                    self._fail_deposed(take)
                    return
                self._free.extend(slots)
                if isinstance(exc, BadRequestError):
                    # the request's own payload was refused (migrated
                    # KV geometry mismatch, ...) — a client error, not
                    # an engine fault: the loop breaker must not move
                    continue
                self.consecutive_failures += 1
                if self.stats:
                    self.stats.bump("engine_failures")
                self._fail_active_if_bank_lost(exc)
                continue
            if self._epoch != epoch:
                # deposed while blocked in the prefill (it eventually
                # returned): the restarted loop owns the slot bank —
                # fail EVERY taken request instead of registering any
                self._fail_deposed(take)
                return
            if group is not fresh and self.stats:
                self.stats.bump("kv_imports", len(group))
            for tok, req, slot in zip(first, group, slots):
                if self.stats:
                    self.stats.bump("generate_requests")
                if getattr(req, "export_kv", False):
                    self._finish_export(req, slot, int(tok))
                    continue
                req.slot = slot
                self._active[slot] = req
                self._pos[slot] = req.prompt.size
                self._temp[slot] = req.temperature
                self._topk[slot] = req.top_k
                self._tok[slot] = tok
                self._deliver_token(req, int(tok))

    def _fail_deposed(self, take):
        """The loop was restarted while this (now deposed) thread held
        requests it had already popped from the queue: fail every one
        that hasn't finished — the restarted loop will never see them,
        and a silent drop would strand their clients until the wire
        wait budget."""
        for req in take:
            if not req.done():
                req.set_error(ServingError(
                    "decode loop restarted during admission; "
                    "the request's prefill was discarded"))
                if self.stats:
                    self.stats.bump("requests_failed")

    def _advance_prefill(self, epoch):
        """Advance the OLDEST chunked prefill by one chunk this decode
        round (round-robin via the list's pop/append) — prompt
        ingestion shares the loop with decode steps instead of stalling
        them. A finished prompt samples its first token and joins the
        decode bank exactly as a monolithic admit would (export_kv rows
        deliver their KV payload instead)."""
        if not self._prefilling:
            return
        st = self._prefilling.pop(0)
        req, slot = st["req"], st["slot"]
        if req.done():                  # abandoned mid-prefill
            self.engine.release_slot(slot)
            self._free.append(slot)
            return
        try:
            done = self.engine.prefill_chunk(st)
            tok = self.engine.finish_prefill(st) if done else None
        except Exception as exc:  # noqa: BLE001 — reach the client
            if self._epoch != epoch:
                return       # deposed: restart() owns the row state
            self.engine.release_slot(slot)
            self._free.append(slot)
            if not req.done():
                req.set_error(exc)
            if isinstance(exc, ServerOverloadedError):
                # pool pressure mid-prefill: typed shed, same
                # bookkeeping as the admission-time shed
                if self.stats:
                    self.stats.bump("shed_overload")
                return
            self.consecutive_failures += 1
            if self.stats:
                self.stats.bump("engine_failures")
                self.stats.bump("requests_failed")
            self._fail_active_if_bank_lost(exc)
            return
        if self._epoch != epoch:
            return
        if not done:
            self._prefilling.append(st)
            return
        if self.stats:
            self.stats.bump("generate_requests")
        if getattr(req, "export_kv", False):
            self._finish_export(req, slot, int(tok))
            return
        req.slot = slot
        self._active[slot] = req
        self._pos[slot] = req.prompt.size
        self._temp[slot] = req.temperature
        self._topk[slot] = req.top_k
        self._tok[slot] = tok
        self._deliver_token(req, int(tok))

    def _finish_export(self, req, slot, tok):
        """Deliver a prefill-only request (disaggregated split): the
        freshly prefilled slot's KV blocks are serialized as the result
        — ``first_token`` and the prompt length ride inside the payload
        — and the slot is freed immediately; the row never joins the
        decode bank (its decode runs on another replica)."""
        try:
            payload = self.engine.export_slot(slot)
        except Exception as exc:  # noqa: BLE001 — typed to the client
            self.engine.release_slot(slot)
            self._free.append(slot)
            if not req.done():
                req.set_error(exc)
                if self.stats:
                    self.stats.bump("requests_failed")
            return
        self.engine.release_slot(slot)
        self._free.append(slot)
        payload["first_token"] = tok
        payload["prompt_tokens"] = int(req.prompt.size)
        if req.done():          # abandoned while prefilling
            return
        req.set_result([payload])
        # NOT record_class_done: in a disaggregated fleet this is the
        # prefill HOP of one user generate — the decode half records
        # the class completion; counting both would double goodput and
        # dilute the gated per-class latency with half-request times
        if self.stats:
            self.stats.bump("kv_exports")
            self.stats.bump("requests_completed")
            self.stats.hist["total"].observe(
                time.monotonic() - req.t_enqueue)

    # -- hot weight swap ---------------------------------------------------
    def request_swap(self, apply_fn):
        """Schedule ``apply_fn`` (the weight swap) onto the decode loop:
        admission pauses (new requests stay QUEUED, not failed), the
        in-flight rows finish their generations on the OLD weights, and
        the swap applies atomically between decode steps once the bank
        is empty. Returns a :class:`SwapHandle`. If the loop is not
        running the swap applies inline (nothing is in flight). A swap
        requested while another is still pending is failed immediately
        (one reload at a time — the caller retries after the first)."""
        handle = SwapHandle(apply_fn)
        with self._swap_lock:
            if self._swap is not None:
                handle.fail(ServingError(
                    "another weight swap is already pending — one "
                    "reload at a time"))
                return handle
            parked = self.alive()
            if parked:
                self._swap = handle
        if not parked:
            handle.apply()
            return handle
        with self.queue._cv:
            self.queue._cv.notify_all()
        # the loop may have exited BETWEEN the liveness check and the
        # store (its exit path only fails a swap it could see): reclaim
        # the parked handle and apply inline — nothing is in flight
        with self._swap_lock:
            orphaned = not self.alive() and self._swap is handle
            if orphaned:
                self._swap = None
        if orphaned:
            handle.apply()
        return handle

    # -- core loop --------------------------------------------------------
    def _loop(self):
        epoch = self._epoch
        try:
            while not self._stop.is_set() and self._epoch == epoch:
                self.heartbeat = time.monotonic()
                sw = self._swap
                if sw is not None:
                    # a pending swap stops admission so the bank drains;
                    # in-flight rows (decoding OR mid chunked-prefill)
                    # keep running on the old weights
                    if not self._active and not self._prefilling:
                        sw.apply()
                        with self._swap_lock:
                            if self._swap is sw:
                                self._swap = None
                        continue
                else:
                    self._admit(epoch)
                if not self._active and not self._prefilling:
                    continue
                self._check_deadlines(time.monotonic())
                self._advance_prefill(epoch)
                if self._epoch != epoch:
                    return
                if not self._active:
                    continue
                # paged pool: allocation-on-append for the live rows;
                # rows the pool cannot grow are shed TYPED while the
                # rest of the bank keeps decoding (their freed blocks
                # unblock the next step's growth)
                # speculative rows draft BEFORE the allocation pass so
                # the whole verify span [pos, pos + nd + 1) is covered
                # by blocks (and COW-duplicated when shared) up front
                drafts = nd = None
                if self.spec_k > 0 and self._active:
                    drafts, nd = self._propose_drafts(self.spec_k)
                prep = getattr(self.engine, "prepare_step", None)
                if prep is not None:
                    widths = None
                    if nd is not None:
                        widths = {slot: int(nd[slot]) + 1
                                  for slot in self._active}
                    shed = prep({slot: int(self._pos[slot])
                                 for slot in self._active},
                                widths=widths)
                    for slot, exc in shed.items():
                        req = self._active.get(slot)
                        if req is None:
                            continue
                        if isinstance(exc, ServerOverloadedError):
                            # overload shed, not a failure: same
                            # bookkeeping as the admission-time shed
                            # (shed_overload only, no requests_failed),
                            # then reclaim the slot + its blocks
                            if not req.done():
                                req.set_error(exc)
                            if self.stats:
                                self.stats.bump("shed_overload")
                            self._finish(req)
                        else:
                            self._finish(req, exc)
                    if not self._active:
                        continue
                # per-token spans for TRACED rows only (sampled at the
                # client edge): untraced traffic pays one list-comp over
                # <= slots entries per step
                traced = [r for r in self._active.values()
                          if r.trace is not None]
                t_step0 = time.perf_counter()
                try:
                    if drafts is not None:
                        live_mask = np.zeros((self.slots,), bool)
                        live_mask[list(self._active)] = True
                        out, acc = self.engine.spec_step(
                            self._tok, self._pos, self._temp,
                            self._topk, drafts, nd, live_mask,
                            budget=self.watchdog_s or None)
                    else:
                        toks = self.engine.step(
                            self._tok, self._pos, self._temp,
                            self._topk, budget=self.watchdog_s or None)
                except Exception as exc:  # noqa: BLE001
                    if self._epoch != epoch:
                        return       # deposed mid-step: restart() owns
                    self.consecutive_failures += 1      # the row state
                    if self.stats:
                        self.stats.bump("engine_failures")
                        if isinstance(exc, WatchdogTimeout):
                            self.stats.bump("watchdog_timeouts")
                    for req in list(self._active.values()):
                        self._finish(req, exc)
                    continue
                if self._epoch != epoch:
                    # deposed while blocked in the step (hung chip call
                    # that eventually returned): the restarted loop owns
                    # _active/_free now — do not touch them
                    return
                self.consecutive_failures = 0
                if traced:
                    t_step1 = time.perf_counter()
                    for r in traced:
                        _trace.record_child("serving/decode", t_step0,
                                            t_step1, r.trace)
                live = len(self._active)
                if self.stats:
                    # inter-token latency: the WHOLE step's wall time
                    # (decode + sample + any stall), the signal the SLO
                    # monitor's default p99 rule evaluates windowed
                    self.stats.hist["token"].observe(
                        time.perf_counter() - t_step0)
                    self.stats.observe_decode_step(live, self.slots)
                if drafts is not None:
                    self._deliver_spec(out, acc, nd)
                else:
                    for slot in list(self._active):
                        req = self._active[slot]
                        if req.done():      # abandoned by its waiter
                            self._finish(req)
                            continue
                        self._pos[slot] += 1
                        self._tok[slot] = toks[slot]
                        self._deliver_token(req, int(toks[slot]))
                # periodic paged-pool leak sweep: blocks held by slots
                # no longer active are a bug — reclaim + flight-record
                # them instead of bleeding capacity
                self._steps_since_sweep += 1
                if self._steps_since_sweep >= 256:
                    self._steps_since_sweep = 0
                    sweep = getattr(self.engine, "reclaim_leaks", None)
                    if sweep is not None:
                        sweep(list(self._active)
                              + [st["slot"] for st in self._prefilling])
        finally:
            # rows still mid-generation when the loop exits (stop() or
            # a crash) must fail fast, not leave their clients waiting.
            # A DEPOSED thread (epoch moved on: restart() owns the row
            # state now) must not touch anything.
            if self._epoch == epoch:
                self._admitting = 0
                release = getattr(self.engine, "release_slot", None)
                for slot, req in list(self._active.items()):
                    if not req.done():
                        req.set_error(ServerShutdownError(
                            "server stopped while the request was "
                            "decoding"))
                    if release is not None:
                        release(slot)
                self._active.clear()
                for st in self._prefilling:
                    if not st["req"].done():
                        st["req"].set_error(ServerShutdownError(
                            "server stopped while the request was "
                            "prefilling"))
                    if release is not None:
                        release(st["slot"])
                self._prefilling = []
                with self._swap_lock:
                    sw, self._swap = self._swap, None
                if sw is not None:
                    sw.fail(ServerShutdownError(
                        "decode loop exited with the weight swap "
                        "pending"))


def next_bucket(rows, min_bucket=1):
    """Smallest power-of-two >= rows (>= min_bucket): bounded padding
    waste (< 2x) and a bounded universe of compiled shapes."""
    b = max(int(min_bucket), 1)
    rows = max(int(rows), 1)
    while b < rows:
        b <<= 1
    return b


class MicroBatcher:
    """Pulls requests off the queue, groups them by per-example
    signature, and flushes a group to ``execute_fn(requests)`` when it
    reaches ``max_batch_size`` rows or its oldest member has waited
    ``batch_timeout_ms``. Single execution thread: batches hit the chip
    serially, which is exactly what a single-TPU serving process wants
    (the chip is the bottleneck resource; concurrency lives in the
    connection threads)."""

    def __init__(self, queue, execute_fn, max_batch_size=None,
                 batch_timeout_ms=None, stats=None, watchdog_s=None):
        from ..flags import flag
        self.queue = queue
        self.execute_fn = execute_fn
        self.max_batch_size = int(max_batch_size
                                  if max_batch_size is not None
                                  else flag("serving_max_batch_size"))
        timeout_ms = (batch_timeout_ms if batch_timeout_ms is not None
                      else flag("serving_batch_timeout_ms"))
        self.batch_timeout_s = float(timeout_ms) / 1e3
        self.watchdog_s = float(watchdog_s if watchdog_s is not None
                                else flag("serving_loop_watchdog_s"))
        self.stats = stats
        self._stop = threading.Event()
        self._thread = None
        self._pending = {}   # sig -> {"reqs": [...], "rows": n, "flush_at": t}
        self.heartbeat = time.monotonic()
        self._epoch = 0
        self._executing = 0           # requests inside execute_fn right now
        self._ingesting = 0           # popped, not yet in _pending
        self.consecutive_failures = 0

    # -- lifecycle --------------------------------------------------------
    def start(self):
        self.heartbeat = time.monotonic()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serving-microbatcher")
        self._thread.start()
        return self

    def alive(self):
        return self._thread is not None and self._thread.is_alive()

    def inflight(self):
        """Requests forming a batch, mid-ingest, or inside the engine
        right now (drain() polls this to zero)."""
        return (sum(len(ent["reqs"]) for ent in self._pending.values())
                + self._executing + self._ingesting)

    def stop(self, timeout=5):
        self._stop.set()
        with self.queue._cv:
            self.queue._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                # the loop thread owns _pending; it is still inside a
                # long execute (e.g. a first-request compile) — touching
                # the dict here would race it, and the in-flight requests
                # will still get their results when it finishes
                return
        # thread is down (or never started): fail anything still forming
        # so no client hangs
        for ent in self._pending.values():
            for req in ent["reqs"]:
                if not req.done():
                    req.set_error(ServerShutdownError(
                        "server stopped while the request was batching"))
        self._pending.clear()

    def restart(self, reason="supervisor restart"):
        """Replace a dead/hung loop thread: depose the old thread (epoch
        bump), fail the batches it was forming with a typed error, start
        a fresh loop. Called by the LoopSupervisor only."""
        self._epoch += 1
        err = ServingError(f"batcher loop restarted ({reason}); the "
                           f"request was failed mid-batch")
        for ent in self._pending.values():
            for req in ent["reqs"]:
                if not req.done():
                    req.set_error(err)
                    if self.stats:
                        self.stats.bump("requests_failed")
        self._pending = {}
        self.consecutive_failures = 0
        self.start()

    # -- core loop --------------------------------------------------------
    def _admit_to_batch(self, req, now):
        if req.expired(now):
            if self.stats:
                self.stats.bump("shed_deadline")
            req.expire(now, where="queue")
            return
        ent = self._pending.get(req.example_sig)
        if ent is None:
            ent = {"reqs": [], "rows": 0,
                   "flush_at": now + self.batch_timeout_s}
            self._pending[req.example_sig] = ent
        ent["reqs"].append(req)
        ent["rows"] += req.rows
        # a full group flushes IMMEDIATELY — never deferred to the drain
        # loop's end, so no signature's group can grow past
        # max_batch_size (+ the final request's own rows) no matter how
        # deep the queue backlog is
        if ent["rows"] >= self.max_batch_size:
            del self._pending[req.example_sig]
            self._flush(ent["reqs"], time.monotonic())

    def _flush_ready(self, now):
        for sig in list(self._pending):
            ent = self._pending[sig]
            if now >= ent["flush_at"]:
                del self._pending[sig]
                self._flush(ent["reqs"], now)

    def _flush(self, reqs, now):
        live = []
        for req in reqs:
            if req.expired(now):
                if self.stats:
                    self.stats.bump("shed_deadline")
                req.expire(now, where="batcher")
            else:
                req.t_flush = now
                if self.stats:
                    self.stats.hist["queue"].observe(now - req.t_enqueue)
                _record_queue_span(req, now)
                live.append(req)
        if not live:
            return
        self._executing = len(live)
        try:
            # the watchdog bounds a hung chip call (or a wedged
            # first-shape compile): the batch's clients get a typed
            # WatchdogTimeout instead of hanging, and the loop survives
            # to serve the next batch
            if self.watchdog_s > 0:
                run_with_watchdog(self.execute_fn, self.watchdog_s, live,
                                  what="serving execute")
            else:
                self.execute_fn(live)
            self.consecutive_failures = 0
        except Exception as exc:  # noqa: BLE001 — must reach the clients
            self.consecutive_failures += 1
            if self.stats:
                self.stats.bump("engine_failures")
                if isinstance(exc, WatchdogTimeout):
                    self.stats.bump("watchdog_timeouts")
            for req in live:
                if not req.done():
                    req.set_error(exc)
            if self.stats:
                self.stats.bump("requests_failed", len(live))
        finally:
            self._executing = 0

    def _loop(self):
        epoch = self._epoch
        try:
            while not self._stop.is_set() and self._epoch == epoch:
                self.heartbeat = time.monotonic()
                now = time.monotonic()
                if self._pending:
                    wake = min(ent["flush_at"]
                               for ent in self._pending.values())
                    timeout = max(min(wake - now, 0.1), 0.0)
                else:
                    timeout = 0.1
                req = self.queue.get(timeout=timeout)
                if self._epoch != epoch:
                    # deposed while blocked (hung execute that finally
                    # returned, or a get that raced a restart): the new
                    # loop owns _pending — fail the popped request
                    # instead of batching it into someone else's state
                    if req is not None and not req.done():
                        req.set_error(ServingError(
                            "batcher loop restarted; the request was "
                            "failed mid-ingest"))
                        if self.stats:
                            self.stats.bump("requests_failed")
                    return
                if req is not None:
                    self._ingesting = 1
                    self._admit_to_batch(req, time.monotonic())
                    # drain whatever is already queued before sleeping
                    # again: a burst coalesces instead of going
                    # request-by-request (full groups flush inside
                    # _admit_to_batch as they fill). Timed-out groups are
                    # checked INSIDE the drain — sustained arrivals must
                    # not starve a rare signature's batch_timeout_ms
                    # while the hot signature churns. The heartbeat is
                    # stamped HERE too: sustained load keeps the thread
                    # in this inner loop, and a fresh heartbeat is what
                    # tells the supervisor busy != hung.
                    while not self._stop.is_set() \
                            and self._epoch == epoch:
                        self.heartbeat = time.monotonic()
                        nxt = self.queue.get(timeout=0)
                        if nxt is None:
                            break
                        now = time.monotonic()
                        self._admit_to_batch(nxt, now)
                        self._flush_ready(now)
                    self._ingesting = 0
                if self._epoch != epoch:
                    return
                self._flush_ready(time.monotonic())
        finally:
            self._ingesting = 0
            # batches still forming when the loop exits (stop() or a
            # crash) fail fast — mirrors the decode loop's exit fix. A
            # deposed thread (restart() bumped the epoch and owns
            # _pending now) must not touch anything.
            if self._epoch == epoch and (self._stop.is_set()
                                         or self._pending):
                for ent in self._pending.values():
                    for r in ent["reqs"]:
                        if not r.done():
                            r.set_error(ServerShutdownError(
                                "server stopped while the request was "
                                "batching"))
                self._pending = {}
