"""Telemetry-driven fleet autoscaler (Autopilot-style, EuroSys 2020).

The Router already exposes every signal a horizontal scaler needs —
per-replica probed ``health()`` snapshots with queue depths,
``kvpool_occupancy``, SLO breach counts and brownout levels — but
nothing acted on them: capacity was whatever the operator started.
:class:`Autoscaler` closes the loop:

- **windowed signals, not instants**: every ``poll_s`` it folds the
  in-rotation replicas' telemetry into one pressure sample (mean queue
  ratio, mean kvpool occupancy, total breached SLO rules) and keeps the
  last ``window`` samples. A scale decision needs the WHOLE window to
  agree — one hot scrape never grows the fleet, one idle scrape never
  shrinks it.
- **hysteresis + cooldown**: scale-up and scale-down use separate
  thresholds (``up_*`` / ``down_*``, the no-man's-land between them is
  the hysteresis band) and every event arms a
  ``FLAGS_fleet_scale_cooldown_s`` cooldown, so the pool cannot flap
  even when load sits exactly at a threshold.
- **replica factory**: ``factory()`` returns a STARTED replica (an
  ``InferenceServer`` or anything with ``.endpoint``); tests and
  ``bench.py --config overload`` spawn in-process replicas, production
  wraps its pod launcher. The autoscaler registers the endpoint with
  the router and owns the replica's retirement.
- **drain-aware scale-down**: the victim leaves the dispatch rotation
  first (``registry.set_state(ep, "draining")``), the autoscaler waits
  for router-tracked in-flight dispatches to hit zero, removes it from
  the router, then retires it through ``retire`` (default:
  ``server.drain()`` — the PR-6 graceful path, in-flight generations
  finish, nothing is dropped).

Bounds come from ``FLAGS_fleet_min_replicas`` /
``FLAGS_fleet_max_replicas``; every decision is flight-recorded,
counted in ``fleet_scale_events_total{direction}`` and visible as the
``fleet_replicas_count{state}`` gauge — ``tools/fleet_report.py``
renders the trail from any metrics dump.
"""
import threading
import time
from collections import deque

from ...flags import flag
from ...observability.metrics import default_registry
from ...observability.recorder import flight_recorder as _flightrec

_REPLICAS = default_registry().gauge(
    "fleet_replicas_count",
    "autoscaled fleet replicas by rotation state "
    "(serving/draining/evicted)",
    labels=("state",), max_series=8)
_SCALE_EVENTS = default_registry().counter(
    "fleet_scale_events_total",
    "autoscaler scale decisions executed, by direction (up/down)",
    labels=("direction",), max_series=4)


class Autoscaler:
    """Scales a Router's replica pool between min/max on windowed fleet
    telemetry. See the module docstring for the control law."""

    def __init__(self, router, factory, *, retire=None,
                 min_replicas=None, max_replicas=None, cooldown_s=None,
                 poll_s=0.25, window=3, up_queue_ratio=0.5,
                 down_queue_ratio=0.05, up_kv_ratio=0.75,
                 down_kv_ratio=0.25, drain_timeout_s=15.0, role="both"):
        self.router = router
        self.factory = factory
        self._retire = retire
        self.min_replicas = int(min_replicas if min_replicas is not None
                                else flag("fleet_min_replicas"))
        self.max_replicas = int(max_replicas if max_replicas is not None
                                else flag("fleet_max_replicas"))
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas ({self.min_replicas}) <= "
                f"max_replicas ({self.max_replicas})")
        self.cooldown_s = float(cooldown_s if cooldown_s is not None
                                else flag("fleet_scale_cooldown_s"))
        self.poll_s = float(poll_s)
        self.window = int(window)
        self.up_queue_ratio = float(up_queue_ratio)
        self.down_queue_ratio = float(down_queue_ratio)
        self.up_kv_ratio = float(up_kv_ratio)
        self.down_kv_ratio = float(down_kv_ratio)
        self.drain_timeout_s = float(drain_timeout_s)
        self.role = str(role)
        self._owned = {}            # endpoint -> replica object
        self._samples = deque(maxlen=self.window)
        self._last_scale_at = 0.0
        # bounded decision trail (the counters/flight events are the
        # durable record): a long-lived fleet's periodic load swings
        # must not grow an unbounded list copied on every stats()
        self.events = deque(maxlen=256)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    # -- lifecycle --------------------------------------------------------
    def start(self):
        """Grow the pool to ``min_replicas`` synchronously (a fleet
        below its floor is a config error, not a signal to wait for),
        then start the control loop."""
        while self._pool_size() < self.min_replicas:
            self._scale_up(reason="min_replicas floor")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-autoscaler")
        self._thread.start()
        return self

    def stop(self, timeout=5, retire_owned=False):
        """Stop the control loop; ``retire_owned=True`` also drains and
        retires every replica this autoscaler spawned."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        if retire_owned:
            with self._lock:
                owned = dict(self._owned)
                self._owned.clear()
            for ep, srv in owned.items():
                self.router.remove_replica(ep)
                self._do_retire(srv)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop(retire_owned=True)

    def _run(self):
        while not self._stop.wait(self.poll_s):
            try:
                self.tick()
            except Exception as exc:  # noqa: BLE001 — never dies, but
                # a failing factory/registry must leave a trail: an
                # overloaded fleet pinned at its size with an empty
                # decision log is undiagnosable
                _flightrec().record(
                    "fleet_scale_error",
                    error=f"{type(exc).__name__}: {exc}"[:200])

    # -- signals ----------------------------------------------------------
    def _pool_size(self):
        return sum(1 for r in self.router.registry.all()
                   if r.state != "evicted")

    def sample(self):
        """One pressure sample over the in-rotation replicas: mean
        queue-depth ratio (probed depths + active rows over the probed
        admission capacity), mean kvpool occupancy, and the total
        breached SLO rule count. None when nothing is dispatchable
        (an empty rotation is a scale-up signal of its own)."""
        reps = [r for r in self.router.registry.all()
                if r.dispatchable()]
        if not reps:
            return None
        q_ratios, kv, breached = [], [], 0
        for r in reps:
            h = r.last_health
            cap = int(h.get("queue_capacity") or 0)
            # max, not sum: router-tracked in-flight dispatches SIT in
            # the replica's probed queue/active rows, so adding them
            # would double-count against the absolute capacity ratio;
            # the max keeps the fresher signal as a lower bound when
            # the probe is stale
            depth = max(r.probed_depth(), r.inflight)
            q_ratios.append(depth / cap if cap > 0 else 0.0)
            kv.append(float(h.get("kvpool_occupancy", 0.0) or 0.0))
            breached += int(h.get("slo_breached", 0) or 0)
        return {
            "replicas": len(reps),
            "queue_ratio": sum(q_ratios) / len(q_ratios),
            "kvpool_occupancy": sum(kv) / len(kv),
            "slo_breached": breached,
        }

    def _overloaded(self, s):
        return (s["queue_ratio"] >= self.up_queue_ratio
                or s["kvpool_occupancy"] >= self.up_kv_ratio
                or s["slo_breached"] > 0)

    def _idle(self, s):
        return (s["queue_ratio"] <= self.down_queue_ratio
                and s["kvpool_occupancy"] <= self.down_kv_ratio
                and s["slo_breached"] == 0)

    # -- control law ------------------------------------------------------
    def tick(self, now=None):
        """One control-loop evaluation: fold a sample into the window,
        decide, act. Public so tests drive it deterministically."""
        now = time.monotonic() if now is None else now
        s = self.sample()
        self._update_gauge()
        if s is None:
            # nothing dispatchable: below the floor by definition
            if self._pool_size() < self.min_replicas:
                self._scale_up(reason="rotation empty")
            return None
        with self._lock:
            self._samples.append(s)
            window_full = len(self._samples) == self.window
            all_over = window_full and all(self._overloaded(x)
                                           for x in self._samples)
            all_idle = window_full and all(self._idle(x)
                                           for x in self._samples)
            cooled = now - self._last_scale_at >= self.cooldown_s
        n = self._pool_size()
        if all_over and cooled and n < self.max_replicas:
            self._scale_up(reason=self._reason(s))
        elif all_idle and cooled and n > self.min_replicas:
            self._scale_down()
        return s

    def _reason(self, s):
        parts = []
        if s["queue_ratio"] >= self.up_queue_ratio:
            parts.append(f"queue_ratio {s['queue_ratio']:.2f}")
        if s["kvpool_occupancy"] >= self.up_kv_ratio:
            parts.append(f"kvpool {s['kvpool_occupancy']:.2f}")
        if s["slo_breached"] > 0:
            parts.append(f"slo_breached {s['slo_breached']}")
        return ", ".join(parts) or "window overloaded"

    def _record(self, direction, endpoint, reason):
        # cooldown measured from when the action COMPLETED (spawning/
        # draining a replica can itself take a while — charging that
        # time against the cooldown would let back-to-back windows
        # bypass it)
        with self._lock:
            self._last_scale_at = time.monotonic()
            self._samples.clear()       # a fresh pool needs fresh data
            self.events.append({
                "t": self._last_scale_at, "direction": direction,
                "endpoint": endpoint,
                "replicas": self._pool_size(), "reason": reason,
            })
        _SCALE_EVENTS.inc(labels=(direction,))
        _flightrec().record("fleet_scale", direction=direction,
                            endpoint=str(endpoint),
                            replicas=self._pool_size(),
                            reason=str(reason)[:200])
        self._update_gauge()

    # -- actions ----------------------------------------------------------
    def _scale_up(self, reason=""):
        srv = self.factory()
        ep = getattr(srv, "endpoint", srv)
        with self._lock:
            self._owned[ep] = srv
        self.router.add_replica(ep, role=self.role)
        self._record("up", ep, reason)
        return ep

    def _pick_victim(self):
        """The least-loaded OWNED in-rotation replica — never one the
        operator registered directly (the autoscaler can only retire
        what it spawned)."""
        with self._lock:
            owned = set(self._owned)
        cands = [r for r in self.router.registry.all()
                 if r.endpoint in owned and r.state != "evicted"]
        if not cands:
            return None
        return min(cands, key=lambda r: (r.load_score(), r.endpoint))

    def _do_retire(self, srv):
        try:
            if self._retire is not None:
                self._retire(srv)
            elif hasattr(srv, "drain"):
                srv.drain(timeout=self.drain_timeout_s)
            elif hasattr(srv, "stop"):
                srv.stop()
        except Exception as exc:  # noqa: BLE001 — a wedged retire must
            # not wedge the control loop, but a replica that failed to
            # drain is a potential leak worth a trail
            _flightrec().record(
                "fleet_retire_error",
                endpoint=str(getattr(srv, "endpoint", srv)),
                error=f"{type(exc).__name__}: {exc}"[:200])

    def _scale_down(self):
        rep = self._pick_victim()
        if rep is None:
            return None
        ep = rep.endpoint
        # drain-aware: out of the rotation first, wait for the router's
        # in-flight dispatches to finish, THEN retire (the replica-side
        # drain() additionally finishes its decode rows)
        self.router.registry.set_state(ep, "draining")
        deadline = time.monotonic() + self.drain_timeout_s
        while time.monotonic() < deadline and rep.inflight > 0:
            time.sleep(0.01)
        self.router.remove_replica(ep)
        with self._lock:
            srv = self._owned.pop(ep, None)
        if srv is not None:
            self._do_retire(srv)
        self._record("down", ep, "window idle")
        return ep

    # -- reporting --------------------------------------------------------
    def _update_gauge(self):
        counts = {"serving": 0, "draining": 0, "evicted": 0}
        for r in self.router.registry.all():
            key = {"healthy": "serving", "unknown": "serving"}.get(
                r.state, r.state)
            counts[key] = counts.get(key, 0) + 1
        for state, n in counts.items():
            _REPLICAS.set(n, labels=(state,))

    def stats(self):
        with self._lock:
            return {
                "replicas": self._pool_size(),
                "owned": sorted(self._owned),
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "window": [dict(s) for s in self._samples],
                "last_scale_at": self._last_scale_at,
                "events": [dict(e) for e in self.events],
            }
