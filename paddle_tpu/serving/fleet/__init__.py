"""Disaggregated serving fleet: a ``Router`` tier over N
``InferenceServer`` replicas.

Everything a fleet needs shipped piecemeal in earlier layers — health
states + drain + hedged clients + request-id dedup (serving
resilience), Prometheus gauges incl. ``kvpool_occupancy_ratio`` and
wire-propagated trace contexts (observability), and a block-paged KV
pool whose block tables make in-flight KV state a well-defined,
migratable unit (serving/kvpool). This package composes them:

- :class:`~.registry.ReplicaRegistry` — replica table with health-probe
  loops, telemetry scraping, eviction after consecutive probe failures
  and automatic readmission;
- :class:`~.router.Router` — wire-compatible front-end with
  least-loaded telemetry-driven dispatch, cross-replica failover and
  hedging (request-id dedup: a failover never double-executes),
  drain-aware rolling weight reloads, and DISAGGREGATED
  prefill/decode pools: compute-bound prefill replicas serialize
  finished KV blocks (int8 scales included) out of their pool and the
  router streams them into bandwidth-bound decode replicas, so each
  pool scales on its own roofline.

Quick start::

    from paddle_tpu import serving
    from paddle_tpu.serving import fleet

    reps = [serving.InferenceServer(generator=mkgen(), kv_paged=True,
                                    kv_pool_name=f"rep{i}").start()
            for i in range(3)]
    router = fleet.Router([r.endpoint for r in reps]).start()
    with serving.Client(router.endpoint) as c:      # same protocol
        out = c.generate(prompt_ids, max_new_tokens=64)

Disaggregated split: register replicas with roles instead::

    router = fleet.Router([(pre.endpoint, "prefill"),
                           (dec.endpoint, "decode")]).start()

Autoscaling (:class:`~.autoscaler.Autoscaler`): hand the router a
replica factory and the pool scales itself between
``FLAGS_fleet_min_replicas`` and ``FLAGS_fleet_max_replicas`` on the
probed fleet telemetry (queue ratios, kvpool occupancy, SLO breach
state), with full-window hysteresis + cooldown so it never flaps and a
drain-aware scale-down path::

    scaler = fleet.Autoscaler(router, factory=spawn_replica).start()
"""
from .autoscaler import Autoscaler  # noqa: F401
from .registry import Replica, ReplicaRegistry  # noqa: F401
from .router import FLEET_EVENT_KINDS, Router  # noqa: F401
