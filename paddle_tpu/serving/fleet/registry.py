"""Replica registry: the router's view of the fleet.

Each :class:`Replica` is one ``InferenceServer`` endpoint with a ROLE —
``"both"`` (colocated prefill+decode), ``"prefill"`` (compute-bound pool)
or ``"decode"`` (bandwidth-bound pool) — and the live telemetry the last
health probe scraped (lifecycle state, queue depths, kvpool occupancy).
The :class:`ReplicaRegistry` owns the probe loop:

- every ``FLAGS_router_probe_interval_s`` each replica answers a
  ``health`` probe under ``FLAGS_router_probe_timeout_s`` (the per-call
  Client timeout — a hung replica, stalled accept loop included, fails
  the probe fast instead of inheriting the long execute-path default);
- ``FLAGS_router_evict_after`` consecutive failed probes EVICT the
  replica from the dispatch rotation (flight-recorded, counted).
  Probing continues — one healthy probe READMITS it, so a bounced
  replica rejoins without operator action;
- a transport death observed by a dispatch (``mark_dead``) evicts
  immediately — the prober's job is detecting quiet deaths, not
  gating the loud ones.

``pick()`` is the telemetry-driven dispatch half: among in-rotation
replicas of the wanted roles, it returns the lowest LOAD SCORE — the
router-tracked in-flight dispatches plus the probed queue depths and
active decode rows, plus the probed ``kvpool_occupancy`` weighted so a
nearly-full pool loses ties against an empty one.
"""
import threading
import time

from ...flags import flag
from ...observability.metrics import default_registry
from ...observability.recorder import flight_recorder as _flightrec
from ...resilience import RetryBudget, maybe_fail
from ..server import Client

# probe clients bypass the process retry budget (a disabled private
# bucket): probing is bounded polling infrastructure, and a dead
# replica probed every interval must not drain the shared bucket and
# suppress hedges/failovers for healthy user traffic
_PROBE_BUDGET = RetryBudget(ratio=-1.0)

_HEALTHY = default_registry().gauge(
    "router_replicas_healthy_count",
    "fleet replicas currently in the dispatch rotation",
    labels=("router",), max_series=8)
_PROBE_FAILS = default_registry().counter(
    "router_probe_failures_total",
    "replica health probes that failed (timeout/transport/typed error)",
    labels=("router",), max_series=8)
_EVICTIONS = default_registry().counter(
    "router_replica_evictions_total",
    "replicas evicted from the dispatch rotation by consecutive failed "
    "probes",
    labels=("router",), max_series=8)
_READMISSIONS = default_registry().counter(
    "router_replica_readmissions_total",
    "evicted/dead replicas readmitted by a healthy probe",
    labels=("router",), max_series=8)
_DEATHS = default_registry().counter(
    "router_replica_deaths_total",
    "replica deaths observed by a dispatch (transport failure "
    "mid-request)",
    labels=("router",), max_series=8)

_ROLES = ("both", "prefill", "decode")

# the probed server lifecycle states a replica may be dispatched in
# (draining/degraded/stopped replicas shed or refuse generation — the
# router routes around them instead of bouncing clients off them)
_DISPATCHABLE_STATES = ("serving", "warming")


class Replica:
    """One registered replica endpoint + its probed telemetry. All
    mutation happens under the owning registry's lock."""

    def __init__(self, endpoint, role="both"):
        if role not in _ROLES:
            raise ValueError(f"replica role must be one of {_ROLES}, "
                             f"got {role!r}")
        self.endpoint = str(endpoint)
        self.role = role
        self.state = "unknown"      # unknown|healthy|evicted|draining
        self.probe_failures = 0     # consecutive
        self.last_health = {}       # last successful health() payload
        self.last_probe = 0.0       # monotonic stamp of it
        self.inflight = 0           # router-tracked dispatches right now
        self.dispatched_total = 0
        self.evictions = 0
        self.readmissions = 0

    def probed_depth(self):
        """Probed queued/active work at the replica (infer queue +
        decode queue + active decode rows) — the one copy of the depth
        sum shared by the dispatch score and the autoscaler's pressure
        signal."""
        h = self.last_health
        return (h.get("queue_depth", 0) or 0) \
            + (h.get("decode_queue_depth", 0) or 0) \
            + (h.get("decode_active_rows", 0) or 0)

    def load_score(self):
        """Lower = less loaded. Router-tracked in-flight dispatches are
        the freshest signal (they move between probes); the probed
        queue depths and active decode rows cover traffic from other
        routers/clients; kvpool occupancy (0..1) is weighted x4 so a
        nearly-full pool loses ties well before it starts shedding; a
        replica whose SLO monitor reports breached rules (p99,
        queue/kvpool pressure — observability/slo.py) takes an 8-point
        penalty PER breached rule, so dispatch shifts away from a
        regressed replica before clients feel its tail."""
        h = self.last_health
        occ = float(h.get("kvpool_occupancy", 0.0) or 0.0)
        slo = int(h.get("slo_breached", 0) or 0)
        return self.inflight + self.probed_depth() + 4.0 * occ \
            + 8.0 * slo

    def dispatchable(self):
        return (self.state == "healthy"
                and self.last_health.get("state")
                in _DISPATCHABLE_STATES)

    def snapshot(self):
        """Wire-safe summary for ``Router.stats()``/``health``."""
        h = self.last_health
        return {
            "endpoint": self.endpoint,
            "role": self.role,
            "state": self.state,
            "replica_state": h.get("state"),
            "probe_failures": self.probe_failures,
            "probe_age_s": round(time.monotonic() - self.last_probe, 3)
            if self.last_probe else None,
            "inflight": self.inflight,
            "dispatched_total": self.dispatched_total,
            "evictions": self.evictions,
            "readmissions": self.readmissions,
            "queue_depth": h.get("queue_depth", 0),
            "decode_queue_depth": h.get("decode_queue_depth", 0),
            "decode_active_rows": h.get("decode_active_rows", 0),
            "kvpool_occupancy": h.get("kvpool_occupancy", 0.0),
            "kvpool_evictable_blocks": h.get("kvpool_evictable_blocks",
                                             0),
            "slo_breached": h.get("slo_breached", 0),
            "brownout_level": h.get("brownout_level", 0),
            "queue_capacity": h.get("queue_capacity", 0),
            "weights_version": h.get("weights_version"),
            "load_score": round(self.load_score(), 3),
        }


class ReplicaRegistry:
    """Thread-safe replica table + the health-probe loop."""

    def __init__(self, name="router", auth_key=None,
                 probe_interval_s=None, probe_timeout_s=None,
                 evict_after=None):
        self.name = str(name)
        self._auth_key = auth_key
        self.probe_interval_s = float(
            probe_interval_s if probe_interval_s is not None
            else flag("router_probe_interval_s"))
        self.probe_timeout_s = float(
            probe_timeout_s if probe_timeout_s is not None
            else flag("router_probe_timeout_s"))
        self.evict_after = int(evict_after if evict_after is not None
                               else flag("router_evict_after"))
        self._lock = threading.Lock()
        self._reps = {}             # endpoint -> Replica
        self._clients = {}          # endpoint -> probe Client
        # the probe Client is one-socket/serial — a register-op probe
        # overlapping the prober loop must not interleave frames on it
        self._probe_locks = {}      # endpoint -> Lock
        self._stop = threading.Event()
        self._thread = None

    # -- membership -------------------------------------------------------
    def add(self, endpoint, role="both", probe=True):
        """Register a replica; an immediate synchronous probe (best
        effort) makes it dispatchable without waiting a probe period."""
        rep = Replica(endpoint, role=role)
        with self._lock:
            if rep.endpoint in self._reps:
                raise ValueError(f"replica {rep.endpoint} is already "
                                 f"registered")
            self._reps[rep.endpoint] = rep
        if probe:
            self.probe_once(rep)
        self._update_gauge()
        return rep

    def remove(self, endpoint):
        with self._lock:
            rep = self._reps.pop(str(endpoint), None)
            client = self._clients.pop(str(endpoint), None)
            self._probe_locks.pop(str(endpoint), None)
        if client is not None:
            client.close()
        self._update_gauge()
        return rep is not None

    def get(self, endpoint):
        with self._lock:
            return self._reps.get(str(endpoint))

    def all(self):
        with self._lock:
            return list(self._reps.values())

    def has_role(self, role):
        with self._lock:
            return any(r.role == role for r in self._reps.values())

    def healthy_count(self):
        with self._lock:
            return sum(1 for r in self._reps.values()
                       if r.state == "healthy")

    def any_brownout(self):
        """True when any in-rotation replica's last probe reported an
        active brownout level — the router stops hedging against a
        fleet that is already shedding optional work."""
        with self._lock:
            return any(
                (r.last_health.get("brownout_level") or 0) > 0
                for r in self._reps.values() if r.state == "healthy")

    def snapshot(self):
        with self._lock:
            return {ep: r.snapshot() for ep, r in self._reps.items()}

    # -- dispatch support -------------------------------------------------
    # how much extra load_score the affinity hint may tolerate over
    # the least-loaded candidate before it yields: a warm prefix saves
    # ONE prefill, so it beats a marginally shorter queue but must
    # never pin a hot-prompt stream onto a congested replica while the
    # rest of the fleet idles
    PREFER_SLACK = 4.0

    def pick(self, roles, exclude=(), prefer=None):
        """The least-loaded in-rotation replica whose role is in
        ``roles`` (endpoints in ``exclude`` skipped); None when the
        rotation is empty. ``prefer`` (the router's cache-affinity
        hint) wins over the load-score scan only while its load stays
        within ``PREFER_SLACK`` of the best candidate — a hint, never
        a constraint: an affine replica that is excluded, out of
        rotation, wrong-role or clearly more loaded falls through."""
        exclude = set(exclude)
        with self._lock:
            cands = [r for r in self._reps.values()
                     if r.role in roles and r.endpoint not in exclude
                     and r.dispatchable()]
            if not cands:
                return None
            best = min(cands, key=lambda r: (r.load_score(),
                                             r.endpoint))
            if prefer is not None:
                r = self._reps.get(str(prefer))
                if r is not None and r in cands and \
                        r.load_score() <= best.load_score() \
                        + self.PREFER_SLACK:
                    return r
            return best

    def checkout(self, rep):
        with self._lock:
            rep.inflight += 1
            rep.dispatched_total += 1

    def checkin(self, rep):
        with self._lock:
            rep.inflight = max(rep.inflight - 1, 0)

    def set_state(self, endpoint, state):
        """Manual rotation control (rolling reload uses ``draining`` /
        ``healthy``)."""
        with self._lock:
            rep = self._reps.get(str(endpoint))
            if rep is not None:
                rep.state = state
        self._update_gauge()

    def mark_dead(self, endpoint, reason):
        """A dispatch watched this replica die (transport failure):
        evict immediately — the prober readmits it when it answers
        health probes again."""
        with self._lock:
            rep = self._reps.get(str(endpoint))
            if rep is None or rep.state == "evicted":
                return
            rep.state = "evicted"
            rep.evictions += 1
            rep.probe_failures = max(rep.probe_failures,
                                     self.evict_after)
            client = self._clients.pop(str(endpoint), None)
        if client is not None:
            client.close()
        _DEATHS.inc(labels=(self.name,))
        _flightrec().record("replica_death", router=self.name,
                            endpoint=str(endpoint), reason=str(reason)[:200])
        self._update_gauge()

    # -- probing ----------------------------------------------------------
    def _client(self, endpoint):
        with self._lock:
            c = self._clients.get(endpoint)
            if c is None:
                c = Client(endpoint, auth_key=self._auth_key,
                           timeout=self.probe_timeout_s,
                           connect_retries=1,
                           retry_budget=_PROBE_BUDGET)
                self._clients[endpoint] = c
            return c

    def probe_once(self, rep):
        """One health probe against ``rep``; updates its telemetry and
        walks the evict/readmit state machine. Returns True when the
        replica answered."""
        with self._lock:
            probe_lock = self._probe_locks.setdefault(
                rep.endpoint, threading.Lock())
        try:
            # chaos point INSIDE the failure accounting: an injected
            # probe fault must walk the same evict path a real one does
            maybe_fail("fleet.probe")
            with probe_lock:
                h = self._client(rep.endpoint).health(
                    timeout=self.probe_timeout_s)
        except Exception as exc:  # noqa: BLE001 — every failure counts
            _PROBE_FAILS.inc(labels=(self.name,))
            evict = False
            with self._lock:
                rep.probe_failures += 1
                if rep.probe_failures >= self.evict_after \
                        and rep.state in ("healthy", "unknown"):
                    rep.state = "evicted"
                    rep.evictions += 1
                    evict = True
                client = self._clients.pop(rep.endpoint, None) \
                    if evict else None
            if client is not None:
                client.close()
            if evict:
                _EVICTIONS.inc(labels=(self.name,))
                _flightrec().record(
                    "replica_evicted", router=self.name,
                    endpoint=rep.endpoint,
                    probe_failures=rep.probe_failures,
                    reason=f"{type(exc).__name__}: {exc}"[:200])
                self._update_gauge()
            return False
        readmitted = False
        with self._lock:
            rep.probe_failures = 0
            rep.last_health = h
            rep.last_probe = time.monotonic()
            if rep.state in ("evicted", "unknown"):
                readmitted = rep.state == "evicted"
                rep.state = "healthy"
        if readmitted:
            rep.readmissions += 1
            _READMISSIONS.inc(labels=(self.name,))
            _flightrec().record("replica_readmitted", router=self.name,
                                endpoint=rep.endpoint)
        self._update_gauge()
        return True

    def _update_gauge(self):
        _HEALTHY.set(self.healthy_count(), labels=(self.name,))

    # -- probe loop -------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="router-prober")
        self._thread.start()
        return self

    def stop(self, timeout=2):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            c.close()

    def _run(self):
        while not self._stop.wait(self.probe_interval_s):
            for rep in self.all():
                if self._stop.is_set():
                    return
                if rep.state == "draining":
                    continue       # rolling reload owns this replica
                try:
                    self.probe_once(rep)
                except Exception:  # noqa: BLE001 — the prober never dies
                    pass
