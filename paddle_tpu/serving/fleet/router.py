"""Router tier: one endpoint fronting N ``InferenceServer`` replicas.

The router speaks the SAME length-prefixed wire protocol as the
replicas (an existing ``serving.Client`` pointed at a router cannot
tell the difference), and composes the machinery the serving layer
already has into a fleet:

- **Telemetry-driven dispatch**: every ``generate`` goes to the
  least-loaded in-rotation replica — router-tracked in-flight
  dispatches plus the probed queue depths and ``kvpool_occupancy``
  (see ``registry.Replica.load_score``). Probing, eviction and
  readmission live in :class:`~.registry.ReplicaRegistry`.
- **Failover**: a replica dying mid-request (transport failure) is
  evicted immediately and the request retries on the next replica with
  the SAME request id — at most ``FLAGS_router_dispatch_retries``
  extra attempts, every hop flight-recorded. Typed error replies are
  the answer, not a failure: they pass through (Overloaded/Shutdown
  retry on another replica first — backpressure from one replica is
  not backpressure from the fleet).
- **Hedging** (``FLAGS_router_hedge_ms`` > 0): a routed generate that
  hasn't replied within the delay fires a twin on a SECOND replica;
  the first ok reply wins and the loser is cancelled by request id on
  its replica (Dean & Barroso — the cross-replica version of the
  client-side hedge PR 6 shipped).
- **Request-id dedup**: the router keeps its own rid table — a
  reconnect-replayed ``generate`` ATTACHES to the in-flight dispatch
  instead of dispatching twice, so a failover never double-executes.
- **Disaggregated prefill/decode**: when the fleet has dedicated
  ``prefill`` and ``decode`` replicas, a generate becomes two hops —
  ``prefill`` on a compute-bound replica serializes the finished
  slot's KV blocks (int8 scales included) out of its pool, and the
  router streams them into a bandwidth-bound decode replica's pool via
  ``generate``'s ``kv=`` field. Each pool scales on its own roofline;
  every migration is counted (``fleet_kv_*``) and flight-recorded.
- **Rolling weight reloads**: :meth:`Router.rolling_reload` drains and
  reloads ONE replica at a time through the PR-6 ``reload_weights``
  machinery — the fleet never loses more than one replica of capacity.
"""
import re
import socket
import threading
import time
import uuid
from collections import OrderedDict

import numpy as np

from ...distributed.wire import (WireError, default_key, recv_frame,
                                 send_frame)
from ...flags import flag
from ...observability import tracing as _trace
from ...observability.metrics import default_registry, render_metrics
from ...observability.recorder import flight_recorder as _flightrec
from ...resilience import default_retry_budget, maybe_fail
from ..batching import (DeadlineExceededError, ServerOverloadedError,
                        priority_rank, remaining_budget_ms)
from ..kvpool import KVBlockPool, prompt_prefix_key
from ..server import _ETYPES, _error_reply
from .registry import ReplicaRegistry

_DISPATCH = default_registry().counter(
    "router_dispatch_total",
    "downstream requests dispatched to replicas, by hop role",
    labels=("router", "role"), max_series=16)
_FAILOVERS = default_registry().counter(
    "router_failovers_total",
    "dispatches retried on another replica after a transport death",
    labels=("router",), max_series=8)
_HEDGES = default_registry().counter(
    "router_hedges_total",
    "cross-replica hedge twins fired by the router",
    labels=("router",), max_series=8)
_DEDUP_HITS = default_registry().counter(
    "router_dedup_hits_total",
    "routed requests that attached to an in-flight dispatch by rid",
    labels=("router",), max_series=8)
_KV_MIGRATIONS = default_registry().counter(
    "fleet_kv_migrations_total",
    "prefill->decode KV-block migrations routed across replicas",
    labels=("router",), max_series=8)
_KV_MIG_BYTES = default_registry().counter(
    "fleet_kv_migrated_bytes_total",
    "payload array bytes streamed prefill->decode across replicas",
    labels=("router",), max_series=8)

_FLEET_SCRAPE_FAILS = default_registry().counter(
    "router_fleet_scrape_failures_total",
    "replica metric scrapes that failed during fleet-wide aggregation",
    labels=("router",), max_series=8)
_PREFIX_HITS = default_registry().counter(
    "router_prefix_hits_total",
    "routed generates dispatched to the replica whose KV pool cached "
    "this prompt's prefix (cache-affinity hit)",
    labels=("router",), max_series=8)
_PREFIX_MISSES = default_registry().counter(
    "router_prefix_misses_total",
    "routed generates whose affine replica was unknown or out of "
    "rotation — dispatched by load score instead",
    labels=("router",), max_series=8)

_COUNTERS = ("dispatches", "failovers", "hedges", "hedge_wins",
             "dedup_hits", "kv_migrations", "kv_migrated_bytes",
             "rolling_reloads", "no_replica_refusals",
             "fleet_scrape_failures", "hedges_suppressed",
             "failovers_suppressed", "deadline_expired_in_router",
             "prefix_hits", "prefix_misses")

# prompt tokens hashed into the affinity key: enough to separate real
# prompt families, short enough that shared system-prompt prefixes
# (the case block-granular caching wins on) collide INTO affinity
_PREFIX_AFFINITY_WINDOW = 32

# flight-recorder event kinds the fleet emits (Router.stats surfaces
# their in-ring counts; the debug_dump wire op returns the events)
FLEET_EVENT_KINDS = ("replica_death", "replica_evicted",
                     "replica_readmitted", "failover", "kv_migration",
                     "rolling_reload")


_SAMPLE_RE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(\{(.*)\})?\s+(\S+)$")


def _merge_expositions(sources, max_replicas=16):
    """Merge ``[(replica_label, prometheus_text)]`` into ONE exposition
    where every sample line carries a ``replica`` label (the router's
    own process metrics ride as ``replica="router"``). Family HELP/TYPE
    headers are emitted once (first seen — duplicate family blocks are
    invalid exposition); sources past ``max_replicas`` fold into
    ``replica="_other"`` with values SUMMED per series, the same
    bounded-cardinality overflow idiom the registry families use."""
    order, meta, fam_lines = [], {}, {}
    other, other_order = {}, {}
    histograms = set()

    def _family(fam):
        if fam not in meta:
            meta[fam] = {}
            order.append(fam)
            fam_lines[fam] = []
        return meta[fam]

    for idx, (label, text) in enumerate(sources):
        fold = idx >= max_replicas
        for line in text.splitlines():
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                parts = line.split(" ", 3)
                if len(parts) < 3:
                    continue
                m = _family(parts[2])
                m.setdefault(parts[1], line)
                if parts[1] == "TYPE" and len(parts) > 3 \
                        and parts[3].strip() == "histogram":
                    histograms.add(parts[2])
                continue
            if not line.strip() or line.startswith("#"):
                continue
            sm = _SAMPLE_RE.match(line)
            if sm is None:
                continue
            name, labelstr, value = sm.group(1), sm.group(3) or "", \
                sm.group(4)
            fam = name
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) \
                        and name[: -len(suffix)] in histograms:
                    fam = name[: -len(suffix)]
                    break
            _family(fam)
            if fold:
                try:
                    v = float(value)
                except ValueError:
                    continue
                k = (name, labelstr)
                if k not in other:
                    other[k] = 0.0
                    other_order.setdefault(fam, []).append(k)
                other[k] += v
            else:
                inner = f'replica="{label}"' \
                    + ("," + labelstr if labelstr else "")
                fam_lines[fam].append(f"{name}{{{inner}}} {value}")
    out = []
    for fam in order:
        for key in ("HELP", "TYPE"):
            if key in meta[fam]:
                out.append(meta[fam][key])
        out.extend(fam_lines[fam])
        for name, labelstr in other_order.get(fam, ()):
            v = other[(name, labelstr)]
            vs = str(int(v)) if v == int(v) else repr(v)
            inner = 'replica="_other"' \
                + ("," + labelstr if labelstr else "")
            out.append(f"{name}{{{inner}}} {vs}")
    return "\n".join(out) + "\n"


class _InflightCall:
    """Router-side dedup entry: the twin of a hedged/replayed routed
    request waits on the first dispatch's reply instead of dispatching
    again. ``targets`` (the endpoints this rid was sent to) is written
    by dispatch threads and read by hedge/cancel bookkeeping — always
    through the locked accessors."""

    __slots__ = ("reply", "_targets", "_tlock", "_done")

    def __init__(self):
        self.reply = None
        self._targets = set()       # endpoints this rid was sent to
        self._tlock = threading.Lock()
        self._done = threading.Event()

    def add_target(self, endpoint):
        with self._tlock:
            self._targets.add(endpoint)

    def targets(self):
        with self._tlock:
            return set(self._targets)

    def finish(self, reply):
        self.reply = reply
        self._done.set()

    def wait(self, timeout):
        if not self._done.wait(timeout):
            return {"ok": False, "etype": "DeadlineExceeded",
                    "error": "joined an in-flight routed request that "
                             "did not finish in time"}
        return self.reply


class Router:
    """Fleet front-end. In-process use::

        router = fleet.Router([srv1.endpoint, srv2.endpoint]).start()
        out = router.generate(prompt_ids, max_new_tokens=32)

    Network use: ``Client(router.endpoint)`` speaks the ordinary wire
    protocol (``generate``/``health``/``stats``/``metrics``/
    ``debug_dump``/``cancel``), plus ``{"op": "register", "endpoint",
    "role"}`` for membership and ``{"op": "reload_weights", "path"}``
    for a fleet-wide rolling reload. ``replicas`` entries are endpoints
    or ``(endpoint, role)`` pairs with role in ``both``/``prefill``/
    ``decode``."""

    def __init__(self, replicas=(), *, name="router", host="127.0.0.1",
                 port=0, auth_key=None, allow_insecure=False,
                 probe_interval_s=None, probe_timeout_s=None,
                 evict_after=None, hedge_ms=None,
                 dispatch_retries=None):
        self.name = str(name)
        self.host = host
        self.port = int(port)
        self._key = auth_key if auth_key is not None else default_key()
        self._allow_insecure = allow_insecure
        self.registry = ReplicaRegistry(
            name=self.name, auth_key=auth_key,
            probe_interval_s=probe_interval_s,
            probe_timeout_s=probe_timeout_s, evict_after=evict_after)
        self._hedge_ms = float(hedge_ms if hedge_ms is not None
                               else flag("router_hedge_ms"))
        self._dispatch_retries = int(
            dispatch_retries if dispatch_retries is not None
            else flag("router_dispatch_retries"))
        for entry in replicas:
            if isinstance(entry, (tuple, list)):
                self.add_replica(*entry)
            else:
                self.add_replica(entry)
        self._sock = None
        self._stop = threading.Event()
        self._threads = []
        self._conns = set()
        self._conns_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._lifecycle = "created"
        self._started_at = time.monotonic()
        # downstream socket pool: per-endpoint free list (each routed
        # exchange is serial on its socket; concurrent handler threads
        # check out their own)
        self._pool = {}
        self._pool_lock = threading.Lock()
        # router-side rid dedup (failover/replay never double-executes)
        self._rids = OrderedDict()
        self._rids_lock = threading.Lock()
        self._rid_cap = 2048
        # prefix-affinity map: prompt-prefix content hash -> the
        # replica that last served (and so block-cached) that prefix.
        # LRU-capped; stale entries cost one miss, never a wrong answer
        # (the preferred replica still has to be in rotation, and a
        # cold pool just re-prefills)
        self._affinity = OrderedDict()
        self._affinity_lock = threading.Lock()
        self._affinity_cap = 4096
        self._c = {k: 0 for k in _COUNTERS}
        self._c_lock = threading.Lock()

    # -- lifecycle --------------------------------------------------------
    @property
    def endpoint(self):
        return f"{self.host}:{self.port}"

    @property
    def state(self):
        with self._state_lock:
            return self._lifecycle

    @property
    def disaggregated(self):
        """True when the fleet has BOTH dedicated prefill and dedicated
        decode replicas — generate then runs as two hops with a KV
        migration between them."""
        return (self.registry.has_role("prefill")
                and self.registry.has_role("decode"))

    def add_replica(self, endpoint, role="both"):
        """Register (and immediately probe) a replica."""
        return self.registry.add(endpoint, role=role)

    def remove_replica(self, endpoint):
        self._drop_pool(endpoint)
        with self._affinity_lock:
            stale = [k for k, ep in self._affinity.items()
                     if ep == endpoint]
            for k in stale:
                del self._affinity[k]
        return self.registry.remove(endpoint)

    def start(self, serve_network=True):
        self.registry.start()
        if serve_network:
            loopback = (self.host.startswith("127.")
                        or self.host in ("localhost", "::1"))
            if not loopback and self._key is None \
                    and not self._allow_insecure:
                raise PermissionError(
                    f"refusing to bind the router on non-loopback "
                    f"{self.host}:{self.port} without authentication — "
                    f"set PADDLE_PS_AUTH_KEY (both ends) or pass "
                    f"allow_insecure=True")
            self._sock = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
            self._sock.bind((self.host, self.port))
            self.port = self._sock.getsockname()[1]
            self._sock.listen(128)
            t = threading.Thread(target=self._accept_loop, daemon=True,
                                 name="router-accept")
            t.start()
            self._threads.append(t)
        with self._state_lock:
            self._lifecycle = "serving"
        return self

    def stop(self):
        with self._state_lock:
            self._lifecycle = "stopped"
        self._stop.set()
        self.registry.stop()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        with self._pool_lock:
            pool, self._pool = self._pool, {}
        for socks in pool.values():
            for s in socks:
                try:
                    s.close()
                except OSError:
                    pass
        for t in self._threads:
            t.join(timeout=2)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- bookkeeping ------------------------------------------------------
    def _bump(self, name, n=1):
        with self._c_lock:
            self._c[name] += n

    def stats(self):
        """Fleet snapshot: router counters, per-replica telemetry (the
        probed load signals the dispatcher reads), the rid-table size
        and the in-ring counts of the fleet's flight-recorder events
        (deaths, failovers, evictions/readmissions, KV migrations,
        rolling reloads)."""
        with self._c_lock:
            c = dict(self._c)
        rec_counts = _flightrec().counts()
        out = {
            "state": self.state,
            "uptime_s": round(time.monotonic() - self._started_at, 3),
            "disaggregated": self.disaggregated,
            "replicas": self.registry.snapshot(),
            "replicas_healthy": self.registry.healthy_count(),
            "rid_table": len(self._rids),
            "affinity_table": len(self._affinity),
            "fleet_events": {k: rec_counts.get(k, 0)
                             for k in FLEET_EVENT_KINDS},
        }
        out.update({f"router_{k}": v for k, v in c.items()})
        return out

    def health(self):
        return {
            "state": self.state,
            "uptime_s": round(time.monotonic() - self._started_at, 3),
            "replicas_total": len(self.registry.all()),
            "replicas_healthy": self.registry.healthy_count(),
            "disaggregated": self.disaggregated,
        }

    def fleet_metrics(self, max_replicas=16):
        """Fleet-wide metrics aggregation (the ``"metrics"`` wire op's
        reply): scrape every registered replica's Prometheus exposition
        over the wire and re-expose all samples — the router's own
        process metrics included — with a ``replica`` label, so ONE
        scrape sees the whole fleet. Replicas past ``max_replicas``
        fold into ``replica="_other"`` (summed — the bounded-
        cardinality overflow idiom); a replica that fails its scrape is
        skipped and counted (``router_fleet_scrape_failures_total``)
        rather than failing the whole scrape."""
        sources = [("router", render_metrics())]
        failures = 0
        for rep in self.registry.all():
            if rep.state == "evicted":
                # a dead replica would burn a full connect timeout PER
                # SCRAPE (serially — 5 dead replicas blow a Prometheus
                # scrape_timeout); the prober readmits it when it
                # answers health again, and then it is scraped
                continue
            try:
                reply = self._exchange(rep.endpoint, {"op": "metrics"},
                                       self.registry.probe_timeout_s)
            except Exception:  # noqa: BLE001 — one replica never kills
                failures += 1  # the fleet scrape
                continue
            if reply.get("ok") and isinstance(reply.get("metrics"),
                                              str):
                sources.append((rep.endpoint, reply["metrics"]))
            else:
                failures += 1
        if failures:
            _FLEET_SCRAPE_FAILS.inc(failures, labels=(self.name,))
            self._bump("fleet_scrape_failures", failures)
        # +1: the router's own exposition occupies slot 0 and must not
        # count a replica out of the cap
        return _merge_expositions(sources,
                                  max_replicas=max_replicas + 1)

    # -- downstream socket pool -------------------------------------------
    def _checkout(self, endpoint, timeout):
        """-> (socket, pooled): ``pooled`` means the socket sat idle in
        the free list and may be stale (the replica bounced since)."""
        with self._pool_lock:
            socks = self._pool.get(endpoint)
            if socks:
                return socks.pop(), True
        host, port = endpoint.rsplit(":", 1)
        return socket.create_connection(
            (host, int(port)),
            timeout=min(timeout, self.registry.probe_timeout_s)
            if timeout else self.registry.probe_timeout_s), False

    def _checkin(self, endpoint, sock):
        with self._pool_lock:
            if self._stop.is_set():
                pass            # closing below, don't re-pool
            else:
                self._pool.setdefault(endpoint, []).append(sock)
                return
        try:
            sock.close()
        except OSError:
            pass

    def _drop_pool(self, endpoint):
        with self._pool_lock:
            socks = self._pool.pop(endpoint, [])
        for s in socks:
            try:
                s.close()
            except OSError:
                pass

    def _exchange(self, endpoint, msg, timeout):
        """One serial request/reply against a replica. Any failure
        poisons the socket (never re-pooled — a half-done exchange
        could pair the next request with a stale reply). A transport
        failure on a POOLED socket retries ONCE on a fresh connection
        — an idle pooled socket to a replica that bounced in between
        is stale, not dead, and must not read as a replica death
        (generate carries a rid the replica dedups; probe/control ops
        are idempotent). An explicit timeout never retries: the reply
        not arriving IS the answer."""
        for attempt in (0, 1):
            sock, pooled = self._checkout(endpoint, timeout)
            try:
                send_frame(sock, msg, self._key, timeout=timeout)
                reply = recv_frame(sock, self._key, timeout=timeout)
            except socket.timeout:
                try:
                    sock.close()
                except OSError:
                    pass
                raise
            except (ConnectionError, OSError):
                try:
                    sock.close()
                except OSError:
                    pass
                if pooled and attempt == 0 and not self._stop.is_set():
                    continue
                raise
            except BaseException:
                try:
                    sock.close()
                except OSError:
                    pass
                raise
            self._checkin(endpoint, sock)
            if not isinstance(reply, dict):
                raise WireError(
                    f"malformed replica reply: {type(reply)}")
            return reply
        raise AssertionError("unreachable")

    # -- prefix affinity --------------------------------------------------
    def _affinity_key(self, tokens):
        """The fleet-wide prefix address of a prompt: the same content
        hash the replica pools key their block index by, over the first
        ``_PREFIX_AFFINITY_WINDOW`` tokens. None (affinity disabled)
        while ``FLAGS_kv_prefix_cache`` is off — with no replica-side
        cache a sticky route buys nothing and only fights the
        load-score balancer."""
        if not flag("kv_prefix_cache"):
            return None
        try:
            a = np.asarray(tokens, np.int32).ravel()
        except (TypeError, ValueError):
            return None
        if a.size == 0:
            return None
        return prompt_prefix_key(a, min(a.size,
                                        _PREFIX_AFFINITY_WINDOW))

    def _affinity_lookup(self, key):
        if key is None:
            return None
        with self._affinity_lock:
            ep = self._affinity.get(key)
            if ep is not None:
                self._affinity.move_to_end(key)
            return ep

    def _affinity_record(self, key, endpoint):
        if key is None or endpoint is None:
            return
        with self._affinity_lock:
            self._affinity[key] = endpoint
            self._affinity.move_to_end(key)
            while len(self._affinity) > self._affinity_cap:
                self._affinity.popitem(last=False)

    # -- dispatch ---------------------------------------------------------
    def _dispatch(self, msg, roles, timeout, entry=None,
                  role_label="both", exclude=(), budget=None,
                  prefer=None):
        """Dispatch ``msg`` to the least-loaded replica of ``roles``;
        fail over (same rid) on transport death or a typed
        Overloaded/Shutdown refusal, up to
        ``FLAGS_router_dispatch_retries`` extra replicas. Returns
        ``(reply, endpoint)`` — ``reply`` is the replica's wire dict
        (or a typed error reply when the rotation is exhausted).

        ``budget`` is ``(deadline_ms, t0)`` deadline propagation: each
        hop carries the budget REMAINING at its send (router queue/
        failover time subtracted), and a spent budget returns the typed
        expiry without touching a replica. Failover attempts past the
        first withdraw from the process retry budget — when the fleet
        is saturated the rotation walk itself must not multiply load
        (typed Overloaded shed instead).

        ``prefer`` (cache-affinity) is an endpoint to try FIRST when it
        is in rotation — a hint, never a constraint: an out-of-rotation
        or refusing affine replica falls back to the load-score pick on
        the very next attempt."""
        tried = set(exclude)
        last_refusal = None
        for attempt in range(self._dispatch_retries + 1):
            # cheap disqualifiers FIRST: a spent deadline or an empty
            # rotation must not burn a retry-budget token — under
            # overload that waste is exactly what drains the bucket
            # the other layers depend on
            if budget is not None and budget[0] is not None:
                rem = remaining_budget_ms(budget[0], budget[1])
                if rem <= 0:
                    self._bump("deadline_expired_in_router")
                    return _error_reply(DeadlineExceededError(
                        f"deadline budget of {float(budget[0]):.1f}ms "
                        f"spent at the router (queue + "
                        f"{attempt} dispatch attempt(s)) — not "
                        f"forwarded", deadline_ms=float(budget[0]))), \
                        None
                msg["deadline_ms"] = rem
            rep = self.registry.pick(roles, exclude=tried,
                                     prefer=prefer)
            if rep is None:
                break
            if attempt > 0 and not default_retry_budget().try_acquire(
                    what="router-failover"):
                self._bump("failovers_suppressed")
                return _error_reply(ServerOverloadedError(
                    f"router {self.name!r}: retry budget exhausted "
                    f"after {attempt} attempt(s) — shedding instead of "
                    f"walking the rotation")), None
            tried.add(rep.endpoint)
            if entry is not None:
                entry.add_target(rep.endpoint)
            maybe_fail("fleet.dispatch")
            _DISPATCH.inc(labels=(self.name, role_label))
            self._bump("dispatches")
            self.registry.checkout(rep)
            try:
                reply = self._exchange(rep.endpoint, msg, timeout)
            except (ConnectionError, OSError) as exc:
                # the rest of the free list to this endpoint is as
                # suspect as the socket that just died
                self._drop_pool(rep.endpoint)
                self.registry.mark_dead(
                    rep.endpoint,
                    f"dispatch transport failure: "
                    f"{type(exc).__name__}: {exc}")
                _FAILOVERS.inc(labels=(self.name,))
                self._bump("failovers")
                _flightrec().record(
                    "failover", router=self.name, rid=msg.get("rid"),
                    from_endpoint=rep.endpoint, attempt=attempt,
                    error=f"{type(exc).__name__}: {exc}"[:200])
                continue
            finally:
                self.registry.checkin(rep)
            if not reply.get("ok") \
                    and reply.get("etype") in ("Overloaded", "Shutdown"):
                # backpressure from ONE replica is not backpressure
                # from the fleet: remember the refusal, try the next
                last_refusal = reply
                continue
            return reply, rep.endpoint
        if last_refusal is not None:
            return last_refusal, None
        self._bump("no_replica_refusals")
        return _error_reply(ServerOverloadedError(
            f"router {self.name!r}: no healthy "
            f"{'/'.join(sorted(roles))} replica in rotation "
            f"({len(self.registry.all())} registered) — back off and "
            f"retry")), None

    def _dispatch_hedged(self, msg, roles, timeout, entry,
                         role_label="both", budget=None, prefer=None):
        """Race the primary dispatch against a delayed twin on ANOTHER
        replica (``FLAGS_router_hedge_ms``; 0 = plain dispatch). First
        ok reply wins; the loser is cancelled by rid on every other
        target.

        Hedging is optional tail-fighting work, so it is the first
        thing overload control turns off: only interactive-class
        requests hedge, a fleet with any brownout-active replica does
        not hedge at all, and the twin withdraws from the process retry
        budget (suppressions counted in ``stats()``)."""
        delay_s = self._hedge_ms / 1e3
        if delay_s > 0 and (priority_rank(msg.get("priority")) > 0
                            or self.registry.any_brownout()):
            delay_s = 0.0
        if delay_s <= 0:
            return self._dispatch(msg, roles, timeout, entry=entry,
                                  role_label=role_label, budget=budget,
                                  prefer=prefer)
        # "ok" holds the first ok reply (the winner); "last" the most
        # recent non-ok one, so a leg that comes back with a typed
        # refusal BEFORE the hedge delay still yields a reply instead
        # of stranding the caller
        state = {"ok": None, "last": None, "done": 0}
        cv = threading.Condition()

        def attempt(tag, exclude):
            try:
                # each leg owns its COPY: _dispatch rewrites the
                # remaining-deadline field per attempt, and a shared
                # dict would let one leg's rewrite race the other
                # leg's frame serialization (the affinity hint rides
                # only the primary leg — a hedge twin on the SAME
                # replica would be no hedge at all)
                r, ep = self._dispatch(dict(msg), roles, timeout,
                                       entry=entry,
                                       role_label=role_label,
                                       exclude=exclude, budget=budget,
                                       prefer=prefer
                                       if tag == "primary" else None)
            except Exception as exc:  # noqa: BLE001 — the leg MUST
                # report in: a dying thread that never bumps "done"
                # (WireError, injected fault, ...) would strand the
                # handler in the final wait_for forever
                r, ep = _error_reply(exc), None
            with cv:
                state["done"] += 1
                if r.get("ok") and state["ok"] is None:
                    state["ok"] = ((r, ep), tag)
                else:
                    state["last"] = ((r, ep), tag)
                cv.notify_all()

        primary_eps = set()
        t = threading.Thread(target=attempt, args=("primary", ()),
                             daemon=True, name="router-primary")
        t.start()
        with cv:
            cv.wait_for(lambda: state["done"] >= 1, timeout=delay_s)
            fire = state["done"] < 1
            primary_eps = entry.targets()
        launched = 1
        if fire and not default_retry_budget().try_acquire(
                what="router-hedge"):
            self._bump("hedges_suppressed")
            fire = False
        if fire:
            _HEDGES.inc(labels=(self.name,))
            self._bump("hedges")
            threading.Thread(target=attempt,
                             args=("hedge", primary_eps),
                             daemon=True, name="router-hedge").start()
            launched = 2
        with cv:
            cv.wait_for(lambda: state["ok"] is not None
                        or state["done"] >= launched)
            (reply, ep), who = (state["ok"] if state["ok"] is not None
                                else state["last"])
        if launched == 2:
            if who == "hedge" and reply.get("ok"):
                self._bump("hedge_wins")
            # cancel the loser wherever else the rid landed —
            # fire-and-forget on a background thread: a hung loser must
            # not delay the winning reply that is already in hand
            losers = entry.targets() - ({ep} if ep else set())
            if losers:
                threading.Thread(
                    target=self._cancel_losers,
                    args=(losers, msg.get("rid")),
                    daemon=True, name="router-hedge-cancel").start()
        return reply, ep

    def _cancel_losers(self, losers, rid):
        for loser in losers:
            try:
                self._exchange(loser, {"op": "cancel", "rid": rid},
                               self.registry.probe_timeout_s)
            except Exception:  # noqa: BLE001 — best-effort cancel
                pass

    # -- rid dedup --------------------------------------------------------
    def _dedup_entry(self, rid):
        """Returns ``(entry, joined)`` — ``joined`` means another
        handler thread already owns the dispatch for this rid and the
        caller should wait on the entry instead of dispatching."""
        if not rid:
            return _InflightCall(), False
        with self._rids_lock:
            ent = self._rids.get(rid)
            if ent is not None:
                self._rids.move_to_end(rid)
                return ent, True
            ent = _InflightCall()
            self._rids[rid] = ent
            while len(self._rids) > self._rid_cap:
                self._rids.popitem(last=False)
            return ent, False

    # -- routed generate --------------------------------------------------
    def _route_generate(self, msg):
        # the deadline clock starts the moment the router OWNS the
        # request: every downstream hop carries what remains after the
        # router's own queue/dispatch time
        t0 = time.monotonic()
        rid = msg.get("rid")
        entry, joined = self._dedup_entry(rid)
        if joined:
            _DEDUP_HITS.inc(labels=(self.name,))
            self._bump("dedup_hits")
            budget = msg.get("deadline_ms")
            return entry.wait((budget / 1e3 + 120.0) if budget
                              else 600.0)
        default_retry_budget().record_request()
        try:
            reply = self._route_generate_inner(msg, entry, t0)
        except Exception as exc:  # noqa: BLE001 — typed reply, not death
            reply = _error_reply(exc)
        entry.finish(reply)
        return reply

    def _route_generate_inner(self, msg, entry, t0):
        tokens = msg.get("tokens")
        if tokens is None:
            return {"ok": False, "etype": "BadRequest",
                    "error": "'tokens' (1-D int prompt) is required"}
        budget = msg.get("deadline_ms")
        hop_timeout = (budget / 1e3 + 120.0) if budget else 600.0
        hop_budget = (budget, t0)
        parent = _trace.from_wire(msg.get("trace"))
        with _trace.span("router/generate", parent=parent) as ctx:
            downstream_trace = _trace.to_wire(ctx)
            if not self.disaggregated:
                fwd = dict(msg)
                if downstream_trace is not None:
                    fwd["trace"] = downstream_trace
                akey = self._affinity_key(tokens)
                prefer = self._affinity_lookup(akey)
                reply, ep = self._dispatch_hedged(
                    fwd, ("both",), hop_timeout, entry,
                    role_label="both", budget=hop_budget,
                    prefer=prefer)
                self._note_affinity(akey, prefer, ep,
                                    bool(reply.get("ok")))
                return reply
            return self._route_disaggregated(msg, entry, hop_timeout,
                                             downstream_trace,
                                             hop_budget)

    def _note_affinity(self, key, prefer, landed, ok):
        """Affinity accounting after a routed prefill landed: a HIT is
        the dispatch actually reaching the affine replica (whose pool
        then answers the prefix out of cached blocks); everything else
        — unknown prefix, affine replica out of rotation or refusing —
        is a MISS that falls back to load-score dispatch, and the
        winning replica becomes the prefix's new home."""
        if key is None:
            return
        if prefer is not None and landed == prefer:
            _PREFIX_HITS.inc(labels=(self.name,))
            self._bump("prefix_hits")
        else:
            _PREFIX_MISSES.inc(labels=(self.name,))
            self._bump("prefix_misses")
        if ok and landed is not None:
            self._affinity_record(key, landed)

    def _route_disaggregated(self, msg, entry, hop_timeout, trace,
                             hop_budget):
        """Two-hop generate: prefill on a compute-bound replica, KV
        blocks streamed into a bandwidth-bound decode replica. Both
        hops carry the REMAINING deadline budget — the decode hop
        inherits what the prefill hop left unspent."""
        rid = msg.get("rid") or uuid.uuid4().hex
        pmsg = {
            "op": "prefill",
            "tokens": msg["tokens"],
            "max_new_tokens": int(msg.get("max_new_tokens", 32)),
            "temperature": float(msg.get("temperature", 0.0)),
            "top_k": int(msg.get("top_k", 0)),
            "deadline_ms": msg.get("deadline_ms"),
            "rid": f"{rid}-prefill",
        }
        if msg.get("priority") is not None:
            pmsg["priority"] = msg["priority"]
        if trace is not None:
            pmsg["trace"] = trace
        # cache affinity binds the PREFILL hop: that is the hop whose
        # pool holds (or rebuilds) the prompt's prefix blocks — the
        # decode hop imports its KV over the wire either way
        akey = self._affinity_key(msg["tokens"])
        prefer = self._affinity_lookup(akey)
        reply, src = self._dispatch_hedged(pmsg, ("prefill", "both"),
                                           hop_timeout, entry,
                                           role_label="prefill",
                                           budget=hop_budget,
                                           prefer=prefer)
        self._note_affinity(akey, prefer, src, bool(reply.get("ok")))
        if not reply.get("ok"):
            return reply
        kv = reply["kv"]
        first = int(kv["first_token"])
        nbytes = KVBlockPool.payload_bytes(kv)
        # the prefill alone may already answer the request: its sampled
        # token hit EOS, or the budget was one token — no migration
        eos = msg.get("eos_id")
        if eos is not None and first == int(eos):
            return {"ok": True, "tokens": np.asarray([], np.int32),
                    "generated": 0}
        if int(msg.get("max_new_tokens", 32)) <= 1:
            return {"ok": True, "tokens": np.asarray([first], np.int32),
                    "generated": 1}
        dmsg = {
            "op": "generate",
            "tokens": msg["tokens"],
            "max_new_tokens": int(msg.get("max_new_tokens", 32)),
            "temperature": float(msg.get("temperature", 0.0)),
            "top_k": int(msg.get("top_k", 0)),
            "eos_id": msg.get("eos_id"),
            "deadline_ms": msg.get("deadline_ms"),
            "kv": kv,
            "first_token": first,
            "rid": rid,
        }
        if msg.get("priority") is not None:
            dmsg["priority"] = msg["priority"]
        if trace is not None:
            dmsg["trace"] = trace
        reply2, dst = self._dispatch_hedged(dmsg, ("decode", "both"),
                                            hop_timeout, entry,
                                            role_label="decode",
                                            budget=hop_budget)
        _KV_MIGRATIONS.inc(labels=(self.name,))
        _KV_MIG_BYTES.inc(nbytes, labels=(self.name,))
        self._bump("kv_migrations")
        self._bump("kv_migrated_bytes", nbytes)
        _flightrec().record(
            "kv_migration", router=self.name, rid=rid,
            from_endpoint=src, to_endpoint=dst,
            blocks=int(kv.get("nblocks", 0)), bytes=nbytes,
            ok=bool(reply2.get("ok")))
        return reply2

    # -- rolling weight reload --------------------------------------------
    def rolling_reload(self, path, drain_timeout=30.0,
                       reload_timeout=120.0):
        """Drain-aware rolling weight reload: ONE replica at a time
        leaves the dispatch rotation (``draining``), the router waits
        for its in-flight dispatches to hit zero (the replica-side
        ``reload_weights`` additionally lets in-flight generations
        finish on the old weights), reloads it over the wire, then
        returns it to rotation. The fleet never loses more than one
        replica of capacity. Returns
        ``{endpoint: {"ok", "weights_version"| "error"}}``."""
        out = {}
        for rep in self.registry.all():
            ep = rep.endpoint
            prev_state = rep.state
            self.registry.set_state(ep, "draining")
            _flightrec().record("rolling_reload", router=self.name,
                                endpoint=ep, phase="drain")
            deadline = time.monotonic() + float(drain_timeout)
            while time.monotonic() < deadline and rep.inflight > 0:
                time.sleep(0.01)
            try:
                reply = self._exchange(
                    ep, {"op": "reload_weights", "path": str(path),
                         "timeout": float(reload_timeout)},
                    float(reload_timeout) + 10.0)
            except Exception as exc:  # noqa: BLE001 — per-replica fate
                reply = _error_reply(exc)
            if reply.get("ok"):
                out[ep] = {"ok": True,
                           "weights_version": reply["weights_version"]}
                self._bump("rolling_reloads")
                self.registry.set_state(ep, "healthy")
                self.registry.probe_once(rep)    # refresh telemetry
                _flightrec().record(
                    "rolling_reload", router=self.name, endpoint=ep,
                    phase="done",
                    weights_version=reply["weights_version"])
            else:
                out[ep] = {"ok": False, "error": reply.get("error"),
                           "etype": reply.get("etype")}
                # a replica that failed its reload is NOT readmitted
                # with ambiguous weights — evict it; the prober
                # readmits once it answers health probes again (an
                # operator bounce or a successful retry)
                self.registry.set_state(ep, prev_state)
                self.registry.mark_dead(
                    ep, f"rolling reload failed: {reply.get('error')}")
                _flightrec().record("rolling_reload", router=self.name,
                                    endpoint=ep, phase="failed",
                                    error=str(reply.get("error"))[:200])
        return out

    # -- in-process convenience (tests / bench) ---------------------------
    def generate(self, tokens, max_new_tokens=32, temperature=0.0,
                 top_k=0, eos_id=None, deadline_ms=None, priority=None):
        """Routed generation without a socket in between: same dispatch
        path the wire op takes; raises the typed serving errors."""
        msg = {
            "op": "generate",
            "tokens": np.asarray(tokens, np.int32).ravel(),
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "top_k": int(top_k),
            "eos_id": None if eos_id is None else int(eos_id),
            "deadline_ms": deadline_ms,
            "rid": uuid.uuid4().hex,
        }
        if priority is not None:
            msg["priority"] = str(priority)
        ctx = _trace.maybe_trace()
        if ctx is not None:
            msg["trace"] = _trace.to_wire(ctx)
        reply = self._route_generate(msg)
        if not reply.get("ok"):
            from ..batching import InternalServerError
            raise _ETYPES.get(reply.get("etype"),
                              InternalServerError)(
                reply.get("error", "routed generate failed"))
        return np.asarray(reply["tokens"], np.int32)

    # -- wire front-end ---------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.2)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True, name="router-conn")
            t.start()
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _serve_conn(self, conn):
        with self._conns_lock:
            self._conns.add(conn)
        try:
            while not self._stop.is_set():
                try:
                    msg = recv_frame(conn, self._key)
                except (ConnectionError, EOFError, OSError):
                    return
                except WireError:
                    return
                try:
                    reply = self._handle(msg)
                except Exception as e:  # noqa: BLE001 — typed reply
                    reply = _error_reply(e)
                try:
                    send_frame(conn, reply, self._key)
                except (ConnectionError, OSError):
                    return
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, msg):
        if not isinstance(msg, dict) or "op" not in msg:
            return {"ok": False, "etype": "BadRequest",
                    "error": "expected a dict with an 'op' field"}
        op = msg["op"]
        if op == "ping":
            return {"ok": True}
        if op in ("stats", "metrics", "health", "cancel"):
            with _trace.span(f"router/{op}",
                             parent=_trace.from_wire(msg.get("trace"))):
                if op == "stats":
                    return {"ok": True, "stats": self.stats()}
                if op == "metrics":
                    # the fleet aggregation: every live replica's
                    # samples re-exposed with a replica label (one
                    # scrape sees the fleet; tools/export_metrics.py
                    # --router is the textfile-collector front-end)
                    return {"ok": True, "metrics": self.fleet_metrics()}
                if op == "health":
                    return {"ok": True, "health": self.health()}
                return self._handle_cancel(msg)
        if op == "debug_dump":
            rec = _flightrec()
            path = None
            if msg.get("write"):
                try:
                    path = rec.dump(reason="router debug_dump wire op")
                except OSError as e:
                    return _error_reply(e)
            return {"ok": True, "events": rec.snapshot(), "path": path}
        if op == "register":
            return self._handle_register(msg)
        if op == "reload_weights":
            path = msg.get("path")
            if not isinstance(path, str) or not path:
                return {"ok": False, "etype": "BadRequest",
                        "error": "'path' (checkpoint dir) is required"}
            return {"ok": True,
                    "replicas": self.rolling_reload(
                        path,
                        reload_timeout=float(msg.get("timeout",
                                                     120.0)))}
        if op == "generate":
            if self.state != "serving":
                return {"ok": False, "etype": "Shutdown",
                        "error": "router is stopped"}
            return self._route_generate(msg)
        return {"ok": False, "etype": "BadRequest",
                "error": f"router does not serve op {msg['op']!r} — "
                         f"it routes 'generate' (plus register/"
                         f"reload_weights/health/stats/metrics/"
                         f"debug_dump/cancel/ping)"}

    def _handle_register(self, msg):
        endpoint = msg.get("endpoint")
        if not isinstance(endpoint, str) or ":" not in endpoint:
            return {"ok": False, "etype": "BadRequest",
                    "error": "'endpoint' (host:port) is required"}
        try:
            if msg.get("remove"):
                removed = self.remove_replica(endpoint)
                return {"ok": True, "removed": removed,
                        "replicas": len(self.registry.all())}
            rep = self.add_replica(endpoint,
                                   role=msg.get("role", "both"))
            return {"ok": True, "state": rep.state,
                    "replicas": len(self.registry.all())}
        except ValueError as e:
            return {"ok": False, "etype": "BadRequest", "error": str(e)}

    def _handle_cancel(self, msg):
        """Forward a cancel to every replica the rid was dispatched
        to."""
        rid = msg.get("rid")
        targets = set()
        if rid:
            with self._rids_lock:
                ent = self._rids.get(rid)
            if ent is not None:
                targets = ent.targets()
        cancelled = False
        for ep in targets:
            # the disaggregated prefill hop was dispatched under
            # rid + "-prefill" (_route_disaggregated) — try both ids
            # so a cancel can reach a request mid-prefill too
            for hop_rid in (rid, f"{rid}-prefill"):
                try:
                    r = self._exchange(
                        ep, {"op": "cancel", "rid": hop_rid},
                        self.registry.probe_timeout_s)
                    cancelled = cancelled or bool(r.get("cancelled"))
                except Exception:  # noqa: BLE001 — best-effort fan-out
                    pass
        return {"ok": True, "cancelled": cancelled}
