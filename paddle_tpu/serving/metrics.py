"""Serving observability: per-stage latency histograms, throughput and
batch-occupancy counters.

Three export paths share one measurement: every stage duration lands in
a fixed-bucket ``LatencyHistogram`` here (always on — integer bumps, no
allocation), in ``paddle_tpu.profiler``'s event table via
``profiler.record_duration`` (visible only while profiling is active),
AND — aggregated across every live ``ServingStats`` sink — in the
process-global ``observability.MetricsRegistry`` through a scrape-time
collector, so the ``"metrics"`` wire op / ``tools/export_metrics.py``
expose ``serving_*_total`` counters and the
``serving_stage_latency_ms`` histogram in Prometheus text format. The
``snapshot()`` payload (the ``server.stats()`` contract) is unchanged.
"""
import threading

import time

from .. import profiler as _prof
# log-spaced upper bounds in milliseconds (last bucket +inf) — ONE
# definition, owned by the lower-level substrate: the registry bridge
# below zips LatencyHistogram counts against these bounds at scrape
# time, so a second copy here could silently truncate the zip
from ..observability.metrics import DEFAULT_BOUNDS_MS  # noqa: F401
from ..observability.metrics import InstanceAggregator, default_registry


class LatencyHistogram:
    """Fixed-bucket latency histogram (observations in seconds, bounds in
    ms). Percentiles are linear-interpolated within the winning bucket —
    the standard prometheus-style estimate, good to a bucket width."""

    def __init__(self, name, bounds_ms=DEFAULT_BOUNDS_MS):
        self.name = name
        self.bounds_ms = tuple(float(b) for b in bounds_ms)
        self._counts = [0] * (len(self.bounds_ms) + 1)
        self._count = 0
        self._sum = 0.0
        self._max = 0.0
        self._lock = threading.Lock()

    def observe(self, seconds):
        ms = seconds * 1e3
        idx = len(self.bounds_ms)
        for i, b in enumerate(self.bounds_ms):
            if ms <= b:
                idx = i
                break
        with self._lock:
            self._counts[idx] += 1
            self._count += 1
            self._sum += seconds
            if seconds > self._max:
                self._max = seconds
        _prof.record_duration(self.name, seconds)

    @property
    def count(self):
        return self._count

    def _state(self):
        """One consistent copy of everything derived values need."""
        with self._lock:
            return list(self._counts), self._count, self._sum, self._max

    def _estimate(self, counts, count, mx, p):
        """Percentile from a CONSISTENT (counts, count, max) snapshot —
        all of snapshot()'s derived values come from one copy, so p50/
        p99 can never disagree with count under concurrent observe()."""
        if not count:
            return 0.0
        target = count * (float(p) / 100.0)
        seen = 0
        for i, c in enumerate(counts):
            if not c:
                continue
            if seen + c >= target:
                lo = self.bounds_ms[i - 1] if i > 0 else 0.0
                hi = (self.bounds_ms[i]
                      if i < len(self.bounds_ms) else mx * 1e3)
                frac = (target - seen) / c
                return (lo + (max(hi, lo) - lo) * frac) / 1e3
            seen += c
        return mx

    def percentile(self, p):
        """p in [0, 100] -> estimated latency in seconds."""
        counts, count, _total, mx = self._state()
        return self._estimate(counts, count, mx, p)

    def snapshot(self):
        counts, count, total, mx = self._state()
        return {
            "count": count,
            "mean_ms": round(total / count * 1e3, 3) if count else 0.0,
            "p50_ms": round(self._estimate(counts, count, mx, 50) * 1e3,
                            3),
            "p99_ms": round(self._estimate(counts, count, mx, 99) * 1e3,
                            3),
            "max_ms": round(mx * 1e3, 3),
        }


# -- priority-class admission telemetry --------------------------------
#
# Native registry families (not ServingStats counters) because they are
# class-labeled and shared across every queue/server in the process:
# one `class` axis is what dashboards and tools/fleet_report.py slice.

_CLASS_SHED = default_registry().counter(
    "serving_admission_shed_total",
    "requests shed at admission by overload machinery (queue-full "
    "refusal, priority eviction, breaker, brownout), by priority class",
    labels=("class",), max_series=8)
_CLASS_DONE = default_registry().counter(
    "serving_class_completed_total",
    "requests completed end-to-end, by priority class",
    labels=("class",), max_series=8)
_CLASS_LAT = default_registry().histogram(
    "serving_class_latency_ms",
    "end-to-end request latency (admission -> result), by priority "
    "class",
    labels=("class",), max_series=8)
_EXPIRED_IN_QUEUE = default_registry().counter(
    "serving_expired_in_queue_total",
    "queued requests evicted because their deadline expired while "
    "waiting (failed typed instead of dequeuing into a doomed batch)")


_SPEC_ACCEPT = default_registry().gauge(
    "serving_spec_accept_ratio",
    "windowed draft-token acceptance rate of the speculative decode "
    "loop (accepted / proposed over the recent window), by decode-loop "
    "scope — the signal that drives adaptive per-request draft depth",
    labels=("scope",), max_series=256)


def record_spec_accept_ratio(scope, ratio):
    _SPEC_ACCEPT.set(float(ratio), labels=(str(scope),))


def record_class_shed(priority):
    _CLASS_SHED.inc(labels=(str(priority),))


def record_class_done(priority, seconds):
    """One completed request of ``priority`` that took ``seconds`` from
    admission to result — feeds the per-class goodput counters and the
    latency histogram ``tools/fleet_report.py`` gates p99 on."""
    _CLASS_DONE.inc(labels=(str(priority),))
    _CLASS_LAT.observe(float(seconds) * 1e3, labels=(str(priority),))


def record_expired_in_queue(n=1):
    _EXPIRED_IN_QUEUE.inc(n)


# -- registry bridge ---------------------------------------------------

# counter banking across sink churn lives in the shared
# InstanceAggregator (see its docstring for the monotonicity
# rationale); the stage-HISTOGRAM mass of garbage-collected sinks is
# serving-specific and banked here, riding the same finalizer
_retired_lock = threading.Lock()
_retired_stages = {}            # stage -> [bucket counts, count, sum]


def _merge_hist(stages, stage, hist):
    """Fold one LatencyHistogram's consistent (counts, count, sum)
    snapshot into ``stages[stage]`` — the one copy of the bucket merge
    shared by the retire bank and the live scrape."""
    with hist._lock:
        counts, count, tot = list(hist._counts), hist._count, hist._sum
    agg = stages.get(stage)
    if agg is None:
        stages[stage] = [counts, count, tot]
    else:
        agg[0] = [a + b for a, b in zip(agg[0], counts)]
        agg[1] += count
        agg[2] += tot


def _retire_hists(hists):
    """Fold a dead sink's stage histograms into the retired totals (the
    closure keeps only the histogram dict alive, not the sink)."""
    with _retired_lock:
        for stage, h in hists.items():
            _merge_hist(_retired_stages, stage, h)

# ServingStats counter keys (module-level so the metrics collector can
# DECLARE serving_<key>_total families without an instance)
_COUNTER_KEYS = (
    "requests_admitted",
    "requests_completed",
    "requests_failed",
    "shed_overload",
    "shed_deadline",
    "batches",
    "rows",               # real example rows executed
    "padded_rows",        # bucket capacity across executed batches
    "compiles",
    # -- generation (decode batching) --
    "generate_requests",
    "tokens_generated",
    "decode_steps",
    "decode_rows",        # live generation rows stepped
    "decode_slot_rows",   # slot capacity across steps
    # -- disaggregated prefill/decode (fleet KV migration) --
    "kv_exports",         # prefill-only requests serialized out
    "kv_imports",         # migrated requests admitted from KV blocks
    # -- resilience layer --
    "engine_failures",      # failed execute / decode steps
    "watchdog_timeouts",    # executes killed by the watchdog
    "loop_restarts",        # supervisor-restarted loop threads
    "weight_reloads",       # successful reload_weights swaps
    "hedge_dedup_hits",     # hedged twins joined in flight
    "requests_cancelled",   # cancel op (hedge losers)
    # -- speculative decoding (paged verify + rejection sampling) --
    "spec_steps",           # verify steps taken (vs plain decode_steps)
    "spec_drafted",         # draft tokens proposed across all rows
    "spec_accepted",        # draft tokens accepted by verification
    "spec_rejected",        # verify runs with >= 1 rejected draft
)


_sink_agg = InstanceAggregator(_COUNTER_KEYS)


def _collect():
    """Scrape-time collector: aggregate counters and stage histograms
    across every live ServingStats sink (multiple servers in one
    process sum — one chip, one exposition) PLUS the retired totals of
    collected sinks, so the exported counters never decrease."""
    totals = _sink_agg.totals(lambda s: s._counts_copy())
    sinks = _sink_agg.live()
    with _retired_lock:
        stage_counts = {stage: [list(a[0]), a[1], a[2]]
                        for stage, a in _retired_stages.items()}
    for s in sinks:
        for stage, h in s.hist.items():
            _merge_hist(stage_counts, stage, h)
    fams = [{"name": f"serving_{k}_total", "kind": "counter",
             "help": f"ServingStats counter {k!r}", "labels": (),
             "samples": [((), totals[k])]} for k in _COUNTER_KEYS]
    hsamples = []
    for stage in sorted(stage_counts):
        counts, count, tot = stage_counts[stage]
        cum, buckets = 0, []
        for le, c in zip(DEFAULT_BOUNDS_MS + (float("inf"),), counts):
            cum += c
            buckets.append((le, cum))
        hsamples.append(((stage,), {"buckets": buckets, "count": count,
                                    "sum": round(tot * 1e3, 6)}))
    fams.append({"name": "serving_stage_latency_ms", "kind": "histogram",
                 "help": "per-stage serving latency (sum in ms)",
                 "labels": ("stage",), "samples": hsamples})
    return fams


default_registry().register_collector(
    _collect,
    families=[{"name": f"serving_{k}_total", "kind": "counter",
               "help": f"ServingStats counter {k!r}", "labels": ()}
              for k in _COUNTER_KEYS]
    + [{"name": "serving_stage_latency_ms", "kind": "histogram",
        "help": "per-stage serving latency (sum in ms)",
        "labels": ("stage",)}])


class ServingStats:
    """One shared stats sink for queue, batcher, engine and server: stage
    histograms plus monotonic counters. ``snapshot()`` is the
    ``server.stats()`` payload — plain ints/floats only, so it crosses
    the wire protocol's typed value universe unchanged. Every live sink
    also aggregates into the process metrics registry (see module
    docstring)."""

    STAGES = ("queue", "pad", "compile", "execute", "total",
              # generation pipeline stages (KV-cached decoding):
              # prefill = prompt ingestion forward, decode = one
              # incremental step over the slot batch, sample = the
              # next-token selection executable, token = one WHOLE
              # decode-loop step (engine.step wall: decode + sample +
              # host work — the inter-token latency the SLO monitor's
              # default p99 rule watches; a stall anywhere in the step
              # lands here even if the compiled call itself was fast)
              "prefill", "decode", "sample", "token")

    def __init__(self):
        self.hist = {s: LatencyHistogram(f"serving/{s}")
                     for s in self.STAGES}
        self._lock = threading.Lock()
        self._started = time.monotonic()
        self._c = {k: 0 for k in _COUNTER_KEYS}
        # closures bind the stat containers, never self
        _sink_agg.track(self, lambda c=self._c: dict(c),
                        extra_retire=lambda h=self.hist: _retire_hists(h))

    def _counts_copy(self):
        with self._lock:
            return dict(self._c)

    def bump(self, name, n=1):
        with self._lock:
            self._c[name] += n

    def observe_batch(self, rows, capacity):
        with self._lock:
            self._c["batches"] += 1
            self._c["rows"] += rows
            self._c["padded_rows"] += capacity

    def observe_decode_step(self, live_rows, slots):
        with self._lock:
            self._c["decode_steps"] += 1
            self._c["decode_rows"] += live_rows
            self._c["decode_slot_rows"] += slots

    def counter(self, name):
        with self._lock:
            return self._c[name]

    def snapshot(self, extra=None):
        with self._lock:
            c = dict(self._c)
            uptime = time.monotonic() - self._started
        out = {"uptime_s": round(uptime, 3)}
        out.update(c)
        out["throughput_rps"] = round(
            c["requests_completed"] / uptime, 3) if uptime > 0 else 0.0
        out["mean_batch_size"] = round(
            c["rows"] / c["batches"], 3) if c["batches"] else 0.0
        out["batch_occupancy"] = round(
            c["rows"] / c["padded_rows"], 4) if c["padded_rows"] else 0.0
        out["tokens_per_s"] = round(
            c["tokens_generated"] / uptime, 3) if uptime > 0 else 0.0
        out["decode_occupancy"] = round(
            c["decode_rows"] / c["decode_slot_rows"], 4) \
            if c["decode_slot_rows"] else 0.0
        out["spec_accept_ratio"] = round(
            c["spec_accepted"] / c["spec_drafted"], 4) \
            if c["spec_drafted"] else 0.0
        for s, h in self.hist.items():
            snap = h.snapshot()
            for k, v in snap.items():
                out[f"{s}_{k}"] = v
        if extra:
            out.update(extra)
        return out
