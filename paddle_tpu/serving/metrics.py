"""Serving observability: per-stage latency histograms, throughput and
batch-occupancy counters.

Two export paths share one measurement: every stage duration lands in a
fixed-bucket ``LatencyHistogram`` here (always on — integer bumps, no
allocation) AND in ``paddle_tpu.profiler``'s event table via
``profiler.record_duration`` (visible only while profiling is active, so
``profiler.profiler()`` around a traffic replay yields the familiar
Fluid-style table with ``serving/queue``, ``serving/pad``,
``serving/compile``, ``serving/execute`` rows)."""
import threading
import time

from .. import profiler as _prof

# log-spaced upper bounds in milliseconds; the last bucket is +inf
DEFAULT_BOUNDS_MS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                     100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0)


class LatencyHistogram:
    """Fixed-bucket latency histogram (observations in seconds, bounds in
    ms). Percentiles are linear-interpolated within the winning bucket —
    the standard prometheus-style estimate, good to a bucket width."""

    def __init__(self, name, bounds_ms=DEFAULT_BOUNDS_MS):
        self.name = name
        self.bounds_ms = tuple(float(b) for b in bounds_ms)
        self._counts = [0] * (len(self.bounds_ms) + 1)
        self._count = 0
        self._sum = 0.0
        self._max = 0.0
        self._lock = threading.Lock()

    def observe(self, seconds):
        ms = seconds * 1e3
        idx = len(self.bounds_ms)
        for i, b in enumerate(self.bounds_ms):
            if ms <= b:
                idx = i
                break
        with self._lock:
            self._counts[idx] += 1
            self._count += 1
            self._sum += seconds
            if seconds > self._max:
                self._max = seconds
        _prof.record_duration(self.name, seconds)

    @property
    def count(self):
        return self._count

    def percentile(self, p):
        """p in [0, 100] -> estimated latency in seconds."""
        with self._lock:
            if not self._count:
                return 0.0
            target = self._count * (float(p) / 100.0)
            seen = 0
            for i, c in enumerate(self._counts):
                if not c:
                    continue
                if seen + c >= target:
                    lo = self.bounds_ms[i - 1] if i > 0 else 0.0
                    hi = (self.bounds_ms[i]
                          if i < len(self.bounds_ms) else self._max * 1e3)
                    frac = (target - seen) / c
                    return (lo + (max(hi, lo) - lo) * frac) / 1e3
                seen += c
            return self._max

    def snapshot(self):
        with self._lock:
            count, total, mx = self._count, self._sum, self._max
        return {
            "count": count,
            "mean_ms": round(total / count * 1e3, 3) if count else 0.0,
            "p50_ms": round(self.percentile(50) * 1e3, 3),
            "p99_ms": round(self.percentile(99) * 1e3, 3),
            "max_ms": round(mx * 1e3, 3),
        }


class ServingStats:
    """One shared stats sink for queue, batcher, engine and server: stage
    histograms plus monotonic counters. ``snapshot()`` is the
    ``server.stats()`` payload — plain ints/floats only, so it crosses
    the wire protocol's typed value universe unchanged."""

    STAGES = ("queue", "pad", "compile", "execute", "total",
              # generation pipeline stages (KV-cached decoding):
              # prefill = prompt ingestion forward, decode = one
              # incremental step over the slot batch, sample = the
              # next-token selection executable
              "prefill", "decode", "sample")

    def __init__(self):
        self.hist = {s: LatencyHistogram(f"serving/{s}")
                     for s in self.STAGES}
        self._lock = threading.Lock()
        self._started = time.monotonic()
        self._c = {
            "requests_admitted": 0,
            "requests_completed": 0,
            "requests_failed": 0,
            "shed_overload": 0,
            "shed_deadline": 0,
            "batches": 0,
            "rows": 0,            # real example rows executed
            "padded_rows": 0,     # bucket capacity across executed batches
            "compiles": 0,
            # -- generation (decode batching) --
            "generate_requests": 0,
            "tokens_generated": 0,
            "decode_steps": 0,
            "decode_rows": 0,       # live generation rows stepped
            "decode_slot_rows": 0,  # slot capacity across steps
            # -- resilience layer --
            "engine_failures": 0,     # failed execute / decode steps
            "watchdog_timeouts": 0,   # executes killed by the watchdog
            "loop_restarts": 0,       # supervisor-restarted loop threads
            "weight_reloads": 0,      # successful reload_weights swaps
            "hedge_dedup_hits": 0,    # hedged twins joined in flight
            "requests_cancelled": 0,  # cancel op (hedge losers)
        }

    def bump(self, name, n=1):
        with self._lock:
            self._c[name] += n

    def observe_batch(self, rows, capacity):
        with self._lock:
            self._c["batches"] += 1
            self._c["rows"] += rows
            self._c["padded_rows"] += capacity

    def observe_decode_step(self, live_rows, slots):
        with self._lock:
            self._c["decode_steps"] += 1
            self._c["decode_rows"] += live_rows
            self._c["decode_slot_rows"] += slots

    def counter(self, name):
        with self._lock:
            return self._c[name]

    def snapshot(self, extra=None):
        with self._lock:
            c = dict(self._c)
            uptime = time.monotonic() - self._started
        out = {"uptime_s": round(uptime, 3)}
        out.update(c)
        out["throughput_rps"] = round(
            c["requests_completed"] / uptime, 3) if uptime > 0 else 0.0
        out["mean_batch_size"] = round(
            c["rows"] / c["batches"], 3) if c["batches"] else 0.0
        out["batch_occupancy"] = round(
            c["rows"] / c["padded_rows"], 4) if c["padded_rows"] else 0.0
        out["tokens_per_s"] = round(
            c["tokens_generated"] / uptime, 3) if uptime > 0 else 0.0
        out["decode_occupancy"] = round(
            c["decode_rows"] / c["decode_slot_rows"], 4) \
            if c["decode_slot_rows"] else 0.0
        for s, h in self.hist.items():
            snap = h.snapshot()
            for k, v in snap.items():
                out[f"{s}_{k}"] = v
        if extra:
            out.update(extra)
        return out
