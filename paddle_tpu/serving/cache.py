"""Bounded executable/compile caches for the serving runtime.

The reference's inference engine amortizes analysis passes by caching a
NaiveExecutor per AnalysisPredictor; the TPU analog caches COMPILED XLA
EXECUTABLES keyed on a feed-shape signature. Unlike jax's internal jit
cache (unbounded, invisible), this one is byte- and entry-capped with
hit/miss/evict counters, so a server fed adversarial shape traffic
degrades to recompiles instead of OOMing the host, and the occupancy is
observable in ``server.stats()``.

The generic capped map lives in ``utils.lru.LRUCache`` (it also bounds
``framework.executor.Executor``'s per-shape program cache — the
executor must not depend on this package); ``ExecutableCache`` adds
shape-signature keys and signature-file record/warmup so a restarted
server can precompile yesterday's traffic.
"""
import json

from ..utils.lru import LRUCache


def feed_signature(feed):
    """Canonical cache key for a feed dict: sorted
    ``(name, shape, dtype)`` triples. Works on numpy arrays and anything
    with ``.shape``/``.dtype``."""
    return tuple(sorted(
        (name, tuple(int(d) for d in arr.shape), str(arr.dtype))
        for name, arr in feed.items()))


class ExecutableCache(LRUCache):
    """LRU of compiled XLA executables keyed by feed signature, plus the
    signature-file half of the warmup story: ``record(path)`` writes the
    signatures currently cached (i.e. observed traffic), and
    ``load_signatures(path)`` reads them back so a fresh server can
    precompile before taking traffic (see ``ServingEngine.warmup``)."""

    def __init__(self, max_entries=None, max_bytes=None, on_evict=None):
        if max_entries is None or max_bytes is None:
            from ..flags import flag
            if max_entries is None:
                max_entries = flag("serving_cache_entries")
            if max_bytes is None:
                max_bytes = flag("serving_cache_bytes")

        def _evict_hook(key, value, _user=on_evict):
            # every eviction lands in the flight recorder: "why did
            # that signature recompile mid-soak" is answerable
            from ..observability.recorder import flight_recorder
            flight_recorder().record("eviction", cache="executable",
                                     signature=str(key)[:200])
            if _user is not None:
                _user(key, value)

        super().__init__(max_entries=max_entries, max_bytes=max_bytes,
                         on_evict=_evict_hook)

    signature = staticmethod(feed_signature)

    def record(self, path):
        """Write the cached signatures (most recently used last) to a
        JSON file; returns the number written. Temp-write + fsync +
        atomic rename: a killed server can never leave a torn file that
        poisons the next launch's warmup."""
        import os
        sigs = self.keys()
        doc = [[[name, list(shape), dtype] for name, shape, dtype in sig]
               for sig in sigs]
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"version": 1, "signatures": doc}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return len(doc)

    @staticmethod
    def load_signatures(path):
        """Read a signature file back into a list of
        ``{name: (shape, dtype)}`` dicts (compile-warmup input). A
        missing/corrupt file returns [] with a warning — warmup is
        best-effort, it must never stop a server from starting."""
        try:
            with open(path) as f:
                doc = json.load(f)
            out = []
            for sig in doc.get("signatures", []):
                out.append({name: (tuple(shape), dtype)
                            for name, shape, dtype in sig})
            return out
        except (OSError, ValueError, TypeError) as e:
            import warnings
            warnings.warn(f"serving signature file {path!r} unreadable "
                          f"({e}); warming up without it", stacklevel=2)
            return []
