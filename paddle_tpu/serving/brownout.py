"""Brownout degradation ladder: a breached-SLO server degrades its
LOWEST-priority traffic first, and recovers symmetrically.

The SLO monitor (observability/slo.py) detects a regression; before
this module the only remediations were binary — shed everything
(degraded state) or serve everything (and let the tail grow). Brownout
is the graduated middle (the Autopilot/Brownout idiom: shed optional
work before mandatory work):

- **level 0** (no breached rules): nothing changes.
- **level 1** (one breached rule, or any breach just appeared):
  ``best_effort`` traffic is shed typed at the door
  (``ServerOverloadedError``), ``batch`` generation budgets are capped
  (``max_new_tokens`` clamped to ``batch_token_cap``) and ``batch``
  admission shrinks to half the queue depth. Interactive traffic is
  untouched.
- **level 2** (>= 2 breached rules, or a level-1 breach held longer
  than ``escalate_s``): ``batch`` sheds too. Interactive traffic is
  still served — the whole point of the ladder is that it degrades
  LAST.

Recovery is symmetric: after ``recover_s`` seconds with zero breached
rules the level steps DOWN by one (not straight to 0), so a server
oscillating around its SLO threshold ratchets gently instead of
slamming admission open and re-breaching.

Hedging interacts through the fleet: a replica's ``health()`` carries
``brownout_level``, and the router skips hedge twins against a fleet
with brownout-active replicas (a hedge is optional tail-fighting work
— exactly what brownout exists to shed first).
"""
import threading
import time

from ..flags import flag as _flag
from ..observability.metrics import default_registry
from ..observability.recorder import flight_recorder as _flightrec

# 256 series like the slo_* families: one scope per server, and an
# in-process fleet/test-suite churns through many more than 64
_LEVEL = default_registry().gauge(
    "serving_brownout_level_state",
    "current brownout degradation level (0 = normal, 1 = best_effort "
    "shed + batch capped, 2 = batch shed too), by server scope",
    labels=("scope",), max_series=256)


class BrownoutController:
    """Maps SLO breach state to a degradation level with hysteresis.

    ``breached_fn()`` returns the CURRENT number of breached SLO rules
    (the server wires ``len(slo_monitor.breached())``). ``level()`` is
    evaluated lazily on every admission — no extra thread — and walks
    the ladder described in the module docstring. All transitions are
    flight-recorded and exported via
    ``serving_brownout_level_state{scope}``.
    """

    MAX_LEVEL = 2

    def __init__(self, breached_fn, *, scope="default", enabled=None,
                 escalate_s=2.0, recover_s=2.0, batch_token_cap=16):
        self._breached_fn = breached_fn
        self.scope = str(scope)
        self.enabled = bool(_flag("serving_brownout")
                            if enabled is None else enabled)
        self.escalate_s = float(escalate_s)
        self.recover_s = float(recover_s)
        self.batch_token_cap = int(batch_token_cap)
        self._level = 0
        self._level_since = None      # when the CURRENT level was set
        self._breach_since = None     # start of the current breach run
        self._healthy_since = None    # start of the current 0-breach run
        self._transitions = 0
        self._shed = 0
        self._capped = 0
        self._lock = threading.Lock()
        _LEVEL.set(0, labels=(self.scope,))

    def _set_level(self, lvl, now, breached):
        self._level = lvl
        self._level_since = now
        self._transitions += 1
        _LEVEL.set(lvl, labels=(self.scope,))
        _flightrec().record("brownout", scope=self.scope, level=lvl,
                            breached=int(breached))

    def level(self, now=None):
        """Current degradation level (0/1/2), re-evaluated from the
        live breach count with escalate/recover hysteresis."""
        if not self.enabled:
            return 0
        try:
            breached = int(self._breached_fn() or 0)
        except Exception:  # noqa: BLE001 — a dying monitor reads as ok
            breached = 0
        now = time.monotonic() if now is None else now
        with self._lock:
            if breached > 0:
                self._healthy_since = None
                if self._breach_since is None:
                    self._breach_since = now
                target = 2 if breached >= 2 else 1
                if self._level < target:
                    self._set_level(target, now, breached)
                elif (self._level < self.MAX_LEVEL
                        and now - self._breach_since
                        >= self.escalate_s):
                    # THIS breach run (not time-at-level: a fresh
                    # breach after a healthy gap restarts the clock)
                    # outlived escalate_s without the current rung
                    # clearing it — one more rung
                    self._set_level(self._level + 1, now, breached)
            elif self._level > 0:
                self._breach_since = None
                if self._healthy_since is None:
                    self._healthy_since = now
                elif now - self._healthy_since >= self.recover_s:
                    # symmetric recovery: one rung per recover_s of
                    # sustained health
                    self._set_level(self._level - 1, now, breached)
                    self._healthy_since = now
            else:
                self._breach_since = None
                self._healthy_since = None
            return self._level

    def admission(self, rank, max_new_tokens=None, queue_depth=None):
        """Admission verdict for a request of priority ``rank`` at the
        current level: ``(shed, max_new_tokens, depth_cap)``. ``shed``
        True means the caller must refuse the request typed;
        ``max_new_tokens`` comes back clamped for capped classes;
        ``depth_cap`` is an admission-depth override (None = the
        queue's own limit)."""
        lvl = self.level()
        if lvl <= 0 or rank <= 0:
            return False, max_new_tokens, None
        if rank >= 2 or lvl >= 2:
            # best_effort sheds at level 1; batch joins it at level 2
            with self._lock:
                self._shed += 1
            return True, max_new_tokens, None
        # level 1, batch: capped budget + shrunken admission
        capped = max_new_tokens
        if max_new_tokens is not None \
                and max_new_tokens > self.batch_token_cap:
            capped = self.batch_token_cap
            with self._lock:
                self._capped += 1
        depth_cap = max(queue_depth // 2, 1) if queue_depth else None
        return False, capped, depth_cap

    def draft_depth(self, rank, k):
        """Speculative draft depth for a row of priority ``rank`` at the
        current level. Drafting is OPTIONAL work — extra verify compute
        spent betting on acceptance — so the ladder shrinks it for the
        same classes whose admission it degrades, before touching their
        admission at the next rung: at level 1 ``batch`` rows draft at
        half depth and ``best_effort`` rows stop drafting; at level 2
        ``batch`` stops too. Interactive rows keep their full ``k`` at
        every level (they degrade LAST, same as admission)."""
        k = int(k)
        lvl = self.level()
        if lvl <= 0 or rank <= 0 or k <= 0:
            return k
        if rank >= 2 or lvl >= 2:
            return 0
        return max(k // 2, 1)

    def snapshot(self):
        with self._lock:
            return {"level": self._level, "enabled": self.enabled,
                    "transitions": self._transitions,
                    "shed": self._shed, "capped": self._capped}
