"""Helpers (reference python/paddle/complex/helper.py)."""
from ..framework.core import ComplexVariable, Variable


def is_complex(x):
    """True if x is a ComplexVariable."""
    return isinstance(x, ComplexVariable)


def is_real(x):
    """True if x is a real-number Variable (or dygraph VarBase)."""
    if isinstance(x, Variable):
        return True
    from ..dygraph.base import VarBase
    return isinstance(x, VarBase)


def complex_variable_exists(inputs, layer_name):
    for inp in inputs:
        if is_complex(inp):
            return
    err_msg = "At least one inputs of layer complex." if len(inputs) > 1 \
        else "The input of layer complex."
    raise ValueError(err_msg + layer_name +
                     "() must be ComplexVariable, please "
                     "use the layer for real number instead.")
