"""Complex shape manipulation (2.0-preview surface: reshape,
transpose) — applied to both parts."""
from ...framework.core import ComplexVariable
from ...layers import tensor as T
from ..helper import complex_variable_exists

__all__ = ["reshape", "transpose"]


def reshape(x, shape, name=None):
    complex_variable_exists([x], "reshape")
    return ComplexVariable(T.reshape(x.real, shape),
                           T.reshape(x.imag, shape))


def transpose(x, perm, name=None):
    complex_variable_exists([x], "transpose")
    return ComplexVariable(T.transpose(x.real, perm),
                           T.transpose(x.imag, perm))
