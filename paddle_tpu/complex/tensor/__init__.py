from . import linalg, manipulation, math
from .linalg import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403

__all__ = math.__all__ + linalg.__all__ + manipulation.__all__
