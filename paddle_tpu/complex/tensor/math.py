"""Complex elementwise math + kron (reference
python/paddle/complex/tensor/math.py — elementwise_add/sub/mul/div,
kron). Each op decomposes into real-part arithmetic through the
ordinary layers surface; a real operand broadcasts as (x, 0)."""
from ...framework.core import ComplexVariable
from ...layers import math as M
from ..helper import complex_variable_exists, is_complex

__all__ = ["elementwise_add", "elementwise_sub", "elementwise_mul",
           "elementwise_div", "kron"]


def _parts(v):
    """(real, imag) with imag=None for a real operand."""
    if is_complex(v):
        return v.real, v.imag
    return v, None


def _zeros_of(v):
    """A zeros stand-in for a real operand's missing imaginary part, so
    imag broadcasts exactly like real does."""
    return M.scale(v, 0.0)


def elementwise_add(x, y, axis=-1, name=None):
    """Complex (x + y) (reference math.py:27)."""
    complex_variable_exists([x, y], "elementwise_add")
    xr, xi = _parts(x)
    yr, yi = _parts(y)
    real = M.elementwise_add(xr, yr, axis=axis)
    imag = M.elementwise_add(xi if xi is not None else _zeros_of(xr),
                             yi if yi is not None else _zeros_of(yr),
                             axis=axis)
    return ComplexVariable(real, imag)


def elementwise_sub(x, y, axis=-1, name=None):
    """Complex (x - y)."""
    complex_variable_exists([x, y], "elementwise_sub")
    xr, xi = _parts(x)
    yr, yi = _parts(y)
    real = M.elementwise_sub(xr, yr, axis=axis)
    imag = M.elementwise_sub(xi if xi is not None else _zeros_of(xr),
                             yi if yi is not None else _zeros_of(yr),
                             axis=axis)
    return ComplexVariable(real, imag)


def elementwise_mul(x, y, axis=-1, name=None):
    """Complex (x * y): (ar*br - ai*bi) + (ar*bi + ai*br) i."""
    complex_variable_exists([x, y], "elementwise_mul")
    xr, xi = _parts(x)
    yr, yi = _parts(y)
    if xi is None:                       # real * complex
        return ComplexVariable(M.elementwise_mul(xr, yr, axis=axis),
                               M.elementwise_mul(xr, yi, axis=axis))
    if yi is None:                       # complex * real
        return ComplexVariable(M.elementwise_mul(xr, yr, axis=axis),
                               M.elementwise_mul(xi, yr, axis=axis))
    real = M.elementwise_sub(M.elementwise_mul(xr, yr, axis=axis),
                             M.elementwise_mul(xi, yi, axis=axis))
    imag = M.elementwise_add(M.elementwise_mul(xr, yi, axis=axis),
                             M.elementwise_mul(xi, yr, axis=axis))
    return ComplexVariable(real, imag)


def elementwise_div(x, y, axis=-1, name=None):
    """Complex (x / y): multiply by the conjugate over |y|^2."""
    complex_variable_exists([x, y], "elementwise_div")
    yr, yi = _parts(y)
    if yi is None:                       # complex / real
        xr, xi = _parts(x)
        return ComplexVariable(M.elementwise_div(xr, yr, axis=axis),
                               M.elementwise_div(xi, yr, axis=axis))
    denom = M.elementwise_add(M.elementwise_mul(yr, yr),
                              M.elementwise_mul(yi, yi))
    conj = ComplexVariable(yr, M.scale(yi, -1.0))
    num = elementwise_mul(x, conj, axis=axis)
    return ComplexVariable(M.elementwise_div(num.real, denom, axis=axis),
                           M.elementwise_div(num.imag, denom, axis=axis))


def _kron_real(a, b):
    from ...layers.more import custom_op
    return custom_op("kron", inputs={"X": a, "Y": b})


def kron(x, y, name=None):
    """Complex Kronecker product (reference math.py kron):
    (kron(ar,br) - kron(ai,bi)) + (kron(ar,bi) + kron(ai,br)) i."""
    complex_variable_exists([x, y], "kron")
    xr, xi = _parts(x)
    yr, yi = _parts(y)
    if xi is None:
        return ComplexVariable(_kron_real(xr, yr), _kron_real(xr, yi))
    if yi is None:
        return ComplexVariable(_kron_real(xr, yr), _kron_real(xi, yr))
    real = M.elementwise_sub(_kron_real(xr, yr), _kron_real(xi, yi))
    imag = M.elementwise_add(_kron_real(xr, yi), _kron_real(xi, yr))
    return ComplexVariable(real, imag)
