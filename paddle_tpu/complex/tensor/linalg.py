"""Complex linear algebra (2.0-preview surface: matmul)."""
from ...framework.core import ComplexVariable
from ...layers import math as M
from ...layers import nn as _nn
from .. import helper
from ..helper import complex_variable_exists

__all__ = ["matmul"]


def _mm(a, b, tx, ty):
    return _nn.matmul(a, b, transpose_x=tx, transpose_y=ty)


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0,
           name=None):
    """Complex matmul: (ar@br - ai@bi) + (ar@bi + ai@br) i. NOTE: a
    transposed complex operand is the plain transpose, not the conjugate
    transpose (matching the reference's real-pair decomposition)."""
    complex_variable_exists([x, y], "matmul")
    if helper.is_complex(x):
        xr, xi = x.real, x.imag
    else:
        xr, xi = x, None
    if helper.is_complex(y):
        yr, yi = y.real, y.imag
    else:
        yr, yi = y, None
    if xi is None:
        real = _mm(xr, yr, transpose_x, transpose_y)
        imag = _mm(xr, yi, transpose_x, transpose_y)
    elif yi is None:
        real = _mm(xr, yr, transpose_x, transpose_y)
        imag = _mm(xi, yr, transpose_x, transpose_y)
    else:
        real = M.elementwise_sub(_mm(xr, yr, transpose_x, transpose_y),
                                 _mm(xi, yi, transpose_x, transpose_y))
        imag = M.elementwise_add(_mm(xr, yi, transpose_x, transpose_y),
                                 _mm(xi, yr, transpose_x, transpose_y))
    if alpha != 1.0:
        real = M.scale(real, float(alpha))
        imag = M.scale(imag, float(alpha))
    return ComplexVariable(real, imag)
