"""paddle.complex — ops over ComplexVariable (reference
python/paddle/complex/: tensor.math elementwise_add/sub/mul/div + kron,
helper.is_complex/is_real; ComplexVariable itself lives in
framework.py:1683). Implemented over (real, imag) Variable pairs through
the ordinary op surface, so everything compiles into the same XLA
program — plus matmul/reshape/transpose from the 2.0-preview surface."""
from . import tensor
from .helper import is_complex, is_real  # noqa: F401
from .tensor import *  # noqa: F401,F403

__all__ = tensor.__all__ + []
