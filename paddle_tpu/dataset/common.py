"""Shared dataset plumbing (reference python/paddle/dataset/common.py:
DATA_HOME + download cache). No egress here: data_home() resolves the
local cache; synthetic() builds the deterministic fallback RNG."""
import os
import zlib

import numpy as np

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATA_HOME",
    os.path.expanduser("~/.cache/paddle_tpu/dataset"))


def data_path(*parts):
    return os.path.join(DATA_HOME, *parts)


def have_local(*parts):
    return os.path.exists(data_path(*parts))


def synthetic_rng(name, split):
    """Deterministic per-(dataset, split) generator. crc32, not hash():
    builtin str hashing is salted per process, which would break the
    'deterministic synthetic streams' promise across runs."""
    seed = zlib.crc32(f"{name}/{split}".encode())
    return np.random.default_rng(seed)
