"""CoNLL-2005 semantic-role-labeling reader creators (reference
python/paddle/dataset/conll05.py: test() yields nine aligned features —
word_idx, five predicate-context sequences, pred_idx, mark, label_idx;
get_dict() -> (word, verb, label) dicts; get_embedding() -> pretrained
word vectors). Synthetic stream policy: deterministic sentences whose
role labels are a fixed function of position relative to the predicate,
so an SRL tagger genuinely learns."""
import numpy as np

from . import common

UNK_IDX = 0

_WORDS = 4000
_VERBS = 200
# B-V plus BIO argument tags (a compact subset of the PropBank label set)
_LABELS = ["O", "B-V", "B-A0", "I-A0", "B-A1", "I-A1", "B-A2", "I-A2",
           "B-AM-TMP", "I-AM-TMP"]
_TEST_N = 800


def get_dict():
    """(word_dict, verb_dict, label_dict) (reference :205)."""
    word_dict = {"<unk>": UNK_IDX, "bos": 1, "eos": 2}
    word_dict.update({f"w{i}": i + 3 for i in range(_WORDS - 3)})
    verb_dict = {f"v{i}": i for i in range(_VERBS)}
    label_dict = {l: i for i, l in enumerate(_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Deterministic pretrained-style embedding table [words, 32]."""
    rng = common.synthetic_rng("conll05", "emb")
    return rng.standard_normal((_WORDS, 32)).astype(np.float32)


def corpus_reader():
    """(sentence words, predicate, labels) triples."""
    def reader():
        rng = common.synthetic_rng("conll05", "test")
        word_dict, verb_dict, label_dict = get_dict()
        words = list(word_dict)
        verbs = list(verb_dict)
        for _ in range(_TEST_N):
            ln = int(rng.integers(5, 30))
            sent = [words[3 + int(rng.integers(0, _WORDS - 3))]
                    for _ in range(ln)]
            vi = int(rng.integers(0, ln))
            labels = ["O"] * ln
            labels[vi] = "B-V"
            # deterministic role structure around the predicate
            if vi >= 1:
                labels[vi - 1] = "B-A0"
            if vi >= 2:
                labels[vi - 2] = "I-A0" if labels[vi - 2] == "O" else \
                    labels[vi - 2]
            if vi + 1 < ln:
                labels[vi + 1] = "B-A1"
            if vi + 2 < ln:
                labels[vi + 2] = "I-A1"
            pred = verbs[int(rng.integers(0, _VERBS))]
            yield sent, pred, labels
    return reader


def reader_creator(corpus, word_dict=None, predicate_dict=None,
                   label_dict=None):
    """Nine aligned sequences per sample (reference :150)."""
    def reader():
        for sentence, predicate, labels in corpus():
            sen_len = len(sentence)
            verb_index = labels.index("B-V")
            mark = [0] * len(labels)
            ctx_n1 = sentence[verb_index - 1] if verb_index > 0 else "bos"
            if verb_index > 0:
                mark[verb_index - 1] = 1
            ctx_n2 = sentence[verb_index - 2] if verb_index > 1 else "bos"
            if verb_index > 1:
                mark[verb_index - 2] = 1
            mark[verb_index] = 1
            ctx_0 = sentence[verb_index]
            ctx_p1 = sentence[verb_index + 1] \
                if verb_index < len(labels) - 1 else "eos"
            if verb_index < len(labels) - 1:
                mark[verb_index + 1] = 1
            ctx_p2 = sentence[verb_index + 2] \
                if verb_index < len(labels) - 2 else "eos"
            if verb_index < len(labels) - 2:
                mark[verb_index + 2] = 1

            word_idx = [word_dict.get(w, UNK_IDX) for w in sentence]
            ctx_n2_idx = [word_dict.get(ctx_n2, UNK_IDX)] * sen_len
            ctx_n1_idx = [word_dict.get(ctx_n1, UNK_IDX)] * sen_len
            ctx_0_idx = [word_dict.get(ctx_0, UNK_IDX)] * sen_len
            ctx_p1_idx = [word_dict.get(ctx_p1, UNK_IDX)] * sen_len
            ctx_p2_idx = [word_dict.get(ctx_p2, UNK_IDX)] * sen_len
            pred_idx = [predicate_dict.get(predicate)] * sen_len
            label_idx = [label_dict.get(w) for w in labels]
            yield (word_idx, ctx_n2_idx, ctx_n1_idx, ctx_0_idx,
                   ctx_p1_idx, ctx_p2_idx, pred_idx, mark, label_idx)
    return reader


def test():
    """Reference uses the test split for training (the train set is not
    free); same here (reference :225)."""
    word_dict, verb_dict, label_dict = get_dict()
    return reader_creator(corpus_reader(), word_dict, verb_dict,
                          label_dict)


def fetch():
    return None
