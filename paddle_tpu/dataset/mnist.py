"""MNIST reader creators (reference python/paddle/dataset/mnist.py:
train()/test() yield (image float32 [784] scaled to [-1, 1], label
int64 in [0, 10))). Local idx-format files are used when present under
DATA_HOME/mnist; otherwise a deterministic synthetic stream of
class-separable images (each class lights a distinct block) so LeNet
book runs still converge."""
import gzip
import os
import struct

import numpy as np

from . import common

_TRAIN_N, _TEST_N = 8192, 1024


def _local_reader(images_path, labels_path, limit=None):
    def reader():
        with gzip.open(labels_path, "rb") as lf:
            magic, n = struct.unpack(">II", lf.read(8))
            labels = np.frombuffer(lf.read(), dtype=np.uint8)
        with gzip.open(images_path, "rb") as imf:
            magic, n, rows, cols = struct.unpack(">IIII", imf.read(16))
            images = np.frombuffer(imf.read(), dtype=np.uint8)
            images = images.reshape(n, rows * cols)
        count = n if limit is None else min(n, limit)
        for i in range(count):
            img = images[i].astype(np.float32) / 127.5 - 1.0
            yield img, int(labels[i])
    return reader


def _synthetic_reader(split, n):
    def reader():
        rng = common.synthetic_rng("mnist", split)
        for _ in range(n):
            label = int(rng.integers(0, 10))
            img = rng.normal(-0.8, 0.15, 784).astype(np.float32)
            # light up a label-specific 8x8 block: linearly separable
            r, c = divmod(label, 4)
            block = np.zeros((28, 28), np.float32)
            block[r * 9:r * 9 + 8, c * 7:c * 7 + 7] = 1.6
            img = np.clip(img + block.reshape(-1)
                          + rng.normal(0, 0.1, 784).astype(np.float32),
                          -1.0, 1.0).astype(np.float32)
            yield img, label
    return reader


def train():
    ip = common.data_path("mnist", "train-images-idx3-ubyte.gz")
    lp = common.data_path("mnist", "train-labels-idx1-ubyte.gz")
    if os.path.exists(ip) and os.path.exists(lp):
        return _local_reader(ip, lp)
    return _synthetic_reader("train", _TRAIN_N)


def test():
    ip = common.data_path("mnist", "t10k-images-idx3-ubyte.gz")
    lp = common.data_path("mnist", "t10k-labels-idx1-ubyte.gz")
    if os.path.exists(ip) and os.path.exists(lp):
        return _local_reader(ip, lp)
    return _synthetic_reader("test", _TEST_N)
