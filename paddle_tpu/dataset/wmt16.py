"""WMT16 en<->de reader creators (reference
python/paddle/dataset/wmt16.py: train/test/validation with separate
src/trg dict sizes and src_lang selection; yields
(src_ids, trg_ids, trg_ids_next)). Synthetic stream policy."""
import numpy as np

from . import common

_TRAIN_N, _TEST_N, _VAL_N = 2000, 400, 400


def _check(src_dict_size, trg_dict_size, src_lang):
    if src_lang not in ("en", "de"):
        raise ValueError("src_lang must be 'en' or 'de'")
    return int(src_dict_size), int(trg_dict_size)


def reader_creator(split, n, src_dict_size, trg_dict_size, src_lang):
    src_dict_size, trg_dict_size = _check(src_dict_size, trg_dict_size,
                                          src_lang)

    def reader():
        rng = common.synthetic_rng(
            "wmt16", f"{split}/{src_dict_size}/{trg_dict_size}/{src_lang}")
        for _ in range(n):
            ln = int(rng.integers(3, 25))
            src = rng.integers(3, src_dict_size, ln)
            trg_core = (src * 11 + 7) % (trg_dict_size - 3) + 3
            yield ([int(i) for i in src],
                   [0] + [int(i) for i in trg_core],
                   [int(i) for i in trg_core] + [1])
    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return reader_creator("train", _TRAIN_N, src_dict_size,
                          trg_dict_size, src_lang)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return reader_creator("test", _TEST_N, src_dict_size,
                          trg_dict_size, src_lang)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return reader_creator("val", _VAL_N, src_dict_size,
                          trg_dict_size, src_lang)


def get_dict(lang, dict_size, reverse=False):
    """word<->id table for `lang` (reference :292)."""
    words = {0: "<s>", 1: "<e>", 2: "<unk>"}
    words.update({i: f"{lang}_{i}" for i in range(3, int(dict_size))})
    if reverse:
        return dict(words)
    return {w: i for i, w in words.items()}


def fetch():
    return None
