"""IMDB-class movie-review sentiment reader creators (reference
python/paddle/dataset/sentiment.py: train()/test() yield
(word-id list, label 0/1), get_word_dict()). Synthetic stream policy
(dataset/common.py): deterministic class-conditional word distributions
so a bag-of-words classifier genuinely separates the classes."""
import numpy as np

from . import common

_VOCAB = 5124
_TRAIN_N, _TEST_N = 1600, 400
NUM_TRAINING_INSTANCES = _TRAIN_N
NUM_TEST_INSTANCES = _TEST_N


def get_word_dict():
    """word -> id, most frequent first (reference :70)."""
    return {f"word_{i:05d}": i for i in range(_VOCAB)}


def _reader(split, n):
    def reader():
        rng = common.synthetic_rng("sentiment", split)
        half = _VOCAB // 2
        for _ in range(n):
            label = int(rng.integers(0, 2))
            ln = int(rng.integers(8, 120))
            # both classes draw from the lower half; label-1 reviews
            # additionally mix in 25% upper-half words — the separable
            # signal a bag-of-words classifier learns
            base = rng.integers(0, half, ln)
            flip = rng.random(ln) < 0.25
            ids = np.where(flip, base + half, base) if label else base
            yield [int(i) for i in ids], label
    return reader


def train():
    return _reader("train", _TRAIN_N)


def test():
    return _reader("test", _TEST_N)


def fetch():
    return None
