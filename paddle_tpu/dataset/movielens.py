"""MovieLens-1M reader creators (reference
python/paddle/dataset/movielens.py: train()/test() yield
usr.value() + mov.value() + [[rating]] = [uid, gender, age_bucket, job,
mov_id, [category ids], [title word ids], [rating]]; plus the meta
accessors max_user_id/max_movie_id/max_job_id/movie_categories/
user_info/movie_info/get_movie_title_dict). Synthetic stream policy:
a deterministic population with a low-rank taste model so recommender
models genuinely fit."""
import functools

import numpy as np

from . import common

__all__ = [
    "train", "test", "get_movie_title_dict", "max_movie_id",
    "max_user_id", "age_table", "movie_categories", "max_job_id",
    "user_info", "movie_info",
]

age_table = [1, 18, 25, 35, 45, 50, 56]

_N_USERS, _N_MOVIES, _N_JOBS = 600, 400, 21
_CATEGORIES = [
    "Action", "Adventure", "Animation", "Children's", "Comedy", "Crime",
    "Documentary", "Drama", "Fantasy", "Film-Noir", "Horror", "Musical",
    "Mystery", "Romance", "Sci-Fi", "Thriller", "War", "Western",
]
_TITLE_VOCAB = 512
_RATINGS_N = 8000


class MovieInfo:
    """Movie id, title-word ids and category ids (reference :48)."""

    def __init__(self, index, categories, title_ids):
        self.index = int(index)
        self.categories = categories        # category id list
        self.title = title_ids              # title word-id list

    def value(self):
        return [self.index, list(self.categories), list(self.title)]

    def __repr__(self):
        return f"<MovieInfo id({self.index})>"


class UserInfo:
    """User id, gender flag, age bucket, job id (reference :74)."""

    def __init__(self, index, is_male, age_bucket, job_id):
        self.index = int(index)
        self.is_male = bool(is_male)
        self.age = int(age_bucket)
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age,
                self.job_id]

    def __repr__(self):
        return f"<UserInfo id({self.index})>"


_META = None


def _meta():
    global _META
    if _META is None:
        rng = common.synthetic_rng("movielens", "meta")
        users = {}
        for uid in range(1, _N_USERS + 1):
            users[uid] = UserInfo(uid, rng.random() < 0.6,
                                  rng.integers(0, len(age_table)),
                                  rng.integers(0, _N_JOBS))
        movies = {}
        for mid in range(1, _N_MOVIES + 1):
            n_cat = int(rng.integers(1, 4))
            cats = sorted(rng.choice(len(_CATEGORIES), n_cat,
                                     replace=False).tolist())
            n_tw = int(rng.integers(1, 6))
            title = rng.integers(0, _TITLE_VOCAB, n_tw).tolist()
            movies[mid] = MovieInfo(mid, cats, title)
        # low-rank taste model: rating = clip(u . m)
        uf = rng.standard_normal((_N_USERS + 1, 4))
        mf = rng.standard_normal((_N_MOVIES + 1, 4))
        _META_local = {"users": users, "movies": movies,
                       "uf": uf, "mf": mf}
        _META = _META_local
    return _META


def __reader__(rand_seed=0, test_ratio=0.1, is_test=False):
    meta = _meta()
    rng = common.synthetic_rng("movielens",
                               f"ratings/{rand_seed}")
    for _ in range(_RATINGS_N):
        uid = int(rng.integers(1, _N_USERS + 1))
        mid = int(rng.integers(1, _N_MOVIES + 1))
        in_test = rng.random() < test_ratio
        if in_test != is_test:
            continue
        raw = float(meta["uf"][uid] @ meta["mf"][mid])
        rating = float(np.clip(np.round(raw + 3.0), 1, 5) * 2 - 5.0)
        usr, mov = meta["users"][uid], meta["movies"][mid]
        yield usr.value() + mov.value() + [[rating]]


def __reader_creator__(**kwargs):
    return lambda: __reader__(**kwargs)


train = functools.partial(__reader_creator__, is_test=False)
test = functools.partial(__reader_creator__, is_test=True)


def get_movie_title_dict():
    return {f"title_{i}": i for i in range(_TITLE_VOCAB)}


def max_movie_id():
    return _N_MOVIES


def max_user_id():
    return _N_USERS


def max_job_id():
    return _N_JOBS - 1


def movie_categories():
    return {c: i for i, c in enumerate(_CATEGORIES)}


def user_info():
    return list(_meta()["users"].values())


def movie_info():
    return list(_meta()["movies"].values())


def fetch():
    return None
