"""PTB language-model reader creators (reference
python/paddle/dataset/imikolov.py: build_dict(), train(word_dict, n)
yields n-gram tuples). Synthetic fallback: sequences from a fixed
first-order Markov chain, so n-gram models have real structure to
learn."""
import numpy as np

from . import common

_VOCAB = 2073      # reference build_dict default min-freq vocab ballpark
_TRAIN_N, _TEST_N = 4096, 512


def build_dict(min_word_freq=50):
    return {f"w{i}": i for i in range(_VOCAB)}


def _chain(rng):
    # deterministic sparse transition structure: w -> (3w+1) % V mostly
    def step(w):
        if rng.random() < 0.8:
            return (3 * w + 1) % _VOCAB
        return int(rng.integers(0, _VOCAB))
    return step


def _synthetic_reader(split, total, n):
    def reader():
        rng = common.synthetic_rng("imikolov", split)
        step = _chain(rng)
        w = int(rng.integers(0, _VOCAB))
        for _ in range(total):
            gram = [w]
            for _ in range(n - 1):
                w = step(w)
                gram.append(w)
            yield tuple(gram)
    return reader


def train(word_dict=None, n=5):
    return _synthetic_reader("train", _TRAIN_N, n)


def test(word_dict=None, n=5):
    return _synthetic_reader("test", _TEST_N, n)
