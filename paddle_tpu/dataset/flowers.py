"""Oxford-102 flowers reader creators (reference
python/paddle/dataset/flowers.py: train()/test()/valid() yield
(image chw float32, label 0..101)). Synthetic stream policy:
class-conditional color/texture statistics so an image classifier
genuinely separates classes."""
import numpy as np

from . import common

_CLASSES = 102
_HW = 32          # synthetic resolution (reference center-crops larger)
_TRAIN_N, _TEST_N, _VAL_N = 2040, 1020, 1020


def _sample(rng, label):
    base = common.synthetic_rng("flowers", f"class/{label}")
    mean = base.random(3).astype(np.float32)          # per-class color
    freq = 1 + int(label % 7)                          # per-class texture
    yy, xx = np.mgrid[0:_HW, 0:_HW].astype(np.float32) / _HW
    tex = 0.25 * np.sin(2 * np.pi * freq * (yy + xx))
    img = mean[:, None, None] + tex[None] \
        + 0.1 * rng.standard_normal((3, _HW, _HW)).astype(np.float32)
    return np.clip(img, 0.0, 1.0).astype(np.float32)


def reader_creator(split, n, mapper=None, buffered_size=1024,
                   use_xmap=False, cycle=False):
    def reader():
        while True:
            rng = common.synthetic_rng("flowers", split)
            for _ in range(n):
                label = int(rng.integers(0, _CLASSES))
                img = _sample(rng, label)
                sample = (img, label)
                if mapper is not None:
                    sample = mapper(sample)
                yield sample
            if not cycle:
                break
    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return reader_creator("train", _TRAIN_N, mapper, buffered_size,
                          use_xmap, cycle)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return reader_creator("test", _TEST_N, mapper, buffered_size,
                          use_xmap, cycle)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return reader_creator("valid", _VAL_N, mapper, buffered_size,
                          use_xmap)


def fetch():
    return None
