"""Image preprocessing utilities (reference
python/paddle/dataset/image.py: resize_short, to_chw, center_crop,
random_crop, left_right_flip, simple_transform, load_and_transform,
load_image/load_image_bytes, batch_images_from_tar). The reference
shells out to cv2; these are numpy-native (bilinear resize), with the
file/bytes decoders gated on an optional cv2/PIL install — everything a
training pipeline calls per-sample works with no image library."""
import numpy as np

__all__ = [
    "load_image_bytes", "load_image", "resize_short", "to_chw",
    "center_crop", "random_crop", "left_right_flip", "simple_transform",
    "load_and_transform", "batch_images_from_tar",
]


def _decoder():
    try:
        import cv2
        return ("cv2", cv2)
    except ImportError:
        pass
    try:
        from PIL import Image
        return ("pil", Image)
    except ImportError:
        return (None, None)


def load_image_bytes(data, is_color=True):
    """Decode encoded image bytes to an HWC uint8 array (reference
    :141). Needs cv2 or PIL; raises a guided error without them."""
    kind, mod = _decoder()
    if kind == "cv2":
        flag = 1 if is_color else 0
        arr = np.frombuffer(data, dtype="uint8")
        return mod.imdecode(arr, flag)
    if kind == "pil":
        import io
        img = mod.open(io.BytesIO(data))
        img = img.convert("RGB" if is_color else "L")
        return np.asarray(img)
    raise ImportError(
        "decoding image bytes needs cv2 or PIL (neither installed); "
        "the numpy-native transforms (resize_short/center_crop/...) "
        "work on already-decoded arrays")


def load_image(file, is_color=True):
    """Load an image file to HWC uint8 (reference :167)."""
    with open(file, "rb") as f:
        return load_image_bytes(f.read(), is_color)


def _resize_bilinear(im, h, w):
    """HWC (or HW) bilinear resize, pure numpy."""
    if im.ndim == 2:
        im = im[:, :, None]
        squeeze = True
    else:
        squeeze = False
    H, W = im.shape[:2]
    ys = (np.arange(h) + 0.5) * H / h - 0.5
    xs = (np.arange(w) + 0.5) * W / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, H - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, W - 1)
    y1 = np.clip(y0 + 1, 0, H - 1)
    x1 = np.clip(x0 + 1, 0, W - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    im = im.astype(np.float32)
    top = im[y0][:, x0] * (1 - wx) + im[y0][:, x1] * wx
    bot = im[y1][:, x0] * (1 - wx) + im[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    if squeeze:
        out = out[:, :, 0]
    return out


def resize_short(im, size):
    """Resize so the SHORTER edge is `size`, keeping aspect (reference
    :197)."""
    h, w = im.shape[:2]
    if h > w:
        new_h, new_w = int(round(h * size / w)), size
    else:
        new_h, new_w = size, int(round(w * size / h))
    return _resize_bilinear(im, new_h, new_w)


def to_chw(im, order=(2, 0, 1)):
    """HWC -> CHW (reference :225)."""
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    """Crop the center size x size patch (reference :249)."""
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    return im[h_start:h_start + size, w_start:w_start + size]


def random_crop(im, size, is_color=True, rng=None):
    """Crop a random size x size patch (reference :277)."""
    rng = rng or np.random.default_rng()
    h, w = im.shape[:2]
    h_start = int(rng.integers(0, h - size + 1))
    w_start = int(rng.integers(0, w - size + 1))
    return im[h_start:h_start + size, w_start:w_start + size]


def left_right_flip(im, is_color=True):
    """Mirror horizontally (reference :305)."""
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None, rng=None):
    """resize_short -> (random|center) crop -> maybe flip -> CHW ->
    mean-subtract (reference :327)."""
    im = resize_short(im, resize_size)
    if is_train:
        rng = rng or np.random.default_rng()
        im = random_crop(im, crop_size, is_color, rng=rng)
        if rng.random() > 0.5:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color)
    if len(im.shape) == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and im.ndim == 3:
            mean = mean[:, None, None]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    """load_image + simple_transform (reference :383)."""
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """Reference :80 pre-batches a tar of images into pickled batches.
    That is a host-side packing utility for a disk layout this framework
    does not use (DataLoader streams readers); raise with guidance."""
    raise NotImplementedError(
        "batch_images_from_tar packs a tar archive into pickle batches "
        "(a Paddle-specific disk layout); stream the images through a "
        "reader + DataLoader instead")
