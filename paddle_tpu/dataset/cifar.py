"""CIFAR reader creators (reference python/paddle/dataset/cifar.py:
train10()/test10() yield (image float32 [3072] in [0, 1], label int);
train100()/test100() likewise over 100 classes). Local python-pickle
batches under DATA_HOME/cifar are used when present; else a
deterministic synthetic class-separable stream."""
import os
import pickle
import tarfile

import numpy as np

from . import common

_TRAIN_N, _TEST_N = 4096, 512


def _local_reader(tar_path, sub_name):
    def reader():
        with tarfile.open(tar_path, mode="r") as f:
            names = [n for n in f.getnames() if sub_name in n]
            for name in names:
                batch = pickle.load(f.extractfile(name), encoding="bytes")
                data = batch[b"data"]
                labels = batch.get(b"labels", batch.get(b"fine_labels"))
                for i in range(len(labels)):
                    yield (data[i].astype(np.float32) / 255.0,
                           int(labels[i]))
    return reader


def _synthetic_reader(split, n, num_classes):
    def reader():
        rng = common.synthetic_rng(f"cifar{num_classes}", split)
        for _ in range(n):
            label = int(rng.integers(0, num_classes))
            img = rng.random(3072).astype(np.float32) * 0.3
            ch = label % 3
            blk = label % 16
            view = img.reshape(3, 32, 32)
            r, c = divmod(blk, 4)
            view[ch, r * 8:r * 8 + 8, c * 8:c * 8 + 8] += 0.7
            yield np.clip(img, 0.0, 1.0), label
    return reader


def train10():
    p = common.data_path("cifar", "cifar-10-python.tar.gz")
    if os.path.exists(p):
        return _local_reader(p, "data_batch")
    return _synthetic_reader("train", _TRAIN_N, 10)


def test10():
    p = common.data_path("cifar", "cifar-10-python.tar.gz")
    if os.path.exists(p):
        return _local_reader(p, "test_batch")
    return _synthetic_reader("test", _TEST_N, 10)


def train100():
    p = common.data_path("cifar", "cifar-100-python.tar.gz")
    if os.path.exists(p):
        return _local_reader(p, "train")
    return _synthetic_reader("train", _TRAIN_N, 100)


def test100():
    p = common.data_path("cifar", "cifar-100-python.tar.gz")
    if os.path.exists(p):
        return _local_reader(p, "test")
    return _synthetic_reader("test", _TEST_N, 100)
