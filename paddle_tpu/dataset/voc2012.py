"""PASCAL VOC2012 segmentation reader creators (reference
python/paddle/dataset/voc2012.py: train()/test()/val() yield
(image chw float32, label mask hw int32, 0..20 + 255 ignore)).
Synthetic stream policy: deterministic scenes of colored rectangles
whose mask is exactly recoverable from the image."""
import numpy as np

from . import common

_CLASSES = 21
_HW = 64
_TRAIN_N, _TEST_N, _VAL_N = 600, 150, 150


def _scene(rng):
    img = np.zeros((3, _HW, _HW), np.float32)
    mask = np.zeros((_HW, _HW), np.int32)
    for _ in range(int(rng.integers(1, 4))):
        cls = int(rng.integers(1, _CLASSES))
        h0, w0 = rng.integers(0, _HW - 8, 2)
        h1 = int(h0 + rng.integers(6, _HW - h0))
        w1 = int(w0 + rng.integers(6, _HW - w0))
        color = common.synthetic_rng("voc2012",
                                     f"class/{cls}").random(3)
        img[:, h0:h1, w0:w1] = color[:, None, None]
        mask[h0:h1, w0:w1] = cls
    img += 0.02 * rng.standard_normal(img.shape).astype(np.float32)
    return np.clip(img, 0, 1).astype(np.float32), mask


def reader_creator(split, n):
    def reader():
        rng = common.synthetic_rng("voc2012", split)
        for _ in range(n):
            yield _scene(rng)
    return reader


def train():
    return reader_creator("train", _TRAIN_N)


def test():
    return reader_creator("test", _TEST_N)


def val():
    return reader_creator("val", _VAL_N)


def fetch():
    return None
