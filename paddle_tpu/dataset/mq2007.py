"""MQ2007 learning-to-rank reader creators (reference
python/paddle/dataset/mq2007.py: train/test are generator functions
yielding per `format` — "pointwise" (score, 46-dim vector), "pairwise"
(label, left vec, right vec), "listwise" (score list, vector list),
"plain_txt" (query_id, relevance, features)). Synthetic stream policy:
deterministic queries with a linear relevance model so rankers fit."""
import functools

import numpy as np

from . import common

_FEATS = 46
_QUERIES = {"train": 120, "test": 40}
_DOCS_PER_QUERY = (5, 15)


class QueryList:
    """One query's documents (reference Query/QueryList, simplified to
    the fields the generators read)."""

    def __init__(self, query_id, scores, vectors):
        self.query_id = query_id
        self.relevance_score_list = scores
        self.feature_vector_list = vectors

    def __len__(self):
        return len(self.relevance_score_list)


def _querylists(split):
    rng = common.synthetic_rng("mq2007", split)
    w = common.synthetic_rng("mq2007", "w").standard_normal(_FEATS)
    out = []
    for qid in range(_QUERIES[split]):
        n = int(rng.integers(*_DOCS_PER_QUERY))
        vecs = [rng.standard_normal(_FEATS).astype(np.float64)
                for _ in range(n)]
        scores = [int(np.clip(np.round(v @ w / _FEATS ** 0.5 + 1), 0, 2))
                  for v in vecs]
        out.append(QueryList(qid, scores, vecs))
    return out


def gen_plain_txt(querylist):
    for score, vec in zip(querylist.relevance_score_list,
                          querylist.feature_vector_list):
        yield querylist.query_id, score, np.array(vec)


def gen_point(querylist):
    for score, vec in zip(querylist.relevance_score_list,
                          querylist.feature_vector_list):
        yield score, np.array(vec)


def gen_pair(querylist, partial_order="full"):
    for i, (si, vi) in enumerate(zip(querylist.relevance_score_list,
                                     querylist.feature_vector_list)):
        for j in range(i + 1, len(querylist)):
            sj = querylist.relevance_score_list[j]
            vj = querylist.feature_vector_list[j]
            if si == sj:
                continue
            if si > sj:
                yield np.array([1.0]), np.array(vi), np.array(vj)
            else:
                yield np.array([1.0]), np.array(vj), np.array(vi)


def gen_list(querylist):
    yield (np.array(querylist.relevance_score_list),
           np.array(querylist.feature_vector_list))


def query_filter(querylists):
    """Drop queries whose docs all share one relevance (reference
    :252 — they carry no ranking signal)."""
    return [q for q in querylists
            if len(set(q.relevance_score_list)) > 1]


def __reader__(split, format="pairwise", shuffle=False, fill_missing=-1):
    for querylist in query_filter(_querylists(split)):
        if format == "plain_txt":
            yield next(gen_plain_txt(querylist))
        elif format == "pointwise":
            yield next(gen_point(querylist))
        elif format == "pairwise":
            for pair in gen_pair(querylist):
                yield pair
        elif format == "listwise":
            yield next(gen_list(querylist))


train = functools.partial(__reader__, split="train")
test = functools.partial(__reader__, split="test")


def fetch():
    return None
