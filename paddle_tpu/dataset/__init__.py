"""paddle.dataset — canned dataset reader creators (reference
python/paddle/dataset/: mnist.py, cifar.py, imdb.py, imikolov.py,
uci_housing.py — each module exposes train()/test() returning reader
creators that yield one sample tuple per next()).

Offline note (documented divergence): the reference downloads from
dataset mirrors at import time; this environment has no egress, so each
module first looks for a local copy under $PADDLE_TPU_DATA_HOME (same
file formats as the reference's cache dir) and otherwise serves a
DETERMINISTIC SYNTHETIC sample stream with the real dataset's shapes,
dtypes, vocabulary sizes and label ranges — enough for the book tests'
convergence gates and any pipeline code, clearly not for real accuracy
numbers."""
from . import (  # noqa: F401
    cifar, conll05, flowers, image, imdb, imikolov, mnist, movielens,
    mq2007, sentiment, uci_housing, voc2012, wmt14, wmt16,
)

__all__ = ["mnist", "cifar", "imdb", "imikolov", "uci_housing",
           "conll05", "movielens", "sentiment", "wmt14", "wmt16",
           "flowers", "voc2012", "mq2007", "image"]
