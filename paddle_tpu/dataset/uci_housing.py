"""UCI housing reader creators (reference
python/paddle/dataset/uci_housing.py: train()/test() yield (features
float32 [13] normalized, [price])). Synthetic fallback: a fixed linear
ground truth + noise, so fit_a_line converges to low loss."""
import numpy as np

from . import common

_TRAIN_N, _TEST_N = 404, 102
_W = None


def _true_w(rng):
    global _W
    if _W is None:
        _W = rng.standard_normal(13).astype(np.float32)
    return _W


def _synthetic_reader(split, n):
    def reader():
        rng = common.synthetic_rng("uci_housing", "w")
        w = _true_w(rng)
        rng = common.synthetic_rng("uci_housing", split)
        for _ in range(n):
            x = rng.standard_normal(13).astype(np.float32)
            y = float(x @ w + 0.1 * rng.standard_normal())
            yield x, np.array([y], np.float32)
    return reader


def train():
    return _synthetic_reader("train", _TRAIN_N)


def test():
    return _synthetic_reader("test", _TEST_N)
