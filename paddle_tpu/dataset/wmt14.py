"""WMT14 en->fr reader creators (reference
python/paddle/dataset/wmt14.py: train/test(dict_size) yield
(src_ids, trg_ids, trg_ids_next); <s>=0, <e>=1, <unk>=2). Synthetic
stream policy: deterministic "translation" pairs where trg is a fixed
affine remap of src, so seq2seq models can genuinely fit."""
import numpy as np

from . import common

WORDDICT = 30000
_TRAIN_N, _TEST_N = 2000, 400


def _pair(rng, dict_size):
    ln = int(rng.integers(3, 25))
    src = rng.integers(3, dict_size, ln)
    # deterministic "translation": affine remap into the dict
    trg_core = (src * 7 + 13) % (dict_size - 3) + 3
    src_ids = [int(i) for i in src]
    trg_ids = [0] + [int(i) for i in trg_core]            # <s> + words
    trg_next = [int(i) for i in trg_core] + [1]           # words + <e>
    return src_ids, trg_ids, trg_next


def reader_creator(split, n, dict_size):
    def reader():
        rng = common.synthetic_rng("wmt14", f"{split}/{dict_size}")
        for _ in range(n):
            yield _pair(rng, dict_size)
    return reader


def train(dict_size):
    return reader_creator("train", _TRAIN_N, dict_size)


def test(dict_size):
    return reader_creator("test", _TEST_N, dict_size)


def gen(dict_size):
    return reader_creator("gen", _TEST_N, dict_size)


def get_dict(dict_size, reverse=True):
    """(src_dict, trg_dict); reverse=True -> id->word (reference :155)."""
    words = {0: "<s>", 1: "<e>", 2: "<unk>"}
    words.update({i: f"w{i}" for i in range(3, dict_size)})
    if reverse:
        return dict(words), dict(words)
    inv = {w: i for i, w in words.items()}
    return dict(inv), dict(inv)


def fetch():
    return None
