"""IMDB sentiment reader creators (reference
python/paddle/dataset/imdb.py: word_dict(), train(word_dict),
test(word_dict) yield ([word ids], label 0/1)). Synthetic fallback:
sentiment is carried by disjoint positive/negative token ranges so
bag-of-words models converge."""
import numpy as np

from . import common

_VOCAB = 5149          # reference's imdb.word_dict() size ballpark
_TRAIN_N, _TEST_N = 2048, 256


def word_dict():
    return {f"w{i}": i for i in range(_VOCAB)}


def _synthetic_reader(split, n):
    def reader():
        rng = common.synthetic_rng("imdb", split)
        for _ in range(n):
            label = int(rng.integers(0, 2))
            ln = int(rng.integers(8, 64))
            base = rng.integers(0, _VOCAB, ln)
            # sentiment tokens: ids [100, 400) positive, [400, 700) neg
            sent = rng.integers(100, 400, max(ln // 4, 1)) \
                if label else rng.integers(400, 700, max(ln // 4, 1))
            ids = np.concatenate([base, sent])
            rng.shuffle(ids)
            yield [int(i) for i in ids], label
    return reader


def train(word_dict=None):
    return _synthetic_reader("train", _TRAIN_N)


def test(word_dict=None):
    return _synthetic_reader("test", _TEST_N)
