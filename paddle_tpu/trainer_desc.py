"""Trainer descriptors (reference python/paddle/fluid/trainer_desc.py:
TrainerDesc base + MultiTrainer / DistMultiTrainer / PipelineTrainer).

In the reference these assemble a protobuf consumed by the C++ trainer
thread runtime; here `Executor.train_from_dataset` compiles the whole
program into one XLA executable and streams the dataset through it, so
a descriptor is a plain config object. They remain the public surface
for code that constructs trainers explicitly (fleet/pslib paths pass
`DistMultiTrainer`); train_from_dataset reads the fetch config off
them."""


class TrainerDesc:
    def __init__(self):
        self._program = None
        self._fetch_vars = []
        self._fetch_info = []
        self._print_period = 100
        self._batch_size = None
        self._thread_num = 1
        self._device_worker = None
        self._infer = False

    # reference trainer_desc.py setter surface
    def _set_fetch_var_and_info(self, fetch_vars, fetch_info,
                                print_period):
        self._fetch_vars = list(fetch_vars or [])
        self._fetch_info = list(fetch_info or [])
        self._print_period = print_period

    def _set_program(self, program):
        self._program = program

    def _set_thread(self, num):
        self._thread_num = num

    def _set_batch_size(self, batch_size):
        self._batch_size = batch_size

    def _set_device_worker(self, worker):
        self._device_worker = worker

    def _set_infer(self, infer):
        self._infer = bool(infer)

    def _desc(self):
        return {
            "class": type(self).__name__,
            "thread_num": self._thread_num,
            "fetch_vars": self._fetch_vars,
            "fetch_info": self._fetch_info,
            "print_period": self._print_period,
            "infer": self._infer,
        }


class MultiTrainer(TrainerDesc):
    """Multi-thread single-node trainer (reference MultiTrainer): the
    thread pool is XLA's; kept for API parity."""


class DistMultiTrainer(TrainerDesc):
    """Downpour/PS trainer descriptor (reference DistMultiTrainer);
    distributed/downpour.py drives the equivalent runtime."""


class PipelineTrainer(TrainerDesc):
    """Pipeline-parallel trainer descriptor (reference
    PipelineTrainer); layers.Pipeline over the `pp` mesh axis is the
    execution path."""
