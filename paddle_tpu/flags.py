"""Runtime flag facade.

Capability parity with the reference's gflags spine
(/root/reference/paddle/fluid/platform/flags.cc — ~26 DEFINE_* runtime
knobs; Python access via pybind/global_value_getter_setter.cc,
fluid.core.globals(), and FLAGS_* env passthrough whitelisted in
python/paddle/fluid/__init__.py).

One typed registry replaces gflags + pybind getters + env whitelist:
flags are declared here with defaults, `FLAGS_<name>` environment
variables override at import, and `set_flags`/`get_flags` mirror the
fluid API. Flags with a real XLA/JAX effect apply immediately
(check_nan_inf -> jax_debug_nans, deterministic -> matching XLA flag);
CUDA-allocator knobs are accepted no-ops so reference launch scripts run
unchanged.
"""
import os

_DEFS = {
    # name: (default, type, applies)
    # checked host-side by Executor.run so the error names the offending
    # variable (reference nan_inf_utils_detail.cc), not via jax_debug_nans
    # (which reports an anonymous FloatingPointError mid-jit)
    "check_nan_inf": (False, bool, None),
    # -- RPC hardening (reference FLAGS_rpc_deadline ms / rpc_retry_times;
    # here the deadline is SECONDS and must exceed the pserver's 120s
    # sync-barrier wait so a slow-but-live barrier isn't killed) --
    "rpc_deadline": (150.0, float, None),
    "rpc_retry_times": (3, int, None),
    "rpc_retry_base_backoff": (0.05, float, None),
    "rpc_circuit_break_failures": (3, int, None),
    "rpc_circuit_reset_secs": (5.0, float, None),
    # -- serving runtime (paddle_tpu/serving) --
    # batch former: flush a signature's batch at max_batch_size rows or
    # after the oldest member waited batch_timeout_ms
    "serving_max_batch_size": (32, int, None),
    "serving_batch_timeout_ms": (5.0, float, None),
    # admission: hard pending-request cap (backpressure) and the default
    # per-request deadline (0 = no deadline unless the request sets one)
    "serving_queue_depth": (256, int, None),
    "serving_default_deadline_ms": (0.0, float, None),
    # compiled-executable cache caps (0 = unbounded on that axis)
    "serving_cache_entries": (32, int, None),
    "serving_cache_bytes": (0, int, None),
    # load-shed breaker: consecutive queue-full refusals that open it,
    # and how long it sheds before re-probing
    "serving_shed_failures": (8, int, None),
    "serving_shed_reset_secs": (0.5, float, None),
    # -- serving resilience layer --
    # wall-clock budget per batcher execute / decode step (run under
    # resilience.run_with_watchdog so a hung chip call fails that
    # batch's clients instead of wedging the loop) and the supervisor's
    # stale-heartbeat threshold. Must exceed the worst-case first-shape
    # compile; 0 disables the watchdog and the hung-loop detector.
    "serving_loop_watchdog_s": (60.0, float, None),
    # client-side hedged requests: hedge `infer` after this many ms
    # without a reply (p99-derived once the client has observed enough
    # traffic; this flag is the cold-start delay). 0 = hedging off.
    "serving_hedge_ms": (0.0, float, None),
    # default seed for resilience.chaos() fault-point streams
    "chaos_seed": (0, int, None),
    # -- unified telemetry (paddle_tpu/observability) --
    # fraction of requests that carry a trace context (wire-propagated
    # request tracing): 0.0 = off, 1.0 = every request. Sampled at the
    # CLIENT (serving.Client / tracing.maybe_trace); untraced requests
    # pay one random() draw and nothing else
    "trace_sample_rate": (0.01, float, None),
    # flight recorder ring capacity (recent structured events kept for
    # postmortem dumps: admissions, evictions, restarts, chaos firings,
    # non-finite hits, weight reloads, preemptions)
    "flight_recorder_events": (512, int, None),
    # directory for AUTOMATIC flight-recorder dumps (written when a
    # typed Internal/Watchdog error crosses the serving wire boundary,
    # rate-limited). "" = automatic dumps off; the "debug_dump" wire op
    # and FlightRecorder.dump() always work
    "flight_recorder_dir": ("", str, None),
    # -- performance attribution & SLO guardrails --
    # sampled MEASURED per-op profiling: 0 = off (the default — the
    # executor hot path pays one flag read and is bitwise-unchanged);
    # N >= 1 = every N-th Executor.run dispatch of a program additionally
    # replays the optimized clone op-by-op (eager, synced) to record a
    # per-op wall-time table + Perfetto op spans + the hbm_live_bytes
    # counter track. The committed step result still comes from the
    # fused executable — profiling never changes numerics.
    "profile_ops": (0, int, None),
    # start the default SLO monitor (observability/slo.py) inside every
    # InferenceServer: p99 inter-token latency, queue-depth ratios,
    # kvpool occupancy, optional MFU floor
    "slo_monitor": (True, bool, None),
    # SLO rule evaluation cadence (the supervised monitor loop)
    "slo_poll_s": (0.25, float, None),
    # default-ruleset thresholds (0 disables the individual rule):
    # windowed p99 of the decode stage (inter-token latency proxy, ms)
    "slo_decode_p99_ms": (2000.0, float, None),
    # queue depth as a fraction of the admission cap
    "slo_queue_ratio": (0.9, float, None),
    # paged KV pool occupancy (blocks in use / allocatable)
    "slo_kvpool_ratio": (0.95, float, None),
    # MFU floor on the decode path (0 = rule off; set > 0 on real
    # accelerators where peak tables are known)
    "slo_mfu_floor": (0.0, float, None),
    # -- sharding audit & collective-traffic ledger (observability/
    # sharding, observability/comms) --
    # audit every newly compiled MESH executable's actual shardings
    # against the declared dist_attr/PartitionSpecs and emit typed
    # findings (replicated-large-param, unsharded-batch,
    # sharding-mismatch, reshard-inserted) as shard_audit_finding
    # flight events + shard_audit_findings_total. Off by default: the
    # compile-miss path pays one flag read and numerics are
    # bitwise-unchanged either way (the audit only READS the compiled
    # executable)
    "shard_audit": (False, bool, None),
    # replicated-large-param threshold: a persistable input replicated
    # across a >1 mesh axis only becomes a finding at or above this
    # many megabytes (small scales/biases legitimately replicate)
    "shard_audit_replicated_mb": (16.0, float, None),
    # parse every newly compiled mesh executable's HLO for collectives
    # (all-reduce / all-gather / reduce-scatter / all-to-all /
    # collective-permute), attribute each to a mesh axis via its
    # replica_groups, and export per-(collective, axis) bytes/op
    # counters plus the predicted device_comm_bound_ratio gauge
    "comms_ledger": (False, bool, None),
    # comma-separated mesh axes that ride DCN instead of ICI (multi-
    # slice deployments: an axis spanning slices prices its
    # collectives at the cross-slice fabric). A collective whose group
    # varies over ANY listed axis uses the DCN peak. "" = all-ICI
    "comms_dcn_axes": ("", str, None),
    # -- multi-slice training (train/slices, framework/passes
    # hier_grad_sync) --
    # run dcn_dp meshes through the hierarchical grad-sync path:
    # reduce-scatter in-slice (ICI), all-reduce across slices (DCN) on
    # the 1/dp shard each chip owns, all-gather in-slice. False =
    # plain GSPMD (the flat-all-reduce A/B baseline; numerics
    # unchanged — hier_allreduce is mathematically the same mean)
    "dcn_hierarchical": (True, bool, None),
    # before the first multi-slice slab is dispatched, parse the
    # compiled HLO and ASSERT the decomposition: DCN-priced traffic
    # only on FLAGS_comms_dcn_axes, and cross-slice wire bytes
    # strictly below the flat all-reduce estimate — raising
    # HierarchicalCommsError before a chip is burned
    "dcn_assert_hier": (True, bool, None),
    # SliceSupervisor liveness: a slice whose last heartbeat is older
    # than this many seconds counts one stale observation
    "slice_heartbeat_timeout_s": (5.0, float, None),
    # hysteresis window: membership only changes after this many
    # CONSECUTIVE stale (shrink) or fresh (regrow) observations
    "slice_window": (3, int, None),
    # cooldown between membership changes (shrink or regrow), so a
    # flapping slice can't thrash checkpoint-restore cycles
    "slice_cooldown_s": (10.0, float, None),
    # -- training observability (observability/goodput, train/health,
    # observability/inputstall) --
    # model-health monitoring cadence: every N-th supervised slab
    # additionally fetches per-slab loss / global grad-norm /
    # param-update-ratio IN-GRAPH through the run_steps fetch path and
    # evaluates the loss-spike / grad-norm-spike SLO rules. 0 (default)
    # = off: no ops are added to the program and the fused-step path is
    # bitwise-unchanged
    "train_health_every_n": (0, int, None),
    # health rule thresholds: breach when the fetched value exceeds
    # this multiple of its trailing EMA (loss spike / grad-norm spike)
    "train_loss_spike_ratio": (3.0, float, None),
    "train_grad_spike_ratio": (10.0, float, None),
    # input-pipeline stall profiler: flag a data_stall flight event
    # when, over a window of at least dataio_stall_window_s seconds,
    # the consumer spent more than dataio_stall_ratio of the wall time
    # blocked waiting on the producer queue
    "dataio_stall_window_s": (1.0, float, None),
    "dataio_stall_ratio": (0.5, float, None),
    # -- elastic training (paddle_tpu/train) --
    # periodic full-training-state checkpoint cadence for
    # TrainingSupervisor: one async (CheckFreq-staged) checkpoint every
    # N fused slabs
    "checkpoint_every_n_slabs": (16, int, None),
    # wall-clock budget for the preemption fast checkpoint (SIGTERM ->
    # save at next slab boundary -> exit); a save that misses it is
    # abandoned and the previous verified checkpoint stands. 0 = no
    # bound (save however long it takes before exiting)
    "preempt_deadline_s": (30.0, float, None),
    # how many supervised-restart attempts (crash/hang -> reload newest
    # checkpoint with capped backoff) before RestartBudgetExceeded
    "train_restart_budget": (3, int, None),
    # -- KV-cached autoregressive decoding (models/generation, serving
    # decode batching) --
    # preallocated per-layer KV cache length [B, H, decode_max_len, D]:
    # prompt length + max_new_tokens must fit (clamped to the model's
    # max_position)
    "decode_max_len": (2048, int, None),
    # minimum prefill sequence bucket: prompts pad up to the next
    # power-of-two >= this, bounding the universe of compiled prefill
    # shapes (buckets: decode_bucket_min, 2x, 4x, ... decode_max_len)
    "decode_bucket_min": (16, int, None),
    # serving decode batch: fixed number of generation slots stepped by
    # one compiled decode executable; finished rows free their slot for
    # the next admitted request (continuous batching)
    "decode_slots": (8, int, None),
    # speculative decoding (Leviathan 2022 / Chen 2023): draft depth K —
    # a drafter proposes up to K tokens per row per step, one verify
    # pass scores all K+1 positions, rejection sampling keeps the
    # model-agreed prefix. 0 = off (the parity baseline); greedy output
    # is bitwise-identical either way
    "decode_spec_k": (0, int, None),
    # default drafter: "ngram" (free prompt-lookup self-drafting) or
    # "model" (1-layer draft GPT sharing the generator's parameter
    # snapshot)
    "decode_spec_mode": ("ngram", str, None),
    # -- paged KV cache (serving/kvpool, kernels/paged_attention) --
    # opt-in block-paged decode memory: KV caches live in a shared
    # block pool with per-slot block tables (vLLM/PagedAttention)
    # instead of the dense [slots, H, max_len, D] bank; blocks allocate
    # on append and free on EOS/deadline/cancel, so concurrency is
    # bounded by actual tokens. 0 keeps the dense bank (the parity
    # baseline)
    "kv_paged": (False, bool, None),
    # KV-cache element type: fp32 (bitwise baseline), bf16 (half the
    # cache bytes), int8 (quarter, with per-(block, head, slot) float32
    # scales) — at bandwidth-bound decode, cache bytes ARE tokens/s
    "kv_cache_dtype": ("fp32", str, None),
    # tokens per KV block: small = fine-grained allocation (less
    # last-block waste), large = smaller tables and fewer allocations
    "kv_block_size": (16, int, None),
    # total pool blocks (incl. the reserved trash block); 0 = size the
    # pool HBM-equivalent to the dense bank it replaces
    # (slots * ceil(max_len/block_size) + 1)
    "kv_pool_blocks": (0, int, None),
    # -- pod-scale serving (tp generation, chunked prefill, prefix
    # cache) --
    # tensor-parallel generation: compile prefill/decode/logits
    # executables under a tp=N mesh (Megatron column/row split via
    # gpt.apply_tp_sharding; pool block arrays sharded on the head
    # axis), gated at compile time by the sharding audit + a
    # comms-ledger wire-byte budget. 0/1 = single-chip (the parity
    # baseline)
    "serving_tp": (0, int, None),
    # chunked prefill (Orca/Sarathi continuous scheduling): admission
    # prefill proceeds in slices of at most this many tokens,
    # interleaved with decode steps so a long prompt never stalls the
    # decode bank's token cadence. 0 = monolithic prefill
    "prefill_chunk_tokens": (0, int, None),
    # block-granular prefix caching: completed prompts deposit their KV
    # blocks into a refcounted hash-keyed index; a new prompt sharing a
    # prefix adopts those blocks (copy-on-write on divergence) and only
    # prefills the tail. Cold entries evict LRU under pool pressure
    "kv_prefix_cache": (False, bool, None),
    # -- overload control (resilience.RetryBudget, serving brownout,
    # fleet autoscaler) --
    # process-global retry budget: every initial request deposits this
    # many retry tokens; every retry/hedge/failover withdraws one, so
    # tail-fighting machinery is bounded at ~ratio x offered load and a
    # saturated fleet sheds instead of amplifying itself (Tail at
    # Scale). A small time-based reserve keeps isolated failures
    # retryable. < 0 disables the budget (unbounded retries — the
    # bench.py --config overload A/B lever)
    "retry_budget_ratio": (0.1, float, None),
    # brownout degradation ladder: a breached-SLO server degrades
    # best-effort, then batch traffic (shed + capped max_new_tokens +
    # shrunken admission) BEFORE interactive traffic, recovering
    # symmetrically as breaches clear
    "serving_brownout": (True, bool, None),
    # fleet autoscaler bounds: the Autoscaler holds the replica pool
    # between these (inclusive), scaling on windowed fleet telemetry
    "fleet_min_replicas": (1, int, None),
    "fleet_max_replicas": (4, int, None),
    # minimum seconds between autoscaler scale events (with the
    # full-window hysteresis this is what keeps the pool from flapping)
    "fleet_scale_cooldown_s": (5.0, float, None),
    # -- disaggregated serving fleet (serving/fleet) --
    # router health-probe cadence against every registered replica, and
    # the per-probe wire timeout (a hung replica's accept loop must fail
    # the probe fast, not inherit the long socket default)
    "router_probe_interval_s": (0.5, float, None),
    "router_probe_timeout_s": (2.0, float, None),
    # consecutive failed probes before a replica is EVICTED from the
    # dispatch rotation (probing continues; a healthy probe readmits it)
    "router_evict_after": (3, int, None),
    # cross-replica hedging: fire a twin of a routed generate on a
    # SECOND replica after this many ms without a reply (the loser is
    # cancelled by request id). 0 = hedging off (failover-on-death only)
    "router_hedge_ms": (0.0, float, None),
    # extra replicas tried when a dispatch target dies mid-request
    # (transport failure -> the replica is marked dead and the request
    # fails over with the SAME request id)
    "router_dispatch_retries": (2, int, None),
    # Executor per-(program, feed-shape) compile cache entry cap — bounds
    # what was previously unbounded growth per input-shape signature
    "executor_cache_entries": (128, int, None),
    # -- pre-lowering program optimization pipeline (framework/passes) --
    # "1"/"default" = the default pipeline (dce,cse,fuse_optimizer) runs
    # on every executor compile-cache miss; "0" = off, reproducing the
    # unoptimized lowering bitwise; or an explicit comma-separated pass
    # list (e.g. "dce,cse") run in canonical registry order
    "program_passes": ("1", str, None),
    # per-pass translation validation (framework/analysis.py): verify
    # every pass's output program and the user program on compile-cache
    # misses, raising typed ProgramVerifyError with pass provenance.
    # Off by default (the hot path pays nothing); tests/CI turn it on
    # (tests/conftest.py), and `python tools/lint_program.py` runs the
    # same checkers standalone
    "verify_passes": (False, bool, None),
    # flattened-concat byte cap per fused-optimizer bucket (multi-tensor
    # apply): same-(op, dtype, hyperparam) update ops group into buckets
    # of at most this many megabytes of parameters
    "fuse_optimizer_bucket_mb": (64, int, None),
    # -- fused multi-step training loop (Executor.run_steps) --
    # default K for train_from_dataset: K steps compile into ONE jitted
    # lax.scan over a stacked feed slab (1 = unfused per-step dispatch)
    "steps_per_run": (1, int, None),
    # materialize fetches only on every N-th slab / print_period hit;
    # in-between slabs run a fetch-free executable (1 = every slab)
    "fetch_every_n": (1, int, None),
    # run_steps scan unroll factor. 1 (default) = loop form: bitwise
    # parity with sequential run() and K-independent compile time.
    # 0 = auto: full unroll on the CPU backend (XLA CPU runs while-loop
    # bodies without intra-op threading, so the loop form serializes
    # convs), loop form on accelerators. N>1 unrolls N steps per loop
    # iteration. Unrolled steps may fuse across step boundaries —
    # numerically equivalent but not bit-identical to sequential run().
    "scan_unroll": (1, int, None),
    "cudnn_deterministic": (False, bool, None),
    "cpu_deterministic": (False, bool, None),
    "benchmark": (False, bool, None),
    "eager_delete_tensor_gb": (0.0, float, None),
    "fraction_of_gpu_memory_to_use": (0.92, float, None),
    "allocator_strategy": ("auto_growth", str, None),
    "fast_eager_deletion_mode": (True, bool, None),
    "memory_fraction_of_eager_deletion": (1.0, float, None),
    "sync_nccl_allreduce": (True, bool, None),
    "communicator_independent_recv_thread": (True, bool, None),
    "communicator_send_queue_size": (20, int, None),
    "communicator_max_merge_var_num": (20, int, None),
    "paddle_num_threads": (1, int, None),
    "inner_op_parallelism": (0, int, None),
    "init_allocated_mem": (False, bool, None),
    "free_idle_chunk": (False, bool, None),
    "use_pinned_memory": (True, bool, None),
    "tracer_profile_fname": ("", str, None),
    "selected_tpus": ("", str, None),
}

# Accepted-but-inert compatibility knobs: declared so reference launch
# scripts (CUDA allocator tuning, communicator threading, eager GC) run
# unchanged, but nothing on the TPU path reads them — XLA owns what they
# governed. tools/lint_flags.py enforces that every OTHER declared flag
# is actually referenced somewhere in paddle_tpu/ (and that every
# FLAGS_* reference is declared); a new flag is either read by code or
# belongs in this set.
_COMPAT_ONLY = frozenset({
    "allocator_strategy", "benchmark",
    "communicator_independent_recv_thread",
    "communicator_max_merge_var_num", "communicator_send_queue_size",
    "cpu_deterministic", "cudnn_deterministic",
    "eager_delete_tensor_gb", "fast_eager_deletion_mode",
    "fraction_of_gpu_memory_to_use", "free_idle_chunk",
    "init_allocated_mem", "inner_op_parallelism",
    "memory_fraction_of_eager_deletion", "paddle_num_threads",
    "sync_nccl_allreduce", "tracer_profile_fname", "use_pinned_memory",
})

_values = {}


def _coerce(raw, typ):
    if typ is bool:
        return str(raw).lower() in ("1", "true", "yes", "on")
    return typ(raw)


def _apply(name, value):
    hook = _DEFS[name][2]
    if hook == "jax_debug_nans":
        import jax
        jax.config.update("jax_debug_nans", bool(value))


def _init():
    for name, (default, typ, _) in _DEFS.items():
        raw = os.environ.get(f"FLAGS_{name}")
        val = _coerce(raw, typ) if raw is not None else default
        _values[name] = val
        if raw is not None:
            _apply(name, val)


def get_flags(flags):
    """fluid.get_flags parity: names with or without the FLAGS_ prefix."""
    single = isinstance(flags, str)
    names = [flags] if single else list(flags)
    out = {}
    for n in names:
        key = n[6:] if n.startswith("FLAGS_") else n
        if key not in _values:
            raise ValueError(f"unknown flag {n!r}")
        out[n] = _values[key]
    return out


def set_flags(flags_dict):
    """fluid.set_flags parity."""
    for n, v in flags_dict.items():
        key = n[6:] if n.startswith("FLAGS_") else n
        if key not in _DEFS:
            raise ValueError(f"unknown flag {n!r}")
        _values[key] = _coerce(v, _DEFS[key][1])
        _apply(key, _values[key])


def flag(name):
    """Fast single-flag getter for hot paths (Executor.run, PSClient)."""
    return _values[name]


def globals_():
    """fluid.core.globals() analog: a live view of every flag."""
    return dict(_values)


_init()
