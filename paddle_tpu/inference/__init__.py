"""Inference engine: AOT predictor + StableHLO export.

Capability parity with the reference's inference stack
(/root/reference/paddle/fluid/inference/api/analysis_predictor.h:82
AnalysisPredictor, analysis_predictor.cc:497 CreatePaddlePredictor,
paddle_analysis_config.h AnalysisConfig, zero-copy tensors
paddle_api.h ZeroCopyTensor).

TPU-native mapping: the reference loads a ProgramDesc, runs ~40 analysis/
fusion passes and executes with NaiveExecutor; here the saved (pruned)
program lowers to ONE XLA module that is AOT-compiled per input-shape
signature — XLA *is* the analysis pipeline, so `switch_ir_optim` etc. are
accepted no-ops. The compiled executable can also be exported as portable
StableHLO text (`export_stablehlo`), the TPU analog of shipping a
TensorRT/Lite engine artifact.
"""
import os
import time

import numpy as np

import jax


class AnalysisConfig:
    """reference paddle_analysis_config.h API shape."""

    def __init__(self, model_dir=None, prog_file=None, params_file=None):
        self._model_dir = model_dir
        self._prog_file = prog_file
        self._params_file = params_file
        self._ir_optim = True
        self._use_feed_fetch_ops = False
        self._memory_optim = False
        self._cpu_math_threads = 1
        self._profile = False
        self._glog_info = True

    # -- model paths -----------------------------------------------------
    def set_model(self, model_dir_or_prog, params_file=None):
        if params_file is None:
            self._model_dir = model_dir_or_prog
        else:
            self._prog_file = model_dir_or_prog
            self._params_file = params_file

    def model_dir(self):
        return self._model_dir

    def prog_file(self):
        return self._prog_file

    def params_file(self):
        return self._params_file

    # -- optimization switches (XLA owns these; kept for API parity) -----
    def switch_ir_optim(self, x=True):
        self._ir_optim = bool(x)

    def ir_optim(self):
        return self._ir_optim

    def switch_use_feed_fetch_ops(self, x=True):
        self._use_feed_fetch_ops = bool(x)

    def enable_memory_optim(self):
        self._memory_optim = True

    def enable_profile(self):
        self._profile = True

    def disable_glog_info(self):
        self._glog_info = False

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_math_threads = int(n)

    def cpu_math_library_num_threads(self):
        return self._cpu_math_threads

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        import warnings
        warnings.warn("enable_use_gpu is a no-op: the device is chosen by "
                      "the jax platform (TPU when available)", stacklevel=2)

    def disable_gpu(self):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        import warnings
        warnings.warn("TensorRT has no TPU analog; XLA compiles the whole "
                      "graph — enable_tensorrt_engine is a no-op",
                      stacklevel=2)


class _IOTensor:
    """Zero-copy-style handle (reference ZeroCopyTensor): the input keeps a
    host buffer the predictor feeds from; the output exposes the last run's
    device array."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return list(np.shape(self._value))


class AnalysisPredictor:
    """reference analysis_predictor.h:82 — load once, AOT-compile per input
    signature, run many; `clone()` shares weights (clone-per-thread)."""

    def __init__(self, config, _shared=None):
        from ..framework.executor import Executor, Scope, scope_guard
        self._config = config
        self._exe = Executor()
        if _shared is not None:
            (self._scope, self._program, self._feed_names,
             self._fetch_targets) = _shared
        else:
            from .. import io as fluid_io
            self._scope = Scope()
            model_dir = config.model_dir()
            model_filename = params_filename = None
            if model_dir is None:
                model_dir = os.path.dirname(config.prog_file())
                model_filename = os.path.basename(config.prog_file())
                params_filename = os.path.basename(config.params_file()) \
                    if config.params_file() else None
            with scope_guard(self._scope):
                (self._program, self._feed_names,
                 self._fetch_targets) = fluid_io.load_inference_model(
                    model_dir, self._exe, model_filename=model_filename,
                    params_filename=params_filename)
        self._inputs = {n: _IOTensor(n) for n in self._feed_names}
        self._outputs = {t.name: _IOTensor(t.name)
                         for t in self._fetch_targets}

    # -- handles ---------------------------------------------------------
    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return [t.name for t in self._fetch_targets]

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_input_tensor(self, name):
        return self._inputs[name]

    def get_output_handle(self, name):
        return self._outputs[name]

    def get_output_tensor(self, name):
        return self._outputs[name]

    # -- execution -------------------------------------------------------
    def run(self, inputs=None):
        """With `inputs` (list of numpy arrays, feed order): returns list
        of numpy outputs. Without: consumes the input handles and fills the
        output handles (zero-copy style). Thread-safe under
        clone-per-thread: the scope is passed explicitly (no global
        scope-guard mutation), so concurrent clones sharing weights can
        run in parallel."""
        if inputs is not None:
            for n, a in zip(self._feed_names, inputs):
                self._inputs[n].copy_from_cpu(a)
        feed = {n: self._inputs[n]._value for n in self._feed_names}
        for n, v in feed.items():
            if v is None:
                raise ValueError(f"input {n!r} was never set — call "
                                 f"get_input_handle({n!r}).copy_from_cpu()")
        outs = self._exe.run(self._program, feed=feed,
                             fetch_list=[t.name
                                         for t in self._fetch_targets],
                             scope=self._scope, return_numpy=False)
        for t, v in zip(self._fetch_targets, outs):
            self._outputs[t.name]._value = v
        if inputs is not None:
            return [np.asarray(v) for v in outs]
        return True

    def prepare(self, input_shapes, dtype_map=None):
        """AOT compile-at-load (reference analysis passes compile before
        the first Run): execute one zero-filled batch per given signature
        so the first real request hits a warm executable cache.
        input_shapes: {feed_name: shape} or list of shapes in feed
        order."""
        from ..framework.dtype import np_dtype
        if isinstance(input_shapes, (list, tuple)):
            input_shapes = dict(zip(self._feed_names, input_shapes))
        feeds = []
        for n in self._feed_names:
            var = self._program.global_block().vars.get(n)
            dt = (dtype_map or {}).get(
                n, getattr(var, "dtype", "float32") or "float32")
            feeds.append(np.zeros(input_shapes[n], dtype=np_dtype(dt)))
        self.run(feeds)
        return self

    def cache_stats(self):
        """Compile-cache counters for THIS predictor's executor: entries,
        hit/miss/evict. The per-shape cache is LRU-bounded by
        ``FLAGS_executor_cache_entries`` (it previously grew without
        limit per input-shape signature). For the multi-client serving
        layer above this predictor see ``paddle_tpu.serving``."""
        return self._exe.cache_stats()

    def clone(self):
        """Share weights/program; private executor cache (reference
        clone-per-thread serving)."""
        return AnalysisPredictor(
            self._config,
            _shared=(self._scope, self._program, self._feed_names,
                     self._fetch_targets))

    def program(self):
        return self._program


def create_paddle_predictor(config):
    """reference CreatePaddlePredictor<AnalysisConfig>
    (analysis_predictor.cc:936)."""
    return AnalysisPredictor(config)


create_predictor = create_paddle_predictor


def export_stablehlo(dirname, feed_shapes, feed_dtypes=None,
                     output_path=None, scope=None):
    """Lower a saved inference model to portable StableHLO text — the TPU
    artifact analog of the reference's engine-serialization paths
    (inference/tensorrt/, inference/lite/). `feed_shapes`: {name: shape}.
    Returns the .mlir path."""
    from .. import io as fluid_io
    from ..framework.executor import Executor, Scope, scope_guard
    from ..framework.lowering import analyze_block_io, build_block_fn
    from ..framework.dtype import np_dtype

    exe = Executor()
    scope = scope or Scope()
    with scope_guard(scope):
        program, feed_names, fetch_targets = fluid_io.load_inference_model(
            dirname, exe)
        state = {}
        state_in, _ = analyze_block_io(program, 0, list(feed_names))
        for n in state_in:
            v = scope.find_var(n)
            if v is not None:
                state[n] = np.asarray(v)
    fetch_names = [t.name for t in fetch_targets]
    fn = build_block_fn(program, 0, list(feed_names), fetch_names,
                        state_in, [])

    gb = program.global_block()
    feed_avals = {}
    for n in feed_names:
        shape = tuple(feed_shapes[n])
        dt = (feed_dtypes or {}).get(n) or np_dtype(gb.var(n).dtype)
        feed_avals[n] = jax.ShapeDtypeStruct(shape, dt)

    def infer_fn(state, feed):
        fetches, _, _ = fn({}, state, feed, jax.random.PRNGKey(0))
        return fetches

    lowered = jax.jit(infer_fn).lower(state, feed_avals)
    text = lowered.as_text()
    output_path = output_path or os.path.join(dirname, "model.stablehlo.mlir")
    with open(output_path, "w") as f:
        f.write(text)
    return output_path
