"""Host-side LoD tensor containers (reference
python/paddle/fluid/lod_tensor.py:24 create_lod_tensor,
lod_tensor.py:114 create_random_int_lodtensor, and the pybind
core.LoDTensor / core.Tensor / core.LoDTensorArray surface).

The framework's DEVICE representation of ragged data is masked-dense
(padded [B, T, ...] + length vectors — see PARITY.md); these classes
are the host-side feed/fetch containers that carry
recursive_sequence_lengths alongside a numpy payload, so reference
user code that builds LoDTensors for feeding ports unchanged. The
executor's feed path accepts them via __array__ (the masked-dense ops
take the lengths separately)."""
import numpy as np


class Tensor:
    """Host tensor: `t = fluid.Tensor(); t.set(arr, place)` (reference
    pybind core.Tensor)."""

    def __init__(self):
        self._array = None
        self._place = None
        self._recursive_seq_lens = []

    def set(self, array, place=None):
        self._array = np.asarray(array)
        self._place = place

    def shape(self):
        return list(self._array.shape) if self._array is not None else []

    def _dtype(self):
        return str(self._array.dtype) if self._array is not None else None

    def set_recursive_sequence_lengths(self, lens):
        self._recursive_seq_lens = [list(l) for l in (lens or [])]

    def recursive_sequence_lengths(self):
        return self._recursive_seq_lens

    def has_valid_recursive_sequence_lengths(self):
        if not self._recursive_seq_lens:
            return True
        # innermost level must tile the leading dim; outer levels must
        # tile the next level's entry count (reference
        # CheckAbsLoD/CheckLoD)
        levels = self._recursive_seq_lens
        if self._array is None or sum(levels[-1]) != self._array.shape[0]:
            return False
        for outer, inner in zip(levels, levels[1:]):
            if sum(outer) != len(inner):
                return False
        return True

    def __array__(self, dtype=None):
        a = self._array
        return a.astype(dtype) if dtype is not None else a

    def __repr__(self):
        return (f"{type(self).__name__}(shape={self.shape()}, "
                f"recursive_sequence_lengths={self._recursive_seq_lens})")


class LoDTensor(Tensor):
    """reference core.LoDTensor: a Tensor + recursive sequence lengths."""


class LoDTensorArray(list):
    """reference core.LoDTensorArray: a growable list of LoDTensors."""


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """Build a LoDTensor from an ndarray / nested list / LoDTensor plus
    level-wise sequence lengths (reference lod_tensor.py:24). A nested
    list of per-sequence rows is flattened; lengths are validated
    against the leading dim."""
    if isinstance(data, LoDTensor):
        return create_lod_tensor(np.asarray(data), recursive_seq_lens,
                                 place)
    if isinstance(data, list):
        # list of sequences: flatten rows, derive the innermost level
        flat = [np.asarray(seq).reshape(len(seq), -1) for seq in data]
        new_lens = [len(seq) for seq in data]
        if recursive_seq_lens and \
                list(recursive_seq_lens[-1]) != new_lens:
            raise ValueError(
                "the provided recursive_seq_lens do not match the "
                "sequence lengths of the nested-list data")
        data = np.concatenate(flat, axis=0) if flat else np.zeros((0, 1))
    arr = np.asarray(data)
    t = LoDTensor()
    t.set(arr, place)
    t.set_recursive_sequence_lengths(recursive_seq_lens)
    if not t.has_valid_recursive_sequence_lengths():
        raise ValueError(
            f"invalid recursive_seq_lens {recursive_seq_lens} for data "
            f"with leading dim {arr.shape[0]}")
    return t


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place,
                                low, high):
    """Random-int LoDTensor whose leading dim is the sum of the
    innermost lengths (reference lod_tensor.py:114)."""
    n = sum(recursive_seq_lens[-1])
    shape = [n] + list(base_shape)
    data = np.random.randint(low, high + 1, size=shape).astype("int64")
    return create_lod_tensor(data, recursive_seq_lens, place)
