"""Full-sequence RNN ops and recurrence-adjacent convolutions.

Capability parity with the reference's recurrent op family: lstm/lstmp
(/root/reference/paddle/fluid/operators/lstm_op.cc, lstmp_op.cc), gru /
gru_unit (gru_op.cc, gru_unit_op.cc), lstm_unit (lstm_unit_op.cc), row_conv
(row_conv_op.cc), conv_shift (conv_shift_op.cc), im2sequence
(im2sequence_op.cc). The reference walks LoD segments with hand-written
CPU/CUDA kernels (math/detail/lstm_kernel.h); here each op is a masked-dense
`lax.scan` over the time dim — one fused gate matmul per step on the MXU,
padding steps carry the previous state through unchanged so arbitrary
per-row lengths work under a static [B, T, ...] shape.

Gate packing follows this framework's fused cells (nn_ops.py
lstm_cell_fused / gru_cell_fused): LSTM gates (i, f, c_hat, o), GRU gates
(u, r) + candidate. The reference's packed weight layout differs
byte-for-byte (it predates these conventions); parity is semantic, verified
against numpy references in tests/test_ops_rnn.py.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import register_op
from .common import x_of

_ACTS = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
}


def _act(attrs, key, default):
    return _ACTS[attrs.get(key, default)]


def _lengths(ins, B, T):
    ln = x_of(ins, "Length")
    if ln is None:
        return jnp.full((B,), T, jnp.int32)
    return jnp.reshape(ln, (-1,)).astype(jnp.int32)


def _maybe_reverse(x, lengths, flag):
    """Reverse each row's valid prefix (padding stays in place)."""
    if not flag:
        return x
    t = jnp.arange(x.shape[1], dtype=jnp.int32)[None, :]
    idx = jnp.where(t < lengths[:, None], lengths[:, None] - 1 - t, t)
    idx = idx.reshape(idx.shape + (1,) * (x.ndim - 2))
    return jnp.take_along_axis(x, idx, axis=1)


@register_op("lstm", infer_shape=False)
def lstm(ctx, ins, attrs):
    """Full-sequence LSTM. Input [B, T, 4H] is the pre-projected x@Wx (the
    reference's contract too — lstm_op.cc Input); Weight [H, 4H] recurrent;
    Bias [1, 4H], or [1, 7H] with use_peepholes (extra W_ic, W_fc, W_oc
    diagonals); optional H0/C0 [B, H]; optional Length [B]. Outputs
    Hidden/Cell [B, T, H]."""
    x = x_of(ins, "Input")
    w = x_of(ins, "Weight")
    bias = x_of(ins, "Bias")
    B, T = x.shape[0], x.shape[1]
    H = w.shape[0]
    use_peep = bool(attrs.get("use_peepholes", False))
    is_rev = bool(attrs.get("is_reverse", False))
    act_g = _act(attrs, "gate_activation", "sigmoid")
    act_c = _act(attrs, "cell_activation", "tanh")
    act_h = _act(attrs, "candidate_activation", "tanh")
    lengths = _lengths(ins, B, T)

    b_gate = bias[:, :4 * H] if bias is not None else 0.0
    if use_peep:
        w_ic = bias[:, 4 * H:5 * H]
        w_fc = bias[:, 5 * H:6 * H]
        w_oc = bias[:, 6 * H:7 * H]
    h0 = x_of(ins, "H0")
    c0 = x_of(ins, "C0")
    h = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)
    c = c0 if c0 is not None else jnp.zeros((B, H), x.dtype)
    xs = _maybe_reverse(x, lengths, is_rev)

    def step(carry, inp):
        h, c = carry
        xt, t = inp
        gates = xt + h @ w + b_gate
        gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
        if use_peep:
            gi = gi + c * w_ic
            gf = gf + c * w_fc
        i = act_g(gi)
        f = act_g(gf)
        c_new = f * c + i * act_h(gc)
        o = act_g(go + c_new * w_oc) if use_peep else act_g(go)
        h_new = o * act_c(c_new)
        live = (t < lengths)[:, None]
        h_new = jnp.where(live, h_new, h)
        c_new = jnp.where(live, c_new, c)
        return (h_new, c_new), (jnp.where(live, h_new, 0),
                                jnp.where(live, c_new, 0))

    ts = jnp.arange(T, dtype=jnp.int32)
    (_, _), (hs, cs) = jax.lax.scan(
        step, (h, c), (jnp.swapaxes(xs, 0, 1), ts))
    hidden = jnp.swapaxes(hs, 0, 1)
    cell = jnp.swapaxes(cs, 0, 1)
    hidden = _maybe_reverse(hidden, lengths, is_rev)
    cell = _maybe_reverse(cell, lengths, is_rev)
    return {"Hidden": hidden, "Cell": cell}


@register_op("lstmp", infer_shape=False)
def lstmp(ctx, ins, attrs):
    """LSTM with a recurrent projection (reference lstmp_op.cc): the carried
    state is r = proj_act(h @ ProjWeight) [B, P]; Weight is [P, 4H].
    Bias [1, 4H], or [1, 7H] with use_peepholes (W_ic, W_fc, W_oc diagonals
    over the cell state, as in the lstm op). Outputs Projection [B, T, P]
    and Cell [B, T, H]."""
    x = x_of(ins, "Input")
    w = x_of(ins, "Weight")            # [P, 4H]
    w_proj = x_of(ins, "ProjWeight")   # [H, P]
    bias = x_of(ins, "Bias")
    B, T = x.shape[0], x.shape[1]
    H, P = w_proj.shape
    use_peep = bool(attrs.get("use_peepholes", False))
    is_rev = bool(attrs.get("is_reverse", False))
    act_g = _act(attrs, "gate_activation", "sigmoid")
    act_c = _act(attrs, "cell_activation", "tanh")
    act_h = _act(attrs, "candidate_activation", "tanh")
    act_p = _act(attrs, "proj_activation", "identity")
    lengths = _lengths(ins, B, T)
    b_gate = bias[:, :4 * H] if bias is not None else 0.0
    if use_peep:
        w_ic = bias[:, 4 * H:5 * H]
        w_fc = bias[:, 5 * H:6 * H]
        w_oc = bias[:, 6 * H:7 * H]

    h0 = x_of(ins, "H0")     # initial PROJECTED state [B, P]
    c0 = x_of(ins, "C0")
    r = h0 if h0 is not None else jnp.zeros((B, P), x.dtype)
    c = c0 if c0 is not None else jnp.zeros((B, H), x.dtype)
    xs = _maybe_reverse(x, lengths, is_rev)

    def step(carry, inp):
        r, c = carry
        xt, t = inp
        gates = xt + r @ w + b_gate
        gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
        if use_peep:
            gi = gi + c * w_ic
            gf = gf + c * w_fc
        c_new = act_g(gf) * c + act_g(gi) * act_h(gc)
        o = act_g(go + c_new * w_oc) if use_peep else act_g(go)
        h_new = o * act_c(c_new)
        r_new = act_p(h_new @ w_proj)
        live = (t < lengths)[:, None]
        r_new = jnp.where(live, r_new, r)
        c_new = jnp.where(live, c_new, c)
        return (r_new, c_new), (jnp.where(live, r_new, 0),
                                jnp.where(live, c_new, 0))

    ts = jnp.arange(T, dtype=jnp.int32)
    (_, _), (rs, cs) = jax.lax.scan(
        step, (r, c), (jnp.swapaxes(xs, 0, 1), ts))
    proj = _maybe_reverse(jnp.swapaxes(rs, 0, 1), lengths, is_rev)
    cell = _maybe_reverse(jnp.swapaxes(cs, 0, 1), lengths, is_rev)
    return {"Projection": proj, "Cell": cell}


@register_op("lstm_unit")
def lstm_unit(ctx, ins, attrs):
    """One LSTM step on pre-computed gate pre-activations (reference
    lstm_unit_op.cc): X [B, 4H] split (i, f, c_hat, o), C_prev [B, H]."""
    x = x_of(ins)
    c_prev = x_of(ins, "C_prev")
    fb = float(attrs.get("forget_bias", 0.0))
    i, f, c_hat, o = jnp.split(x, 4, axis=-1)
    c = jax.nn.sigmoid(f + fb) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(c_hat)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return {"C": c, "H": h}


def _gru_step(xt, h, w_g, w_c, bias, act_g, act_c, origin_mode, H):
    xg = xt[:, :2 * H] + h @ w_g
    xc_in = xt[:, 2 * H:]
    if bias is not None:
        xg = xg + bias[:, :2 * H]
    u, r = jnp.split(act_g(xg), 2, axis=-1)
    xc = xc_in + (r * h) @ w_c
    if bias is not None:
        xc = xc + bias[:, 2 * H:]
    cand = act_c(xc)
    if origin_mode:
        return u * h + (1.0 - u) * cand
    return u * cand + (1.0 - u) * h


@register_op("gru", infer_shape=False)
def gru(ctx, ins, attrs):
    """Full-sequence GRU (reference gru_op.cc). Input [B, T, 3H] is the
    pre-projected x@Wx packed (u, r, c_hat); Weight [H, 3H] recurrent
    (first 2H the u/r gates, last H the candidate); Bias [1, 3H]; optional
    H0 [B, H], Length [B]. Output Hidden [B, T, H]."""
    x = x_of(ins, "Input")
    w = x_of(ins, "Weight")
    bias = x_of(ins, "Bias")
    B, T = x.shape[0], x.shape[1]
    H = w.shape[0]
    is_rev = bool(attrs.get("is_reverse", False))
    origin = bool(attrs.get("origin_mode", False))
    act_g = _act(attrs, "gate_activation", "sigmoid")
    act_c = _act(attrs, "activation", "tanh")
    lengths = _lengths(ins, B, T)
    w_g, w_c = w[:, :2 * H], w[:, 2 * H:]
    h0 = x_of(ins, "H0")
    h = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)
    xs = _maybe_reverse(x, lengths, is_rev)

    def step(h, inp):
        xt, t = inp
        h_new = _gru_step(xt, h, w_g, w_c, bias, act_g, act_c, origin, H)
        live = (t < lengths)[:, None]
        h_new = jnp.where(live, h_new, h)
        return h_new, jnp.where(live, h_new, 0)

    ts = jnp.arange(T, dtype=jnp.int32)
    _, hs = jax.lax.scan(step, h, (jnp.swapaxes(xs, 0, 1), ts))
    hidden = _maybe_reverse(jnp.swapaxes(hs, 0, 1), lengths, is_rev)
    return {"Hidden": hidden}


@register_op("gru_unit")
def gru_unit(ctx, ins, attrs):
    """One GRU step (reference gru_unit_op.cc): Input [B, 3H] pre-projected,
    HiddenPrev [B, H], Weight [H, 3H], optional Bias [1, 3H]."""
    x = x_of(ins, "Input")
    h = x_of(ins, "HiddenPrev")
    w = x_of(ins, "Weight")
    bias = x_of(ins, "Bias")
    H = h.shape[-1]
    act_g = _act(attrs, "gate_activation", "sigmoid")
    act_c = _act(attrs, "activation", "tanh")
    origin = bool(attrs.get("origin_mode", False))
    out = _gru_step(x, h, w[:, :2 * H], w[:, 2 * H:], bias, act_g, act_c,
                    origin, H)
    return {"Hidden": out}


@register_op("row_conv")
def row_conv(ctx, ins, attrs):
    """Lookahead row convolution (reference row_conv_op.cc, from the DS2
    paper): out[b, t] = sum_k x[b, t+k] * filter[k], k in [0, future_ctx);
    steps beyond each row's length contribute zero."""
    x = x_of(ins)                      # [B, T, D]
    filt = x_of(ins, "Filter")         # [K, D]
    ln = x_of(ins, "Length")
    B, T, D = x.shape
    K = filt.shape[0]
    lengths = (jnp.reshape(ln, (-1,)).astype(jnp.int32)
               if ln is not None else jnp.full((B,), T, jnp.int32))
    t = jnp.arange(T, dtype=jnp.int32)
    out = jnp.zeros_like(x)
    for k in range(K):
        src = t + k
        ok = (src[None, :] < lengths[:, None])[..., None]
        g = jnp.take(x, jnp.clip(src, 0, T - 1), axis=1)
        out = out + jnp.where(ok, g, 0) * filt[k]
    mask = (t[None, :] < lengths[:, None])[..., None]
    return {"Out": jnp.where(mask, out, 0)}


@register_op("conv_shift")
def conv_shift(ctx, ins, attrs):
    """Circular correlation (reference conv_shift_op.cc, NTM-style):
    out[b, i] = sum_j x[b, (i + j - M//2) mod N] * y[b, j], M odd."""
    x = x_of(ins)                      # [B, N]
    y = x_of(ins, "Y")                 # [B, M]
    N, M = x.shape[1], y.shape[1]
    i = jnp.arange(N, dtype=jnp.int32)[:, None]
    j = jnp.arange(M, dtype=jnp.int32)[None, :]
    idx = (i + j - M // 2) % N         # [N, M]
    g = x[:, idx]                      # [B, N, M]
    return {"Out": jnp.einsum("bnm,bm->bn", g, y)}


@register_op("im2sequence", infer_shape=False)
def im2sequence(ctx, ins, attrs):
    """Image -> patch sequence (reference im2sequence_op.cc): x [B,C,H,W]
    with kernels/strides/paddings unfolds to [B, oh*ow, C*kh*kw]; every row
    has length oh*ow. Patch features are ordered (C, kh, kw)."""
    x = x_of(ins)
    kh, kw = attrs["kernels"]
    sh, sw = attrs.get("strides", [1, 1])
    pads = attrs.get("paddings", [0, 0, 0, 0])
    pu, pl, pd, pr = (pads if len(pads) == 4 else
                      [pads[0], pads[1], pads[0], pads[1]])
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), [(pu, pd), (pl, pr)])  # [B, C*kh*kw, oh, ow]
    B, F = patches.shape[0], patches.shape[1]
    oh, ow = patches.shape[2], patches.shape[3]
    out = patches.reshape(B, F, oh * ow).transpose(0, 2, 1)
    return {"Out": out,
            "OutLength": jnp.full((B,), oh * ow, jnp.int32)}
