"""Fake-quantization ops (QAT/PTQ support).

Capability parity with the reference's quantization operators
(/root/reference/paddle/fluid/operators/fake_quantize_op.cc — abs_max,
range_abs_max, moving_average_abs_max, channel_wise variants;
fake_dequantize_op.cc). Forward simulates int-k rounding in float
("fake" quant); backward is the straight-through estimator (identity on
X) exactly like the reference's grad kernels, so QAT trains through the
rounding. XLA folds the scale math into neighboring ops.
"""
import jax
import jax.numpy as jnp

from ..framework.registry import register_op, register_grad_lower
from .common import x_of


def _qmax(bits):
    return float((1 << (int(bits) - 1)) - 1)


def _quant(x, scale, bits):
    q = _qmax(bits)
    s = jnp.maximum(scale, 1e-9)
    return jnp.round(jnp.clip(x / s, -1.0, 1.0) * q) * s / q


def _ste_grad(ins, attrs):
    g = x_of(ins, "Out@GRAD")
    return {"X@GRAD": [g]}


@register_op("fake_quantize_abs_max", grad=None, infer_shape=False)
def fake_quantize_abs_max(ctx, ins, attrs):
    """attrs['frozen_scale'] (set by post-training quantization after
    calibration) pins the scale; otherwise it is the dynamic |x|max."""
    x = x_of(ins)
    frozen = attrs.get("frozen_scale")
    scale = (jnp.asarray(float(frozen), x.dtype) if frozen is not None
             else jnp.max(jnp.abs(x)))
    return {"Out": _quant(x, scale, attrs.get("bit_length", 8)),
            "OutScale": scale.reshape(1)}


register_grad_lower("fake_quantize_abs_max")(
    lambda ctx, ins, attrs: _ste_grad(ins, attrs))


@register_op("fake_channel_wise_quantize_abs_max", grad=None,
             infer_shape=False)
def fake_channel_wise_quantize_abs_max(ctx, ins, attrs):
    """Per-output-channel scales (dim 0, conv/fc weight layout)."""
    x = x_of(ins)
    bits = attrs.get("bit_length", 8)
    scale = jnp.max(jnp.abs(x.reshape(x.shape[0], -1)), axis=1)
    s = scale.reshape((-1,) + (1,) * (x.ndim - 1))
    q = _qmax(bits)
    out = jnp.round(jnp.clip(x / jnp.maximum(s, 1e-9), -1, 1) * q) * \
        jnp.maximum(s, 1e-9) / q
    return {"Out": out, "OutScale": scale}


register_grad_lower("fake_channel_wise_quantize_abs_max")(
    lambda ctx, ins, attrs: _ste_grad(ins, attrs))


@register_op("fake_quantize_moving_average_abs_max", grad=None,
             infer_shape=False)
def fake_quantize_moving_average_abs_max(ctx, ins, attrs):
    """Activation quant with a moving-average scale (reference
    fake_quantize_op.cc FakeQuantizeMovingAverageAbsMaxKernel): state
    counts decayed steps, accum holds the decayed |x|max sum."""
    x = x_of(ins)
    accum = x_of(ins, "InAccum")
    state = x_of(ins, "InState")
    rho = float(attrs.get("moving_rate", 0.9))
    cur = jnp.max(jnp.abs(x))
    if bool(attrs.get("is_test", False)):
        scale = x_of(ins, "InScale").reshape(())
        return {"Out": _quant(x, scale, attrs.get("bit_length", 8))}
    new_state = rho * state + 1.0
    new_accum = rho * accum + cur
    scale = (new_accum / new_state).reshape(())
    return {"Out": _quant(x, scale, attrs.get("bit_length", 8)),
            "OutScale": scale.reshape(1),
            "StateOut": new_state, "AccumOut": new_accum}


register_grad_lower("fake_quantize_moving_average_abs_max")(
    lambda ctx, ins, attrs: _ste_grad(ins, attrs))


@register_op("fake_quantize_range_abs_max", grad=None, infer_shape=False)
def fake_quantize_range_abs_max(ctx, ins, attrs):
    """Sliding-window max scale (reference FakeQuantizeRangeAbsMax):
    scales ring-buffer keeps the last `window_size` batch maxima."""
    x = x_of(ins)
    iter_ = x_of(ins, "Iter")
    scales = x_of(ins, "InScales")
    window = scales.shape[0]
    cur = jnp.max(jnp.abs(x))
    if bool(attrs.get("is_test", False)):
        scale = x_of(ins, "InScale").reshape(())
        return {"Out": _quant(x, scale, attrs.get("bit_length", 8))}
    idx = (iter_.reshape(()).astype(jnp.int32)) % window
    new_scales = scales.at[idx].set(cur)
    scale = jnp.max(new_scales)
    return {"Out": _quant(x, scale, attrs.get("bit_length", 8)),
            "OutScale": scale.reshape(1),
            "OutScales": new_scales,
            "IterOut": iter_ + 1}


register_grad_lower("fake_quantize_range_abs_max")(
    lambda ctx, ins, attrs: _ste_grad(ins, attrs))


@register_op("fake_quantize_dequantize_abs_max", grad=None,
             infer_shape=False)
def fake_quantize_dequantize_abs_max(ctx, ins, attrs):
    x = x_of(ins)
    scale = jnp.max(jnp.abs(x))
    return {"Out": _quant(x, scale, attrs.get("bit_length", 8)),
            "OutScale": scale.reshape(1)}


register_grad_lower("fake_quantize_dequantize_abs_max")(
    lambda ctx, ins, attrs: _ste_grad(ins, attrs))


@register_op("fake_quantize_dequantize_moving_average_abs_max", grad=None,
             infer_shape=False)
def fake_quantize_dequantize_moving_average_abs_max(ctx, ins, attrs):
    """Quant-dequant variant of the moving-average scale op (reference
    fake_quantize_op.cc FakeQuantizeDequantizeMovingAverageAbsMax) —
    identical float simulation + STE grad."""
    return fake_quantize_moving_average_abs_max(ctx, ins, attrs)


register_grad_lower("fake_quantize_dequantize_moving_average_abs_max")(
    lambda ctx, ins, attrs: _ste_grad(ins, attrs))


@register_op("moving_average_abs_max_scale", grad=None, infer_shape=False)
def moving_average_abs_max_scale(ctx, ins, attrs):
    """Scale OBSERVER only (reference fake_quantize_op.h
    MovingAverageAbsMaxScaleKernel): Out = X unchanged; the moving
    |x|max statistics update exactly like the quantizing variant."""
    x = x_of(ins)
    if bool(attrs.get("is_test", False)):
        return {"Out": x}
    accum = x_of(ins, "InAccum")
    state = x_of(ins, "InState")
    rho = float(attrs.get("moving_rate", 0.9))
    cur = jnp.max(jnp.abs(x))
    new_state = rho * state + 1.0
    new_accum = rho * accum + cur
    return {"Out": x, "OutScale": (new_accum / new_state).reshape(1),
            "StateOut": new_state, "AccumOut": new_accum}


register_grad_lower("moving_average_abs_max_scale")(
    lambda ctx, ins, attrs: _ste_grad(ins, attrs))


@register_op("fake_channel_wise_dequantize_max_abs", grad=None,
             infer_shape=False)
def fake_channel_wise_dequantize_max_abs(ctx, ins, attrs):
    """reference fake_dequantize_op.h
    FakeChannelWiseDequantizeMaxAbsKernel: one scale tensor -> per-dim-0
    channel scales; two -> per-dim-1 channel scales times a scalar
    activation scale; max_range multiplies (2^(bits_i - 1) - 1)."""
    x = x_of(ins)
    scales = ins["Scales"]
    bits = [int(b) for b in attrs.get("quant_bits", [])]
    bits += [8] * (len(scales) - len(bits))   # reference default: 8 per scale
    max_range = 1.0
    for i in range(len(scales)):
        max_range *= float((1 << (bits[i] - 1)) - 1)
    if len(scales) == 1:
        s = jnp.reshape(scales[0], (-1,))
        s = s.reshape((x.shape[0],) + (1,) * (x.ndim - 1))
        out = x * s / max_range
    else:
        s0 = jnp.reshape(scales[0], (-1,))
        s1 = jnp.reshape(scales[1], ())
        s = s0.reshape((1, x.shape[1]) + (1,) * (x.ndim - 2))
        out = x * (s * s1) / max_range
    return {"Out": out}


@register_grad_lower("fake_channel_wise_dequantize_max_abs")
def fake_channel_wise_dequantize_max_abs_grad(ctx, ins, attrs):
    # linear in X, like fake_dequantize_max_abs
    g = x_of(ins, "Out@GRAD")
    x = x_of(ins)
    scales = ins["Scales"]
    fattrs = attrs["__fwd_op__"]["attrs"]
    bits = [int(b) for b in fattrs.get("quant_bits", [])]
    bits += [8] * (len(scales) - len(bits))
    max_range = 1.0
    for i in range(len(scales)):
        max_range *= float((1 << (bits[i] - 1)) - 1)
    if len(scales) == 1:
        s = jnp.reshape(scales[0], (-1,)).reshape(
            (x.shape[0],) + (1,) * (x.ndim - 1))
    else:
        s = jnp.reshape(scales[0], (-1,)).reshape(
            (1, x.shape[1]) + (1,) * (x.ndim - 2)) * \
            jnp.reshape(scales[1], ())
    return {"X@GRAD": [g * s / max_range]}


@register_op("fake_dequantize_max_abs", grad=None, infer_shape=False)
def fake_dequantize_max_abs(ctx, ins, attrs):
    """Out = X * Scale / max_range (reference fake_dequantize_op.cc).
    This op is LINEAR in X (no rounding), so its grad is the scaled
    upstream grad — not the straight-through identity the fake_quantize
    ops use."""
    x = x_of(ins)
    scale = x_of(ins, "Scale").reshape(())
    max_range = float(attrs.get("max_range", 127.0))
    return {"Out": x * scale / max_range}


@register_grad_lower("fake_dequantize_max_abs")
def fake_dequantize_max_abs_grad(ctx, ins, attrs):
    g = x_of(ins, "Out@GRAD")
    scale = x_of(ins, "Scale").reshape(())
    max_range = float(attrs["__fwd_op__"]["attrs"].get("max_range", 127.0))
    return {"X@GRAD": [g * scale / max_range]}
