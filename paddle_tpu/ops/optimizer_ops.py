"""Optimizer update ops.

TPU-native lowerings for the reference's optimizer op kernels
(/root/reference/paddle/fluid/operators/optimizers/ — sgd_op.cc,
momentum_op.h, adam_op.h, adamw, adagrad_op.cc, rmsprop_op.cc, lamb_op.h,
lars_momentum_op.cc, ftrl_op.h, adadelta_op.cc, adamax_op.cc, dpsgd).
The reference updates params in place on device; here each op returns the new
param/accumulator values, which rebind the same var names in the functional
env and donate back to the scope (XLA reuses the buffers — same memory
behavior, no aliasing hazards).
"""
import jax
import jax.numpy as jnp

from ..framework.registry import register_op
from .common import x_of


def _p(ins):
    return x_of(ins, "Param"), x_of(ins, "Grad"), x_of(ins, "LearningRate")


@register_op("sgd", grad=False)
def sgd(ctx, ins, attrs):
    p, g, lr = _p(ins)
    from ..framework.selected_rows import is_selected_rows
    if is_selected_rows(g):
        # sparse row update (reference sgd_op.h SelectedRows kernel):
        # only touched embedding rows move; duplicates coalesce in the
        # scatter-add
        return {"ParamOut": p.at[g.rows].add(
            -lr.astype(p.dtype) * g.values.astype(p.dtype))}
    return {"ParamOut": (p - lr.astype(p.dtype) * g.astype(p.dtype))}


@register_op("momentum", grad=False)
def momentum(ctx, ins, attrs):
    p, g, lr = _p(ins)
    v = x_of(ins, "Velocity")
    mu = attrs.get("mu", 0.9)
    lr = lr.astype(p.dtype)
    from ..framework.selected_rows import is_selected_rows, to_dense
    if is_selected_rows(g):
        # momentum needs the dense velocity decay anyway (v = mu*v + g):
        # densify the sparse grad (reference momentum SelectedRows kernel
        # does the same math)
        g = to_dense(g, p.shape, p.dtype)
    g = g.astype(p.dtype)
    v_new = mu * v + g
    if attrs.get("use_nesterov", False):
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    return {"ParamOut": p_new, "VelocityOut": v_new}


@register_op("lars_momentum", grad=False)
def lars_momentum(ctx, ins, attrs):
    """LARS (reference optimizers/lars_momentum_op.cc): layer-adaptive lr."""
    p, g, lr = _p(ins)
    v = x_of(ins, "Velocity")
    mu = attrs.get("mu", 0.9)
    coeff = attrs.get("lars_coeff", 0.001)
    decay = attrs.get("lars_weight_decay", 0.0005)
    eps = attrs.get("epsilon", 1e-9)
    pn = jnp.sqrt(jnp.sum(jnp.square(p)))
    gn = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = jnp.where(
        (pn > 0) & (gn > 0),
        coeff * pn / (gn + decay * pn + eps), 1.0)
    lr_t = lr.astype(p.dtype) * local_lr
    v_new = mu * v + lr_t * (g + decay * p)
    return {"ParamOut": p - v_new, "VelocityOut": v_new}


@register_op("adam", grad=False)
def adam(ctx, ins, attrs):
    p, g, lr = _p(ins)
    m1 = x_of(ins, "Moment1")
    m2 = x_of(ins, "Moment2")
    b1p = x_of(ins, "Beta1Pow")
    b2p = x_of(ins, "Beta2Pow")
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    from ..framework.selected_rows import is_selected_rows
    if is_selected_rows(g) and attrs.get("lazy_mode", False):
        # lazy sparse adam (reference adam_op.h lazy_mode): moments and
        # params update ONLY on touched rows. Duplicate ids must merge
        # FIRST (reference MergeAdd) — a per-occurrence read-modify-write
        # would double-apply against stale moments.
        from ..framework.selected_rows import coalesce
        g = coalesce(g)
        rows = g.rows
        gv = g.values.astype(p.dtype)
        m1r = b1 * m1[rows] + (1 - b1) * gv
        m2r = b2 * m2[rows] + (1 - b2) * jnp.square(gv)
        # beta-pow accumulators may be param-shaped; they are uniform, so
        # a scalar view broadcasts correctly against the row slice
        b1p_s = jnp.reshape(b1p, (-1,))[0].astype(p.dtype)
        b2p_s = jnp.reshape(b2p, (-1,))[0].astype(p.dtype)
        lr_t = jnp.reshape(lr, (-1,))[0].astype(p.dtype) * \
            jnp.sqrt(1 - b2p_s) / (1 - b1p_s)
        upd = lr_t * m1r / (jnp.sqrt(m2r) + eps)
        return {"ParamOut": p.at[rows].add(-upd, mode="drop"),
                "Moment1Out": m1.at[rows].set(m1r, mode="drop"),
                "Moment2Out": m2.at[rows].set(m2r, mode="drop"),
                "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2}
    if is_selected_rows(g):
        from ..framework.selected_rows import to_dense
        g = to_dense(g, p.shape, p.dtype)
    g = g.astype(p.dtype)
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * jnp.square(g)
    lr_t = lr.astype(p.dtype) * jnp.sqrt(1 - b2p.astype(p.dtype)) / \
        (1 - b1p.astype(p.dtype))
    p_new = p - lr_t * m1n / (jnp.sqrt(m2n) + eps)
    return {"ParamOut": p_new, "Moment1Out": m1n, "Moment2Out": m2n,
            "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2}


@register_op("adamw", grad=False)
def adamw(ctx, ins, attrs):
    p = x_of(ins, "Param")
    lr = x_of(ins, "LearningRate")
    coeff = attrs.get("coeff", 0.01)
    with_decay = attrs.get("with_decay", True)
    outs = adam(ctx, ins, attrs)
    if with_decay:
        outs["ParamOut"] = outs["ParamOut"] - lr.astype(p.dtype) * coeff * p
    return outs


@register_op("adagrad", grad=False)
def adagrad(ctx, ins, attrs):
    p, g, lr = _p(ins)
    mom = x_of(ins, "Moment")
    eps = attrs.get("epsilon", 1e-6)
    g = g.astype(p.dtype)
    mom_new = mom + jnp.square(g)
    p_new = p - lr.astype(p.dtype) * g / (jnp.sqrt(mom_new) + eps)
    return {"ParamOut": p_new, "MomentOut": mom_new}


@register_op("decayed_adagrad", grad=False)
def decayed_adagrad(ctx, ins, attrs):
    p, g, lr = _p(ins)
    mom = x_of(ins, "Moment")
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    g = g.astype(p.dtype)
    mom_new = decay * mom + (1 - decay) * jnp.square(g)
    p_new = p - lr.astype(p.dtype) * g / (jnp.sqrt(mom_new) + eps)
    return {"ParamOut": p_new, "MomentOut": mom_new}


@register_op("adadelta", grad=False)
def adadelta(ctx, ins, attrs):
    p = x_of(ins, "Param")
    g = x_of(ins, "Grad").astype(p.dtype)
    avg_sq_g = x_of(ins, "AvgSquaredGrad")
    avg_sq_u = x_of(ins, "AvgSquaredUpdate")
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    asg = rho * avg_sq_g + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((avg_sq_u + eps) / (asg + eps)) * g
    asu = rho * avg_sq_u + (1 - rho) * jnp.square(update)
    return {"ParamOut": p + update, "AvgSquaredGradOut": asg,
            "AvgSquaredUpdateOut": asu}


@register_op("adamax", grad=False)
def adamax(ctx, ins, attrs):
    p, g, lr = _p(ins)
    m = x_of(ins, "Moment")
    inf_norm = x_of(ins, "InfNorm")
    b1p = x_of(ins, "Beta1Pow")
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    g = g.astype(p.dtype)
    m_new = b1 * m + (1 - b1) * g
    inf_new = jnp.maximum(b2 * inf_norm, jnp.abs(g))
    lr_t = lr.astype(p.dtype) / (1 - b1p.astype(p.dtype))
    p_new = p - lr_t * m_new / (inf_new + eps)
    return {"ParamOut": p_new, "MomentOut": m_new, "InfNormOut": inf_new}


@register_op("rmsprop", grad=False)
def rmsprop(ctx, ins, attrs):
    p, g, lr = _p(ins)
    ms = x_of(ins, "MeanSquare")
    mg = x_of(ins, "MeanGrad")
    mom = x_of(ins, "Moment")
    rho = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    mu = attrs.get("momentum", 0.0)
    centered = attrs.get("centered", False)
    g = g.astype(p.dtype)
    lr = lr.astype(p.dtype)
    ms_new = rho * ms + (1 - rho) * jnp.square(g)
    if centered:
        mg_new = rho * mg + (1 - rho) * g
        denom = ms_new - jnp.square(mg_new) + eps
    else:
        mg_new = mg
        denom = ms_new + eps
    mom_new = mu * mom + lr * g * jax.lax.rsqrt(denom)
    return {"ParamOut": p - mom_new, "MeanSquareOut": ms_new,
            "MeanGradOut": mg_new, "MomentOut": mom_new}


@register_op("ftrl", grad=False)
def ftrl(ctx, ins, attrs):
    p, g, lr = _p(ins)
    sq = x_of(ins, "SquaredAccumulator")
    lin = x_of(ins, "LinearAccumulator")
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    power = attrs.get("lr_power", -0.5)
    g = g.astype(p.dtype)
    lr = lr.astype(p.dtype)
    new_sq = sq + jnp.square(g)
    sigma = (jnp.power(new_sq, -power) - jnp.power(sq, -power)) / lr
    new_lin = lin + g - sigma * p
    x = l1 * jnp.sign(new_lin) - new_lin
    y = jnp.power(new_sq, -power) / lr + 2 * l2
    p_new = jnp.where(jnp.abs(new_lin) > l1, x / y, 0.0)
    return {"ParamOut": p_new, "SquaredAccumOut": new_sq,
            "LinearAccumOut": new_lin}


@register_op("lamb", grad=False)
def lamb(ctx, ins, attrs):
    """LAMB (reference optimizers/lamb_op.h): layer-adaptive Adam for large
    batches."""
    p, g, lr = _p(ins)
    m1 = x_of(ins, "Moment1")
    m2 = x_of(ins, "Moment2")
    b1p = x_of(ins, "Beta1Pow")
    b2p = x_of(ins, "Beta2Pow")
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    g = g.astype(p.dtype)
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * jnp.square(g)
    m1h = m1n / (1 - b1p.astype(p.dtype))
    m2h = m2n / (1 - b2p.astype(p.dtype))
    r = m1h / (jnp.sqrt(m2h) + eps) + wd * p
    pn = jnp.sqrt(jnp.sum(jnp.square(p)))
    rn = jnp.sqrt(jnp.sum(jnp.square(r)))
    trust = jnp.where((pn > 0) & (rn > 0), pn / rn, 1.0)
    p_new = p - lr.astype(p.dtype) * trust * r
    return {"ParamOut": p_new, "Moment1Out": m1n, "Moment2Out": m2n,
            "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2}


@register_op("dpsgd", grad=False, needs_rng=True)
def dpsgd(ctx, ins, attrs):
    """Differentially-private SGD (reference optimizers/dpsgd_op.h):
    clip per-batch grad + gaussian noise."""
    p, g, lr = _p(ins)
    clip = attrs.get("clip", 10.0)
    sigma = attrs.get("sigma", 1.0)
    g = g.astype(p.dtype)
    gn = jnp.sqrt(jnp.sum(jnp.square(g)))
    scale = jnp.minimum(1.0, clip / jnp.maximum(gn, 1e-12))
    key = ctx.op_key(attrs)
    noise = jax.random.normal(key, g.shape, g.dtype) * sigma * clip
    return {"ParamOut": p - lr.astype(p.dtype) * (g * scale + noise)}


# ---------------------------------------------------------------------------
# Fused multi-tensor optimizer kernels (framework/passes.py
# FuseOptimizerPass; reference ir/fuse_optimizer_ops_pass + NVIDIA Apex
# multi_tensor_apply). Each op receives N params (+ grads/accumulators)
# in parallel slot lists and applies ONE flattened-concat elementwise
# update (framework/lowering.py flatten_concat/split_unflatten). All the
# math is elementwise, so every element undergoes exactly the arithmetic
# of its per-param op — bitwise-identical results from 1 kernel launch
# instead of N. Per-param scalars (adam's bias-corrected step size) are
# broadcast per segment, never shared across params.
# ---------------------------------------------------------------------------

def _scalar(x, dtype):
    """A () scalar view of a ()- or (1,)-shaped hyperparameter tensor
    in the bucket's param dtype (same value the per-param op broadcasts)."""
    return jnp.reshape(x, ()).astype(dtype)


def _flat_pg(ctx, ins):
    """(flat_params, flat_grads_cast, shapes, dtype) of the bucket."""
    from ..framework.lowering import flatten_concat
    mesh = getattr(ctx, "mesh", None)
    ps = ins["Param"]
    dtype = ps[0].dtype
    flat_p, shapes = flatten_concat(ps, mesh=mesh)
    flat_g, _ = flatten_concat([g.astype(dtype) for g in ins["Grad"]],
                               mesh=mesh)
    return flat_p, flat_g, shapes, dtype


@register_op("fused_sgd", grad=False, infer_shape=False)
def fused_sgd(ctx, ins, attrs):
    from ..framework.lowering import split_unflatten
    flat_p, flat_g, shapes, dtype = _flat_pg(ctx, ins)
    lr = _scalar(ins["LearningRate"][0], dtype)
    return {"ParamOut": split_unflatten(flat_p - lr * flat_g, shapes)}


@register_op("fused_momentum", grad=False, infer_shape=False)
def fused_momentum(ctx, ins, attrs):
    from ..framework.lowering import flatten_concat, split_unflatten
    mesh = getattr(ctx, "mesh", None)
    flat_p, flat_g, shapes, dtype = _flat_pg(ctx, ins)
    flat_v, _ = flatten_concat(ins["Velocity"], mesh=mesh)
    mu = attrs.get("mu", 0.9)
    lr = _scalar(ins["LearningRate"][0], dtype)
    v_new = mu * flat_v + flat_g
    if attrs.get("use_nesterov", False):
        p_new = flat_p - (flat_g + mu * v_new) * lr
    else:
        p_new = flat_p - lr * v_new
    return {"ParamOut": split_unflatten(p_new, shapes),
            "VelocityOut": split_unflatten(v_new, shapes)}


def _fused_adam_core(ctx, ins, attrs):
    """Shared adam/adamw bucket math; returns
    (outs, flat_new_param, flat_old_param, shapes, dtype, lr_scalar).
    `outs` holds the moment/beta-pow outputs but NOT ParamOut — the
    caller splits its (possibly further-updated) flat param itself;
    adamw needs `flat_old_param` (pre-update values) and `lr_scalar`
    for the decoupled weight decay."""
    from ..framework.lowering import (broadcast_segments, flatten_concat,
                                      split_unflatten)
    mesh = getattr(ctx, "mesh", None)
    flat_p, flat_g, shapes, dtype = _flat_pg(ctx, ins)
    flat_m1, _ = flatten_concat(ins["Moment1"], mesh=mesh)
    flat_m2, _ = flatten_concat(ins["Moment2"], mesh=mesh)
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _scalar(ins["LearningRate"][0], dtype)
    m1n = b1 * flat_m1 + (1 - b1) * flat_g
    m2n = b2 * flat_m2 + (1 - b2) * jnp.square(flat_g)
    # the bias-corrected step size is PER-PARAM (each param carries its
    # own beta-pow accumulators). Beta-pows arrive either param-shaped
    # (elementwise: concat them like the moments) or ()/(1,)-scalar
    # (broadcast each scalar over its param's segment); the fusion pass
    # keys buckets so a bucket is homogeneous in this.
    if tuple(ins["Beta1Pow"][0].shape) == tuple(shapes[0]):
        flat_b1p, _ = flatten_concat(
            [b.astype(dtype) for b in ins["Beta1Pow"]], mesh=mesh)
        flat_b2p, _ = flatten_concat(
            [b.astype(dtype) for b in ins["Beta2Pow"]], mesh=mesh)
        lr_t = lr * jnp.sqrt(1 - flat_b2p) / (1 - flat_b1p)
    else:
        lr_t = broadcast_segments(
            [lr * jnp.sqrt(1 - _scalar(b2p, dtype))
             / (1 - _scalar(b1p, dtype))
             for b1p, b2p in zip(ins["Beta1Pow"], ins["Beta2Pow"])],
            shapes, dtype)
    p_new = flat_p - lr_t * m1n / (jnp.sqrt(m2n) + eps)
    outs = {"Moment1Out": split_unflatten(m1n, shapes),
            "Moment2Out": split_unflatten(m2n, shapes),
            "Beta1PowOut": [b1p * b1 for b1p in ins["Beta1Pow"]],
            "Beta2PowOut": [b2p * b2 for b2p in ins["Beta2Pow"]]}
    return outs, p_new, flat_p, shapes, dtype, lr


@register_op("fused_adam", grad=False, infer_shape=False)
def fused_adam(ctx, ins, attrs):
    from ..framework.lowering import split_unflatten
    outs, p_new, _, shapes, _, _ = _fused_adam_core(ctx, ins, attrs)
    outs["ParamOut"] = split_unflatten(p_new, shapes)
    return outs


@register_op("fused_adamw", grad=False, infer_shape=False)
def fused_adamw(ctx, ins, attrs):
    from ..framework.lowering import split_unflatten
    outs, p_new, flat_p, shapes, dtype, lr = _fused_adam_core(ctx, ins,
                                                              attrs)
    if attrs.get("with_decay", True):
        coeff = attrs.get("coeff", 0.01)
        p_new = p_new - lr * coeff * flat_p
    outs["ParamOut"] = split_unflatten(p_new, shapes)
    return outs


@register_op("dgc_sparsify", grad=False, infer_shape=False)
def dgc_sparsify(ctx, ins, attrs):
    """Deep Gradient Compression core (reference operators/dgc_op.cc +
    dgc_momentum_op): momentum-correct into the local buffer U; before
    rampup_begin_step the FULL corrected gradient is emitted (dense
    momentum warm-up, U acts as the velocity), after it only the
    top-(1-s) fraction of |U| is emitted (masked DENSE tensor — same
    numerics, XLA owns comm) and the residual stays in U."""
    u = x_of(ins, "U")
    g = x_of(ins, "Grad")
    step = x_of(ins, "Step")
    s = float(attrs.get("sparsity", 0.999))
    m = float(attrs.get("momentum", 0.9))
    rampup = float(attrs.get("rampup_begin_step", 0))
    u_new = m * u + g
    flat = jnp.abs(u_new).reshape(-1)
    k = max(int(flat.shape[0] * (1.0 - s)), 1)
    thresh = jax.lax.top_k(flat, k)[0][-1]
    mask = jnp.abs(u_new) >= thresh
    sparse_send = jnp.where(mask, u_new, 0.0)
    dense = jnp.reshape(step, ()) <= rampup
    send = jnp.where(dense, u_new, sparse_send)
    u_out = jnp.where(dense, u_new, u_new - sparse_send)
    return {"Out": send, "UOut": u_out}
