"""Collective ops in the program IR.

Capability parity with the reference's collective operator family
(/root/reference/paddle/fluid/operators/collective/c_allreduce_op.h:58,
c_allgather_op.cc, c_reducescatter_op.cc, c_broadcast_op.cc). TPU-first
re-design: NCCL rings keyed by ring_id become *mesh axis names*; inside a
shard_map/SPMD region the ops lower to XLA collectives riding the ICI
(lax.psum / all_gather / psum_scatter / ppermute). Outside any mapped axis
they are identities, matching single-process semantics. Stream-sync ops
(c_sync_calc_stream / c_sync_comm_stream, reference
operators/collective/c_sync_*_stream_op.cc) are no-ops: XLA owns scheduling.
"""
import jax
import jax.numpy as jnp

from ..framework.registry import register_op
from .common import x_of


# Explicit ring_id -> mesh-axis-name registry (the TPU analog of the
# reference's NCCLCommContext ring registry, platform/collective_helper.h:62).
# Populated by c_comm_init (axis_name attr) or register_ring(); ring 0
# defaults to the data-parallel axis.
_RING_AXES = {}


def register_ring(ring_id, axis_name, program=None):
    """Bind a reference-style ring_id to a mesh axis name. With `program`,
    the binding is scoped to that Program (what c_comm_init does); without,
    it is a process-wide default."""
    if program is not None:
        if not hasattr(program, "_ring_axes"):
            program._ring_axes = {}
        program._ring_axes[int(ring_id)] = axis_name
    else:
        _RING_AXES[int(ring_id)] = axis_name


def _ring_axis(ctx, attrs):
    """Map the reference's ring_id to a mesh axis name. Explicit
    `axis_name` attr wins, then the program-scoped registry (c_comm_init
    bindings), then the process-wide registry; ring 0 defaults to the
    data-parallel axis. Unregistered ring_id>0 is an error rather than a
    silent guess."""
    name = attrs.get("axis_name")
    if name:
        return name
    ring = attrs.get("ring_id", 0)
    prog_rings = getattr(ctx.program, "_ring_axes", None)
    if prog_rings and ring in prog_rings:
        return prog_rings[ring]
    if ring in _RING_AXES:
        return _RING_AXES[ring]
    if ring == 0:
        mesh = ctx.mesh
        if mesh is not None and "dp" not in mesh.axis_names:
            return mesh.axis_names[0]
        return "dp"
    raise ValueError(
        f"ring_id {ring} has no mesh axis bound — pass axis_name on the "
        f"collective op or call paddle_tpu.ops.collective_ops.register_ring"
        f"({ring}, '<axis>') (the reference bound rings via c_comm_init, "
        f"operators/collective/c_comm_init_op.cc)")


def _axis_in_scope(axis_name):
    try:
        jax.lax.axis_index(axis_name)
        return True
    except NameError:
        return False
    except Exception:
        return False


def _c_reduce(name, op):
    @register_op(name)
    def _impl(ctx, ins, attrs, _op=op):
        x = x_of(ins)
        axis = _ring_axis(ctx, attrs)
        if not _axis_in_scope(axis):
            return {"Out": x}
        return {"Out": _op(x, axis)}
    return _impl


_c_reduce("c_allreduce_sum", lambda x, a: jax.lax.psum(x, a))
_c_reduce("c_allreduce_max", lambda x, a: jax.lax.pmax(x, a))
_c_reduce("c_allreduce_min", lambda x, a: jax.lax.pmin(x, a))
_c_reduce("c_allreduce_prod",
          lambda x, a: jnp.exp(jax.lax.psum(jnp.log(x), a)))
_c_reduce("allreduce", lambda x, a: jax.lax.psum(x, a))


@register_op("c_allgather")
def c_allgather(ctx, ins, attrs):
    x = x_of(ins)
    axis = _ring_axis(ctx, attrs)
    if not _axis_in_scope(axis):
        return {"Out": x}
    out = jax.lax.all_gather(x, axis)          # (n, *x.shape)
    return {"Out": out.reshape((-1,) + x.shape[1:])}


@register_op("c_reducescatter")
def c_reducescatter(ctx, ins, attrs):
    x = x_of(ins)
    axis = _ring_axis(ctx, attrs)
    if not _axis_in_scope(axis):
        return {"Out": x}
    return {"Out": jax.lax.psum_scatter(x, axis, tiled=True)}


@register_op("hier_allreduce")
def hier_allreduce(ctx, ins, attrs):
    """Hierarchical data-parallel gradient reduction (the MegaScale
    multi-slice decomposition): reduce-scatter in-slice over the fast
    ICI axis, all-reduce across slices over DCN on only the 1/dp shard
    each chip owns, all-gather in-slice. Inside a shard_map region with
    both axes bound this moves ``2(dp-1)/dp * |g|`` bytes on ICI and
    ``2(dcn-1)/dcn * |g|/dp`` bytes on DCN — the flat all-reduce's DCN
    traffic divided by the in-slice degree. The op is inserted by the
    ``hier_grad_sync`` pass right after each gradient's producer, so
    XLA can overlap the cross-slice phase of layer k's gradient against
    layer k-1's backward compute. Outside any mapped axis it is an
    identity (the plain-GSPMD flat path — the A/B baseline — and
    single-chip runs are numerically untouched).

    ``mean=True`` (default) divides by the combined group size: under
    shard_map each device's gradient is the mean over its LOCAL batch,
    so sum/S is exactly the global-batch mean the GSPMD path computes
    (CoeffNumDevice semantics; assumes the standard mean-reduced loss).
    """
    x = x_of(ins)
    inner = attrs.get("inner_axis", "dp")
    outer = attrs.get("outer_axis", "dcn_dp")
    inner_in = _axis_in_scope(inner)
    outer_in = _axis_in_scope(outer)
    if not (inner_in or outer_in):
        return {"Out": x}
    # static axis sizes from the mesh (the pad below must be a
    # trace-time constant)
    mesh = ctx.mesh
    _size = lambda a: int(mesh.shape[a])  # noqa: E731
    group = 1
    if not inner_in:
        out = jax.lax.psum(x, outer)
        group = _size(outer)
    else:
        n = _size(inner)
        group = n * (_size(outer) if outer_in else 1)
        flat = x.reshape(-1)
        pad = (-flat.size) % n
        if pad:
            flat = jnp.pad(flat, (0, pad))
        shard = jax.lax.psum_scatter(flat, inner, tiled=True)
        if outer_in:
            shard = jax.lax.psum(shard, outer)     # the DCN hop: |g|/dp
        full = jax.lax.all_gather(shard, inner, tiled=True)
        if pad:
            full = full[:x.size]
        out = full.reshape(x.shape)
    if attrs.get("mean", True) and group > 1 and \
            jnp.issubdtype(out.dtype, jnp.inexact):
        out = out / jnp.asarray(group, dtype=out.dtype)
    return {"Out": out}


@register_op("c_broadcast")
def c_broadcast(ctx, ins, attrs):
    x = x_of(ins)
    axis = _ring_axis(ctx, attrs)
    if not _axis_in_scope(axis):
        return {"Out": x}
    root = attrs.get("root", 0)
    # broadcast = psum of the root's (masked) contribution: O(size) traffic
    # over the reduction tree, vs O(N*size) for all-gather-then-index
    idx = jax.lax.axis_index(axis)
    contrib = jnp.where(idx == root, x, jnp.zeros_like(x))
    out = jax.lax.psum(contrib, axis)
    return {"Out": out.astype(x.dtype)}


@register_op("broadcast")
def broadcast(ctx, ins, attrs):
    return c_broadcast(ctx, ins, attrs)


@register_op("alltoall")
def alltoall(ctx, ins, attrs):
    x = x_of(ins)
    axis = _ring_axis(ctx, attrs)
    if not _axis_in_scope(axis):
        return {"Out": x}
    n = jax.lax.axis_size(axis)
    xs = x.reshape((n, x.shape[0] // n) + x.shape[1:])
    out = jax.lax.all_to_all(xs, axis, split_axis=0, concat_axis=0,
                             tiled=False)
    return {"Out": out.reshape(x.shape)}


@register_op("sharding_constraint")
def sharding_constraint(ctx, ins, attrs):
    """TPU-native primitive with no reference counterpart: pins an activation
    to a mesh sharding (PartitionSpec given as the `spec` attr, one entry per
    dim, None = replicate). This is how sequence parallelism ("sp" on the
    sequence dim) and activation dp sharding are declared; GSPMD propagates
    the rest. Identity without a mesh."""
    x = x_of(ins)
    mesh = ctx.mesh
    if mesh is None:
        return {"Out": x}
    # inside a shard_map region (pipeline stages) arrays are per-device and
    # GSPMD constraints don't apply — identity there
    if any(_axis_in_scope(a) for a in mesh.axis_names):
        return {"Out": x}
    from jax.sharding import NamedSharding
    from ..parallel.mesh import partition_spec
    spec = partition_spec(mesh, attrs.get("spec", ()), x.shape)
    return {"Out": jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, spec))}


@register_op("c_sync_calc_stream")
def c_sync_calc_stream(ctx, ins, attrs):
    return {"Out": x_of(ins)}  # XLA owns stream scheduling


@register_op("c_sync_comm_stream")
def c_sync_comm_stream(ctx, ins, attrs):
    return {"Out": x_of(ins)}


@register_op("c_gen_nccl_id", grad=False, infer_shape=False)
def c_gen_nccl_id(ctx, ins, attrs):
    """NCCL-id RPC bootstrap (reference c_gen_nccl_id_op.cc) is unnecessary:
    jax.distributed + the mesh give deterministic rendezvous."""
    return None


@register_op("c_comm_init", grad=False, infer_shape=False)
def c_comm_init(ctx, ins, attrs):
    # ring bootstrap collapses to a registry entry: bind ring_id -> axis.
    # Written both program-scoped and process-wide (last-wins): init ops
    # conventionally live in the STARTUP program while the collectives run
    # in the main program, so the cross-program fallback is load-bearing.
    if "axis_name" in attrs:
        register_ring(attrs.get("ring_id", 0), attrs["axis_name"],
                      program=ctx.program)
        register_ring(attrs.get("ring_id", 0), attrs["axis_name"])
    return None


@register_op("c_comm_init_all", grad=False, infer_shape=False)
def c_comm_init_all(ctx, ins, attrs):
    return None
