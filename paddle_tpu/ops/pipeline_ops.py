"""Pipeline-parallel op: GPipe over the "pp" mesh axis.

Capability parity with the reference's pipeline stack (PipelineOptimizer
/root/reference/python/paddle/fluid/optimizer.py:3554, PipelineTrainer +
SectionWorker /root/reference/paddle/fluid/framework/pipeline_trainer.cc:122,
device_worker.h:329): the reference cuts a program into sections placed on
different devices and streams microbatches through scope queues between
section-worker threads.

TPU-native design: stages are UNIFORM (same sub-block, per-stage weight
slices stacked on a leading [S] dim sharded over "pp"), and the schedule is
one shard_map over the mesh — each tick every device runs its stage on its
current microbatch and rotates activations to the next stage via
lax.ppermute (ICI neighbor traffic). A scan over M + S - 1 ticks fills and
drains the pipeline; reverse-mode AD through the scan gives the backward
pipeline (and per-microbatch gradient accumulation) for free. This is the
standard JAX/praxis pipelining recipe rather than a thread/queue port —
XLA sees one static program it can overlap.

Without a "pp" mesh axis the op lowers to a sequential microbatch loop with
identical math, so pipelined and non-pipelined runs are numerically equal
(the parity the reference asserts between pipelined and plain programs).
"""
import jax
import jax.numpy as jnp
from ._shard_compat import shard_map
from jax.sharding import PartitionSpec as P

from ..framework.registry import register_op
from .common import x_of


@register_op("pipeline", grad=None, infer_shape=False)
def pipeline_op(ctx, ins, attrs):
    """inputs: X=[batch input [B, ...]], P=[stacked params [S, ...]],
    R=[replicated non-param outer reads]; attrs: sub_block, num_stages,
    num_microbatches, x_name, out_name, p_names, r_names.
    output: Out [B, ...] (stage chain output; in/out shapes must match)."""
    x = x_of(ins)
    stacked = list(ins.get("P", []))
    repl = list(ins.get("R", []))
    S = int(attrs["num_stages"])
    M = int(attrs["num_microbatches"])
    x_name = attrs["x_name"]
    out_name = attrs["out_name"]
    p_names = list(attrs.get("p_names", []))
    r_names = list(attrs.get("r_names", []))
    sub = attrs["sub_block"]

    B = x.shape[0]
    if B % M:
        raise ValueError(f"pipeline: batch {B} not divisible by "
                         f"num_microbatches {M}")
    xs = x.reshape((M, B // M) + x.shape[1:])

    def stage_fn(stage_params, repl_vals, x_mb):
        # strict env: every outer read must arrive via P (stacked params)
        # or R (replicated) — nothing may be closed over from outside the
        # shard_map region (a missing binding raises by name)
        env = {}
        env.update(zip(r_names, repl_vals))
        env.update(zip(p_names, stage_params))
        env[x_name] = x_mb
        ctx.lower_block_ops(sub, env)
        y = env[out_name]
        if y.shape != x_mb.shape or y.dtype != x_mb.dtype:
            raise ValueError(
                f"pipeline stage must be shape/dtype-preserving (uniform "
                f"chain): in {x_mb.shape}/{x_mb.dtype} vs out "
                f"{y.shape}/{y.dtype}")
        return y

    mesh = ctx.mesh
    use_pp = (mesh is not None and "pp" in mesh.axis_names
              and mesh.shape["pp"] == S and S > 1 and not ctx.abstract)

    if not use_pp:
        # sequential fallback: same per-microbatch math, no pp axis
        def chain(x_mb):
            y = x_mb
            for s in range(S):
                y = stage_fn([p[s] for p in stacked], repl, y)
            return y

        return {"Out": jax.lax.map(chain, xs).reshape(x.shape)}

    batch_axis = "dp" if "dp" in mesh.axis_names and \
        xs.shape[1] % mesh.shape["dp"] == 0 else None
    xspec = P(None, batch_axis) if batch_axis else P()

    def per_device(params_local, repl_local, xs_local):
        params_here = [p[0] for p in params_local]   # [1,...] slice -> stage
        idx = jax.lax.axis_index("pp")
        state0 = jnp.zeros(xs_local.shape[1:], xs_local.dtype)
        outbuf0 = jnp.zeros(xs_local.shape, xs_local.dtype)
        fwd_ring = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            state, outbuf = carry
            x_in = jax.lax.dynamic_index_in_dim(
                xs_local, jnp.clip(t, 0, M - 1), keepdims=False)
            inp = jnp.where(idx == 0, x_in, state)
            y = stage_fn(params_here, repl_local, inp)
            ot = t - (S - 1)
            write = jnp.logical_and(
                idx == S - 1, jnp.logical_and(ot >= 0, ot < M))
            slot = jnp.clip(ot, 0, M - 1)
            cur = jax.lax.dynamic_index_in_dim(outbuf, slot, keepdims=False)
            outbuf = jax.lax.dynamic_update_index_in_dim(
                outbuf, jnp.where(write, y, cur), slot, 0)
            nxt = jax.lax.ppermute(y, "pp", fwd_ring)
            return (nxt, outbuf), None

        (_, outbuf), _ = jax.lax.scan(
            tick, (state0, outbuf0), jnp.arange(M + S - 1))
        # only the last stage holds real outputs; psum replicates over pp
        outbuf = jax.lax.psum(
            jnp.where(idx == S - 1, outbuf, jnp.zeros_like(outbuf)), "pp")
        return outbuf

    mapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(tuple(P("pp") for _ in stacked),
                  tuple(P() for _ in repl), xspec),
        out_specs=xspec, check_vma=False)
    out = mapped(tuple(stacked), tuple(repl), xs)
    return {"Out": out.reshape(x.shape)}
