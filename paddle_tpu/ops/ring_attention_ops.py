"""Ring attention: sequence/context parallelism for long sequences.

North-star capability with no reference counterpart (the reference's
sequence story is LoD ops + recurrent_op, bounded by one device's memory —
SURVEY §5.7): attention over a sequence sharded across the "sp" mesh axis,
where no device ever materializes the full [S, S] score matrix OR the full
K/V. The canonical TPU formulation (Ring Attention / blockwise attention):

  - Q stays put, sharded over sp; K/V blocks ROTATE around the sp ring via
    lax.ppermute (neighbor ICI traffic, overlapped with compute by XLA).
  - Each step folds one K/V block into a numerically-stable ONLINE softmax
    accumulator (running max m, normalizer l, weighted value sum acc) —
    flash-attention numerics, so the result is exact, not approximate.
  - sp_steps hops close the ring; the final out = acc / l.

Reverse-mode AD flows through shard_map + scan + ppermute, so the backward
pass is automatically the reverse ring — no hand-written grad.

Without an "sp" axis the lowering computes the same blockwise math in one
pass (exact standard attention), so sp-sharded and single-device runs are
numerically comparable.
"""
import jax
import jax.numpy as jnp
from ._shard_compat import shard_map
from jax.sharding import PartitionSpec as P

from ..framework.registry import register_op
from .common import x_of

_NEG_INF = -1e30


def _block_fold(q, k_blk, v_blk, bias_blk, scale, m, l, acc,
                row0=None, col0=None):
    """Fold one K/V block into the online-softmax accumulator. With
    (row0, col0) global offsets, a causal mask is synthesized from
    iota — no [S, S] mask tensor ever exists (the point of ring
    attention at long S)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) * scale
    if bias_blk is not None:
        s = s + bias_blk
    if row0 is not None:
        rows = row0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        cols = col0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    corr = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd",
                                                 p, v_blk)
    return m_new, l_new, acc_new


@register_op("ring_attention", infer_shape=False)
def ring_attention(ctx, ins, attrs):
    """inputs: Q, K, V [B, H, S, D] (+ optional Bias [B, 1, 1, S] or
    [B, H, S, S] additive mask); attrs: scale (default 1/sqrt(D)).
    output: Out [B, H, S, D]."""
    q = x_of(ins, "Q")
    k = x_of(ins, "K")
    v = x_of(ins, "V")
    bias = ins.get("Bias")
    bias = bias[0] if bias else None
    scale = float(attrs.get("scale", 0.0)) or float(q.shape[-1]) ** -0.5
    causal = bool(attrs.get("causal", False))

    mesh = ctx.mesh
    sp = (mesh.shape["sp"]
          if mesh is not None and "sp" in mesh.axis_names else 1)
    B, H, S, D = q.shape
    if sp > 1 and not ctx.abstract and S % sp:
        raise ValueError(
            f"ring_attention: sequence length {S} is not divisible by the "
            f"sp axis size {sp} — pad the sequence or resize the mesh "
            f"(a silent dense fallback would defeat the memory scaling)")
    use_ring = sp > 1 and not ctx.abstract

    if not use_ring:
        m = jnp.full(q.shape[:3], _NEG_INF, q.dtype)
        l = jnp.zeros(q.shape[:3], q.dtype)
        acc = jnp.zeros(q.shape, q.dtype)
        bias_full = None
        if bias is not None:
            bias_full = jnp.broadcast_to(bias, (B, bias.shape[1],
                                                bias.shape[2], S))
        m, l, acc = _block_fold(q, k, v, bias_full, scale, m, l, acc,
                                row0=0 if causal else None,
                                col0=0 if causal else None)
        return {"Out": acc / l[..., None]}

    qspec = P(None, None, "sp", None)
    # two supported bias layouts under sharding:
    #   [B, 1, 1, S]  key-position mask -> sharded on keys, ROTATES with
    #                 the K/V blocks
    #   [B, H, S, S]  full additive mask -> sharded on the QUERY dim; the
    #                 key-block slice is selected per ring step
    key_bias = bias is None or (bias.shape[1] == 1 and bias.shape[2] == 1)
    if bias is None:
        bias = jnp.zeros((B, 1, 1, S), q.dtype)
    bspec = P(None, None, None, "sp") if key_bias else qspec
    blk = S // sp

    def per_device(q_l, k_l, v_l, bias_l):
        idx = jax.lax.axis_index("sp")
        m = jnp.full(q_l.shape[:3], _NEG_INF, q_l.dtype)
        l = jnp.zeros(q_l.shape[:3], q_l.dtype)
        acc = jnp.zeros(q_l.shape, q_l.dtype)
        ring = [(i, (i + 1) % sp) for i in range(sp)]

        def step(carry, t):
            k_blk, v_blk, b_rot, m, l, acc = carry
            j = (idx - t) % sp
            if key_bias:
                b_blk = b_rot
            else:
                # full bias: columns of this step's key block
                b_blk = jax.lax.dynamic_slice_in_dim(
                    bias_l, j * blk, blk, axis=3)
            if causal:
                # global offsets of this device's query rows and the
                # current key block's columns; step t=0 folds the
                # DIAGONAL block first, so every row is live from the
                # start (the online-softmax all-masked hazard never
                # arises). Blocks entirely ABOVE the diagonal (j > idx)
                # skip the fold — that halves total FLOPs/energy, but
                # NOT wall-clock: the ppermute synchronizes every step
                # and device sp-1 folds on all of them (balancing needs
                # striped block assignment, which would change the
                # user-visible contiguous-shard layout).
                m, l, acc = jax.lax.cond(
                    j <= idx,
                    lambda m, l, acc: _block_fold(
                        q_l, k_blk, v_blk, b_blk, scale, m, l, acc,
                        row0=idx * blk, col0=j * blk),
                    lambda m, l, acc: (m, l, acc),
                    m, l, acc)
            else:
                m, l, acc = _block_fold(q_l, k_blk, v_blk, b_blk, scale,
                                        m, l, acc)
            k_blk = jax.lax.ppermute(k_blk, "sp", ring)
            v_blk = jax.lax.ppermute(v_blk, "sp", ring)
            if key_bias:
                b_rot = jax.lax.ppermute(b_rot, "sp", ring)
            return (k_blk, v_blk, b_rot, m, l, acc), None

        b0 = bias_l if key_bias else bias_l[:, :, :, :blk]
        (k_l, v_l, _, m, l, acc), _ = jax.lax.scan(
            step, (k_l, v_l, b0, m, l, acc), jnp.arange(sp))
        return acc / l[..., None]

    mapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(qspec, qspec, qspec, bspec),
        out_specs=qspec, check_vma=False)
    return {"Out": mapped(q, k, v, bias)}


@register_op("flash_attention", infer_shape=False)
def flash_attention_op(ctx, ins, attrs):
    """Single-device fused attention via the Pallas flash kernel
    (kernels/flash_attention.py) — the TPU-native equivalent of the
    reference's fused CUDA attention
    (operators/fused/multihead_matmul_op.cu). inputs: Q, K, V
    [B, H, S, D] (+ optional additive key Bias [B, 1, 1, S], treated as a
    constant mask); attrs: scale (default 1/sqrt(D)), causal, impl
    ("" = auto: Pallas on TPU, XLA composite elsewhere)."""
    from ..kernels.flash_attention import flash_attention as _fa

    q = x_of(ins, "Q")
    k = x_of(ins, "K")
    v = x_of(ins, "V")
    bias = ins.get("Bias")
    bias = bias[0] if bias else None
    scale = float(attrs.get("scale", 0.0)) or None
    out = _fa(q, k, v, bias, scale=scale,
              causal=bool(attrs.get("causal", False)),
              impl=attrs.get("impl") or None,
              block_q=int(attrs.get("block_q", 0)) or None,
              block_k=int(attrs.get("block_k", 0)) or None)
    return {"Out": out}


@register_op("ulysses_attention", infer_shape=False)
def ulysses_attention(ctx, ins, attrs):
    """Ulysses-style sequence parallelism (the all-to-all alternative to
    the ring): swap the sharded dim from sequence to heads with one
    lax.all_to_all, run FULL attention on H/sp heads per device, swap
    back. Cheaper than the ring when heads divide evenly and the ICI
    all-to-all is fast; same exact math. Same signature as
    ring_attention; requires H % sp == 0."""
    q = x_of(ins, "Q")
    k = x_of(ins, "K")
    v = x_of(ins, "V")
    bias = ins.get("Bias")
    bias = bias[0] if bias else None
    scale = float(attrs.get("scale", 0.0)) or float(q.shape[-1]) ** -0.5
    causal = bool(attrs.get("causal", False))

    mesh = ctx.mesh
    sp = (mesh.shape["sp"]
          if mesh is not None and "sp" in mesh.axis_names else 1)
    B, H, S, D = q.shape
    if sp > 1 and not ctx.abstract and (S % sp or H % sp):
        raise ValueError(
            f"ulysses_attention: S={S} and n_head={H} must both be "
            f"divisible by the sp axis size {sp} (the all-to-all swaps the "
            f"shard dim from sequence to heads); use mechanism='ring' for "
            f"head counts that don't divide")
    use = sp > 1 and not ctx.abstract

    def full_attn(q_, k_, v_, bias_):
        s = jnp.einsum("bhqd,bhkd->bhqk", q_, k_) * scale
        if bias_ is not None:
            s = s + bias_
        if causal:
            # after the all-to-all each device holds FULL sequences for
            # its heads, so plain iota masking applies
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
            cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 3)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v_)

    if not use:
        return {"Out": full_attn(q, k, v, bias)}

    qspec = P(None, None, "sp", None)
    # bias layouts: [B,1,1,S] key mask -> sharded on keys, gathered
    # locally; [B,H,S,S] per-head mask -> sharded on HEADS (after the
    # all-to-all each device holds exactly its H/sp heads' mask);
    # [B,1,S,S] head-broadcast mask (e.g. causal) -> replicated (its
    # size-1 head axis cannot shard)
    key_bias = bias is None or (bias.shape[1] == 1 and bias.shape[2] == 1)
    head_bcast = (bias is not None and bias.shape[1] == 1
                  and bias.shape[2] > 1)
    if bias is None:
        bias = jnp.zeros((B, 1, 1, S), q.dtype)
    if key_bias:
        bspec = P(None, None, None, "sp")
    elif head_bcast:
        bspec = P(None, None, None, None)
    else:
        bspec = P(None, "sp", None, None)

    def per_device(q_l, k_l, v_l, bias_l):
        def seq_to_heads(a):      # [B, H, S/sp, D] -> [B, H/sp, S, D]
            return jax.lax.all_to_all(a, "sp", split_axis=1,
                                      concat_axis=2, tiled=True)

        qh, kh, vh = seq_to_heads(q_l), seq_to_heads(k_l), seq_to_heads(v_l)
        if key_bias:
            bias_h = jax.lax.all_gather(bias_l, "sp", axis=3, tiled=True)
        else:
            bias_h = bias_l           # already this device's heads
        out_h = full_attn(qh, kh, vh, bias_h)     # [B, H/sp, S, D]
        # heads -> sequence: inverse all_to_all
        return jax.lax.all_to_all(out_h, "sp", split_axis=2,
                                  concat_axis=1, tiled=True)

    mapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(qspec, qspec, qspec, bspec),
        out_specs=qspec, check_vma=False)
    return {"Out": mapped(q, k, v, bias)}
