"""Mixture-of-Experts op: GShard-style expert parallelism over "ep".

No reference counterpart (the reference predates MoE) — this is a
north-star extra alongside sequence parallelism: the "ep" mesh axis must
be a first-class scaling dimension. The formulation is the canonical
GShard/Switch einsum dance: top-1 gating, capacity-bounded one-hot
dispatch, per-expert batched matmuls on tensors whose leading expert dim
is sharded over "ep" (sharding_constraint), so GSPMD inserts the
all-to-alls on the dispatch/combine einsums — no hand-written collectives
and one XLA module.

Outputs the combined tokens plus the standard load-balance auxiliary loss
(mean_gate * mean_dispatch * E^2).
"""
import jax
import jax.numpy as jnp

from ..framework.registry import register_op
from .common import x_of


@register_op("switch_moe", infer_shape=False)
def switch_moe(ctx, ins, attrs):
    """inputs: X [N, d], GateW [d, E], W1 [E, d, h], B1 [E, h],
    W2 [E, h, d], B2 [E, d]; attrs: capacity_factor (default 1.25).
    outputs: Out [N, d], AuxLoss [] (load-balance loss)."""
    x = x_of(ins)
    gate_w = x_of(ins, "GateW")
    w1 = x_of(ins, "W1")
    b1 = x_of(ins, "B1")
    w2 = x_of(ins, "W2")
    b2 = x_of(ins, "B2")
    cap_factor = float(attrs.get("capacity_factor", 1.25))
    N, d = x.shape
    E = gate_w.shape[1]
    C = max(int(cap_factor * N / E), 1)

    logits = x @ gate_w                           # [N, E]
    gates = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(gates, axis=-1)           # [N] top-1
    gate_val = jnp.max(gates, axis=-1)            # [N]

    onehot = jax.nn.one_hot(expert, E, dtype=x.dtype)       # [N, E]
    # 0-based position of each token within its expert's queue: the
    # running count of same-expert tokens up to and including this one
    rank = jnp.sum(jnp.cumsum(onehot, axis=0) * onehot, axis=-1)
    pos_in_expert = (rank - 1.0).astype(jnp.int32)          # [N]
    keep = pos_in_expert < C
    # dispatch tensor [N, E, C]
    dispatch = (onehot * keep[:, None].astype(x.dtype))[:, :, None] * \
        jax.nn.one_hot(jnp.clip(pos_in_expert, 0, C - 1), C,
                       dtype=x.dtype)[:, None, :]

    def shard_ep(a):
        if ctx.mesh is not None and "ep" in ctx.mesh.axis_names and \
                not ctx.abstract and a.shape[0] % ctx.mesh.shape["ep"] == 0:
            from jax.sharding import NamedSharding, PartitionSpec as P
            return jax.lax.with_sharding_constraint(
                a, NamedSharding(ctx.mesh,
                                 P(*(("ep",) + (None,) * (a.ndim - 1)))))
        return a

    expert_in = jnp.einsum("nec,nd->ecd", dispatch, x)      # [E, C, d]
    expert_in = shard_ep(expert_in)
    h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", expert_in, w1) +
                    b1[:, None, :])
    expert_out = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]
    expert_out = shard_ep(expert_out)
    combine = dispatch * gate_val[:, None, None]
    out = jnp.einsum("nec,ecd->nd", combine, expert_out)    # [N, d]

    # GShard/Switch load-balance aux loss: E * sum_e f_e * P_e
    # (== mean(f*P) * E^2); 1.0 at perfectly uniform routing for any E
    density = jnp.mean(onehot, axis=0)            # fraction routed / expert
    density_proxy = jnp.mean(gates, axis=0)       # mean gate prob / expert
    aux = jnp.mean(density * density_proxy) * (E * E)
    return {"Out": out, "AuxLoss": aux.reshape(())}
