"""CTR / tree-index / text-matching ops (reference: tree_conv_op.h +
math/tree2col.cc, tdm_child_op.h, tdm_sampler_op.h, pyramid_hash_op.cc,
match_matrix_tensor_op.cc, var_conv_2d_op.cc, filter_by_instag_op.h,
rank_attention_op.cc + rank_attention.cu.h).

TPU design notes: the reference walks trees/LoD rows on the host; here
tree reachability is computed by max_depth boolean matmul hops (a tree
has unique paths, so depth masks are exact), LoD text pairs come in
padded [B, ...] + length vectors, and dynamically-sized filters return
padded rows + counts, like the rest of this op library."""
import jax
import jax.numpy as jnp

from ..framework.registry import register_op
from .common import as_dtype, int64_t, x_of


@register_op("tree_conv", infer_shape=False)
def tree_conv(ctx, ins, attrs):
    """Tree-based convolution (reference tree_conv_op.h; patch math
    math/tree2col.cc). NodesVector [B, N, F] (nodes 1-indexed, row v-1
    holds node v), EdgeSet [B, E, 2] int (zero rows pad), Filter
    [F, 3, out_size, num_filters]. Out [B, N, out_size, num_filters].
    Per root u: patch = sum over nodes v within depth < max_depth of
    (eta_l, eta_r, eta_t)(v) * feat[v]; Out[u] = patch @ Filter."""
    feats = x_of(ins, "NodesVector")
    edges = x_of(ins, "EdgeSet").astype(jnp.int32)
    filt = x_of(ins, "Filter")
    max_depth = int(attrs.get("max_depth", 2))
    B, N, F = feats.shape
    Fdim, three, out_size, nf = filt.shape
    w2d = filt.reshape(F * 3, out_size * nf)

    def one_tree(feat, edge):
        u, v = edge[:, 0], edge[:, 1]
        ok = (u != 0) & (v != 0)
        # child adjacency over 1-indexed nodes (slot 0 unused)
        adj = jnp.zeros((N + 1, N + 1), jnp.float32)
        adj = adj.at[jnp.where(ok, u, 0), jnp.where(ok, v, 0)].max(
            ok.astype(jnp.float32))
        adj = adj.at[0, :].set(0.0).at[:, 0].set(0.0)
        # per-node child position (1-based, edge order) + sibling count
        E = u.shape[0]
        same_parent = (u[:, None] == u[None, :]) & ok[None, :] & ok[:, None]
        earlier = jnp.arange(E)[None, :] <= jnp.arange(E)[:, None]
        order = jnp.sum((same_parent & earlier).astype(jnp.float32),
                        axis=1)                            # [E]
        idx_of = jnp.zeros((N + 1,), jnp.float32).at[
            jnp.where(ok, v, 0)].max(jnp.where(ok, order, 0.0))
        n_child = jnp.zeros((N + 1,), jnp.float32).at[
            jnp.where(ok, u, 0)].add(ok.astype(jnp.float32))
        parent = jnp.zeros((N + 1,), jnp.int32).at[
            jnp.where(ok, v, 0)].max(jnp.where(ok, u, 0))
        sibs = n_child[parent]                    # pclen per node v

        def coeffs(depth, is_root):
            eta_t = jnp.full((N + 1,), (max_depth - depth) / max_depth)
            temp = jnp.where(is_root | (sibs <= 1), 0.5,
                             (idx_of - 1.0)
                             / jnp.maximum(sibs - 1.0, 1.0))
            eta_l = (1.0 - eta_t) * temp
            eta_r = (1.0 - eta_t) * (1.0 - eta_l)
            return eta_l, eta_r, eta_t            # each [N+1]

        feat1 = jnp.concatenate(
            [jnp.zeros((1, F), feats.dtype), feat], axis=0)  # node-id rows
        patch = jnp.zeros((N + 1, F, 3), jnp.float32)
        reach = jnp.eye(N + 1, dtype=jnp.float32)
        for d in range(max_depth):
            el, er, et = coeffs(float(d), d == 0)
            contrib = jnp.stack([el[:, None] * feat1,
                                 er[:, None] * feat1,
                                 et[:, None] * feat1], axis=-1)
            patch = patch + jnp.einsum("uv,vfk->ufk", reach, contrib)
            reach = jnp.minimum(reach @ adj, 1.0)
        out = patch.reshape(N + 1, F * 3) @ w2d   # [N+1, out*nf]
        # only nodes that exist (appear in an edge or are node 1) emit
        exists = jnp.zeros((N + 1,), bool).at[
            jnp.where(ok, u, 0)].max(ok).at[
            jnp.where(ok, v, 0)].max(ok).at[1].set(True).at[0].set(False)
        out = jnp.where(exists[:, None], out, 0.0)
        return out[1:].reshape(N, out_size, nf)

    return {"Out": jax.vmap(one_tree)(feats, edges)}


@register_op("tdm_child", grad=False, infer_shape=False)
def tdm_child(ctx, ins, attrs):
    """reference tdm_child_op.h: look up each node id's children in
    TreeInfo (row per node id: [item_id, layer_id, ancestor_id,
    child_0..child_n-1] — item_id at column 0, children from column 3).
    X [..., 1] ids -> Child [..., child_nums], LeafMask (child is a
    leaf iff its item_id != 0)."""
    x = x_of(ins).astype(jnp.int32)
    info = x_of(ins, "TreeInfo").astype(jnp.int32)
    child_nums = int(attrs["child_nums"])
    dt = as_dtype(attrs, default="int32")
    flat = x.reshape(-1)
    has_child = (flat != 0) & (info[flat, 3] != 0)
    kids = info[flat][:, 3:3 + child_nums]                # [M, child_nums]
    kids = jnp.where(has_child[:, None], kids, 0)
    leaf = jnp.where(has_child[:, None] & (kids != 0),
                     (info[kids, 0] != 0).astype(jnp.int32), 0)
    shape = x.shape[:-1] + (child_nums,)
    return {"Child": kids.reshape(shape).astype(dt),
            "LeafMask": leaf.reshape(shape).astype(dt)}


@register_op("tdm_sampler", grad=False, infer_shape=False, needs_rng=True)
def tdm_sampler(ctx, ins, attrs):
    """reference tdm_sampler_op.h: per input item, walk its Travel path
    and draw negatives from each tree layer. Travel [N, L] (0 pads an
    absent layer), Layer [total_nodes] flat with layer_offset_lod.
    Out/Labels/Mask [N, sum(neg_nums_i + output_positive)].
    Divergence (documented): a colliding negative is shifted to the
    next layer slot instead of reject-resampled."""
    x = x_of(ins).astype(jnp.int32).reshape(-1)
    travel = x_of(ins, "Travel").astype(jnp.int32)
    layer = x_of(ins, "Layer").astype(jnp.int32).reshape(-1)
    neg_nums = [int(n) for n in attrs["neg_samples_num_list"]]
    offsets = [int(o) for o in attrs["layer_offset_lod"]]
    out_pos = bool(attrs.get("output_positive", True))
    dt = as_dtype(attrs, default="int32")
    key = ctx.op_key(attrs)
    N = x.shape[0]
    L = len(neg_nums)
    per_layer = [n + (1 if out_pos else 0) for n in neg_nums]
    total = sum(per_layer)

    outs, labels, masks = [], [], []
    for li in range(L):
        start, end = offsets[li], offsets[li + 1]
        size = max(end - start, 1)
        pos = travel[jnp.maximum(x, 0), li]               # [N]
        live = pos != 0
        if out_pos:
            outs.append(jnp.where(live, pos, 0)[:, None])
            labels.append(jnp.where(live, 1, 0)[:, None])
            masks.append(live.astype(jnp.int32)[:, None])
        k = jax.random.fold_in(key, li)
        draw = jax.random.randint(k, (N, neg_nums[li]), 0, size)
        cand = layer[start + draw]
        # shift collisions with the positive to the next node in layer
        coll = cand == pos[:, None]
        alt = layer[start + (draw + 1) % size]
        cand = jnp.where(coll, alt, cand)
        outs.append(jnp.where(live[:, None], cand, 0))
        labels.append(jnp.zeros((N, neg_nums[li]), jnp.int32))
        masks.append(jnp.broadcast_to(live[:, None].astype(jnp.int32),
                                      (N, neg_nums[li])))
    out = jnp.concatenate(outs, axis=1)
    assert out.shape[1] == total
    return {"Out": out.astype(dt),
            "Labels": jnp.concatenate(labels, axis=1).astype(dt),
            "Mask": jnp.concatenate(masks, axis=1).astype(dt)}


@register_op("pyramid_hash", infer_shape=False)
def pyramid_hash(ctx, ins, attrs):
    """Pyramid hashing embedding for text (reference pyramid_hash_op.cc):
    every n-gram (2..max_pyramid+1 tokens) hashes to `num_hash` rows of
    the compressed table W [space_len, 1] viewed as a flat parameter;
    the n-gram embedding is the mean of its hashed rows; a sequence's
    output is the sum over its n-grams. Padded form: X [B, T] ids +
    Length [B]. Out [B, rand_len].
    Divergence (documented): the reference uses xxHash on raw bytes;
    here a fixed-coefficient polynomial hash keeps the op jittable —
    same capability (hash-bucketed n-gram embeddings), different
    bucketing."""
    x = x_of(ins).astype(jnp.int32)
    w = x_of(ins, "W").reshape(-1)
    lens = ins.get("Length")
    B, T = x.shape
    if lens:
        lengths = jnp.reshape(lens[0], (-1,)).astype(jnp.int32)
    else:
        lengths = jnp.full((B,), T, jnp.int32)
    num_hash = int(attrs.get("num_hash", 1))
    rand_len = int(attrs.get("rand_len", 16))
    max_pyr = int(attrs.get("max_pyramid", 2))
    space = max(int(w.shape[0]) - rand_len, 1)

    def h(ids, salt):
        # polynomial hash of the n-gram window, salted per hash fn
        acc = jnp.zeros(ids.shape[:-1], jnp.uint32) + jnp.uint32(
            2166136261 + 1013904223 * salt)
        for j in range(ids.shape[-1]):
            acc = acc * jnp.uint32(16777619) ^ ids[..., j].astype(
                jnp.uint32)
        return (acc % jnp.uint32(space)).astype(jnp.int32)

    out = jnp.zeros((B, rand_len), w.dtype)
    pos = jnp.arange(T)
    for n in range(2, max_pyr + 2):
        if n > T:
            break
        grams = jnp.stack([x[:, i:T - n + 1 + i] for i in range(n)],
                          axis=-1)                        # [B, T-n+1, n]
        valid = (pos[None, :T - n + 1] + n) <= lengths[:, None]
        emb = jnp.zeros((B, T - n + 1, rand_len), w.dtype)
        for s in range(num_hash):
            start = h(grams, s)                           # [B, T-n+1]
            rows = start[..., None] + jnp.arange(rand_len)
            emb = emb + w[rows]
        emb = emb / num_hash
        out = out + jnp.sum(
            jnp.where(valid[..., None], emb, 0.0), axis=1)
    return {"Out": out}


@register_op("match_matrix_tensor", infer_shape=False)
def match_matrix_tensor(ctx, ins, attrs):
    """Bilinear text-pair match matrix (reference
    match_matrix_tensor_op.cc): out[b, t, i, j] = x_i' W_t y_j. Padded
    form: X [B, Lx, D], Y [B, Ly, D] (+ XLength/YLength), W
    [D, dim_t, D]. Out [B, dim_t, Lx, Ly] (pads zero), Tmp [B, Lx,
    dim_t, D] (the x'W intermediate the reference stores for grad)."""
    x = x_of(ins)
    y = x_of(ins, "Y")
    w = x_of(ins, "W")
    B, Lx, D = x.shape
    Ly = y.shape[1]
    xl = ins.get("XLength")
    yl = ins.get("YLength")
    tmp = jnp.einsum("bxd,dte->bxte", x, w)
    out = jnp.einsum("bxte,bye->btxy", tmp, y)
    if xl:
        xm = jnp.arange(Lx)[None, :] < jnp.reshape(
            xl[0], (-1,)).astype(jnp.int32)[:, None]
        out = jnp.where(xm[:, None, :, None], out, 0.0)
    if yl:
        ym = jnp.arange(Ly)[None, :] < jnp.reshape(
            yl[0], (-1,)).astype(jnp.int32)[:, None]
        out = jnp.where(ym[:, None, None, :], out, 0.0)
    return {"Out": out, "Tmp": tmp}


@register_op("var_conv_2d", infer_shape=False)
def var_conv_2d(ctx, ins, attrs):
    """Conv over per-sample-sized 2D maps (reference var_conv_2d_op.cc):
    X [B, C_in, H, W] padded with per-sample valid sizes ROW [B] /
    COLUMN [B]; W [out_c, in_c*kh*kw]. SAME-center padding, stride
    (sh, sw); out size per sample = (dim-1)//stride + 1, zeros beyond.
    Out [B, out_c, H', W'] with H' = (H-1)//sh + 1."""
    x = x_of(ins)
    w = x_of(ins, "W")
    rows = jnp.reshape(x_of(ins, "ROW"), (-1,)).astype(jnp.int32)
    cols = jnp.reshape(x_of(ins, "COLUMN"), (-1,)).astype(jnp.int32)
    kh = int(attrs.get("KernelH", 1))
    kw = int(attrs.get("KernelW", 1))
    sh = int(attrs.get("StrideH", 1))
    sw = int(attrs.get("StrideW", 1))
    out_c = int(attrs.get("OutputChannel", w.shape[0]))
    B, C, H, W = x.shape
    # zero out padding beyond each sample's valid region first
    hm = jnp.arange(H)[None, :] < rows[:, None]
    wm = jnp.arange(W)[None, :] < cols[:, None]
    xm = x * hm[:, None, :, None] * wm[:, None, None, :]
    filt = w.reshape(out_c, C, kh, kw)
    out = jax.lax.conv_general_dilated(
        xm, filt, (sh, sw),
        [((kh - 1) // 2, kh - 1 - (kh - 1) // 2),
         ((kw - 1) // 2, kw - 1 - (kw - 1) // 2)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    Ho, Wo = out.shape[2], out.shape[3]
    oh = (rows - 1) // sh + 1
    ow = (cols - 1) // sw + 1
    ohm = jnp.arange(Ho)[None, :] < oh[:, None]
    owm = jnp.arange(Wo)[None, :] < ow[:, None]
    out = out * ohm[:, None, :, None] * owm[:, None, None, :]
    return {"Out": out, "Col": jnp.zeros((1,), x.dtype)}


@register_op("filter_by_instag", infer_shape=False)
def filter_by_instag(ctx, ins, attrs):
    """reference filter_by_instag_op.h: keep rows whose tag set
    intersects Filter_tag. Padded form: Ins [N, D], Ins_tag [N, Tmax]
    (-1 pads), Filter_tag [K]. Out [N, D] (kept rows compacted,
    zero pad), LossWeight [N, 1], IndexMap [N, 2] (out row -> in row),
    OutCount [1]. Differentiable: Out is a masked gather of Ins, so the
    generic vjp scatters Out@GRAD back through the gather (zero for
    filtered rows) — the reference's FilterByInstagGrad kernel."""
    rows = x_of(ins, "Ins")
    tags = x_of(ins, "Ins_tag").astype(int64_t())
    filt = x_of(ins, "Filter_tag").astype(int64_t()).reshape(-1)
    is_lod = bool(attrs.get("is_lod", True))  # noqa: F841 (API parity)
    N = rows.shape[0]
    hit = jnp.any((tags[:, :, None] == filt[None, None, :])
                  & (tags[:, :, None] >= 0), axis=(1, 2))
    order = jnp.argsort(jnp.where(hit, jnp.arange(N), N + jnp.arange(N)))
    cnt = jnp.sum(hit.astype(jnp.int32))
    live = jnp.arange(N) < cnt
    out = jnp.where(live[:, None], rows[order], 0.0)
    idx_map = jnp.stack(
        [jnp.arange(N, dtype=jnp.int32),
         jnp.where(live, order, -1).astype(jnp.int32)], axis=1)
    return {"Out": out,
            "LossWeight": live.astype(rows.dtype)[:, None],
            "IndexMap": idx_map,
            "OutCount": cnt.reshape(1)}


@register_op("rank_attention", infer_shape=False)
def rank_attention(ctx, ins, attrs):
    """reference rank_attention_op.cc (+ rank_attention.cu.h): per-ins
    rank-conditioned attention for CTR. X [N, D]; RankOffset
    [N, 1 + 2*max_rank] int — col 0 is the ins rank (1-based, 0 =
    none), then (rank_flag_k, row_index_k) pairs; RankParam
    [max_rank*max_rank*D, p]. InputHelp [N, max_rank*D] gathers the
    flagged rows; the per-ins parameter block selects rows by
    (ins_rank, k); Out [N, p] = InputHelp @ param_ins."""
    x = x_of(ins)
    offset = x_of(ins, "RankOffset").astype(jnp.int32)
    param = x_of(ins, "RankParam")
    max_rank = int(attrs.get("MaxRank", 3))
    N, D = x.shape
    p = param.shape[1]
    lower = offset[:, 0] - 1                              # [N]
    flags = offset[:, 1::2] - 1                           # [N, max_rank]
    index = offset[:, 2::2]                               # [N, max_rank]
    ok = (lower[:, None] >= 0) & (flags >= 0)
    gathered = x[jnp.maximum(index, 0)]                   # [N, K, D]
    help_ = jnp.where(ok[:, :, None], gathered, 0.0)
    # param rows for ins i, block k, feature f:
    #   (lower*max_rank + k)*D + f
    par3 = param.reshape(max_rank, max_rank, D, p)
    par_ins = par3[jnp.maximum(lower, 0)]                 # [N, K, D, p]
    par_ins = jnp.where(ok[:, :, None, None], par_ins, 0.0)
    out = jnp.einsum("nkd,nkdp->np", help_, par_ins)
    return {"Out": out,
            "InputHelp": help_.reshape(N, max_rank * D),
            "InsRank": lower.astype(x.dtype)[:, None] + 1}
