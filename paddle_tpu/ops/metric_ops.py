"""Metric ops (reference: /root/reference/paddle/fluid/operators/metrics/ —
accuracy_op.cc, auc_op.cc, precision_recall_op.cc)."""
import jax.numpy as jnp

from ..framework.registry import register_op
from .common import x_of


@register_op("accuracy", grad=False)
def accuracy(ctx, ins, attrs):
    indices = x_of(ins, "Indices")
    label = x_of(ins, "Label")
    if label.ndim == 2 and label.shape[1] == 1:
        label = label[:, 0]
    hit = jnp.any(indices == label[:, None], axis=1)
    correct = jnp.sum(hit.astype(jnp.int32))
    total = jnp.asarray(label.shape[0], jnp.int32)
    acc = correct.astype(jnp.float32) / total.astype(jnp.float32)
    return {"Accuracy": acc.reshape(1), "Correct": correct.reshape(1),
            "Total": total.reshape(1)}


@register_op("auc", grad=False)
def auc(ctx, ins, attrs):
    """Streaming AUC: histogram state vars thread through the functional env
    (reference metrics/auc_op.cc keeps StatPos/StatNeg buffers in scope)."""
    predict = x_of(ins, "Predict")
    label = x_of(ins, "Label")
    stat_pos = x_of(ins, "StatPos")
    stat_neg = x_of(ins, "StatNeg")
    num_thresholds = attrs.get("num_thresholds", 4095)
    if label.ndim == 2:
        label = label[:, 0]
    pos_prob = predict[:, -1] if predict.ndim == 2 else predict
    bins = jnp.clip((pos_prob * num_thresholds).astype(jnp.int32), 0,
                    num_thresholds)
    is_pos = (label > 0).astype(stat_pos.dtype)
    pos_hist = jnp.zeros_like(stat_pos).at[bins].add(is_pos)
    neg_hist = jnp.zeros_like(stat_neg).at[bins].add(1 - is_pos)
    new_pos = stat_pos + pos_hist
    new_neg = stat_neg + neg_hist
    # AUC over accumulated histograms (trapezoid over thresholds, high->low)
    tp = jnp.cumsum(new_pos[::-1])
    fp = jnp.cumsum(new_neg[::-1])
    tot_pos = tp[-1]
    tot_neg = fp[-1]
    tp0 = jnp.concatenate([jnp.zeros(1, tp.dtype), tp[:-1]])
    fp0 = jnp.concatenate([jnp.zeros(1, fp.dtype), fp[:-1]])
    area = jnp.sum((fp - fp0) * (tp + tp0) / 2.0)
    denom = jnp.maximum(tot_pos * tot_neg, 1.0)
    auc_val = (area / denom).astype(jnp.float64
                                    if new_pos.dtype == jnp.int64
                                    else jnp.float32)
    return {"AUC": auc_val.reshape(1), "StatPosOut": new_pos,
            "StatNegOut": new_neg}
