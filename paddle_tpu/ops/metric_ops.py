"""Metric ops (reference: /root/reference/paddle/fluid/operators/metrics/ —
accuracy_op.cc, auc_op.cc, precision_recall_op.cc)."""
import jax
import jax.numpy as jnp

from ..framework.registry import register_op
from .common import x_of


@register_op("accuracy", grad=False)
def accuracy(ctx, ins, attrs):
    indices = x_of(ins, "Indices")
    label = x_of(ins, "Label")
    if label.ndim == 2 and label.shape[1] == 1:
        label = label[:, 0]
    hit = jnp.any(indices == label[:, None], axis=1)
    correct = jnp.sum(hit.astype(jnp.int32))
    total = jnp.asarray(label.shape[0], jnp.int32)
    acc = correct.astype(jnp.float32) / total.astype(jnp.float32)
    return {"Accuracy": acc.reshape(1), "Correct": correct.reshape(1),
            "Total": total.reshape(1)}


@register_op("precision_recall", grad=False)
def precision_recall(ctx, ins, attrs):
    """Per-class TP/FP/TN/FN streaming stats + macro/micro P/R/F1
    (reference metrics/precision_recall_op.h: the same state layout
    [class_number, 4] and 6-element metric vectors, computed vectorized
    via one-hot outer products instead of the per-sample loop)."""
    idx = x_of(ins, "Indices").reshape(-1).astype(jnp.int32)
    label = x_of(ins, "Labels").reshape(-1).astype(jnp.int32)
    weights = x_of(ins, "Weights")
    states = x_of(ins, "StatesInfo")
    C = int(attrs["class_number"])
    w = (jnp.ones(idx.shape, jnp.float32) if weights is None
         else weights.reshape(-1).astype(jnp.float32))
    oh_p = jax.nn.one_hot(idx, C, dtype=jnp.float32)
    oh_l = jax.nn.one_hot(label, C, dtype=jnp.float32)
    tp = jnp.sum(w[:, None] * oh_p * oh_l, axis=0)
    fp = jnp.sum(w[:, None] * oh_p * (1 - oh_l), axis=0)
    fn = jnp.sum(w[:, None] * (1 - oh_p) * oh_l, axis=0)
    tn = jnp.sum(w[:, None] * (1 - oh_p) * (1 - oh_l), axis=0)
    batch = jnp.stack([tp, fp, tn, fn], axis=1)        # [C, 4]
    accum = batch if states is None else batch + states

    def metrics(s):
        tp_, fp_, fn_ = s[:, 0], s[:, 1], s[:, 3]
        p = jnp.where(tp_ + fp_ > 0, tp_ / jnp.maximum(tp_ + fp_, 1e-12), 0.0)
        r = jnp.where(tp_ + fn_ > 0, tp_ / jnp.maximum(tp_ + fn_, 1e-12), 0.0)
        f1 = jnp.where(p + r > 0, 2 * p * r / jnp.maximum(p + r, 1e-12), 0.0)
        stp, sfp, sfn = jnp.sum(tp_), jnp.sum(fp_), jnp.sum(fn_)
        mp = jnp.where(stp + sfp > 0, stp / jnp.maximum(stp + sfp, 1e-12), 0.0)
        mr = jnp.where(stp + sfn > 0, stp / jnp.maximum(stp + sfn, 1e-12), 0.0)
        mf = jnp.where(mp + mr > 0, 2 * mp * mr / jnp.maximum(mp + mr, 1e-12),
                       0.0)
        return jnp.stack([jnp.mean(p), jnp.mean(r), jnp.mean(f1), mp, mr, mf])

    return {"BatchMetrics": metrics(batch), "AccumMetrics": metrics(accum),
            "AccumStatesInfo": accum}


@register_op("auc", grad=False)
def auc(ctx, ins, attrs):
    """Streaming AUC: histogram state vars thread through the functional env
    (reference metrics/auc_op.cc keeps StatPos/StatNeg buffers in scope)."""
    predict = x_of(ins, "Predict")
    label = x_of(ins, "Label")
    stat_pos = x_of(ins, "StatPos")
    stat_neg = x_of(ins, "StatNeg")
    num_thresholds = attrs.get("num_thresholds", 4095)
    if label.ndim == 2:
        label = label[:, 0]
    pos_prob = predict[:, -1] if predict.ndim == 2 else predict
    bins = jnp.clip((pos_prob * num_thresholds).astype(jnp.int32), 0,
                    num_thresholds)
    is_pos = (label > 0).astype(stat_pos.dtype)
    pos_hist = jnp.zeros_like(stat_pos).at[bins].add(is_pos)
    neg_hist = jnp.zeros_like(stat_neg).at[bins].add(1 - is_pos)
    new_pos = stat_pos + pos_hist
    new_neg = stat_neg + neg_hist
    # AUC over accumulated histograms (trapezoid over thresholds, high->low)
    tp = jnp.cumsum(new_pos[::-1])
    fp = jnp.cumsum(new_neg[::-1])
    tot_pos = tp[-1]
    tot_neg = fp[-1]
    tp0 = jnp.concatenate([jnp.zeros(1, tp.dtype), tp[:-1]])
    fp0 = jnp.concatenate([jnp.zeros(1, fp.dtype), fp[:-1]])
    area = jnp.sum((fp - fp0) * (tp + tp0) / 2.0)
    denom = jnp.maximum(tot_pos * tot_neg, 1.0)
    auc_val = (area / denom).astype(jnp.float64
                                    if new_pos.dtype == jnp.int64
                                    else jnp.float32)
    return {"AUC": auc_val.reshape(1), "StatPosOut": new_pos,
            "StatNegOut": new_neg}
