"""Neural-net ops: conv, pool, norms, dropout, embeddings, losses.

TPU-native lowerings for the reference's dense NN operators
(/root/reference/paddle/fluid/operators/conv_op.cc, pool_op.cc,
batch_norm_op.cc, layer_norm_op.cc, dropout_op.cc, lookup_table_v2_op.cc,
softmax_with_cross_entropy_op.cc, ...). Convs lower to
lax.conv_general_dilated so XLA maps them onto the MXU; running-stat updates
of batch_norm use the functional env rebinding in place of the reference's
in-place MeanOut/VarianceOut aliasing.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import register_grad_lower, register_op
from ..framework.dtype import np_dtype
from .common import bilinear_sample, x_of, normalize_padding


# ---------------------------------------------------------------------------
# Convolutions
# ---------------------------------------------------------------------------

def _conv_nd(x, w, attrs, n_spatial, transpose=False):
    strides = tuple(attrs.get("strides", [1] * n_spatial))
    dilations = tuple(attrs.get("dilations", [1] * n_spatial))
    groups = attrs.get("groups", 1)
    algo = attrs.get("padding_algorithm", "EXPLICIT")
    if algo == "SAME":
        padding = "SAME"
    elif algo == "VALID":
        padding = "VALID"
    else:
        padding = normalize_padding(attrs.get("paddings", [0] * n_spatial),
                                    n_spatial)
    spatial = "DHW"[-n_spatial:] if n_spatial <= 3 else None
    lhs_spec = "NC" + spatial
    rhs_spec = "OI" + spatial
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape, (lhs_spec, rhs_spec, lhs_spec))
    if not transpose:
        return jax.lax.conv_general_dilated(
            x, w, strides, padding, rhs_dilation=dilations,
            dimension_numbers=dn, feature_group_count=groups,
            preferred_element_type=None)
    # conv_transpose: gradient of conv wrt input
    if padding in ("SAME", "VALID"):
        pads = None
    else:
        pads = padding
    out_pad = attrs.get("output_padding", [])
    # paddle conv2d_transpose weight layout: (C_in, C_out/groups, kh, kw)
    return _conv_transpose(x, w, strides, pads, dilations, groups, n_spatial,
                           padding, out_pad)


def _conv_transpose(x, w, strides, pads, dilations, groups, n_spatial,
                    padding, out_pad):
    # transposed conv = lhs-dilated conv with flipped kernel
    kh = w.shape[2:]
    if pads is None:
        pads = [(0, 0)] * n_spatial if padding == "VALID" else None
        if pads is None:
            raise NotImplementedError(
                "SAME padding for conv_transpose not supported; use explicit")
    tpads = []
    for i in range(n_spatial):
        eff_k = (kh[i] - 1) * dilations[i] + 1
        lo = eff_k - 1 - pads[i][0]
        hi = eff_k - 1 - pads[i][1]
        if out_pad:
            hi += out_pad[i]
        tpads.append((lo, hi))
    # w: (Cin, Cout/groups, *k) -> flip spatial, swap io -> (Cout, Cin/groups, *k)
    wf = jnp.flip(w, axis=tuple(range(2, 2 + n_spatial)))
    if groups == 1:
        wt = jnp.swapaxes(wf, 0, 1)
    else:
        cin, cog = w.shape[0], w.shape[1]
        wg = wf.reshape((groups, cin // groups, cog) + w.shape[2:])
        wt = jnp.swapaxes(wg, 1, 2).reshape((groups * cog, cin // groups) +
                                            w.shape[2:])
    spatial = "DHW"[-n_spatial:]
    lhs_spec = "NC" + spatial
    rhs_spec = "OI" + spatial
    dn = jax.lax.conv_dimension_numbers(
        x.shape, wt.shape, (lhs_spec, rhs_spec, lhs_spec))
    return jax.lax.conv_general_dilated(
        x, wt, window_strides=(1,) * n_spatial, padding=tpads,
        lhs_dilation=strides, rhs_dilation=dilations, dimension_numbers=dn,
        feature_group_count=groups)


@register_op("conv2d")
def conv2d(ctx, ins, attrs):
    x = x_of(ins, "Input")
    w = x_of(ins, "Filter")
    return {"Output": _conv_nd(x, w, attrs, 2)}


@register_op("depthwise_conv2d")
def depthwise_conv2d(ctx, ins, attrs):
    x = x_of(ins, "Input")
    w = x_of(ins, "Filter")
    attrs = dict(attrs)
    attrs["groups"] = x.shape[1]
    return {"Output": _conv_nd(x, w, attrs, 2)}


@register_op("conv3d")
def conv3d(ctx, ins, attrs):
    x = x_of(ins, "Input")
    w = x_of(ins, "Filter")
    return {"Output": _conv_nd(x, w, attrs, 3)}


@register_op("conv2d_transpose")
def conv2d_transpose(ctx, ins, attrs):
    x = x_of(ins, "Input")
    w = x_of(ins, "Filter")
    return {"Output": _conv_nd(x, w, attrs, 2, transpose=True)}


@register_op("conv3d_transpose")
def conv3d_transpose(ctx, ins, attrs):
    """reference conv_transpose_op.cc (3-D variant)."""
    x = x_of(ins, "Input")
    w = x_of(ins, "Filter")
    return {"Output": _conv_nd(x, w, attrs, 3, transpose=True)}


def _deformable_conv(ctx, ins, attrs, with_mask):
    """Deformable convolution (reference deformable_conv_op.cc — v2 with
    modulation mask, deformable_conv_v1_op.cc without): each kernel tap
    (u, v) samples the input at its regular location plus a learned
    per-position offset, bilinearly; v2 scales each tap by a learned mask.
    Layout matches the reference: Offset [B, 2*dg*kh*kw, Ho, Wo] packed
    (dy, dx) per tap, Mask [B, dg*kh*kw, Ho, Wo], deformable_groups=dg
    splits input channels."""
    x = x_of(ins, "Input")             # [B, Cin, H, W]
    offset = x_of(ins, "Offset")
    mask = x_of(ins, "Mask") if with_mask else None
    w = x_of(ins, "Filter")            # [Cout, Cin/g, kh, kw]
    B, Cin, H, W = x.shape
    Cout, _, kh, kw = w.shape
    sh, sw = attrs.get("strides", [1, 1])
    ph, pw = attrs.get("paddings", [0, 0])
    dh, dw = attrs.get("dilations", [1, 1])
    groups = int(attrs.get("groups", 1))
    dg = int(attrs.get("deformable_groups", 1))
    Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    cpg = Cin // dg                    # channels per deformable group

    oy = jnp.arange(Ho, dtype=x.dtype)[:, None] * sh - ph
    ox = jnp.arange(Wo, dtype=x.dtype)[None, :] * sw - pw
    off = offset.reshape(B, dg, kh * kw, 2, Ho, Wo)
    if mask is not None:
        msk = mask.reshape(B, dg, kh * kw, Ho, Wo)

    def sample(py, px, g):
        """Bilinear sample of deformable-group g's channels at [B,Ho,Wo]
        float coords; OOB taps contribute zero (shared bilinear_sample)."""
        seg = x[:, g * cpg:(g + 1) * cpg]
        return jax.vmap(bilinear_sample)(seg, py, px)

    cols = []                           # per-tap sampled input
    for u in range(kh):
        for v in range(kw):
            t = u * kw + v
            per_g = []
            for g in range(dg):
                py = oy[None] + u * dh + off[:, g, t, 0]
                px = ox[None] + v * dw + off[:, g, t, 1]
                s = sample(py, px, g)
                if mask is not None:
                    s = s * msk[:, g, t][:, None]
                per_g.append(s)
            cols.append(jnp.concatenate(per_g, axis=1))  # [B, Cin, Ho, Wo]
    col = jnp.stack(cols, axis=2)       # [B, Cin, kh*kw, Ho, Wo]
    cpcg = Cin // groups               # conv-group input channels
    outs = []
    for g in range(groups):
        cg = col[:, g * cpcg:(g + 1) * cpcg]
        wg = w[g * (Cout // groups):(g + 1) * (Cout // groups)]
        outs.append(jnp.einsum("bckhw,ock->bohw",
                               cg.reshape(B, cpcg, kh * kw, Ho, Wo),
                               wg.reshape(Cout // groups, cpcg, kh * kw)))
    return {"Output": jnp.concatenate(outs, axis=1)}


@register_op("deformable_conv", infer_shape=False)
def deformable_conv(ctx, ins, attrs):
    return _deformable_conv(ctx, ins, attrs, with_mask=True)


@register_op("deformable_conv_v1", infer_shape=False)
def deformable_conv_v1(ctx, ins, attrs):
    return _deformable_conv(ctx, ins, attrs, with_mask=False)


# ---------------------------------------------------------------------------
# Pooling
# ---------------------------------------------------------------------------

@register_op("pool2d")
def pool2d(ctx, ins, attrs):
    x = x_of(ins)
    ptype = attrs.get("pooling_type", "max")
    ksize = list(attrs.get("ksize", [2, 2]))
    strides = list(attrs.get("strides", ksize))
    exclusive = attrs.get("exclusive", True)
    adaptive = attrs.get("adaptive", False)
    if attrs.get("global_pooling", False) or (
            adaptive and ksize == [1, 1]):
        if ptype == "max":
            return {"Out": jnp.max(x, axis=(2, 3), keepdims=True)}
        return {"Out": jnp.mean(x, axis=(2, 3), keepdims=True)}
    if adaptive:
        n, c, h, w = x.shape
        oh, ow = ksize
        if h % oh or w % ow:
            raise NotImplementedError(
                "adaptive pool needs divisible spatial dims on TPU")
        xr = x.reshape(n, c, oh, h // oh, ow, w // ow)
        red = jnp.max if ptype == "max" else jnp.mean
        return {"Out": red(xr, axis=(3, 5))}
    algo = attrs.get("padding_algorithm", "EXPLICIT")
    if algo in ("SAME", "VALID"):
        pads = algo
    else:
        pads = ((0, 0), (0, 0)) + normalize_padding(
            attrs.get("paddings", [0, 0]), 2)
    window = (1, 1) + tuple(ksize)
    wstrides = (1, 1) + tuple(strides)
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        out = jax.lax.reduce_window(x, init, jax.lax.max, window, wstrides,
                                    pads)
        return {"Out": out}
    ssum = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, wstrides, pads)
    if exclusive and pads != "VALID":
        # divide border windows by the count of real (non-padded) elements
        ones = jnp.ones_like(x)
        cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, wstrides,
                                    pads)
        return {"Out": ssum / cnt}
    return {"Out": ssum / float(np.prod(ksize))}


def _max_pool_with_index(x, ksize, strides, pads, n_spatial):
    """Max pooling that also returns each window's argmax as a flat index
    into the spatial dims (reference pool_with_index_op.cc). Built on
    dilated patches: [B, C*prod(k), *out] -> max + argmax per window, with
    the patch-local argmax mapped back to global coordinates."""
    B, C = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    patches = jax.lax.conv_general_dilated_patches(
        x, tuple(ksize), tuple(strides), list(zip(pads, pads)))
    out_sp = patches.shape[2:]
    K = int(np.prod(ksize))
    p = patches.reshape((B, C, K) + out_sp)
    # taps that fell in the zero-padding must not win the max
    valid = np.ones((K,) + out_sp, bool)
    for k in range(K):
        loc_k = np.unravel_index(k, tuple(ksize))
        ok = np.ones(out_sp, bool)
        for d in range(n_spatial):
            o = np.arange(out_sp[d])
            coord = o * strides[d] - pads[d] + loc_k[d]
            in_range = (coord >= 0) & (coord < spatial[d])
            shape = [1] * n_spatial
            shape[d] = out_sp[d]
            ok &= in_range.reshape(shape)
        valid[k] = ok
    p = jnp.where(jnp.asarray(valid)[None, None], p, -jnp.inf)
    out = jnp.max(p, axis=2)
    arg = jnp.argmax(p, axis=2).astype(jnp.int32)       # patch-local
    # map patch-local index -> global flat spatial index
    loc = jnp.unravel_index(arg, tuple(ksize))
    flat = jnp.zeros_like(arg)
    mul = 1
    for d in reversed(range(n_spatial)):
        o = jnp.arange(out_sp[d], dtype=jnp.int32)
        shape = [1] * arg.ndim
        shape[2 + d] = out_sp[d]
        start = (o * strides[d] - pads[d]).reshape(shape)
        flat = flat + (start + loc[d]) * mul
        mul *= spatial[d]
    return out, flat


@register_op("max_pool2d_with_index", infer_shape=False)
def max_pool2d_with_index(ctx, ins, attrs):
    x = x_of(ins)
    ksize = list(attrs.get("ksize", [2, 2]))
    strides = list(attrs.get("strides", ksize))
    pads = list(attrs.get("paddings", [0, 0]))
    out, idx = _max_pool_with_index(x, ksize, strides, pads, 2)
    return {"Out": out, "Mask": idx}


@register_op("max_pool3d_with_index", infer_shape=False)
def max_pool3d_with_index(ctx, ins, attrs):
    x = x_of(ins)
    ksize = list(attrs.get("ksize", [2, 2, 2]))
    strides = list(attrs.get("strides", ksize))
    pads = list(attrs.get("paddings", [0, 0, 0]))
    out, idx = _max_pool_with_index(x, ksize, strides, pads, 3)
    return {"Out": out, "Mask": idx}


@register_op("unpool", infer_shape=False)
def unpool(ctx, ins, attrs):
    """Max unpooling (reference unpool_op.cc): scatter x's values back to
    the positions recorded by max_pool2d_with_index's Mask; everything else
    zero. Output spatial size from attr unpooled_height/width (or ksize
    inference is the caller's job)."""
    x = x_of(ins)                      # [B, C, h, w]
    idx = x_of(ins, "Indices").astype(jnp.int32)
    B, C, h, w = x.shape
    H = int(attrs["unpooled_height"])
    W = int(attrs["unpooled_width"])
    flat_out = jnp.zeros((B, C, H * W), x.dtype)
    out = flat_out.at[
        jnp.arange(B)[:, None, None],
        jnp.arange(C)[None, :, None],
        idx.reshape(B, C, h * w)].add(x.reshape(B, C, h * w), mode="drop")
    return {"Out": out.reshape(B, C, H, W)}


@register_op("affine_grid", infer_shape=False)
def affine_grid(ctx, ins, attrs):
    """2-D affine sampling grid from theta [B, 2, 3] (reference
    affine_grid_op.cc): output [B, H, W, 2] of (x, y) coords in [-1, 1]
    space, ready for grid_sampler."""
    theta = x_of(ins, "Theta")
    H, W = attrs["output_shape"][-2:]
    ys = jnp.linspace(-1.0, 1.0, H, dtype=theta.dtype)
    xs = jnp.linspace(-1.0, 1.0, W, dtype=theta.dtype)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)   # [H, W, 3]
    grid = jnp.einsum("hwk,bok->bhwo", base, theta)          # [B, H, W, 2]
    return {"Output": grid}


# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------

@register_op("batch_norm")
def batch_norm(ctx, ins, attrs):
    """Reference: operators/batch_norm_op.cc. Running stats flow through the
    functional env (MeanOut/VarianceOut rebind the Mean/Variance names)."""
    x = x_of(ins)
    scale = x_of(ins, "Scale")
    bias = x_of(ins, "Bias")
    mean = x_of(ins, "Mean")
    var = x_of(ins, "Variance")
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = attrs.get("is_test", False)
    use_global = attrs.get("use_global_stats", False)
    layout = attrs.get("data_layout", "NCHW")
    caxis = 1 if layout == "NCHW" else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != caxis)
    bshape = [1] * x.ndim
    bshape[caxis] = x.shape[caxis]

    if is_test or use_global:
        m, v = mean, var
        mean_out, var_out = mean, var
        saved_m, saved_v = mean, jax.lax.rsqrt(var + eps)
    else:
        # statistics ALWAYS accumulate in fp32 (a bf16 mean over a
        # 224x224 batch loses whole digits) — the normalize stays in
        # x.dtype, so bf16 AMP can whitelist batch_norm and keep
        # activation traffic half-width (the BN-between-convs cast
        # round-trip is the dominant HBM cost of AMP resnet otherwise)
        xs = x.astype(jnp.float32)
        m = jnp.mean(xs, axis=axes)
        v = jnp.var(xs, axis=axes)
        mean_out = mean * momentum + m.astype(mean.dtype) * (1 - momentum)
        var_out = var * momentum + v.astype(var.dtype) * (1 - momentum)
        saved_m, saved_v = m, jax.lax.rsqrt(v + eps)
    # normalize with the fp32 rsqrt already in saved_v (downcasting v to
    # bf16 before rsqrt would throw away the fp32-stats precision)
    xm = (x - m.reshape(bshape).astype(x.dtype)) * \
        saved_v.reshape(bshape).astype(x.dtype)
    y = xm * scale.reshape(bshape).astype(x.dtype) + \
        bias.reshape(bshape).astype(x.dtype)
    return {"Y": y, "MeanOut": mean_out, "VarianceOut": var_out,
            "SavedMean": saved_m, "SavedVariance": saved_v}


@register_op("sync_batch_norm")
def sync_batch_norm(ctx, ins, attrs):
    """Cross-replica BN (reference: operators/sync_batch_norm_op.cu — NCCL
    allreduce of mean/var inside the kernel). Under GSPMD the batch axis is a
    mesh dim, so plain jnp.mean over the batch IS the cross-replica mean —
    XLA inserts the all-reduce. Identical lowering to batch_norm."""
    return batch_norm(ctx, ins, attrs)


@register_op("layer_norm")
def layer_norm(ctx, ins, attrs):
    """Stats always accumulate in fp32 (in-register — XLA fuses the
    upcast into the reduction), output in the input dtype. This makes
    bf16-resident layer_norm numerically safe, so AMP can keep LN
    activations in bf16 instead of spilling fp32 copies to HBM."""
    x = x_of(ins)
    scale = x_of(ins, "Scale")
    bias = x_of(ins, "Bias")
    eps = attrs.get("epsilon", 1e-5)
    begin = attrs.get("begin_norm_axis", 1)
    axes = tuple(range(begin, x.ndim))
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=axes, keepdims=True)
    v = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - m) * jax.lax.rsqrt(v + eps)
    norm_shape = x.shape[begin:]
    if scale is not None:
        y = y * scale.astype(jnp.float32).reshape(
            (1,) * begin + norm_shape)
    if bias is not None:
        y = y + bias.astype(jnp.float32).reshape(
            (1,) * begin + norm_shape)
    lead = x.shape[:begin]
    return {"Y": y.astype(x.dtype), "Mean": m.reshape(lead),
            "Variance": v.reshape(lead)}


@register_op("instance_norm")
def instance_norm(ctx, ins, attrs):
    x = x_of(ins)
    scale = x_of(ins, "Scale")
    bias = x_of(ins, "Bias")
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    m = jnp.mean(x, axis=axes, keepdims=True)
    v = jnp.var(x, axis=axes, keepdims=True)
    y = (x - m) * jax.lax.rsqrt(v + eps)
    bshape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(bshape)
    if bias is not None:
        y = y + bias.reshape(bshape)
    return {"Y": y, "SavedMean": jnp.squeeze(m),
            "SavedVariance": jnp.squeeze(jax.lax.rsqrt(v + eps))}


@register_op("group_norm")
def group_norm(ctx, ins, attrs):
    x = x_of(ins)
    scale = x_of(ins, "Scale")
    bias = x_of(ins, "Bias")
    eps = attrs.get("epsilon", 1e-5)
    groups = attrs.get("groups", 1)
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape((n, groups, c // groups) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    m = jnp.mean(xg, axis=axes, keepdims=True)
    v = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - m) * jax.lax.rsqrt(v + eps)).reshape(x.shape)
    bshape = (1, c) + (1,) * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(bshape)
    if bias is not None:
        y = y + bias.reshape(bshape)
    return {"Y": y, "Mean": jnp.squeeze(m, axis=axes),
            "Variance": jnp.squeeze(v, axis=axes)}


# ---------------------------------------------------------------------------
# Dropout / embeddings
# ---------------------------------------------------------------------------

@register_op("dropout", needs_rng=True)
def dropout(ctx, ins, attrs):
    x = x_of(ins)
    p = attrs.get("dropout_prob", 0.5)
    is_test = attrs.get("is_test", False)
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if p == 0.0:                    # identity: skip mask generation
        return {"Out": x, "Mask": jnp.ones_like(x)}
    if is_test:
        if impl == "upscale_in_train":
            return {"Out": x, "Mask": jnp.ones_like(x)}
        return {"Out": x * (1.0 - p), "Mask": jnp.ones_like(x)}
    key = ctx.op_key(attrs)
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    mask = keep.astype(x.dtype)
    if impl == "upscale_in_train":
        out = jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    else:
        out = x * mask
    return {"Out": out, "Mask": mask}


@register_op("lookup_table_v2")
def lookup_table_v2(ctx, ins, attrs):
    w = x_of(ins, "W")
    ids = x_of(ins, "Ids")
    padding_idx = attrs.get("padding_idx", -1)
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None]
        out = jnp.where(mask, out, 0.0)
    return {"Out": out}


@register_op("lookup_table")
def lookup_table(ctx, ins, attrs):
    """v1: ids have trailing [,1] dim (reference operators/lookup_table_op.h)."""
    w = x_of(ins, "W")
    ids = x_of(ins, "Ids")
    squeeze = ids.ndim >= 2 and ids.shape[-1] == 1
    if squeeze:
        ids = ids[..., 0]
    out = jnp.take(w, ids, axis=0)
    padding_idx = attrs.get("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((ids != padding_idx)[..., None], out, 0.0)
    return {"Out": out}


@register_op("embedding")
def embedding(ctx, ins, attrs):
    return lookup_table_v2(ctx, ins, attrs)


# ---------------------------------------------------------------------------
# Losses
# ---------------------------------------------------------------------------

@register_op("cross_entropy")
def cross_entropy(ctx, ins, attrs):
    x = x_of(ins)  # probabilities (N, C)
    label = x_of(ins, "Label")
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(jnp.maximum(x, 1e-20)), axis=-1,
                        keepdims=True)
        return {"Y": loss}
    if label.ndim == x.ndim:
        label = label[..., 0]
    picked = jnp.take_along_axis(x, label[..., None].astype(jnp.int32),
                                 axis=-1)
    ignore = attrs.get("ignore_index", -100)
    loss = -jnp.log(jnp.maximum(picked, 1e-20))
    loss = jnp.where(label[..., None] == ignore, 0.0, loss)
    return {"Y": loss}


@register_op("softmax_with_cross_entropy")
def softmax_with_cross_entropy(ctx, ins, attrs):
    logits = x_of(ins, "Logits")
    label = x_of(ins, "Label")
    axis = attrs.get("axis", -1)
    soft_label = attrs.get("soft_label", False)
    logp = jax.nn.log_softmax(logits, axis=axis)
    softmax = jnp.exp(logp)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lbl = label
        if lbl.ndim == logits.ndim:
            lbl = jnp.squeeze(lbl, axis=axis)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(lbl.astype(jnp.int32), axis), axis=axis)
        loss = -picked
        ignore = attrs.get("ignore_index", -100)
        if ignore >= 0:
            loss = jnp.where(jnp.expand_dims(lbl, axis) == ignore, 0.0, loss)
    return {"Softmax": softmax, "Loss": loss}


@register_op("sigmoid_cross_entropy_with_logits")
def sigmoid_cross_entropy_with_logits(ctx, ins, attrs):
    from .common import sigmoid_bce
    x = x_of(ins)
    label = x_of(ins, "Label")
    loss = sigmoid_bce(x, label)
    ignore = attrs.get("ignore_index", -100)
    loss = jnp.where(label == ignore, 0.0, loss)
    if attrs.get("normalize", False):
        norm = jnp.maximum(jnp.sum((label != ignore).astype(x.dtype)), 1.0)
        loss = loss / norm
    return {"Out": loss}


@register_op("square_error_cost")
def square_error_cost(ctx, ins, attrs):
    x = x_of(ins)
    y = x_of(ins, "Y")
    return {"Out": jnp.square(x - y)}


@register_op("smooth_l1_loss")
def smooth_l1_loss(ctx, ins, attrs):
    x = x_of(ins)
    y = x_of(ins, "Y")
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    diff = jnp.abs(x - y)
    loss = jnp.where(diff < 1.0 / s2, 0.5 * s2 * jnp.square(diff),
                     diff - 0.5 / s2)
    return {"Out": jnp.sum(loss, axis=-1, keepdims=True),
            "Diff": x - y}


@register_op("huber_loss")
def huber_loss(ctx, ins, attrs):
    x = x_of(ins)
    y = x_of(ins, "Y")
    d = attrs.get("delta", 1.0)
    r = y - x
    loss = jnp.where(jnp.abs(r) <= d, 0.5 * jnp.square(r),
                     d * (jnp.abs(r) - 0.5 * d))
    return {"Out": loss, "Residual": r}


@register_op("log_loss")
def log_loss(ctx, ins, attrs):
    p = x_of(ins, "Predicted")
    label = x_of(ins, "Labels")
    eps = attrs.get("epsilon", 1e-4)
    loss = -label * jnp.log(p + eps) - (1 - label) * jnp.log(1 - p + eps)
    return {"Loss": loss}


@register_op("bce_loss")
def bce_loss(ctx, ins, attrs):
    x = x_of(ins)
    label = x_of(ins, "Label")
    loss = -(label * jnp.log(jnp.maximum(x, 1e-12)) +
             (1 - label) * jnp.log(jnp.maximum(1 - x, 1e-12)))
    return {"Out": loss}


@register_op("kldiv_loss")
def kldiv_loss(ctx, ins, attrs):
    x = x_of(ins)
    target = x_of(ins, "Target")
    loss = target * (jnp.log(jnp.maximum(target, 1e-12)) - x)
    red = attrs.get("reduction", "mean")
    if red == "mean":
        return {"Loss": jnp.mean(loss)}
    if red == "sum":
        return {"Loss": jnp.sum(loss)}
    if red == "batchmean":
        return {"Loss": jnp.sum(loss) / x.shape[0]}
    return {"Loss": loss}


@register_op("mse_loss")
def mse_loss(ctx, ins, attrs):
    x = x_of(ins, "Input")
    label = x_of(ins, "Label")
    return {"Out": jnp.square(x - label)}


@register_op("margin_rank_loss")
def margin_rank_loss(ctx, ins, attrs):
    x1 = x_of(ins, "X1")
    x2 = x_of(ins, "X2")
    label = x_of(ins, "Label")
    margin = attrs.get("margin", 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {"Out": out, "Activated": (out > 0).astype(x1.dtype)}


@register_op("nll_loss")
def nll_loss(ctx, ins, attrs):
    x = x_of(ins)  # log-probs (N, C)
    label = x_of(ins, "Label")
    picked = jnp.take_along_axis(x, label[:, None].astype(jnp.int32),
                                 axis=1)[:, 0]
    red = attrs.get("reduction", "mean")
    loss = -picked
    total = jnp.asarray(x.shape[0], x.dtype)
    if red == "mean":
        return {"Out": jnp.mean(loss), "Total_weight": total}
    if red == "sum":
        return {"Out": jnp.sum(loss), "Total_weight": total}
    return {"Out": loss, "Total_weight": total}


# ---------------------------------------------------------------------------
# Misc NN
# ---------------------------------------------------------------------------

@register_op("label_smooth")
def label_smooth(ctx, ins, attrs):
    x = x_of(ins)
    eps = attrs.get("epsilon", 0.1)
    dist = ins.get("PriorDist")
    if dist:
        out = (1 - eps) * x + eps * dist[0]
    else:
        out = (1 - eps) * x + eps / x.shape[-1]
    return {"Out": out}


@register_op("interp_nearest")
def interp_nearest(ctx, ins, attrs):
    x = x_of(ins)
    oh, ow = attrs["out_h"], attrs["out_w"]
    return {"Out": jax.image.resize(
        x, (x.shape[0], x.shape[1], oh, ow), method="nearest")}


@register_op("bilinear_interp")
def bilinear_interp(ctx, ins, attrs):
    x = x_of(ins)
    oh, ow = attrs["out_h"], attrs["out_w"]
    return {"Out": jax.image.resize(
        x, (x.shape[0], x.shape[1], oh, ow), method="bilinear")}


@register_op("nearest_interp")
def nearest_interp(ctx, ins, attrs):
    return interp_nearest(ctx, ins, attrs)


@register_op("bicubic_interp")
def bicubic_interp(ctx, ins, attrs):
    x = x_of(ins)
    oh, ow = attrs["out_h"], attrs["out_w"]
    return {"Out": jax.image.resize(
        x, (x.shape[0], x.shape[1], oh, ow), method="bicubic")}


@register_op("trilinear_interp")
def trilinear_interp(ctx, ins, attrs):
    x = x_of(ins)                       # [B, C, D, H, W]
    od, oh, ow = attrs["out_d"], attrs["out_h"], attrs["out_w"]
    return {"Out": jax.image.resize(
        x, (x.shape[0], x.shape[1], od, oh, ow), method="trilinear")}


@register_op("grid_sampler")
def grid_sampler(ctx, ins, attrs):
    """Bilinear sampling of x [B,C,H,W] at grid [B,Hg,Wg,2] locations in
    [-1, 1] (reference grid_sampler_op.cc, align_corners semantics;
    out-of-bounds reads contribute zero)."""
    x = x_of(ins)
    grid = x_of(ins, "Grid")
    B, C, H, W = x.shape
    gx = (grid[..., 0] + 1.0) * (W - 1) / 2.0      # [B, Hg, Wg]
    gy = (grid[..., 1] + 1.0) * (H - 1) / 2.0
    return {"Out": jax.vmap(bilinear_sample)(x, gy, gx)}


@register_op("prelu")
def prelu(ctx, ins, attrs):
    x = x_of(ins)
    alpha = x_of(ins, "Alpha")
    mode = attrs.get("mode", "all")
    if mode == "channel":
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    return {"Out": jnp.where(x > 0, x, alpha * x)}


@register_op("pixel_shuffle")
def pixel_shuffle(ctx, ins, attrs):
    x = x_of(ins)
    r = attrs.get("upscale_factor", 1)
    n, c, h, w = x.shape
    out = x.reshape(n, c // (r * r), r, r, h, w)
    out = out.transpose(0, 1, 4, 2, 5, 3).reshape(n, c // (r * r), h * r,
                                                  w * r)
    return {"Out": out}


@register_op("temporal_shift")
def temporal_shift(ctx, ins, attrs):
    x = x_of(ins)
    seg = attrs["seg_num"]
    ratio = attrs.get("shift_ratio", 0.25)
    nt, c, h, w = x.shape
    xr = x.reshape(nt // seg, seg, c, h, w)
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    pre = jnp.pad(xr[:, 1:, :c1], ((0, 0), (0, 1), (0, 0), (0, 0), (0, 0)))
    post = jnp.pad(xr[:, :-1, c1:c2], ((0, 0), (1, 0), (0, 0), (0, 0),
                                       (0, 0)))
    rest = xr[:, :, c2:]
    out = jnp.concatenate([pre, post, rest], axis=2)
    return {"Out": out.reshape(nt, c, h, w)}


@register_op("lstm_cell_fused")
def lstm_cell_fused(ctx, ins, attrs):
    """One LSTM step (reference operators/lstm_unit_op.h math; fused
    x/h projection): Gates = [X, HPrev] @ W + B split into i,f,c,o."""
    x = x_of(ins)
    h_prev = x_of(ins, "HPrev")
    c_prev = x_of(ins, "CPrev")
    w = x_of(ins, "W")            # [D+H, 4H]
    b = x_of(ins, "B")            # [4H]
    forget_bias = float(attrs.get("forget_bias", 0.0))
    gates = jnp.concatenate([x, h_prev], axis=-1) @ w + b
    i, f, c_hat, o = jnp.split(gates, 4, axis=-1)
    c = jax.nn.sigmoid(f + forget_bias) * c_prev + \
        jax.nn.sigmoid(i) * jnp.tanh(c_hat)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return {"H": h, "C": c}


@register_op("gru_cell_fused")
def gru_cell_fused(ctx, ins, attrs):
    """One GRU step (reference operators/gru_unit_op.h math, fused):
    update/reset from [X, HPrev] @ Wg; candidate from [X, r*HPrev] @ Wc."""
    x = x_of(ins)
    h_prev = x_of(ins, "HPrev")
    wg = x_of(ins, "WGate")       # [D+H, 2H]
    bg = x_of(ins, "BGate")       # [2H]
    wc = x_of(ins, "WCand")       # [D+H, H]
    bc = x_of(ins, "BCand")       # [H]
    gates = jax.nn.sigmoid(jnp.concatenate([x, h_prev], axis=-1) @ wg + bg)
    u, r = jnp.split(gates, 2, axis=-1)
    cand = jnp.tanh(jnp.concatenate([x, r * h_prev], axis=-1) @ wc + bc)
    # reference default (origin_mode=False, gru_unit_op.h): u gates the
    # CANDIDATE; origin_mode=True is the u-gates-previous variant
    if bool(attrs.get("origin_mode", False)):
        h = u * h_prev + (1.0 - u) * cand
    else:
        h = u * cand + (1.0 - u) * h_prev
    return {"H": h}


def _sparse_lookup_grad(ctx, ins, attrs):
    """Custom backward for lookup_table(_v2) honoring is_sparse: the W
    gradient is a SelectedRows (ids, rows) pair instead of a dense
    [vocab, dim] scatter (reference lookup_table_op.h emits SelectedRows
    when is_sparse=True; framework/selected_rows.py)."""
    from ..framework.selected_rows import SelectedRows

    fwd = attrs["__fwd_op__"]
    fattrs = fwd["attrs"]
    w = x_of(ins, "W")
    ids = x_of(ins, "Ids")
    g = x_of(ins, "Out@GRAD")
    if ids.ndim >= 2 and ids.shape[-1] == 1 and g.ndim == ids.ndim:
        ids = ids[..., 0]
    flat_ids = ids.reshape(-1).astype(jnp.int32)
    flat_g = g.reshape(-1, w.shape[-1])
    padding_idx = fattrs.get("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        flat_g = jnp.where((flat_ids != padding_idx)[:, None], flat_g, 0.0)
    if fattrs.get("is_sparse", False):
        wgrad = SelectedRows(flat_ids, flat_g)
    else:
        wgrad = jnp.zeros_like(w).at[flat_ids].add(
            flat_g.astype(w.dtype))
    return {"W@GRAD": [wgrad]}


register_grad_lower("lookup_table")(_sparse_lookup_grad)
register_grad_lower("lookup_table_v2")(_sparse_lookup_grad)
register_grad_lower("embedding")(_sparse_lookup_grad)


@register_op("spectral_norm")
def spectral_norm(ctx, ins, attrs):
    """Spectral weight normalization (reference spectral_norm_op.h):
    power-iterate the largest singular value with the carried U/V vectors,
    return W / sigma. U/V update functionally (UOut/VOut rebind)."""
    w = x_of(ins, "Weight")
    u = x_of(ins, "U")
    v = x_of(ins, "V")
    dim = int(attrs.get("dim", 0))
    power_iters = int(attrs.get("power_iters", 1))
    eps = float(attrs.get("eps", 1e-12))
    perm = (dim,) + tuple(i for i in range(w.ndim) if i != dim)
    mat = jnp.transpose(w, perm).reshape(w.shape[dim], -1)

    def norm(x):
        return x / (jnp.linalg.norm(x) + eps)

    for _ in range(max(power_iters, 1)):
        v = norm(mat.T @ u)
        u = norm(mat @ v)
    # U/V are constants for the backward (reference spectral_norm_grad
    # does not differentiate the power iteration)
    u = jax.lax.stop_gradient(u)
    v = jax.lax.stop_gradient(v)
    sigma = u @ (mat @ v)
    return {"Out": w / sigma, "UOut": u, "VOut": v}
