"""Control-flow ops over sub-blocks.

TPU-native replacement for the reference's control-flow operators
(/root/reference/paddle/fluid/operators/controlflow/while_op.cc,
conditional_block_op.cc, /root/reference/paddle/fluid/operators/recurrent_op.cc).
The reference runs sub-blocks through a nested Executor with step scopes; here
each sub-block lowers into the SAME traced function via jax.lax structured
control flow (while_loop / cond / scan) — no interpreter, no scope churn, and
XLA fuses across the loop boundary. Constraints inherited from XLA: carried
shapes/dtypes are fixed across iterations and bodies are traced once.

Differentiability contract: `cond` and `recurrent` declare every outer var
they read as a real op input (slots Cond/X/Boot/P), so program-level autodiff
(backward.py) emits generic vjp grad ops whose primals connect through the
lax control-flow primitives. `while` is differentiable only when built with
`max_trip_count` (the loop lowers to a bounded, predicate-masked lax.scan —
lax.while_loop itself has no reverse-mode rule); unbounded While in a grad
path raises at append_backward time (reference while_op.cc has a grad because
its executor re-runs blocks; XLA needs a static trip bound instead).
"""
import jax
import jax.numpy as jnp

from ..framework.registry import register_op
from .common import x_of


def block_writes(program, block_idx):
    """Var names written by a block's ops (incl. nested sub-blocks)."""
    names = []
    seen = set()
    blk = program.blocks[block_idx]
    for op in blk.ops:
        for n in op.output_arg_names:
            if n not in seen:
                seen.add(n)
                names.append(n)
        for key in ("sub_block", "sub_block_true", "sub_block_false"):
            sb = op.attrs.get(key)
            if sb is not None:
                for n in block_writes(program, sb):
                    if n not in seen:
                        seen.add(n)
                        names.append(n)
    return names


def _as_pred(x):
    return jnp.reshape(x, ()).astype(bool)


@register_op("while", grad=None, infer_shape=False)
def while_op(ctx, ins, attrs):
    """Carry = condition var + every var the body writes that pre-exists
    outside (loop state). Reference semantics: while_op.cc re-runs the block
    until Condition is false.

    Functional over ins (Condition + X) so the generic vjp grad works.
    Two lowerings:
      - unbounded: one lax.while_loop (forward-only);
      - attrs["max_trip_count"]: a lax.scan of that length where each step's
        writes are jnp.where-masked by the live predicate — semantically the
        same loop, but reverse-mode differentiable. Finished iterations still
        execute (masked), the price of a static trip bound on TPU.
    """
    sub = attrs["sub_block"]
    cond_name = attrs["cond_name"]
    out_names = list(attrs.get("out_names") or
                     [n for n in block_writes(ctx.program, sub)
                      if n in ctx.env])
    x_names = list(attrs.get("x_names", []))
    x_map = dict(zip(x_names, ins.get("X", [])))
    cond0 = ins["Condition"][0]
    x_map[cond_name] = cond0

    carried = list(out_names)
    if cond_name not in carried:
        carried.insert(0, cond_name)
    outer_env = dict(ctx.env)
    outer_env.update(x_map)
    carry0 = {}
    for n in carried:
        if n not in outer_env:
            raise KeyError(
                f"While loop state {n!r} has no value before the loop; "
                f"initialize it (e.g. fill_constant) before While.block()")
        carry0[n] = outer_env[n]

    def run_body(carry):
        env = dict(outer_env)
        env.update(carry)
        ctx.lower_block_ops(sub, env)
        return {n: env[n] for n in carried}

    max_trip = attrs.get("max_trip_count")
    if max_trip is not None and attrs.get("max_trip_count_auto"):
        # the bound was auto-derived at build time; re-derive against
        # the FINAL program (ops appended after the While block — e.g.
        # an outer loop mutating the bound constant — could invalidate
        # it, which must be an error, not silent truncation)
        from ..layers.control_flow import _infer_max_trip
        sub_blk = ctx.program.blocks[sub]
        parent_blk = sub_blk.parent_block
        # find the forward while op by its (unique) sub-block index —
        # attrs may be a copy here (grad lowering re-enters with the
        # fwd spec), so identity comparison would miss
        this_op = next((op for op in parent_blk.ops
                        if op.type == "while"
                        and op.attrs.get("sub_block") == sub), None)
        now = _infer_max_trip(ctx.program, parent_blk, sub_blk,
                              cond_name, stop_op=this_op)
        if now != int(max_trip):
            # the bound is consumed only by the differentiable (scan)
            # lowering: in a program with a backward pass an invalid
            # bound must be an ERROR (silent truncation corrupts
            # training, and a nested loop may be differentiated
            # implicitly through an enclosing while_grad); forward-only
            # programs just fall back to the unbounded while_loop
            has_grad = any(
                op.type.endswith("_grad")
                for blk in ctx.program.blocks for op in blk.ops)
            if has_grad:
                raise ValueError(
                    f"While: the auto-derived max_trip_count "
                    f"({max_trip}) is no longer valid in the final "
                    f"program (re-derivation gives {now}); the loop "
                    f"bound is mutated after the loop was built — pass "
                    f"max_trip_count explicitly")
            max_trip = None
    if max_trip is None:
        def cond_fn(carry):
            return _as_pred(carry[cond_name])

        def body_fn(carry):
            return run_body(carry)

        final = jax.lax.while_loop(cond_fn, body_fn, carry0)
    else:
        def step(carry, _):
            pred, state = carry
            new_state = run_body(state)
            state = {n: jnp.where(pred, new_state[n], state[n])
                     for n in carried}
            pred = jnp.logical_and(pred, _as_pred(state[cond_name]))
            return (pred, state), None

        (_, final), _ = jax.lax.scan(
            step, (_as_pred(cond0), carry0), None, length=int(max_trip))
    return {"Out": [final[n] for n in out_names]}


@register_op("cond", grad=None, infer_shape=False)
def cond_op(ctx, ins, attrs):
    """Two-branch conditional (fluid layers.cond; the reference builds two
    conditional_block ops + select_input — here it's one lax.cond).

    inputs: Cond=[pred], X=[outer vars read by either branch]
    attrs: sub_block_true/false, x_names (inner names of X), true_outs,
    false_outs (in-branch var names per output).
    """
    pred = _as_pred(x_of(ins, "Cond"))
    x_vals = list(ins.get("X", []))
    x_names = list(attrs.get("x_names", []))
    outer_env = dict(ctx.env)
    outer_env.update(zip(x_names, x_vals))

    def branch(block_idx, out_names):
        def fn(xs):
            env = dict(outer_env)
            env.update(zip(x_names, xs))
            ctx.lower_block_ops(block_idx, env)
            return tuple(env[n] for n in out_names)
        return fn

    res = jax.lax.cond(pred,
                       branch(attrs["sub_block_true"],
                              list(attrs["true_outs"])),
                       branch(attrs["sub_block_false"],
                              list(attrs["false_outs"])),
                       tuple(x_vals))
    return {"Out": list(res)}


@register_op("recurrent", grad=None, infer_shape=False)
def recurrent_op(ctx, ins, attrs):
    """StaticRNN / recurrent_op as ONE lax.scan over the time dim.

    inputs: X=[outer time-major sequences], Boot=[initial memory values],
    P=[outer vars read inside the step (weights etc.)]
    attrs: sub_block; step_input_vars (inner names for X slices); memories
    [(pre_name, post_name)] aligned with Boot; p_names (inner names for P);
    step_outputs (in-block names); is_reverse.
    Outputs "Out": stacked step outputs, time-major.
    """
    sub = attrs["sub_block"]
    step_in_inner = list(attrs["step_input_vars"])
    memories = [tuple(m) for m in attrs["memories"]]
    p_names = list(attrs.get("p_names", []))
    step_outs = list(attrs["step_outputs"])
    reverse = bool(attrs.get("is_reverse", False))

    xs = tuple(ins.get("X", []))
    carry0 = tuple(ins.get("Boot", []))
    p_vals = tuple(ins.get("P", []))

    outer_env = dict(ctx.env)

    def body(carry, x_t):
        env = dict(outer_env)
        env.update(zip(p_names, p_vals))
        env.update(zip(step_in_inner, x_t))
        for (pre, _), c in zip(memories, carry):
            env[pre] = c
        ctx.lower_block_ops(sub, env)
        new_carry = tuple(env[post] for _, post in memories)
        ys = tuple(env[n] for n in step_outs)
        return new_carry, ys

    # lax.scan(reverse=True) already returns ys position-aligned with xs
    final_carry, stacked = jax.lax.scan(body, carry0, xs, reverse=reverse)
    out = {"Out": list(stacked)}
    if memories:
        out["FinalStates"] = list(final_carry)
    return out


# ---- LoDTensorArray ops ----
# The reference's tensor-array ops (controlflow/tensor_array_read_write_op.cc)
# mutate a vector<LoDTensor> variable. Trace-time arrays here are Python
# lists living in the env (indices must be trace-time constants); inside
# scan/while use the recurrent op's stacked outputs instead.

@register_op("write_to_array", grad=False, infer_shape=False)
def write_to_array(ctx, ins, attrs):
    x = x_of(ins)
    i = int(attrs["index"])  # folded at build time (layers.array_write)
    name = attrs["array_name"]
    arr = ctx.env.get(name)
    arr = list(arr) if isinstance(arr, list) else []
    while len(arr) <= i:
        arr.append(None)
    arr[i] = x
    ctx.env[name] = arr
    return None


@register_op("read_from_array", grad=False, infer_shape=False)
def read_from_array(ctx, ins, attrs):
    arr = ctx.env[attrs["array_name"]]
    return {"Out": arr[int(attrs["index"])]}


@register_op("lod_array_length", grad=False, infer_shape=False)
def lod_array_length(ctx, ins, attrs):
    arr = ctx.env.get(attrs["array_name"], [])
    return {"Out": jnp.asarray([len(arr)], jnp.int32)}
