"""Residual long-tail op types (round-4 registry-diff closure).

References:
- teacher_student_sigmoid_loss_op.{cc,h} — distillation CTR loss
- positive_negative_pair_op.h — ranking pair metric
- similarity_focus_op.h — greedy row/col focus mask
- diag_embed_op.h — batched diagonal embed
- fill_op.{cc,h} — fill from a flat value list
- fill_zeros_like_op.cc (fill_zeros_like2: dtype-attr variant)
- uniform_random_batch_size_like_op.cc / gaussian_random_batch_size_like
  (batch_size_like.h shape contract)
- lookup_table_dequant_op.{cc,h} — uint8-packed quantized embedding
- dequantize_abs_max_op.cc, dequantize_log_op.cc — int8 dequant
- seed_op.{cc,h} — RNG seed materialization
- attention_lstm_op.cc — fused attention + LSTM CPU kernel

TPU design notes: sequence ops take the padded [B, T, ...] + Length
masked-dense form; the greedy CPU loops (similarity_focus) become
fixed-trip lax.fori with mask state; attention_lstm is one lax.scan over
time with a masked softmax over the full padded sequence per step.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import register_op
from .common import as_dtype, x_of


@register_op("teacher_student_sigmoid_loss", infer_shape=False)
def teacher_student_sigmoid_loss(ctx, ins, attrs):
    """reference teacher_student_sigmoid_loss_op.h: label encodes
    (teacher score z', click z): -2 = no-z' noclick, -1 = no-z' click,
    [0,1) = z' noclick, [1,2] = 1 + z' click. Loss is the click BCE
    term plus (when z' exists) a distillation BCE against z'."""
    x = jnp.reshape(x_of(ins), (-1,))
    label = jnp.reshape(x_of(ins, "Label"), (-1,)).astype(x.dtype)
    relu_x = jnp.maximum(x, 0.0)
    softplus = jnp.log1p(jnp.exp(-jnp.abs(x)))
    bce0 = relu_x + softplus              # -log sigmoid(-x): z = 0
    bce1 = relu_x - x + softplus          # -log sigmoid(x):  z = 1
    zprime = jnp.where(label < 1.0, label, label - 1.0)
    distill = relu_x - x * zprime + softplus
    y = jnp.where(label < -1.0, bce0,
                  jnp.where(label < 0.0, bce1,
                            jnp.where(label < 1.0, bce0 + distill,
                                      bce1 + distill)))
    return {"Y": y.reshape(-1, 1)}


@register_op("positive_negative_pair", grad=False, infer_shape=False)
def positive_negative_pair(ctx, ins, attrs):
    """reference positive_negative_pair_op.h: within each QueryID group,
    count ordered pairs whose score ranking agrees (positive) /
    disagrees (negative) with the label ranking; equal scores with
    different labels are neutral. Pair weight = mean of the two
    instance weights. O(N^2) pair masks replace the host hash-map."""
    score = x_of(ins, "Score")
    col = int(attrs.get("column", 0))   # reference SetDefault(0)
    s = score[:, col] if score.ndim == 2 else jnp.reshape(score, (-1,))
    label = jnp.reshape(x_of(ins, "Label"), (-1,)).astype(jnp.float32)
    query = jnp.reshape(x_of(ins, "QueryID"), (-1,))
    w_in = ins.get("Weight")
    w = (jnp.reshape(w_in[0], (-1,)).astype(jnp.float32) if w_in
         else jnp.ones_like(label))
    s = s.astype(jnp.float32)
    n = s.shape[0]
    same_q = query[:, None] == query[None, :]
    upper = jnp.arange(n)[:, None] < jnp.arange(n)[None, :]
    diff_label = label[:, None] != label[None, :]
    pair = same_q & upper & diff_label
    pw = 0.5 * (w[:, None] + w[None, :])
    prod = (s[:, None] - s[None, :]) * (label[:, None] - label[None, :])
    tie = s[:, None] == s[None, :]
    pos = jnp.sum(jnp.where(pair & (prod > 0), pw, 0.0))
    neg = jnp.sum(jnp.where(pair & ~(prod > 0), pw, 0.0))
    neu = jnp.sum(jnp.where(pair & tie, pw, 0.0))
    if ins.get("AccumulatePositivePair"):
        pos = pos + jnp.reshape(ins["AccumulatePositivePair"][0], ())
        neg = neg + jnp.reshape(ins["AccumulateNegativePair"][0], ())
        neu = neu + jnp.reshape(ins["AccumulateNeutralPair"][0], ())
    return {"PositivePair": pos.reshape(1), "NegativePair": neg.reshape(1),
            "NeutralPair": neu.reshape(1)}


@register_op("similarity_focus", grad=False, infer_shape=False)
def similarity_focus(ctx, ins, attrs):
    """reference similarity_focus_op.h: X [B, d1, d2, d3]; for each
    `index` slice along `axis`, greedily pick the largest entries of the
    2D slice whose row AND column are untagged (one per row/col, like
    greedy bipartite matching), and set 1 across the whole `axis` dim at
    each picked (row, col). The host sort+scan loop becomes a
    fixed-trip argmax/mask fori."""
    x = x_of(ins)
    axis = int(attrs["axis"])
    indexes = [int(i) for i in attrs["indexes"]]
    B = x.shape[0]
    if axis not in (1, 2, 3):
        raise ValueError(f"similarity_focus: axis must be 1..3, got {axis}")
    # move `axis` to position 1: slices are [B, dA, dR, dC]
    perm = {1: (0, 1, 2, 3), 2: (0, 2, 1, 3), 3: (0, 3, 1, 2)}[axis]
    xt = jnp.transpose(x, perm)
    _, dA, dR, dC = xt.shape
    npick = min(dR, dC)
    out_t = jnp.zeros(xt.shape, x.dtype)

    def one_index(out_t, index):
        sl = xt[:, index]                                  # [B, dR, dC]

        def body(t, st):
            rtag, ctag, mask = st
            live = (~rtag[:, :, None]) & (~ctag[:, None, :])
            masked = jnp.where(live, sl, -jnp.inf)
            flat = masked.reshape(B, -1)
            best = jnp.argmax(flat, axis=1)                # [B]
            r, c = best // dC, best % dC
            ok = jnp.take_along_axis(
                flat, best[:, None], axis=1)[:, 0] > -jnp.inf
            rtag = rtag.at[jnp.arange(B), r].set(
                rtag[jnp.arange(B), r] | ok)
            ctag = ctag.at[jnp.arange(B), c].set(
                ctag[jnp.arange(B), c] | ok)
            mask = mask.at[jnp.arange(B), r, c].set(
                jnp.where(ok, 1.0, mask[jnp.arange(B), r, c]))
            return rtag, ctag, mask

        rtag = jnp.zeros((B, dR), bool)
        ctag = jnp.zeros((B, dC), bool)
        mask = jnp.zeros((B, dR, dC), jnp.float32)
        _, _, mask = jax.lax.fori_loop(0, npick, body, (rtag, ctag, mask))
        # set 1 across the whole axis dim at the picked positions
        return jnp.maximum(out_t, mask[:, None, :, :].astype(x.dtype))

    for index in indexes:
        out_t = one_index(out_t, index)
    inv = {1: (0, 1, 2, 3), 2: (0, 2, 1, 3), 3: (0, 2, 3, 1)}[axis]
    return {"Out": jnp.transpose(out_t, inv)}


@register_op("diag_embed", grad=None, infer_shape=False)
def diag_embed(ctx, ins, attrs):
    """reference diag_embed_op.h: embed the last dim of X as a diagonal
    of a new 2D tail (dims dim1/dim2 of the output, offset off the main
    diagonal)."""
    x = x_of(ins, "Input")
    if x is None:
        x = x_of(ins)
    offset = int(attrs.get("offset", 0))
    dim1 = int(attrs.get("dim1", -2))
    dim2 = int(attrs.get("dim2", -1))
    n = x.shape[-1]
    size = n + abs(offset)
    eye = jnp.eye(size, k=offset, dtype=x.dtype)
    diag_rows = jnp.arange(n) + max(-offset, 0)
    # out2d[..., i + max(-off,0), :] gets x[..., i] at col i + max(off, 0)
    out = jnp.zeros(x.shape[:-1] + (size, size), x.dtype)
    out = out.at[..., diag_rows, diag_rows + offset].set(x)
    nd = out.ndim
    dim1 = dim1 % nd
    dim2 = dim2 % nd
    # move the two trailing (row, col) dims to (dim1, dim2)
    rest = [d for d in range(nd) if d not in (nd - 2, nd - 1)]
    perm = [None] * nd
    perm[dim1] = nd - 2
    perm[dim2] = nd - 1
    ri = iter(rest)
    for i in range(nd):
        if perm[i] is None:
            perm[i] = next(ri)
    return {"Out": jnp.transpose(out, perm)}


@register_op("fill", grad=False, infer_shape=False)
def fill(ctx, ins, attrs):
    """reference fill_op.h: materialize attr `value` (flat row-major
    float list) into shape/dtype."""
    shape = tuple(int(s) for s in attrs["shape"])
    dt = as_dtype(attrs)
    vals = np.asarray([float(v) for v in attrs["value"]],
                      np.float64).reshape(shape)
    return {"Out": jnp.asarray(vals.astype(dt))}


@register_op("fill_zeros_like2", grad=False, infer_shape=False)
def fill_zeros_like2(ctx, ins, attrs):
    """fill_zeros_like with an explicit dtype attr (reference
    fill_zeros_like_op.cc FillZerosLike2)."""
    x = x_of(ins)
    dt = as_dtype(attrs) if attrs.get("dtype") is not None else x.dtype
    return {"Out": jnp.zeros(x.shape, dt)}


def _batch_size_like_shape(ins, attrs):
    ref = x_of(ins, "Input")
    shape = [int(s) for s in attrs["shape"]]
    in_idx = int(attrs.get("input_dim_idx", 0))
    out_idx = int(attrs.get("output_dim_idx", 0))
    shape[out_idx] = ref.shape[in_idx]
    return tuple(shape)


@register_op("uniform_random_batch_size_like", grad=False,
             infer_shape=False, needs_rng=True)
def uniform_random_batch_size_like(ctx, ins, attrs):
    """reference uniform_random_batch_size_like_op.cc: uniform_random
    whose shape[output_dim_idx] copies Input.shape[input_dim_idx]."""
    shape = _batch_size_like_shape(ins, attrs)
    dt = as_dtype(attrs)
    key = ctx.op_key(attrs)
    return {"Out": jax.random.uniform(
        key, shape, dtype=dt, minval=attrs.get("min", -1.0),
        maxval=attrs.get("max", 1.0))}


@register_op("gaussian_random_batch_size_like", grad=False,
             infer_shape=False, needs_rng=True)
def gaussian_random_batch_size_like(ctx, ins, attrs):
    shape = _batch_size_like_shape(ins, attrs)
    dt = as_dtype(attrs)
    key = ctx.op_key(attrs)
    out = jax.random.normal(key, shape, dtype=dt)
    return {"Out": out * attrs.get("std", 1.0) + attrs.get("mean", 0.0)}


@register_op("seed", grad=False, infer_shape=False, needs_rng=True)
def seed(ctx, ins, attrs):
    """reference seed_op.h: emit attr seed if nonzero, else a random
    one (drawn from the op key here — no host RNG on device)."""
    user_seed = int(attrs.get("seed", 0))
    if user_seed != 0:
        return {"Out": jnp.full((1,), user_seed, jnp.int32)}
    key = ctx.op_key(attrs)
    return {"Out": jax.random.randint(key, (1,), 1, 2**31 - 1,
                                      dtype=jnp.int32)}


# ------------------------------------------------------- int8 dequant trio

@register_op("dequantize_abs_max", grad=False, infer_shape=False)
def dequantize_abs_max(ctx, ins, attrs):
    """reference dequantize_abs_max_op.cc: out = scale * int8_x /
    max_range."""
    x = x_of(ins).astype(jnp.float32)
    scale = jnp.reshape(x_of(ins, "Scale"), ()).astype(jnp.float32)
    max_range = float(attrs.get("max_range", 127.0))
    return {"Out": scale * x / max_range}


@register_op("dequantize_log", grad=False, infer_shape=False)
def dequantize_log(ctx, ins, attrs):
    """reference dequantize_log_op.cc: int8 codes index a 128-entry
    log2 dictionary; negative codes mirror with sign (code < 0 ->
    -2^dict[code + 128], else 2^dict[code])."""
    x = x_of(ins).astype(jnp.int32)
    dict_ = jnp.reshape(x_of(ins, "Dict"), (-1,)).astype(jnp.float32)
    idx = jnp.where(x < 0, x + 128, x)
    mag = jnp.exp2(dict_[idx])
    return {"Out": jnp.where(x < 0, -mag, mag)}


@register_op("lookup_table_dequant", infer_shape=False)
def lookup_table_dequant(ctx, ins, attrs):
    """reference lookup_table_dequant_op.h: W rows are [min, max,
    packed...] float32 where each packed float carries 4 uint8 codes;
    out[id] = (max-min)/256 * code + min, row width (cols-2)*4.
    padding_idx rows emit zeros. Differentiable w.r.t. nothing useful
    (the table is quantized storage), but Ids flow is index-only —
    registered with default grad so graphs containing it still build;
    the W cotangent is zero by construction (bitcast is int)."""
    ids = x_of(ins, "Ids").astype(jnp.int32).reshape(-1)
    w = x_of(ins, "W")
    padding_idx = int(attrs.get("padding_idx", -1))
    mins = w[:, 0]
    maxs = w[:, 1]
    packed = w[:, 2:]
    # float32 -> 4x uint8 codes, little-endian byte order (the CPU
    # kernel reinterprets the row buffer as unsigned char*)
    codes = jax.lax.bitcast_convert_type(packed, jnp.uint8)  # [R, C-2, 4]
    codes = codes.reshape(w.shape[0], -1).astype(jnp.float32)
    scale = (maxs - mins) / 256.0
    table = codes * scale[:, None] + mins[:, None]           # [R, width]
    out = table[ids]
    if padding_idx >= 0:
        out = jnp.where((ids == padding_idx)[:, None], 0.0, out)
    orig = x_of(ins, "Ids").shape
    return {"Out": out.reshape(tuple(orig[:-1]) + (out.shape[-1],))}


# ---------------------------------------------------------- attention_lstm

@register_op("attention_lstm", infer_shape=False)
def attention_lstm(ctx, ins, attrs):
    """reference attention_lstm_op.cc: per step t, attention scores over
    the whole (padded) sequence from concat(x, prev_cell) through a
    (M+D)x1 fc (+bias, relu), optional scalar rescale (+bias, relu),
    masked softmax; the pooled x feeds one LSTM step with gate order
    [forget, input, output, candidate].

    Padded form: X [B, T, M] (+ Length [B]), C0 [B, D], H0 [B, D].
    LSTMWeight [(D+M), 4D] with the HIDDEN rows first (rows [0:D] are the
    recurrent weights, rows [D:D+M] the x weights — attention_lstm_op.cc
    reads the x GEMM from lstm_w_data + D*4D), LSTMBias [1, 4D],
    AttentionWeight [(M+D), 1] (x rows first).
    Outputs Hidden/Cell [B, T, D] (zeros past each row's length)."""
    x = x_of(ins)
    c0 = x_of(ins, "C0")
    h0_in = ins.get("H0")
    aw = x_of(ins, "AttentionWeight")
    ab = ins.get("AttentionBias")
    ascal = ins.get("AttentionScalar")
    ascal_b = ins.get("AttentionScalarBias")
    lw = x_of(ins, "LSTMWeight")
    lb = x_of(ins, "LSTMBias").reshape(-1)
    B, T, M = x.shape
    D = c0.shape[1]
    lens = ins.get("Length")
    length = (jnp.reshape(lens[0], (-1,)).astype(jnp.int32) if lens
              else jnp.full((B,), T, jnp.int32))
    valid = jnp.arange(T)[None, :] < length[:, None]         # [B, T]
    h0 = h0_in[0] if h0_in else jnp.zeros_like(c0)
    aw_x, aw_c = aw[:M, 0], aw[M:, 0]                        # [M], [D]
    atted_x = x @ aw_x                                       # [B, T]
    if ab:
        atted_x = atted_x + jnp.reshape(ab[0], ())
    # reference attention_lstm_op.cc:406-410 reads the x GEMM from
    # lstm_w_data + D*D4 and the hidden GEMM from lstm_w_data — i.e. the
    # first D rows are the hidden weights, the next M rows the x weights.
    wh, wx = lw[:D], lw[D:]                                  # [D,4D],[M,4D]

    def step(carry, t):
        h_prev, c_prev = carry
        cell_bias = c_prev @ aw_c                            # [B]
        fc = jax.nn.relu(atted_x + cell_bias[:, None])       # [B, T]
        if ascal:
            fc = fc * jnp.reshape(ascal[0], ())
            if ascal_b:
                fc = fc + jnp.reshape(ascal_b[0], ())
            fc = jax.nn.relu(fc)
        fc = jnp.where(valid, fc, -jnp.inf)
        probs = jax.nn.softmax(fc, axis=1)                   # [B, T]
        lstm_x = jnp.einsum("bt,btm->bm", probs, x)          # [B, M]
        gates = lstm_x @ wx + h_prev @ wh + lb               # [B, 4D]
        f = jax.nn.sigmoid(gates[:, :D])
        i = jax.nn.sigmoid(gates[:, D:2 * D])
        o = jax.nn.sigmoid(gates[:, 2 * D:3 * D])
        cand = jnp.tanh(gates[:, 3 * D:])
        c_new = f * c_prev + i * cand
        h_new = jnp.tanh(c_new) * o
        live = valid[:, t][:, None]
        c_new = jnp.where(live, c_new, c_prev)
        h_new = jnp.where(live, h_new, h_prev)
        out_h = jnp.where(live, h_new, 0.0)
        out_c = jnp.where(live, c_new, 0.0)
        return (h_new, c_new), (out_h, out_c)

    (_, _), (hs, cs) = jax.lax.scan(step, (h0, c0), jnp.arange(T))
    hidden = jnp.transpose(hs, (1, 0, 2))                    # [B, T, D]
    cell = jnp.transpose(cs, (1, 0, 2))
    return {"Hidden": hidden, "Cell": cell}
