"""Runtime/bridge ops: TensorArray<->tensor bridges, SelectedRows
splitting, gradient-buffer coalescing, mkldnn-class int8 scale ops, the
fused in-place ABN, and run_program (the dygraph->static execution
bridge). Reference: lod_tensor_to_array_op.cc, array_to_lod_tensor_op.cc,
split_selected_rows_op.h, split_byref_op.h, coalesce_tensor_op.cc,
quantize_op.cc / dequantize_op.cc / requantize_op.cc, inplace_abn_op.cc,
run_program_op.h."""
import jax.numpy as jnp

from ..framework.registry import OPS, register_op
from .common import x_of


@register_op("lod_tensor_to_array", grad=False, infer_shape=False)
def lod_tensor_to_array(ctx, ins, attrs):
    """reference lod_tensor_to_array_op.cc: split X into a TensorArray.
    The reference splits by a rank table (dynamic-RNN machinery that the
    recurrent op subsumes here); the padded bridge splits axis 0 into T
    single-step entries stored in the env-backed array."""
    x = x_of(ins)
    name = attrs["array_name"]
    ctx.env[name] = [x[i] for i in range(x.shape[0])]
    return None


@register_op("array_to_lod_tensor", grad=False, infer_shape=False)
def array_to_lod_tensor(ctx, ins, attrs):
    """reference array_to_lod_tensor_op.cc: stack the TensorArray back
    into one tensor along axis 0."""
    arr = ctx.env[attrs["array_name"]]
    return {"Out": jnp.stack(arr, axis=0)}


@register_op("split_selected_rows", grad=False, infer_shape=False)
def split_selected_rows(ctx, ins, attrs):
    """reference split_selected_rows_op.h: route rows to per-section
    outputs by global row id (height_sections give each shard's height).
    Padded: every output keeps the input's [N] slots; out-of-section
    slots get row id -1 and zero values."""
    from ..framework.selected_rows import SelectedRows, is_selected_rows
    x = ins["X"][0]
    if not is_selected_rows(x):
        raise ValueError("split_selected_rows expects a SelectedRows input")
    sections = [int(s) for s in attrs["height_sections"]]
    outs = []
    lo = 0
    for h in sections:
        hi = lo + h
        keep = (x.rows >= lo) & (x.rows < hi)
        rows = jnp.where(keep, x.rows - lo, -1)
        vals = jnp.where(
            keep.reshape((-1,) + (1,) * (x.values.ndim - 1)),
            x.values, 0)
        outs.append(SelectedRows(rows=rows.astype(jnp.int32),
                                 values=vals))
        lo = hi
    return {"Out": outs}


@register_op("split_byref", grad=False, infer_shape=False)
def split_byref(ctx, ins, attrs):
    """reference split_byref_op.h: split axis 0 by sections (the PS
    transpiler's zero-copy split; a real split here — XLA owns memory)."""
    x = x_of(ins)
    sections = attrs.get("sections")
    if sections:
        sizes = [int(s) for s in sections]
    else:
        n = int(attrs.get("num", 1))
        sizes = [x.shape[0] // n] * n
    outs, off = [], 0
    for s in sizes:
        outs.append(x[off:off + s])
        off += s
    return {"Out": outs}


@register_op("coalesce_tensor", grad=False, infer_shape=False)
def coalesce_tensor(ctx, ins, attrs):
    """reference coalesce_tensor_op.cc: pack a var list into one
    contiguous buffer (gradient-fusion machinery). XLA owns layout, so
    FusedOutput is a real concat of the flattened inputs and Output
    passes the inputs through (set_constant fills both)."""
    xs = [jnp.asarray(v) for v in ins["Input"]]
    if bool(attrs.get("set_constant", False)):
        c = float(attrs.get("constant", 0.0))
        xs = [jnp.full_like(v, c) for v in xs]
    fused = jnp.concatenate([v.reshape(-1) for v in xs])
    return {"Output": xs, "FusedOutput": fused}


@register_op("quantize", grad=False, infer_shape=False)
def quantize(ctx, ins, attrs):
    """reference quantize_op.cc (mkldnn int8 entry): out = round(x *
    Scale), saturated to int8 (uint8 when is_negative_input=False)."""
    x = x_of(ins, "Input")
    scale = float(attrs.get("Scale", 1.0))
    signed = bool(attrs.get("is_negative_input", True))
    y = jnp.round(x * scale)
    if signed:
        return {"Output": jnp.clip(y, -128, 127).astype(jnp.int8)}
    return {"Output": jnp.clip(y, 0, 255).astype(jnp.uint8)}


@register_op("dequantize", grad=False, infer_shape=False)
def dequantize(ctx, ins, attrs):
    """reference dequantize_op.cc: out = x / Scale as float32."""
    x = x_of(ins, "Input")
    scale = float(attrs.get("Scale", 1.0))
    return {"Output": x.astype(jnp.float32) / scale}


@register_op("requantize", grad=False, infer_shape=False)
def requantize(ctx, ins, attrs):
    """reference requantize_op.cc: rescale int8 by Scale_out/Scale_in."""
    x = x_of(ins, "Input")
    s_in = float(attrs.get("Scale_in", 1.0))
    s_out = float(attrs.get("Scale_out", 1.0))
    y = jnp.round(x.astype(jnp.float32) * (s_out / s_in))
    return {"Output": jnp.clip(y, -128, 127).astype(jnp.int8)}


@register_op("inplace_abn", infer_shape=False)
def inplace_abn(ctx, ins, attrs):
    """reference inplace_abn_op.cc: batch_norm fused with its activation
    (identity/leaky_relu/elu). In-place-ness is XLA's concern (buffer
    donation); numerically it is batch_norm + activation."""
    out = OPS["batch_norm"].lower(ctx, ins, attrs)
    act = attrs.get("activation", "identity")
    y = out["Y"]
    if act == "leaky_relu":
        alpha = float(attrs.get("alpha", 0.01))
        y = jnp.where(y >= 0, y, alpha * y)
    elif act == "elu":
        alpha = float(attrs.get("alpha", 1.0))
        y = jnp.where(y >= 0, y, alpha * (jnp.exp(y) - 1.0))
    elif act not in ("identity", ""):
        raise NotImplementedError(f"inplace_abn activation {act!r}")
    out["Y"] = y
    return out


@register_op("run_program", grad=False, infer_shape=False)
def run_program(ctx, ins, attrs):
    """reference run_program_op.h (the @declarative/dygraph->static
    bridge): execute a sub-block against the current env. Inputs X bind
    to attrs['x_names']; Params are already in the env by name; outputs
    listed in attrs['out_names'] come back in order."""
    sub = attrs["sub_block"]
    x_names = list(attrs.get("x_names", []))
    out_names = list(attrs.get("out_names", []))
    env = dict(ctx.env)
    env.update(dict(zip(x_names, ins.get("X", []))))
    for name, v in zip(attrs.get("param_names", []),
                       ins.get("Params", [])):
        env[name] = v
    ctx.lower_block_ops(sub, env)
    return {"Out": [env[n] for n in out_names]}
