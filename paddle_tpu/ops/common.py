"""Shared helpers for op lowerings."""
import jax.numpy as jnp

from ..framework.dtype import np_dtype


def x_of(ins, slot="X"):
    v = ins.get(slot)
    return v[0] if v else None


def int64_t():
    """Canonical device dtype for a fluid `int64` tensor.

    Int64 policy (see PARITY.md): TPU vector units are 32-bit; with
    jax_enable_x64 off (the default) int64 device tensors are stored
    int32 — deliberately and silently HERE (values are op-internal
    indices/counts that provably fit), while user-fed int64 data is
    validated at the executor feed boundary and raises on overflow
    instead of wrapping (framework/executor.py). Enabling
    jax_enable_x64 restores true int64 end to end."""
    import jax
    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32


def as_dtype(attrs, key="dtype", default="float32"):
    """Resolve an op's dtype attr to the device dtype. Int64 policy
    (PARITY.md): with x64 off, attr-requested (u)int64 storage maps to
    32-bit — op outputs are indices/counts that fit; user-fed int64 is
    range-checked at the executor feed boundary instead."""
    dt = np_dtype(attrs.get(key, default))
    import numpy as np
    if dt in (np.int64, np.uint64):
        import jax
        if not jax.config.jax_enable_x64:
            return np.int32 if dt == np.int64 else np.uint32
    return dt


def host_concrete(*vals):
    """True when every value is host-resident (numpy / python scalar).

    Shape arithmetic stays on host: the `shape` op emits a numpy array
    (a tensor's shape is trace-time metadata, not device data), and the
    scalar-arithmetic lowerings below preserve numpy-ness so dims
    flowing into ShapeTensorList inputs (reshape/fill_constant) remain
    concrete ints at lowering. Mirrors the reference, which computes
    shapes on CPU (reshape_op.cc reads its ShapeTensor host-side)."""
    import numpy as _np
    return all(v is None or isinstance(v, (_np.ndarray, _np.generic,
                                           int, float, bool))
               for v in vals)


def bcast_y(x, y, axis):
    """Fluid elementwise broadcast: Y's shape matches a contiguous slice of
    X's shape starting at `axis` (reference:
    operators/elementwise/elementwise_op_function.h). axis=-1 means align to
    the trailing dims (numpy broadcasting)."""
    if x.ndim == y.ndim:
        return y
    if axis is None or axis == -1:
        axis = x.ndim - y.ndim
    # strip trailing size-1 dims fluid allows on Y
    yshape = list(y.shape)
    while len(yshape) > 0 and len(yshape) + axis > x.ndim and yshape[-1] == 1:
        yshape.pop()
    n_trail = x.ndim - axis - len(yshape)
    return y.reshape(tuple(yshape) + (1,) * n_trail)


def reduce_axes(attrs, ndim):
    if attrs.get("reduce_all", False):
        return tuple(range(ndim)), bool(attrs.get("keep_dim", False))
    dim = attrs.get("dim", [0])
    if isinstance(dim, int):
        dim = [dim]
    axes = tuple(d % ndim for d in dim)
    return axes, bool(attrs.get("keep_dim", False))


def normalize_padding(paddings, n_spatial):
    """[p]*n, [ph, pw], or [ph0, ph1, pw0, pw1] -> ((lo, hi), ...)."""
    p = list(paddings)
    if len(p) == n_spatial:
        return tuple((q, q) for q in p)
    if len(p) == 2 * n_spatial:
        return tuple((p[2 * i], p[2 * i + 1]) for i in range(n_spatial))
    if len(p) == 1:
        return tuple((p[0], p[0]) for _ in range(n_spatial))
    raise ValueError(f"bad paddings {paddings}")


def bilinear_sample(img, yy, xx):
    """Bilinear sample img [C, H, W] at float coords yy/xx (same shape);
    taps outside the image contribute ZERO (the convention every sampling
    op here shares — grid_sampler, deformable_conv, prroi_pool)."""
    H, W = img.shape[-2], img.shape[-1]
    y0 = jnp.floor(yy)
    x0 = jnp.floor(xx)
    wy = yy - y0
    wx = xx - x0
    out = 0.0
    for (ys, xs, wgt) in ((y0, x0, (1 - wy) * (1 - wx)),
                          (y0, x0 + 1, (1 - wy) * wx),
                          (y0 + 1, x0, wy * (1 - wx)),
                          (y0 + 1, x0 + 1, wy * wx)):
        ok = (ys >= 0) & (ys < H) & (xs >= 0) & (xs < W)
        yi = jnp.clip(ys, 0, H - 1).astype(jnp.int32)
        xi = jnp.clip(xs, 0, W - 1).astype(jnp.int32)
        v = img[..., yi, xi]                      # [C, *coords]
        out = out + v * (wgt * ok.astype(img.dtype))
    return out


def compact_rows(x, keep):
    """Compact kept rows to a zero-padded prefix (masked-dense idiom shared
    by split_lod_tensor, split_ids, sequence_erase): returns
    (out_like_x, count) where out[:count] are x's rows with keep==True in
    order and the tail is zero."""
    keep = keep.astype(bool)
    pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    dest = jnp.where(keep, pos, x.shape[0])
    out = jnp.zeros_like(x).at[dest].set(x, mode="drop")
    return out, jnp.sum(keep, dtype=jnp.int32)


def sigmoid_bce(logit, label):
    """Numerically stable sigmoid binary cross-entropy (shared by
    sigmoid_cross_entropy_with_logits and yolov3_loss)."""
    return (jnp.maximum(logit, 0) - logit * label
            + jnp.log1p(jnp.exp(-jnp.abs(logit))))


def roi_batch_indices(ins, n_rois):
    """Per-ROI image index from the optional RoisBatch ([R] explicit) or
    RoisNum ([B] counts) inputs; all-zero when neither is given. Shared
    by every roi-consuming op (roi_align, psroi family, perspective
    transform, roi_pool)."""
    import jax.numpy as jnp
    if ins.get("RoisBatch"):
        return jnp.reshape(ins["RoisBatch"][0], (-1,)).astype(jnp.int32)
    if ins.get("RoisNum"):
        counts = jnp.reshape(ins["RoisNum"][0], (-1,)).astype(jnp.int32)
        ends = jnp.cumsum(counts)
        return jnp.searchsorted(
            ends, jnp.arange(n_rois, dtype=jnp.int32),
            side="right").astype(jnp.int32)
    return jnp.zeros((n_rois,), jnp.int32)
