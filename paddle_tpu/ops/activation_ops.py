"""Activation ops.

TPU-native lowerings for the reference's activation functor registry
(/root/reference/paddle/fluid/operators/activation_op.cc — dozens of
activations registered via functors with hand-written grads). Here each is a
one-line jnp/jax.nn expression; XLA fuses them into surrounding matmuls on the
VPU, and backward comes from the generic vjp path.
"""
import jax
import jax.numpy as jnp

from ..framework.registry import register_op
from .common import x_of


def _act(name, fn, grad=None):
    @register_op(name, grad=grad)
    def _op(ctx, ins, attrs, _fn=fn):
        return {"Out": _fn(x_of(ins), attrs)}
    return _op


_act("relu", lambda x, a: jax.nn.relu(x))
_act("sigmoid", lambda x, a: jax.nn.sigmoid(x))
_act("tanh", lambda x, a: jnp.tanh(x))
_act("exp", lambda x, a: jnp.exp(x))
_act("log", lambda x, a: jnp.log(x))
_act("log2", lambda x, a: jnp.log2(x))
_act("log10", lambda x, a: jnp.log10(x))
_act("log1p", lambda x, a: jnp.log1p(x))
_act("sqrt", lambda x, a: jnp.sqrt(x))
_act("rsqrt", lambda x, a: jax.lax.rsqrt(x))
_act("square", lambda x, a: jnp.square(x))
_act("abs", lambda x, a: jnp.abs(x))
_act("reciprocal", lambda x, a: 1.0 / x)
_act("floor", lambda x, a: jnp.floor(x), grad=False)
_act("ceil", lambda x, a: jnp.ceil(x), grad=False)
_act("round", lambda x, a: jnp.round(x), grad=False)
_act("sign", lambda x, a: jnp.sign(x), grad=False)
_act("sin", lambda x, a: jnp.sin(x))
_act("cos", lambda x, a: jnp.cos(x))
_act("tan", lambda x, a: jnp.tan(x))
_act("asin", lambda x, a: jnp.arcsin(x))
_act("acos", lambda x, a: jnp.arccos(x))
_act("atan", lambda x, a: jnp.arctan(x))
_act("sinh", lambda x, a: jnp.sinh(x))
_act("cosh", lambda x, a: jnp.cosh(x))
_act("erf", lambda x, a: jax.lax.erf(x))
_act("softplus", lambda x, a: jax.nn.softplus(x))
_act("softsign", lambda x, a: jax.nn.soft_sign(x))
_act("logsigmoid", lambda x, a: jax.nn.log_sigmoid(x))
_act("tanh_shrink", lambda x, a: x - jnp.tanh(x))
_act("softshrink", lambda x, a: jnp.where(
    x > a.get("lambda", 0.5), x - a.get("lambda", 0.5),
    jnp.where(x < -a.get("lambda", 0.5), x + a.get("lambda", 0.5), 0.0)))
_act("hard_shrink", lambda x, a: jnp.where(
    jnp.abs(x) > a.get("threshold", 0.5), x, 0.0))
_act("relu6", lambda x, a: jnp.clip(x, 0.0, a.get("threshold", 6.0)))
_act("leaky_relu", lambda x, a: jax.nn.leaky_relu(x, a.get("alpha", 0.02)))
_act("elu", lambda x, a: jax.nn.elu(x, a.get("alpha", 1.0)))
_act("selu", lambda x, a: jax.nn.selu(x))
_act("gelu", lambda x, a: jax.nn.gelu(x, approximate=a.get("approximate",
                                                           False)))
_act("swish", lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x))
_act("silu", lambda x, a: jax.nn.silu(x))
_act("mish", lambda x, a: x * jnp.tanh(jax.nn.softplus(x)))
_act("hard_sigmoid", lambda x, a: jnp.clip(
    a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0))
_act("hard_swish", lambda x, a: x * jnp.clip(
    x + a.get("offset", 3.0), 0.0, a.get("threshold", 6.0)) /
    a.get("scale", 6.0))
_act("brelu", lambda x, a: jnp.clip(x, a.get("t_min", 0.0),
                                    a.get("t_max", 24.0)))
_act("stanh", lambda x, a: a.get("scale_b", 1.7159) *
     jnp.tanh(a.get("scale_a", 0.67) * x))
_act("thresholded_relu", lambda x, a: jnp.where(
    x > a.get("threshold", 1.0), x, 0.0))
_act("expm1", lambda x, a: jnp.expm1(x))


@register_op("pow")
def pow_op(ctx, ins, attrs):
    x = x_of(ins)
    f = ins.get("FactorTensor")
    factor = f[0] if f else attrs.get("factor", 1.0)
    return {"Out": jnp.power(x, factor)}


@register_op("softmax")
def softmax(ctx, ins, attrs):
    x = x_of(ins)
    return {"Out": jax.nn.softmax(x, axis=attrs.get("axis", -1))}


@register_op("log_softmax")
def log_softmax(ctx, ins, attrs):
    x = x_of(ins)
    return {"Out": jax.nn.log_softmax(x, axis=attrs.get("axis", -1))}


@register_op("maxout")
def maxout(ctx, ins, attrs):
    """reference maxout_op.h: channel groups along `axis` (1=NCHW,
    -1/3=NHWC)."""
    x = x_of(ins)
    groups = attrs["groups"]
    axis = int(attrs.get("axis", 1)) % x.ndim
    c = x.shape[axis]
    shape = (x.shape[:axis] + (c // groups, groups) +
             x.shape[axis + 1:])
    return {"Out": x.reshape(shape).max(axis=axis + 1)}
