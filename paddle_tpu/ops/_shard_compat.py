"""shard_map across jax versions.

Newer jax exposes ``jax.shard_map`` with a ``check_vma`` kwarg; older
releases ship ``jax.experimental.shard_map.shard_map`` where the same
knob is spelled ``check_rep``. Ops import from here so both work.
"""
try:
    from jax import shard_map  # noqa: F401  (jax >= 0.6)
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma, **kw)
