"""Detection/vision ops (core of the reference's
/root/reference/paddle/fluid/operators/detection/ family — 61 files;
implemented here: prior_box, density_prior_box, anchor_generator,
box_coder, iou_similarity, yolo_box, multiclass_nms, plus roi_align from
the top-level operators).

TPU design notes: everything is static-shape. multiclass_nms — which in
the reference emits a dynamically sized LoD result — returns a PADDED
[keep_top_k, 6] tensor per image plus a valid count (the XLA-native NMS
shape, same scheme the sequence ops use). The NMS selection loop is a
fixed-trip lax.fori over keep_top_k with IoU suppression masks — O(k*n)
dense math that XLA vectorizes, instead of the reference's per-box greedy
CPU loop (multiclass_nms_op.cc)."""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import register_op
from .common import bilinear_sample, roi_batch_indices, x_of


def _iou_matrix(a, b):
    """[N,4] x [M,4] xyxy -> [N,M] IoU."""
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * \
        jnp.maximum(a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * \
        jnp.maximum(b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


@register_op("iou_similarity", grad=False)
def iou_similarity(ctx, ins, attrs):
    """reference detection/iou_similarity_op.h."""
    x = x_of(ins)
    y = x_of(ins, "Y")
    return {"Out": _iou_matrix(x, y)}


@register_op("prior_box", grad=False, infer_shape=False)
def prior_box(ctx, ins, attrs):
    """SSD prior boxes (reference detection/prior_box_op.h): one box per
    (feature-map cell, aspect ratio/size combo) + per-box variances."""
    feat = x_of(ins, "Input")   # [N, C, H, W]
    image = x_of(ins, "Image")  # [N, C, IH, IW]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    ratios = [float(r) for r in attrs.get("aspect_ratios", [1.0])]
    flip = bool(attrs.get("flip", False))
    clip = bool(attrs.get("clip", False))
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    offset = float(attrs.get("offset", 0.5))
    H, W = int(feat.shape[2]), int(feat.shape[3])
    IH, IW = int(image.shape[2]), int(image.shape[3])
    step_w = float(attrs.get("step_w", 0.0)) or IW / W
    step_h = float(attrs.get("step_h", 0.0)) or IH / H

    full_ratios = [1.0]
    for r in ratios:
        if abs(r - 1.0) < 1e-6:
            continue
        full_ratios.append(r)
        if flip:
            full_ratios.append(1.0 / r)

    # reference prior_box_op.cc: default order is [min, ratios..., max];
    # min_max_aspect_ratios_order=True moves max right after min
    mm_order = bool(attrs.get("min_max_aspect_ratios_order", False))
    whs = []
    for si, ms in enumerate(min_sizes):
        whs.append((ms, ms))
        ratio_whs = [(ms * float(np.sqrt(r)), ms / float(np.sqrt(r)))
                     for r in full_ratios[1:]]
        max_wh = []
        if max_sizes:
            big = float(np.sqrt(ms * max_sizes[si]))
            max_wh = [(big, big)]
        if mm_order:
            whs.extend(max_wh + ratio_whs)
        else:
            whs.extend(ratio_whs + max_wh)

    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)            # [H, W]
    boxes = []
    for w, h in whs:
        boxes.append(jnp.stack([
            (cxg - w / 2) / IW, (cyg - h / 2) / IH,
            (cxg + w / 2) / IW, (cyg + h / 2) / IH], axis=-1))
    out = jnp.stack(boxes, axis=2)             # [H, W, P, 4]
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), out.shape)
    return {"Boxes": out, "Variances": var}


@register_op("density_prior_box", grad=False, infer_shape=False)
def density_prior_box(ctx, ins, attrs):
    """reference detection/density_prior_box_op.h: dense grid of shifted
    fixed-size boxes per cell."""
    feat = x_of(ins, "Input")
    image = x_of(ins, "Image")
    fixed_sizes = [float(s) for s in attrs["fixed_sizes"]]
    fixed_ratios = [float(r) for r in attrs.get("fixed_ratios", [1.0])]
    densities = [int(d) for d in attrs["densities"]]
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    offset = float(attrs.get("offset", 0.5))
    clip = bool(attrs.get("clip", False))
    H, W = int(feat.shape[2]), int(feat.shape[3])
    IH, IW = int(image.shape[2]), int(image.shape[3])
    step_w = float(attrs.get("step_w", 0.0)) or IW / W
    step_h = float(attrs.get("step_h", 0.0)) or IH / H
    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)
    boxes = []
    for size, dens in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            w = size * float(np.sqrt(ratio))
            h = size / float(np.sqrt(ratio))
            shift = size / dens
            for di in range(dens):
                for dj in range(dens):
                    c_x = cxg + (dj + 0.5) * shift - size / 2
                    c_y = cyg + (di + 0.5) * shift - size / 2
                    boxes.append(jnp.stack([
                        (c_x - w / 2) / IW, (c_y - h / 2) / IH,
                        (c_x + w / 2) / IW, (c_y + h / 2) / IH], axis=-1))
    out = jnp.stack(boxes, axis=2)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), out.shape)
    return {"Boxes": out, "Variances": var}


@register_op("anchor_generator", grad=False, infer_shape=False)
def anchor_generator(ctx, ins, attrs):
    """RPN anchors (reference detection/anchor_generator_op.h)."""
    feat = x_of(ins, "Input")
    sizes = [float(s) for s in attrs["anchor_sizes"]]
    ratios = [float(r) for r in attrs["aspect_ratios"]]
    stride = [float(s) for s in attrs["stride"]]
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    offset = float(attrs.get("offset", 0.5))
    H, W = int(feat.shape[2]), int(feat.shape[3])
    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * stride[0]
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * stride[1]
    cxg, cyg = jnp.meshgrid(cx, cy)
    anchors = []
    for r in ratios:
        for s in sizes:
            w = s * float(np.sqrt(1.0 / r))
            h = s * float(np.sqrt(r))
            anchors.append(jnp.stack([
                cxg - w / 2, cyg - h / 2, cxg + w / 2, cyg + h / 2],
                axis=-1))
    out = jnp.stack(anchors, axis=2)           # [H, W, A, 4]
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), out.shape)
    return {"Anchors": out, "Variances": var}


@register_op("box_coder", grad=False, infer_shape=False)
def box_coder(ctx, ins, attrs):
    """encode_center_size / decode_center_size (reference
    detection/box_coder_op.h)."""
    prior = x_of(ins, "PriorBox").reshape(-1, 4)
    pvar = ins.get("PriorBoxVar")
    pvar = pvar[0] if pvar else None
    tb = x_of(ins, "TargetBox")
    code_type = attrs.get("code_type", "encode_center_size")
    norm = bool(attrs.get("box_normalized", True))
    add = 0.0 if norm else 1.0
    pw = prior[:, 2] - prior[:, 0] + add
    ph = prior[:, 3] - prior[:, 1] + add
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    if pvar is not None:
        pvar = jnp.broadcast_to(jnp.reshape(pvar, (-1, 4)),
                                prior.shape)
    if code_type.startswith("encode"):
        tb = tb.reshape(-1, 4)
        tw = tb[:, 2] - tb[:, 0] + add
        th = tb[:, 3] - tb[:, 1] + add
        tcx = tb[:, 0] + tw / 2
        tcy = tb[:, 1] + th / 2
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :],
            (tcy[:, None] - pcy[None, :]) / ph[None, :],
            jnp.log(tw[:, None] / pw[None, :]),
            jnp.log(th[:, None] / ph[None, :])], axis=-1)  # [T, P, 4]
        if pvar is not None:
            out = out / pvar[None, :, :]
        return {"OutputBox": out}
    # decode: tb [P, C*4] (per-prior class codes) or [T, P, 4] (dim1
    # aligned with the priors)
    if tb.ndim == 2:
        d = tb.reshape(tb.shape[0], -1, 4)         # [P, C, 4]
        if pvar is not None:
            d = d * pvar[:, None, :]
        dcx = d[..., 0] * pw[:, None] + pcx[:, None]
        dcy = d[..., 1] * ph[:, None] + pcy[:, None]
        dw = jnp.exp(d[..., 2]) * pw[:, None]
        dh = jnp.exp(d[..., 3]) * ph[:, None]
    else:
        d = tb * pvar[None, :, :] if pvar is not None else tb
        dcx = d[..., 0] * pw[None, :] + pcx[None, :]
        dcy = d[..., 1] * ph[None, :] + pcy[None, :]
        dw = jnp.exp(d[..., 2]) * pw[None, :]
        dh = jnp.exp(d[..., 3]) * ph[None, :]
    out = jnp.stack([dcx - dw / 2 + add / 2, dcy - dh / 2 + add / 2,
                     dcx + dw / 2 - add / 2, dcy + dh / 2 - add / 2],
                    axis=-1)
    return {"OutputBox": out}


@register_op("yolo_box", grad=False, infer_shape=False)
def yolo_box(ctx, ins, attrs):
    """YOLOv3 head decode (reference detection/yolo_box_op.h)."""
    x = x_of(ins)               # [N, A*(5+C), H, W]
    img_size = x_of(ins, "ImgSize")  # [N, 2] (h, w)
    anchors = [float(a) for a in attrs["anchors"]]
    class_num = int(attrs["class_num"])
    conf_thresh = float(attrs.get("conf_thresh", 0.01))
    downsample = int(attrs.get("downsample_ratio", 32))
    N, _, H, W = x.shape
    A = len(anchors) // 2
    x = x.reshape(N, A, 5 + class_num, H, W)
    grid_x = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    grid_y = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
    in_w, in_h = W * downsample, H * downsample
    bx = (jax.nn.sigmoid(x[:, :, 0]) + grid_x) / W
    by = (jax.nn.sigmoid(x[:, :, 1]) + grid_y) / H
    bw = jnp.exp(x[:, :, 2]) * aw / in_w
    bh = jnp.exp(x[:, :, 3]) * ah / in_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    keep = conf > conf_thresh
    img_h = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    img_w = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    boxes = jnp.stack([(bx - bw / 2) * img_w, (by - bh / 2) * img_h,
                       (bx + bw / 2) * img_w, (by + bh / 2) * img_h],
                      axis=-1)                     # [N, A, H, W, 4]
    boxes = jnp.where(keep[..., None], boxes, 0.0)
    probs = jnp.where(keep[:, :, None], probs, 0.0)
    boxes = boxes.reshape(N, -1, 4)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(N, -1, class_num)
    return {"Boxes": boxes, "Scores": scores}


@register_op("multiclass_nms2", grad=False, infer_shape=False)
@register_op("multiclass_nms", grad=False, infer_shape=False)
def multiclass_nms(ctx, ins, attrs):
    """Per-class greedy NMS + cross-class top-k (reference
    detection/multiclass_nms_op.cc). Static-shape result: Out is
    [N, keep_top_k, 6] = (class, score, x1, y1, x2, y2) padded with
    class=-1 rows; NmsRoisNum gives the valid counts."""
    bboxes = x_of(ins, "BBoxes")      # [N, M, 4]
    scores = x_of(ins, "Scores")      # [N, C, M]
    score_thresh = float(attrs.get("score_threshold", 0.05))
    nms_thresh = float(attrs.get("nms_threshold", 0.3))
    nms_top_k = int(attrs.get("nms_top_k", 64))
    keep_top_k = int(attrs.get("keep_top_k", 16))
    background = int(attrs.get("background_label", 0))
    N, C, M = scores.shape
    nms_top_k = min(nms_top_k if nms_top_k > 0 else M, M)
    fg_classes = [c for c in range(C) if c != background]
    if not fg_classes:
        raise ValueError(
            f"multiclass_nms: no foreground class (class_num={C}, "
            f"background_label={background})")
    if keep_top_k <= 0:              # reference sentinel: keep everything
        keep_top_k = len(fg_classes) * nms_top_k

    def per_image(boxes, sc):
        # per class: take nms_top_k by score, greedy-suppress by IoU
        all_scores = []
        all_boxes = []
        all_cls = []
        all_idx = []
        for c in fg_classes:
            s = sc[c]
            top_s, top_i = jax.lax.top_k(s, nms_top_k)
            b = boxes[top_i]
            iou = _iou_matrix(b, b)
            alive = top_s > score_thresh

            def body(i, alive):
                # suppress anything overlapping an earlier live box
                sup = jnp.logical_and(alive[i], iou[i] > nms_thresh)
                sup = sup.at[i].set(False)
                later = jnp.arange(nms_top_k) > i
                return jnp.where(jnp.logical_and(sup, later),
                                 False, alive)

            alive = jax.lax.fori_loop(0, nms_top_k, body, alive)
            all_scores.append(jnp.where(alive, top_s, -1.0))
            all_boxes.append(b)
            all_cls.append(jnp.full((nms_top_k,), c, jnp.float32))
            all_idx.append(top_i.astype(jnp.int32))
        cat_s = jnp.concatenate(all_scores)
        cat_b = jnp.concatenate(all_boxes, axis=0)
        cat_c = jnp.concatenate(all_cls)
        cat_i = jnp.concatenate(all_idx)
        k = min(keep_top_k, cat_s.shape[0])
        fin_s, fin_i = jax.lax.top_k(cat_s, k)
        valid = fin_s > score_thresh
        rows = jnp.concatenate([
            jnp.where(valid, cat_c[fin_i], -1.0)[:, None],
            jnp.where(valid, fin_s, 0.0)[:, None],
            jnp.where(valid[:, None], cat_b[fin_i], 0.0)], axis=1)
        # original box index of each kept row (-1 pads) — the v2
        # (multiclass_nms2) Index output
        index = jnp.where(valid, cat_i[fin_i], -1)
        return rows, jnp.sum(valid.astype(jnp.int32)), index

    rows, counts, index = jax.vmap(per_image)(bboxes, scores)
    return {"Out": rows, "NmsRoisNum": counts,
            "Index": index[:, :, None]}


@register_op("roi_align", infer_shape=False)
def roi_align(ctx, ins, attrs):
    """ROI Align (reference operators/roi_align_op.h): bilinear-sampled
    average pooling of each ROI; differentiable w.r.t. X."""
    x = x_of(ins)                 # [N, C, H, W]
    rois = x_of(ins, "ROIs")      # [R, 4] xyxy in input scale
    pooled_h = int(attrs.get("pooled_height", 1))
    pooled_w = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    N, C, H, W = x.shape
    sampling = int(attrs.get("sampling_ratio", -1))
    if sampling <= 0:
        # reference adaptive default: ceil(roi_size / pooled_size) samples
        # per bin, computed PER ROI. Static shapes need one count; use the
        # worst case over the feature map (full-image ROI)
        sampling = max(int(np.ceil(H / pooled_h)),
                       int(np.ceil(W / pooled_w)), 1)
        sampling = min(sampling, 8)   # cap the static cost
    R = rois.shape[0]
    batch_idx = roi_batch_indices(ins, R)

    def one_roi(roi, bi):
        x1, y1, x2, y2 = roi * scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / pooled_w
        bin_h = rh / pooled_h
        # sampling grid [ph, pw, s, s, 2]
        py = jnp.arange(pooled_h, dtype=jnp.float32)
        px = jnp.arange(pooled_w, dtype=jnp.float32)
        sy = (jnp.arange(sampling, dtype=jnp.float32) + 0.5) / sampling
        ys = y1 + (py[:, None] + sy[None, :]) * bin_h        # [ph, s]
        xs = x1 + (px[:, None] + sy[None, :]) * bin_w        # [pw, s]

        def bilinear(img, yy, xx):
            y0 = jnp.clip(jnp.floor(yy), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, W - 1)
            y1i = jnp.clip(y0 + 1, 0, H - 1)
            x1i = jnp.clip(x0 + 1, 0, W - 1)
            wy = yy - y0
            wx = xx - x0
            y0, x0, y1i, x1i = (a.astype(jnp.int32)
                                for a in (y0, x0, y1i, x1i))
            v00 = img[:, y0, x0]
            v01 = img[:, y0, x1i]
            v10 = img[:, y1i, x0]
            v11 = img[:, y1i, x1i]
            return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                    v10 * wy * (1 - wx) + v11 * wy * wx)

        img = x[bi]
        yy = ys.reshape(-1)                       # [ph*s]
        xx = xs.reshape(-1)                       # [pw*s]
        yg, xg = jnp.meshgrid(yy, xx, indexing="ij")
        vals = bilinear(img, yg, xg)              # [C, ph*s, pw*s]
        vals = vals.reshape(C, pooled_h, sampling, pooled_w, sampling)
        return vals.mean(axis=(2, 4))             # [C, ph, pw]

    out = jax.vmap(one_roi)(rois, batch_idx)
    return {"Out": out}


@register_op("psroi_pool", infer_shape=False)
def psroi_pool(ctx, ins, attrs):
    """Position-sensitive RoI average pooling (reference
    detection/psroi_pool_op.cc, R-FCN): input channels are laid out
    [out_c, ph, pw]; output channel c's bin (i, j) averages input channel
    c*ph*pw + i*pw + j over that bin's region. ROIs [N, 4] absolute
    (x1, y1, x2, y2) + RoisBatch [N] image index."""
    x = x_of(ins)                       # [B, out_c*ph*pw, H, W]
    rois = x_of(ins, "ROIs")
    batch_idx = x_of(ins, "RoisBatch").astype(jnp.int32).reshape(-1)
    ph = int(attrs["pooled_height"])
    pw = int(attrs["pooled_width"])
    out_c = int(attrs["output_channels"])
    scale = float(attrs.get("spatial_scale", 1.0))
    H, W = x.shape[2], x.shape[3]

    def one_roi(roi, bi):
        x1, y1, x2, y2 = roi * scale
        # reference rounds roi to integral bins and forces min size 1
        x1, y1 = jnp.floor(x1), jnp.floor(y1)
        x2, y2 = jnp.ceil(x2), jnp.ceil(y2)
        bw = jnp.maximum(x2 - x1, 0.1) / pw
        bh = jnp.maximum(y2 - y1, 0.1) / ph
        img = x[bi].reshape(out_c, ph * pw, H, W)
        ys = jnp.arange(H, dtype=jnp.float32)
        xs = jnp.arange(W, dtype=jnp.float32)
        outs = []
        for i in range(ph):
            for j in range(pw):
                ys0 = jnp.clip(jnp.floor(y1 + i * bh), 0, H)
                ys1 = jnp.clip(jnp.ceil(y1 + (i + 1) * bh), 0, H)
                xs0 = jnp.clip(jnp.floor(x1 + j * bw), 0, W)
                xs1 = jnp.clip(jnp.ceil(x1 + (j + 1) * bw), 0, W)
                my = ((ys >= ys0) & (ys < ys1)).astype(x.dtype)
                mx = ((xs >= xs0) & (xs < xs1)).astype(x.dtype)
                m = my[:, None] * mx[None, :]
                cnt = jnp.maximum(jnp.sum(m), 1.0)
                v = jnp.sum(img[:, i * pw + j] * m, axis=(1, 2)) / cnt
                empty = (ys1 <= ys0) | (xs1 <= xs0)
                outs.append(jnp.where(empty, 0.0, v))     # [out_c]
        return jnp.stack(outs, axis=1).reshape(out_c, ph, pw)

    out = jax.vmap(one_roi)(rois.astype(jnp.float32), batch_idx)
    return {"Out": out}


@register_op("prroi_pool", infer_shape=False)
def prroi_pool(ctx, ins, attrs):
    """Precise RoI pooling (reference detection/prroi_pool_op.cc): each
    output bin integrates the bilinear surface over the bin. This lowering
    approximates the integral with a dense fixed sample grid (attr
    sample_points per bin side, default 4) — denser than roi_align's 2x2
    and converging to the exact integral; the reference computes it in
    closed form. ROIs [N, 4] + RoisBatch [N]."""
    x = x_of(ins)
    rois = x_of(ins, "ROIs")
    batch_idx = x_of(ins, "RoisBatch").astype(jnp.int32).reshape(-1)
    ph = int(attrs["pooled_height"])
    pw = int(attrs["pooled_width"])
    scale = float(attrs.get("spatial_scale", 1.0))
    s = int(attrs.get("sample_points", 4))
    C, H, W = x.shape[1], x.shape[2], x.shape[3]

    def one_roi(roi, bi):
        x1, y1, x2, y2 = roi * scale
        bin_h = (y2 - y1) / ph
        bin_w = (x2 - x1) / pw
        py = jnp.arange(ph, dtype=jnp.float32)
        px = jnp.arange(pw, dtype=jnp.float32)
        sy = (jnp.arange(s, dtype=jnp.float32) + 0.5) / s
        ys = (y1 + (py[:, None] + sy[None, :]) * bin_h).reshape(-1)
        xs = (x1 + (px[:, None] + sy[None, :]) * bin_w).reshape(-1)
        img = x[bi]
        yg, xg = jnp.meshgrid(ys, xs, indexing="ij")
        vals = bilinear_sample(img, yg, xg)
        vals = vals.reshape(C, ph, s, pw, s)
        return vals.mean(axis=(2, 4))

    out = jax.vmap(one_roi)(rois.astype(jnp.float32), batch_idx)
    return {"Out": out}


@register_op("yolov3_loss", infer_shape=False)
def yolov3_loss(ctx, ins, attrs):
    """YOLOv3 training loss (reference detection/yolov3_loss_op.cc):
    X [B, mask*(5+cls), H, W] raw head output, GTBox [B, M, 4] normalized
    (cx, cy, w, h), GTLabel [B, M] int, GTCount [B] valid boxes per image.
    Per-cell anchors come from attrs anchors (flat pairs) + anchor_mask.
    Loss terms follow the reference: sigmoid-BCE on tx/ty + L2 on tw/th
    (scaled by 2 - w*h), objectness BCE where a gt is assigned, noobj BCE
    where best IoU < ignore_thresh, class BCE on assigned cells. Downsample
    ratio fixes the grid->input scale."""
    x = x_of(ins)
    gtbox = x_of(ins, "GTBox").astype(jnp.float32)
    gtlabel = x_of(ins, "GTLabel").astype(jnp.int32)
    gtcnt = ins.get("GTCount")
    anchors = np.asarray(attrs["anchors"], np.float32).reshape(-1, 2)
    mask = list(attrs["anchor_mask"])
    cls = int(attrs["class_num"])
    ignore = float(attrs.get("ignore_thresh", 0.7))
    down = float(attrs.get("downsample_ratio", 32))
    B, _, Hc, Wc = x.shape
    A = len(mask)
    M = gtbox.shape[1]
    input_size = down * Hc
    x = x.reshape(B, A, 5 + cls, Hc, Wc)
    valid = (jnp.arange(M)[None, :] <
             (jnp.reshape(gtcnt[0], (-1,))[:, None] if gtcnt
              else jnp.full((B, 1), M))) & (gtbox[..., 2] > 0)

    tx, ty = x[:, :, 0], x[:, :, 1]
    tw, th = x[:, :, 2], x[:, :, 3]
    tobj = x[:, :, 4]
    tcls = x[:, :, 5:]

    # decode predicted boxes (normalized) for the noobj IoU test
    gy, gx = jnp.meshgrid(jnp.arange(Hc, dtype=jnp.float32),
                          jnp.arange(Wc, dtype=jnp.float32), indexing="ij")
    aw = jnp.asarray(anchors[mask, 0]) / input_size
    ah = jnp.asarray(anchors[mask, 1]) / input_size
    pcx = (jax.nn.sigmoid(tx) + gx) / Wc
    pcy = (jax.nn.sigmoid(ty) + gy) / Hc
    pw_ = jnp.exp(tw) * aw[None, :, None, None]
    phh = jnp.exp(th) * ah[None, :, None, None]

    def iou_cwh(c1x, c1y, w1, h1, c2x, c2y, w2, h2):
        l = jnp.maximum(c1x - w1 / 2, c2x - w2 / 2)
        r = jnp.minimum(c1x + w1 / 2, c2x + w2 / 2)
        t = jnp.maximum(c1y - h1 / 2, c2y - h2 / 2)
        b = jnp.minimum(c1y + h1 / 2, c2y + h2 / 2)
        inter = jnp.maximum(r - l, 0) * jnp.maximum(b - t, 0)
        return inter / jnp.maximum(w1 * h1 + w2 * h2 - inter, 1e-10)

    # best IoU of each prediction vs any gt -> noobj mask (vectorized
    # over the M gt boxes; the broadcast [B, M, A, Hc, Wc] is the same
    # peak footprint the per-m loop reached one slice at a time)
    gx_ = gtbox[..., 0][:, :, None, None, None]
    gy_ = gtbox[..., 1][:, :, None, None, None]
    gw_ = gtbox[..., 2][:, :, None, None, None]
    gh_ = gtbox[..., 3][:, :, None, None, None]
    iou_all = iou_cwh(pcx[:, None], pcy[:, None], pw_[:, None],
                      phh[:, None], gx_, gy_, gw_, gh_)
    best = jnp.max(jnp.where(valid[:, :, None, None, None], iou_all, 0.0),
                   axis=1)
    noobj = best < ignore

    from .common import sigmoid_bce as bce

    # per-gt assignment: responsible anchor = best shape-IoU anchor at the
    # gt's cell, restricted to this head's anchor_mask. lax.scan over the
    # gt dim keeps the traced graph O(1) in M (review finding: the python
    # loop unrolled ~M*A gather/scatter nodes).
    mask_arr = jnp.asarray(mask, jnp.int32)                  # [A]
    anc = jnp.asarray(anchors, jnp.float32)                  # [num_anc, 2]
    bidx = jnp.arange(B)
    aidx = jnp.arange(A)

    def assign(carry, m):
        loss, obj_t = carry
        g = gtbox[:, m]                                      # [B, 4]
        v = valid[:, m]
        lbl = gtlabel[:, m]
        ci = jnp.clip((g[:, 0] * Wc).astype(jnp.int32), 0, Wc - 1)
        ri = jnp.clip((g[:, 1] * Hc).astype(jnp.int32), 0, Hc - 1)
        # anchor choice by shape-only IoU over the FULL anchor set
        ious = iou_cwh(0.0, 0.0, g[:, 2:3], g[:, 3:4], 0.0, 0.0,
                       (anc[:, 0] / input_size)[None, :],
                       (anc[:, 1] / input_size)[None, :])    # [B, num_anc]
        best_a = jnp.argmax(ious, axis=1)                    # [B]
        sel = v[:, None] & (best_a[:, None] == mask_arr[None, :])  # [B, A]
        scale_wh = (2.0 - g[:, 2] * g[:, 3])[:, None]
        ttx = (g[:, 0] * Wc - ci)[:, None]
        tty = (g[:, 1] * Hc - ri)[:, None]
        ttw = jnp.log(jnp.maximum(
            g[:, 2:3] * input_size / anc[mask_arr, 0][None, :], 1e-9))
        tth = jnp.log(jnp.maximum(
            g[:, 3:4] * input_size / anc[mask_arr, 1][None, :], 1e-9))
        px_ = tx[bidx[:, None], aidx[None, :], ri[:, None], ci[:, None]]
        py_ = ty[bidx[:, None], aidx[None, :], ri[:, None], ci[:, None]]
        pwv = tw[bidx[:, None], aidx[None, :], ri[:, None], ci[:, None]]
        phv = th[bidx[:, None], aidx[None, :], ri[:, None], ci[:, None]]
        pob = tobj[bidx[:, None], aidx[None, :], ri[:, None], ci[:, None]]
        pcl = tcls[bidx[:, None], aidx[None, :], :, ri[:, None],
                   ci[:, None]]                              # [B, A, cls]
        l_xy = bce(px_, ttx) + bce(py_, tty)
        l_wh = 0.5 * ((pwv - ttw) ** 2 + (phv - tth) ** 2)
        l_obj = bce(pob, 1.0)
        onehot = jax.nn.one_hot(lbl, cls)[:, None, :]        # [B, 1, cls]
        l_cls = jnp.sum(bce(pcl, onehot), axis=-1)
        term = scale_wh * (l_xy + l_wh) + l_obj + l_cls
        loss = loss + jnp.sum(jnp.where(sel, term, 0.0), axis=1)
        obj_t = obj_t.at[bidx[:, None], aidx[None, :], ri[:, None],
                         ci[:, None]].max(sel.astype(jnp.float32))
        return (loss, obj_t), None

    (loss, obj_target), _ = jax.lax.scan(
        assign, (jnp.zeros((B,)), jnp.zeros((B, A, Hc, Wc))),
        jnp.arange(M))
    l_noobj = jnp.sum(
        bce(tobj, 0.0) * noobj * (1.0 - obj_target), axis=(1, 2, 3))
    return {"Loss": loss + l_noobj}


# ---------------------------------------------------------------------------
# SSD target machinery + evaluation (reference detection/target_assign_op.cc,
# mine_hard_examples_op.cc, detection_map_op.cc, locality_aware_nms_op.cc,
# box_decoder_and_assign companion ops live in detection_rcnn_ops.py)
# ---------------------------------------------------------------------------

@register_op("target_assign", grad=False, infer_shape=False)
def target_assign(ctx, ins, attrs):
    """reference detection/target_assign_op.h: scatter per-gt rows onto
    prior positions by MatchIndices. Padded form: X [B, G, P, K] (the
    reference's LoD rows, per image), MatchIndices [B, M] (-1 =
    mismatch), optional NegIndices [B, Q] padded -1. Out [B, M, K],
    OutWeight [B, M, 1]."""
    x = x_of(ins)
    match = x_of(ins, "MatchIndices").astype(jnp.int32)
    mismatch = float(attrs.get("mismatch_value", 0))
    B, M = match.shape
    if x.ndim == 3:                   # [B, G, K] -> P=1
        x = x[:, :, None, :]
    G, P, K = x.shape[1], x.shape[2], x.shape[3]

    m_pos = jnp.arange(M) % P
    matched = match >= 0
    safe = jnp.maximum(match, 0)
    gathered = x[jnp.arange(B)[:, None], safe, m_pos[None, :], :]
    out = jnp.where(matched[:, :, None], gathered, mismatch)
    wt = matched.astype(x.dtype)[:, :, None]
    neg = ins.get("NegIndices")
    if neg:
        ni = jnp.asarray(neg[0]).reshape(B, -1).astype(jnp.int32)
        neg_mask = jnp.zeros((B, M), bool)
        neg_mask = neg_mask.at[jnp.arange(B)[:, None],
                               jnp.maximum(ni, 0)].max(ni >= 0)
        wt = jnp.maximum(wt, neg_mask.astype(x.dtype)[:, :, None])
    return {"Out": out, "OutWeight": wt}


@register_op("mine_hard_examples", grad=False, infer_shape=False)
def mine_hard_examples(ctx, ins, attrs):
    """reference detection/mine_hard_examples_op.cc. ClsLoss/LocLoss
    [B, M], MatchIndices [B, M], MatchDist [B, M]. NegIndices comes back
    padded [B, M] (-1 pad, ascending order per image — the reference's
    std::set) + NegCount [B]; UpdatedMatchIndices [B, M]."""
    cls_loss = x_of(ins, "ClsLoss")
    match = x_of(ins, "MatchIndices").astype(jnp.int32)
    dist = x_of(ins, "MatchDist")
    loc = ins.get("LocLoss")
    mining = attrs.get("mining_type", "max_negative")
    neg_ratio = float(attrs.get("neg_pos_ratio", 1.0))
    neg_dist_thresh = float(attrs.get("neg_dist_threshold", 0.5))
    sample_size = int(attrs.get("sample_size", 0))
    B, M = match.shape
    loss = cls_loss
    if mining == "hard_example" and loc:
        loss = cls_loss + jnp.asarray(loc[0]).reshape(B, M)

    def one_image(loss_b, match_b, dist_b):
        if mining == "max_negative":
            elig = (match_b == -1) & (dist_b < neg_dist_thresh)
            n_pos = jnp.sum((match_b != -1).astype(jnp.int32))
            cap = jnp.minimum((n_pos.astype(jnp.float32)
                               * neg_ratio).astype(jnp.int32),
                              jnp.sum(elig.astype(jnp.int32)))
        else:                          # hard_example
            elig = jnp.ones((M,), bool)
            cap = jnp.minimum(sample_size,
                              jnp.sum(elig.astype(jnp.int32)))
        # top-cap by loss among eligible
        key = jnp.where(elig, loss_b, -jnp.inf)
        order = jnp.argsort(-key)
        rank_of = jnp.zeros((M,), jnp.int32).at[order].set(
            jnp.arange(M, dtype=jnp.int32))
        sel = elig & (rank_of < cap)
        if mining == "hard_example":
            upd = jnp.where((match_b > -1) & ~sel, -1, match_b)
            neg_sel = sel & (match_b <= -1)
        else:
            upd = match_b
            neg_sel = sel
        # ascending index order (reference std::set), padded -1
        idx = jnp.where(neg_sel, jnp.arange(M), M + jnp.arange(M))
        srt = jnp.sort(idx)
        n_neg = jnp.sum(neg_sel.astype(jnp.int32))
        neg = jnp.where(jnp.arange(M) < n_neg, srt, -1)
        return neg.astype(jnp.int32), n_neg, upd

    neg, n_neg, upd = jax.vmap(one_image)(loss, match, dist)
    return {"NegIndices": neg, "NegCount": n_neg,
            "UpdatedMatchIndices": upd}


@register_op("locality_aware_nms", grad=False, infer_shape=False)
def locality_aware_nms(ctx, ins, attrs):
    """reference detection/locality_aware_nms_op.cc (EAST-style): first a
    locality pass merges consecutive same-class boxes with IoU >
    nms_threshold by score-weighted averaging, then standard per-class
    NMS + cross-class top-k. Single-class input in practice. BBoxes
    [N, M, 4], Scores [N, C, M] -> Out [N, keep_top_k, 6] + counts."""
    bboxes = x_of(ins, "BBoxes")
    scores = x_of(ins, "Scores")
    score_thresh = float(attrs.get("score_threshold", 0.05))
    nms_thresh = float(attrs.get("nms_threshold", 0.3))
    nms_top_k = int(attrs.get("nms_top_k", 64))
    keep_top_k = int(attrs.get("keep_top_k", 16))
    background = int(attrs.get("background_label", -1))
    N, C, M = scores.shape
    nms_top_k = min(nms_top_k if nms_top_k > 0 else M, M)
    fg = [c for c in range(C) if c != background]
    if keep_top_k <= 0:
        keep_top_k = len(fg) * nms_top_k

    def merge_pass(boxes, sc):
        """Sequential merge over input order (the locality pass)."""
        def step(carry, inp):
            cur_box, cur_sc, valid = carry
            b, s = inp
            iou = _iou_matrix(cur_box[None], b[None])[0, 0]
            do_merge = valid & (iou > nms_thresh) & (s > score_thresh)
            wsum = cur_sc + s
            merged = (cur_box * cur_sc + b * s) / jnp.maximum(wsum, 1e-10)
            emit_box = jnp.where(do_merge, jnp.zeros(4), cur_box)
            emit_sc = jnp.where(do_merge, -1.0, cur_sc)
            emit_valid = valid & ~do_merge
            new_box = jnp.where(do_merge, merged, b)
            new_sc = jnp.where(do_merge, wsum, s)
            live = s > score_thresh
            new_valid = do_merge | live
            # when current emits, the incoming box starts a new group
            return ((new_box, jnp.where(live | do_merge, new_sc, -1.0),
                     new_valid),
                    (emit_box, jnp.where(emit_valid, emit_sc, -1.0)))

        init = (jnp.zeros(4), jnp.asarray(-1.0), jnp.asarray(False))
        (last_b, last_s, last_v), (out_b, out_s) = jax.lax.scan(
            step, init, (boxes, sc))
        out_b = jnp.concatenate([out_b, last_b[None]], axis=0)
        out_s = jnp.concatenate(
            [out_s, jnp.where(last_v, last_s, -1.0)[None]], axis=0)
        return out_b, out_s

    def per_image(boxes, sc):
        all_s, all_b, all_c = [], [], []
        for c in fg:
            mb, ms = merge_pass(boxes, sc[c])
            k = min(nms_top_k, ms.shape[0])
            top_s, top_i = jax.lax.top_k(ms, k)
            b = mb[top_i]
            iou = _iou_matrix(b, b)
            alive = top_s > score_thresh

            def body(i, alive):
                sup = jnp.logical_and(alive[i], iou[i] > nms_thresh)
                sup = sup.at[i].set(False)
                later = jnp.arange(k) > i
                return jnp.where(jnp.logical_and(sup, later), False,
                                 alive)

            alive = jax.lax.fori_loop(0, k, body, alive)
            all_s.append(jnp.where(alive, top_s, -1.0))
            all_b.append(b)
            all_c.append(jnp.full((k,), c, jnp.float32))
        cat_s = jnp.concatenate(all_s)
        cat_b = jnp.concatenate(all_b, axis=0)
        cat_c = jnp.concatenate(all_c)
        kk = min(keep_top_k, cat_s.shape[0])
        fin_s, fin_i = jax.lax.top_k(cat_s, kk)
        valid = fin_s > score_thresh
        rows = jnp.concatenate([
            jnp.where(valid, cat_c[fin_i], -1.0)[:, None],
            jnp.where(valid, fin_s, 0.0)[:, None],
            jnp.where(valid[:, None], cat_b[fin_i], 0.0)], axis=1)
        return rows, jnp.sum(valid.astype(jnp.int32))

    rows, counts = jax.vmap(per_image)(bboxes, scores)
    return {"Out": rows, "NmsRoisNum": counts}


@register_op("detection_map", grad=False, infer_shape=False)
def detection_map(ctx, ins, attrs):
    """mean Average Precision (reference detection/detection_map_op.h).
    Padded one-shot form: DetectRes [B, D, 6] (label, score, box; label
    -1 pads), GtLabel [B, G], GtBox [B, G, 4] (+ GtCount [B], optional
    GtDifficult [B, G]). Emits MAP [1]. Divergence (documented): the
    reference's streaming accumulator inputs/outputs (PosCount/TruePos/
    FalsePos LoD states) are not consumed; fluid.metrics.DetectionMAP
    accumulates MAP host-side instead."""
    det = x_of(ins, "DetectRes")
    gt_label = x_of(ins, "GtLabel")
    gt_box = x_of(ins, "GtBox")
    thresh = float(attrs.get("overlap_threshold", 0.5))
    ap_type = attrs.get("ap_type", "integral")
    class_num = int(attrs["class_num"])
    eval_difficult = bool(attrs.get("evaluate_difficult", True))
    B, D = det.shape[0], det.shape[1]
    G = gt_box.shape[1]
    gt_label = gt_label.reshape(B, G)
    cnt = ins.get("GtCount")
    gt_valid = jnp.ones((B, G), bool)
    if cnt:
        counts = jnp.reshape(cnt[0], (-1,)).astype(jnp.int32)
        gt_valid = jnp.arange(G)[None, :] < counts[:, None]
    difficult = ins.get("GtDifficult")
    if difficult:
        diff = jnp.reshape(difficult[0], (B, G)) != 0
    else:
        diff = jnp.zeros((B, G), bool)

    det_label = det[:, :, 0].astype(jnp.int32)
    det_score = det[:, :, 1]
    det_box = det[:, :, 2:6]
    det_valid = det_label >= 0
    # IoU between each image's detections and gts (normalized convention
    # follows the SSD pipeline's iou_similarity)
    iou = jax.vmap(_iou_matrix)(det_box, gt_box)          # [B, D, G]

    background = int(attrs.get("background_label", 0))
    aps = []
    n_classes_with_gt = []
    for c in range(class_num):
        if c == background:
            continue
        gt_c = gt_valid & (gt_label == c)
        count_gt = jnp.sum(
            (gt_c & (eval_difficult | ~diff)).astype(jnp.int32))
        det_c = det_valid & (det_label == c)
        score_c = jnp.where(det_c, det_score, -jnp.inf)
        flat_score = score_c.reshape(-1)                   # [B*D]
        order = jnp.argsort(-flat_score)                   # global desc

        def match_step(i, carry):
            matched, tp, fp = carry
            fi = order[i]
            b, d = fi // D, fi % D
            live = flat_score[fi] > -jnp.inf
            row = jnp.where(gt_c[b], iou[b, d], -1.0)      # [G]
            best = jnp.argmax(row)
            best_iou = row[best]
            hit = live & (best_iou > thresh)
            is_diff = diff[b, best]
            fresh = hit & ~matched[b, best]
            # difficult gts are ignored unless evaluate_difficult
            if eval_difficult:
                counts_tp = fresh
                ignore = jnp.asarray(False)
            else:
                counts_tp = fresh & ~is_diff
                ignore = hit & is_diff
            tp = tp.at[i].set(jnp.where(counts_tp, 1.0, 0.0))
            fp = fp.at[i].set(
                jnp.where(live & ~counts_tp & ~ignore, 1.0, 0.0))
            matched = matched.at[b, best].max(hit)
            return matched, tp, fp

        n = B * D
        matched0 = jnp.zeros((B, G), bool)
        _, tp, fp = jax.lax.fori_loop(
            0, n, match_step,
            (matched0, jnp.zeros((n,)), jnp.zeros((n,))))
        ctp = jnp.cumsum(tp)
        cfp = jnp.cumsum(fp)
        precision = ctp / jnp.maximum(ctp + cfp, 1e-10)
        recall = ctp / jnp.maximum(count_gt, 1)
        has_det = (tp + fp) > 0
        if ap_type == "11point":
            pts = []
            for t in range(11):
                ok = has_det & (recall >= t / 10.0)
                pts.append(jnp.max(jnp.where(ok, precision, 0.0)))
            ap = jnp.sum(jnp.stack(pts)) / 11.0
        else:
            prev_rec = jnp.concatenate([jnp.zeros(1), recall[:-1]])
            ap = jnp.sum(jnp.where(has_det,
                                   (recall - prev_rec) * precision, 0.0))
        aps.append(jnp.where(count_gt > 0, ap, 0.0))
        n_classes_with_gt.append((count_gt > 0).astype(jnp.float32))
    total = jnp.sum(jnp.stack(n_classes_with_gt))
    m_ap = jnp.sum(jnp.stack(aps)) / jnp.maximum(total, 1.0)
    return {"MAP": m_ap.astype(jnp.float32).reshape(1),
            "AccumPosCount": jnp.zeros((class_num, 1), jnp.int32),
            "AccumTruePos": jnp.zeros((class_num, B * D, 2)),
            "AccumFalsePos": jnp.zeros((class_num, B * D, 2))}


@register_op("deformable_psroi_pooling", infer_shape=False)
def deformable_psroi_pooling(ctx, ins, attrs):
    """reference deformable_psroi_pooling_op.h: position-sensitive RoI
    pooling with learned per-part offsets (Trans [R, 2*num_classes,
    part_h, part_w] scaled by trans_std). Output [R, output_dim, ph, pw]
    + TopCount (valid sample counts per bin)."""
    x = x_of(ins, "Input")
    rois = x_of(ins, "ROIs")
    trans = x_of(ins, "Trans")
    no_trans = bool(attrs.get("no_trans", False))
    scale = float(attrs.get("spatial_scale", 1.0))
    out_dim = int(attrs["output_dim"])
    group = attrs.get("group_size", [1, 1])
    gh, gw = int(group[0]), int(group[1])
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    part = attrs.get("part_size", [ph, pw])
    part_h, part_w = int(part[0]), int(part[1])
    spp = int(attrs.get("sample_per_part", 1))
    trans_std = float(attrs.get("trans_std", 0.1))
    N, C, H, W = x.shape
    R = rois.shape[0]
    num_classes = 1 if no_trans else max(trans.shape[1] // 2, 1)
    ch_per_class = max(out_dim // num_classes, 1)
    batch_idx = roi_batch_indices(ins, R)

    def one_roi(roi, tr, bi):
        x1 = jnp.round(roi[0]) * scale - 0.5
        y1 = jnp.round(roi[1]) * scale - 0.5
        x2 = (jnp.round(roi[2]) + 1.0) * scale - 0.5
        y2 = (jnp.round(roi[3]) + 1.0) * scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_h, bin_w = rh / ph, rw / pw
        sub_h, sub_w = bin_h / spp, bin_w / spp
        img = x[bi]

        def one_cell(ct, py, px):
            pt_h = jnp.floor(py.astype(jnp.float32) / ph
                             * part_h).astype(jnp.int32)
            pt_w = jnp.floor(px.astype(jnp.float32) / pw
                             * part_w).astype(jnp.int32)
            cls = ct // ch_per_class
            if no_trans:
                tx = ty = 0.0
            else:
                tx = tr[cls * 2, pt_h, pt_w] * trans_std
                ty = tr[cls * 2 + 1, pt_h, pt_w] * trans_std
            wstart = px * bin_w + x1 + tx * rw
            hstart = py * bin_h + y1 + ty * rh
            g_w = jnp.clip(jnp.floor(px.astype(jnp.float32) * gw / pw),
                           0, gw - 1).astype(jnp.int32)
            g_h = jnp.clip(jnp.floor(py.astype(jnp.float32) * gh / ph),
                           0, gh - 1).astype(jnp.int32)
            c_in = (ct * gh + g_h) * gw + g_w
            iw = jnp.arange(spp, dtype=jnp.float32)
            ww = wstart + iw * sub_w                       # [spp]
            hh = hstart + iw * sub_h                       # [spp]
            wg, hg = jnp.meshgrid(ww, hh)                  # [spp, spp]
            ok = ((wg >= -0.5) & (wg <= W - 0.5)
                  & (hg >= -0.5) & (hg <= H - 0.5))
            wc = jnp.clip(wg, 0.0, W - 1.0)
            hc = jnp.clip(hg, 0.0, H - 1.0)
            plane = img[c_in]
            x0 = jnp.floor(wc)
            y0 = jnp.floor(hc)
            x1i = jnp.clip(x0 + 1, 0, W - 1).astype(jnp.int32)
            y1i = jnp.clip(y0 + 1, 0, H - 1).astype(jnp.int32)
            x0i = x0.astype(jnp.int32)
            y0i = y0.astype(jnp.int32)
            dx = wc - x0
            dy = hc - y0
            val = (plane[y0i, x0i] * (1 - dy) * (1 - dx)
                   + plane[y0i, x1i] * (1 - dy) * dx
                   + plane[y1i, x0i] * dy * (1 - dx)
                   + plane[y1i, x1i] * dy * dx)
            cnt = jnp.sum(ok.astype(jnp.float32))
            s = jnp.sum(jnp.where(ok, val, 0.0))
            return jnp.where(cnt > 0, s / cnt, 0.0), cnt

        cts = jnp.arange(out_dim)
        pys = jnp.arange(ph)
        pxs = jnp.arange(pw)
        f = jax.vmap(jax.vmap(jax.vmap(one_cell, (None, None, 0)),
                              (None, 0, None)), (0, None, None))
        return f(cts, pys, pxs)

    out, cnt = jax.vmap(one_roi)(rois, trans, batch_idx)
    return {"Output": out, "TopCount": cnt}


@register_op("roi_perspective_transform", infer_shape=False)
def roi_perspective_transform(ctx, ins, attrs):
    """reference detection/roi_perspective_transform_op.cc: warp each
    quad ROI ([R, 8] corner coords) to a [transformed_h, transformed_w]
    patch by the estimated perspective matrix. Outputs Out
    [R, C, th, tw], Mask [R, 1, th, tw], TransformMatrix [R, 9]."""
    x = x_of(ins)
    rois = x_of(ins, "ROIs")
    scale = float(attrs.get("spatial_scale", 1.0))
    th = int(attrs["transformed_height"])
    tw = int(attrs["transformed_width"])
    N, C, H, W = x.shape
    R = rois.shape[0]
    batch_idx = roi_batch_indices(ins, R)

    def one_roi(roi, bi):
        rx = roi[0::2] * scale                             # [4]
        ry = roi[1::2] * scale
        x0, x1b, x2, x3 = rx[0], rx[1], rx[2], rx[3]
        y0, y1b, y2, y3 = ry[0], ry[1], ry[2], ry[3]
        len1 = jnp.sqrt((x0 - x1b) ** 2 + (y0 - y1b) ** 2)
        len2 = jnp.sqrt((x1b - x2) ** 2 + (y1b - y2) ** 2)
        len3 = jnp.sqrt((x2 - x3) ** 2 + (y2 - y3) ** 2)
        len4 = jnp.sqrt((x3 - x0) ** 2 + (y3 - y0) ** 2)
        est_h = (len2 + len4) / 2.0
        est_w = (len1 + len3) / 2.0
        nh = max(2, th)
        nw_f = jnp.round(est_w * (nh - 1)
                         / jnp.maximum(est_h, 1e-5)) + 1
        nw = jnp.clip(nw_f, 2, tw)
        dx1, dx2, dx3 = x1b - x2, x3 - x2, x0 - x1b + x2 - x3
        dy1, dy2, dy3 = y1b - y2, y3 - y2, y0 - y1b + y2 - y3
        den = dx1 * dy2 - dx2 * dy1 + 1e-5
        m6 = (dx3 * dy2 - dx2 * dy3) / den / (nw - 1)
        m7 = (dx1 * dy3 - dx3 * dy1) / den / (nh - 1)
        m3 = (y1b - y0 + m6 * (nw - 1) * y1b) / (nw - 1)
        m4 = (y3 - y0 + m7 * (nh - 1) * y3) / (nh - 1)
        m0 = (x1b - x0 + m6 * (nw - 1) * x1b) / (nw - 1)
        m1 = (x3 - x0 + m7 * (nh - 1) * x3) / (nh - 1)
        mat = jnp.stack([m0, m1, x0, m3, m4, y0, m6, m7,
                         jnp.asarray(1.0)])
        ow = jnp.arange(tw, dtype=jnp.float32)
        oh = jnp.arange(th, dtype=jnp.float32)
        og_w, og_h = jnp.meshgrid(ow, oh)                  # [th, tw]
        wden = m6 * og_w + m7 * og_h + 1.0
        in_w = (m0 * og_w + m1 * og_h + x0) / wden
        in_h = (m3 * og_w + m4 * og_h + y0) / wden

        # point-in-quad test (even-odd over the 4 edges)
        qx = jnp.stack([rx[0], rx[1], rx[2], rx[3]])
        qy = jnp.stack([ry[0], ry[1], ry[2], ry[3]])
        nxt = jnp.array([1, 2, 3, 0])
        xi, yi = qx[:, None, None], qy[:, None, None]
        xj, yj = qx[nxt][:, None, None], qy[nxt][:, None, None]
        cond = (yi > in_h[None]) != (yj > in_h[None])
        xc = xi + (in_h[None] - yi) / jnp.where(
            jnp.abs(yj - yi) < 1e-12, 1e-12, yj - yi) * (xj - xi)
        inside_quad = (jnp.sum((cond & (in_w[None] < xc)).astype(
            jnp.int32), axis=0) % 2) == 1
        in_range = ((in_w > -0.5) & (in_w < W - 0.5)
                    & (in_h > -0.5) & (in_h < H - 0.5))
        ok = inside_quad & in_range
        wc = jnp.clip(in_w, 0.0, W - 1.0)
        hc = jnp.clip(in_h, 0.0, H - 1.0)
        img = x[bi]
        x0f = jnp.floor(wc)
        y0f = jnp.floor(hc)
        x1i = jnp.clip(x0f + 1, 0, W - 1).astype(jnp.int32)
        y1i = jnp.clip(y0f + 1, 0, H - 1).astype(jnp.int32)
        x0i = x0f.astype(jnp.int32)
        y0i = y0f.astype(jnp.int32)
        dx = wc - x0f
        dy = hc - y0f
        val = (img[:, y0i, x0i] * (1 - dy) * (1 - dx)
               + img[:, y0i, x1i] * (1 - dy) * dx
               + img[:, y1i, x0i] * dy * (1 - dx)
               + img[:, y1i, x1i] * dy * dx)                # [C, th, tw]
        out = jnp.where(ok[None], val, 0.0)
        return out, ok.astype(jnp.int32)[None], mat

    out, mask, mats = jax.vmap(one_roi)(rois, batch_idx)
    return {"Out": out, "Mask": mask, "TransformMatrix": mats}
