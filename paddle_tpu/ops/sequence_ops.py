"""Sequence (ragged) ops, masked-dense TPU design.

Capability parity with the reference's LoD sequence family
(/root/reference/paddle/fluid/operators/sequence_ops/ — 47 files). The
reference packs variable-length sequences into one [total_tokens, ...] tensor
plus LoD offsets and every kernel walks the offsets. XLA wants static shapes,
so here a batch of sequences is a PADDED dense tensor [B, T, ...] plus an
explicit `Length` [B] int vector (the representation the reference itself
uses at the sequence_pad/unpad boundary, sequence_pad_op.h). Every op masks
by Length; padding positions carry zeros and receive zero gradients. The
packed<->padded converters (sequence_pad / sequence_unpad) keep a static
[cap, ...] packed buffer whose valid prefix is sum(Length).
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import register_op
from .common import as_dtype, int64_t, x_of


def _len_of(ins):
    ln = x_of(ins, "Length")
    if ln is None:
        raise ValueError(
            "sequence op needs a Length input ([B] int lengths); the "
            "reference reads LoD offsets off the tensor, the TPU build "
            "passes lengths explicitly (masked-dense design)")
    return jnp.reshape(ln, (-1,)).astype(jnp.int32)


def _time_mask(lengths, T):
    """[B, T] bool validity mask."""
    return jnp.arange(T, dtype=jnp.int32)[None, :] < lengths[:, None]


def _expand(mask, ndim):
    """Broadcast a [B, T] mask to rank `ndim` ([B, T, 1, 1, ...])."""
    return mask.reshape(mask.shape + (1,) * (ndim - 2))


@register_op("sequence_mask", grad=False)
def sequence_mask(ctx, ins, attrs):
    """reference sequence_mask_op.h: out[.., j] = j < x[..]."""
    x = x_of(ins).astype(jnp.int32)
    maxlen = int(attrs.get("maxlen", -1))
    if maxlen <= 0:
        raise ValueError(
            "sequence_mask needs a static maxlen>0 on TPU (the reference's "
            "maxlen=-1 derives it from data — a dynamic output shape)")
    dt = as_dtype(attrs, "out_dtype", "int64")
    if np.issubdtype(dt, np.signedinteger) and not jax.config.jax_enable_x64:
        dt = np.int32  # x64 disabled: avoid jax's silent-truncation warning
    out = (jnp.arange(maxlen, dtype=jnp.int32) < x[..., None]).astype(dt)
    return {"Out": out}


@register_op("sequence_pool")
def sequence_pool(ctx, ins, attrs):
    """reference sequence_pool_op.h pooltypes: SUM/MEAN/SQRT/MAX/MIN/FIRST/
    LAST over the valid prefix of each row."""
    x = x_of(ins)
    lengths = _len_of(ins)
    ptype = attrs.get("pooltype", "SUM").upper()
    pad_value = attrs.get("pad_value", 0.0)
    mask = _expand(_time_mask(lengths, x.shape[1]), x.ndim)
    n = jnp.maximum(lengths, 1).astype(x.dtype)
    n = n.reshape((-1,) + (1,) * (x.ndim - 2))
    if ptype == "SUM":
        out = jnp.sum(jnp.where(mask, x, 0), axis=1)
    elif ptype == "MEAN":
        out = jnp.sum(jnp.where(mask, x, 0), axis=1) / n
    elif ptype == "SQRT":
        out = jnp.sum(jnp.where(mask, x, 0), axis=1) / jnp.sqrt(n)
    elif ptype == "MAX":
        out = jnp.max(jnp.where(mask, x, -jnp.inf), axis=1)
    elif ptype == "MIN":
        out = jnp.min(jnp.where(mask, x, jnp.inf), axis=1)
    elif ptype == "FIRST":
        out = x[:, 0]
    elif ptype == "LAST":
        idx = jnp.maximum(lengths - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((-1,) + (1,) * (x.ndim - 1)), axis=1)
        out = jnp.squeeze(out, axis=1)
    else:
        raise ValueError(f"unknown pooltype {ptype!r}")
    # empty rows yield pad_value (reference sequence_pool_op.h writes
    # pad_value for zero-length sequences; also keeps -inf/garbage from the
    # MAX/MIN/FIRST/LAST paths out of downstream math)
    empty = (lengths == 0).reshape((-1,) + (1,) * (out.ndim - 1))
    out = jnp.where(empty, jnp.asarray(pad_value, out.dtype), out)
    return {"Out": out}


@register_op("sequence_softmax")
def sequence_softmax(ctx, ins, attrs):
    """Masked softmax over the time dim (reference sequence_softmax_op.h
    softmaxes each LoD segment independently)."""
    x = x_of(ins)
    lengths = _len_of(ins)
    mask = _expand(_time_mask(lengths, x.shape[1]), x.ndim)
    z = jnp.where(mask, x, -jnp.inf)
    out = jax.nn.softmax(z, axis=1)
    return {"Out": jnp.where(mask, out, 0)}


@register_op("sequence_reverse")
def sequence_reverse(ctx, ins, attrs):
    """Reverse each valid prefix, keep padding in place
    (reference sequence_reverse_op.h)."""
    x = x_of(ins)
    lengths = _len_of(ins)
    t = jnp.arange(x.shape[1], dtype=jnp.int32)[None, :]
    idx = jnp.where(t < lengths[:, None], lengths[:, None] - 1 - t, t)
    idx = idx.reshape(idx.shape + (1,) * (x.ndim - 2))
    return {"Out": jnp.take_along_axis(x, idx, axis=1)}


@register_op("sequence_expand_as")
def sequence_expand_as(ctx, ins, attrs):
    """Tile each row over the ref row's length (reference
    sequence_expand_as_op.h: x row i is repeated to y's i-th segment size;
    padded form: broadcast along T and mask)."""
    x = x_of(ins)          # [B, ...]
    lengths = _len_of(ins)  # ref lengths
    T = int(attrs["maxlen"]) if "maxlen" in attrs else None
    if T is None:
        raise ValueError("sequence_expand_as needs static attr maxlen")
    out = jnp.broadcast_to(x[:, None], (x.shape[0], T) + x.shape[1:])
    mask = _expand(_time_mask(lengths, T), out.ndim)
    return {"Out": jnp.where(mask, out, 0)}


@register_op("sequence_pad")
def sequence_pad(ctx, ins, attrs):
    """Packed [total, ...] + lengths -> padded [B, P, ...]
    (reference sequence_pad_op.h)."""
    x = x_of(ins)
    lengths = _len_of(ins)
    P = int(attrs["padded_length"])
    pad_value = attrs.get("pad_value", 0.0)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(lengths)[:-1]])
    t = jnp.arange(P, dtype=jnp.int32)[None, :]
    idx = offsets[:, None] + t                       # [B, P]
    valid = t < lengths[:, None]
    gathered = jnp.take(x, jnp.clip(idx, 0, x.shape[0] - 1), axis=0)
    mask = _expand(valid, gathered.ndim)
    pv = jnp.asarray(pad_value, x.dtype)
    return {"Out": jnp.where(mask, gathered, pv)}


@register_op("sequence_unpad")
def sequence_unpad(ctx, ins, attrs):
    """Padded [B, P, ...] + lengths -> packed [B*P, ...] buffer whose valid
    prefix (sum of lengths) holds the tokens back to back; the tail is zero
    (reference sequence_unpad_op.h emits a dynamically-sized LoD tensor —
    XLA needs the static B*P cap)."""
    x = x_of(ins)
    lengths = _len_of(ins)
    B, P = x.shape[0], x.shape[1]
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(lengths)[:-1]])
    t = jnp.arange(P, dtype=jnp.int32)[None, :]
    valid = t < lengths[:, None]
    pos = jnp.where(valid, offsets[:, None] + t, B * P)   # OOB -> dropped
    flat = x.reshape((B * P,) + x.shape[2:])
    out = jnp.zeros_like(flat)
    out = out.at[pos.reshape(-1)].set(flat, mode="drop")
    return {"Out": out}


@register_op("sequence_concat")
def sequence_concat(ctx, ins, attrs):
    """Concatenate along time per row: out row b = x1[b,:l1] ++ x2[b,:l2] ++
    ... with the result padded to sum(Ti) (reference sequence_concat_op.h
    splices LoD segments)."""
    xs = list(ins["X"])
    lens = [jnp.reshape(v, (-1,)).astype(jnp.int32) for v in ins["Length"]]
    B = xs[0].shape[0]
    T_out = sum(int(v.shape[1]) for v in xs)
    t = jnp.arange(T_out, dtype=jnp.int32)[None, :]       # [1, T_out]
    out = jnp.zeros((B, T_out) + xs[0].shape[2:], xs[0].dtype)
    start = jnp.zeros((B, 1), jnp.int32)
    for x, ln in zip(xs, lens):
        rel = t - start                                    # [B, T_out]
        within = jnp.logical_and(rel >= 0, rel < ln[:, None])
        relc = jnp.clip(rel, 0, x.shape[1] - 1)
        relc = relc.reshape(relc.shape + (1,) * (x.ndim - 2))
        g = jnp.take_along_axis(x, relc, axis=1)
        out = jnp.where(_expand(within, out.ndim), g, out)
        start = start + ln[:, None]
    total = sum(lens)
    return {"Out": out, "OutLength": total}


@register_op("sequence_slice")
def sequence_slice(ctx, ins, attrs):
    """Per-row slice [offset, offset+length) of the valid prefix
    (reference sequence_slice_op.h)."""
    x = x_of(ins)
    offset = jnp.reshape(x_of(ins, "Offset"), (-1,)).astype(jnp.int32)
    length = jnp.reshape(x_of(ins, "SliceLength"), (-1,)).astype(jnp.int32)
    T = x.shape[1]
    t = jnp.arange(T, dtype=jnp.int32)[None, :]
    idx = jnp.clip(offset[:, None] + t, 0, T - 1)
    idx = idx.reshape(idx.shape + (1,) * (x.ndim - 2))
    g = jnp.take_along_axis(x, idx, axis=1)
    mask = _expand(t < length[:, None], g.ndim)
    return {"Out": jnp.where(mask, g, 0), "OutLength": length}


@register_op("sequence_erase", grad=False)
def sequence_erase(ctx, ins, attrs):
    """Drop listed token ids and compact each row left
    (reference sequence_erase_op.h)."""
    x = x_of(ins)
    lengths = _len_of(ins)
    tokens = np.asarray(attrs.get("tokens", []), x.dtype)
    B, T = x.shape[0], x.shape[1]
    valid = _time_mask(lengths, T)
    keep = valid
    for tok in tokens:
        keep = jnp.logical_and(keep, x != tok)
    new_pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    cols = jnp.where(keep, new_pos, T)                    # OOB -> dropped
    rows = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
    out = jnp.zeros_like(x)
    out = out.at[rows.reshape(-1), cols.reshape(-1)].set(
        x.reshape(-1), mode="drop")
    return {"Out": out, "OutLength": jnp.sum(keep, axis=1, dtype=jnp.int32)}


@register_op("sequence_enumerate", grad=False)
def sequence_enumerate(ctx, ins, attrs):
    """Sliding win_size id windows, pad_value beyond the valid prefix
    (reference sequence_enumerate_op.h)."""
    x = x_of(ins)
    lengths = _len_of(ins)
    win = int(attrs["win_size"])
    pad_value = attrs.get("pad_value", 0)
    T = x.shape[1]
    t = jnp.arange(T, dtype=jnp.int32)[None, :, None]
    k = jnp.arange(win, dtype=jnp.int32)[None, None, :]
    idx = t + k                                           # [1, T, win]
    g = jnp.take(x, jnp.clip(idx[0], 0, T - 1), axis=1)   # [B, T, win]
    ok = idx < lengths[:, None, None]
    return {"Out": jnp.where(ok, g, jnp.asarray(pad_value, x.dtype))}


@register_op("sequence_expand", infer_shape=False)
def sequence_expand(ctx, ins, attrs):
    """Repeat each row by a per-row count (reference sequence_expand_op.h:
    x's segment i is tiled to match y's ref-level segment i). Masked-dense
    contract: X [B, T, ...] + Length [B] + RepeatTimes [B] int; attr
    out_rows caps the static output batch. Output rows beyond
    sum(RepeatTimes) are zero with OutLength 0."""
    x = x_of(ins)
    lengths = _len_of(ins)
    rep = jnp.reshape(x_of(ins, "RepeatTimes"), (-1,)).astype(jnp.int32)
    out_rows = int(attrs["out_rows"])
    ends = jnp.cumsum(rep)                                 # [B]
    j = jnp.arange(out_rows, dtype=jnp.int32)
    src = jnp.searchsorted(ends, j, side="right")          # row j <- x[src]
    valid = j < ends[-1]
    srcc = jnp.clip(src, 0, x.shape[0] - 1)
    out = jnp.take(x, srcc, axis=0)
    mask = valid.reshape((-1,) + (1,) * (x.ndim - 1))
    out_len = jnp.where(valid, jnp.take(lengths, srcc), 0)
    return {"Out": jnp.where(mask, out, 0), "OutLength": out_len}


@register_op("sequence_scatter")
def sequence_scatter(ctx, ins, attrs):
    """Per-row scatter-add into X (reference sequence_scatter_op.h:
    out[b, ids[b, u]] += updates[b, u] over each Ids segment). Masked-dense:
    X [B, D], Ids [B, U] int, Updates [B, U], UpdLength [B]."""
    x = x_of(ins)
    ids = x_of(ins, "Ids").astype(jnp.int32)
    upd = x_of(ins, "Updates")
    B, U = ids.shape
    ln_in = x_of(ins, "UpdLength")
    ln = (jnp.reshape(ln_in, (-1,)).astype(jnp.int32) if ln_in is not None
          else jnp.full((B,), U, jnp.int32))   # absent: all updates valid
    valid = jnp.arange(U, dtype=jnp.int32)[None, :] < ln[:, None]
    rows = jnp.broadcast_to(jnp.arange(B)[:, None], (B, U))
    cols = jnp.where(valid, ids, x.shape[1])               # OOB -> dropped
    return {"Out": x.at[rows.reshape(-1), cols.reshape(-1)].add(
        jnp.where(valid, upd, 0).reshape(-1), mode="drop")}


@register_op("lod_reset")
def lod_reset(ctx, ins, attrs):
    """Re-segment a batch: keep the data, swap the lengths (reference
    lod_reset_op.h replaces the LoD). New lengths come from input Y or attr
    target_lengths; positions beyond the new length are zeroed to keep the
    masked-dense invariant (padding carries zeros)."""
    x = x_of(ins)
    y = x_of(ins, "Y")
    if y is not None:
        new_len = jnp.reshape(y, (-1,)).astype(jnp.int32)
    else:
        new_len = jnp.asarray(attrs["target_lengths"], jnp.int32)
    mask = _expand(_time_mask(new_len, x.shape[1]), x.ndim)
    return {"Out": jnp.where(mask, x, 0), "OutLength": new_len}


@register_op("shrink_rnn_memory")
def shrink_rnn_memory(ctx, ins, attrs):
    """Keep only rows still alive at RNN step i (reference
    shrink_rnn_memory_op.cc drops finished rows from the batch; the
    masked-dense form keeps the static [B, ...] shape and zeroes rows whose
    sequence ended). X [B, ...], Length [B], attr step."""
    x = x_of(ins)
    lengths = _len_of(ins)
    i = int(attrs.get("step", 0))
    alive = (lengths > i).reshape((-1,) + (1,) * (x.ndim - 1))
    return {"Out": jnp.where(alive, x, 0)}


@register_op("sequence_conv")
def sequence_conv(ctx, ins, attrs):
    """Context-window projection: im2col over time then one matmul
    (reference sequence_conv_op.h builds the same [T, ctx*D] matrix with
    math/context_project.h; here the unfold is gather + one MXU matmul)."""
    x = x_of(ins)                  # [B, T, D]
    filt = x_of(ins, "Filter")     # [ctx*D, M]
    lengths = _len_of(ins)
    start = int(attrs.get("contextStart", 0))
    ctx_len = int(attrs.get("contextLength", 3))
    mask = _time_mask(lengths, x.shape[1])
    xm = jnp.where(mask[..., None], x, 0)
    cols = []
    T = x.shape[1]
    t = jnp.arange(T, dtype=jnp.int32)
    for k in range(ctx_len):
        src = t + start + k
        ok = jnp.logical_and(src >= 0, src < T)[None, :, None]
        g = jnp.take(xm, jnp.clip(src, 0, T - 1), axis=1)
        cols.append(jnp.where(ok, g, 0))
    unfolded = jnp.concatenate(cols, axis=-1)             # [B, T, ctx*D]
    out = unfolded @ filt                                 # [B, T, M]
    return {"Out": jnp.where(mask[..., None], out, 0)}


@register_op("sequence_reshape")
def sequence_reshape(ctx, ins, attrs):
    """Change the token width D -> new_dim; row lengths rescale by D/new_dim
    (reference sequence_reshape_op.h)."""
    x = x_of(ins)                  # [B, T, D]
    lengths = _len_of(ins)
    new_dim = int(attrs["new_dim"])
    B, T, D = x.shape
    if (T * D) % new_dim:
        raise ValueError(f"T*D={T*D} not divisible by new_dim={new_dim}")
    out = x.reshape(B, (T * D) // new_dim, new_dim)
    new_len = (lengths * D) // new_dim
    return {"Out": out, "OutLength": new_len}


@register_op("sequence_topk_avg_pooling", infer_shape=False)
def sequence_topk_avg_pooling(ctx, ins, attrs):
    """reference sequence_ops/sequence_topk_avg_pooling_op.h (text
    matching): X is a per-pair match-matrix stack; for every (row,
    channel) take the top-k column values and emit the running-average
    at each k in `topks`. Padded form: X [B, C, R, Cmax] with ROW [B] /
    COLUMN [B] valid sizes. Out [B, R, C * len(topks)] (reference row
    layout: channel-major per row), pos [B, R, C, max_k] top indices
    (-1 where fewer than k valid columns)."""
    x = x_of(ins)
    rows = jnp.reshape(x_of(ins, "ROW"), (-1,)).astype(jnp.int32)
    cols = jnp.reshape(x_of(ins, "COLUMN"), (-1,)).astype(jnp.int32)
    topks = [int(k) for k in attrs["topks"]]
    max_k = topks[-1]
    B, C, R, Cm = x.shape

    def one(xb, nrow, ncol):
        valid_c = jnp.arange(Cm) < ncol                  # [Cm]
        masked = jnp.where(valid_c[None, None, :], xb, -jnp.inf)
        top_v, top_i = jax.lax.top_k(masked, min(max_k, Cm))  # [C,R,k]
        k_live = jnp.arange(top_v.shape[-1]) < ncol
        pos = jnp.where(k_live[None, None, :] , top_i, -1)
        vals = jnp.where(k_live[None, None, :], top_v, 0.0)
        csum = jnp.cumsum(vals, axis=-1)                 # [C, R, k]
        outs = []
        for k in topks:
            kk = min(k, csum.shape[-1])
            outs.append(csum[..., kk - 1] / k)           # [C, R]
        out = jnp.stack(outs, axis=-1)                   # [C, R, k_num]
        out = jnp.transpose(out, (1, 0, 2)).reshape(R, -1)
        row_live = (jnp.arange(R) < nrow)[:, None]
        if pos.shape[-1] < max_k:
            pos = jnp.pad(pos, ((0, 0), (0, 0),
                                (0, max_k - pos.shape[-1])),
                          constant_values=-1)
        return (jnp.where(row_live, out, 0.0),
                jnp.transpose(pos, (1, 0, 2)))           # [R, C, max_k]

    out, pos = jax.vmap(one)(x, rows, cols)
    return {"Out": out, "pos": pos.astype(jnp.int32)}


# ------------------------------------------------------- DynamicRNN support
# (reference lod_rank_table_op.cc / max_sequence_len_op.cc /
# reorder_lod_tensor_by_rank_op.cc / rnn_memory_helper_op.cc — the LoD
# machinery behind DynamicRNN decoders. Masked-dense form: the rank table
# is a descending-stable argsort of the Length vector; "reorder by rank"
# is a row gather; memory helper is the identity whose grad zero-fills.)

@register_op("lod_rank_table", grad=False, infer_shape=False)
def lod_rank_table(ctx, ins, attrs):
    """Index + length of each sequence, sorted by length DESCENDING with
    original order preserved among equals (reference
    framework/lod_rank_table.cc Reset — std::stable_sort). Out: Index
    [B] int64 (original row of rank r), Length [B] int64 (sorted)."""
    lengths = _len_of(ins)
    # jnp.argsort is STABLE (lowers to sort with is_stable=True), so
    # sorting on -length alone preserves original order among equals
    order = jnp.argsort(-lengths)
    out_idx = order.astype(int64_t())
    return {"Index": out_idx,
            "Length": lengths[order].astype(int64_t())}


@register_op("max_sequence_len", grad=False, infer_shape=False)
def max_sequence_len(ctx, ins, attrs):
    """reference max_sequence_len_op.cc: longest sequence in the batch
    (reads the rank table's first entry; here max of Length)."""
    lengths = _len_of(ins)
    return {"Out": jnp.max(lengths).astype(int64_t()).reshape(1)}


@register_op("reorder_lod_tensor_by_rank", infer_shape=False)
def reorder_lod_tensor_by_rank(ctx, ins, attrs):
    """reference reorder_lod_tensor_by_rank_op.cc: permute batch rows by
    the rank table (X [B, ...], RankTable Index [B])."""
    x = x_of(ins)
    idx = jnp.reshape(x_of(ins, "RankTable"), (-1,)).astype(jnp.int32)
    return {"Out": x[idx]}


@register_op("rnn_memory_helper", infer_shape=False)
def rnn_memory_helper(ctx, ins, attrs):
    """reference rnn_memory_helper_op.cc: identity used to thread RNN
    memory through blocks; its grad zero-fills where upstream is
    absent (the generic vjp provides exactly that)."""
    return {"Out": x_of(ins)}
