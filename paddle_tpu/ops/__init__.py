"""Op library: importing this package registers every op lowering."""
from . import (  # noqa: F401
    tensor_ops,
    math_ops,
    activation_ops,
    nn_ops,
    optimizer_ops,
    metric_ops,
    collective_ops,
    control_flow_ops,
    sequence_ops,
    pipeline_ops,
    distributed_ops,
    quantize_ops,
    detection_ops,
    moe_ops,
    ring_attention_ops,
    extra_ops,
)
