"""Tensor creation / manipulation ops.

TPU-native lowerings for the reference's fill/random/shape-manipulation
operators (/root/reference/paddle/fluid/operators/fill_constant_op.cc,
uniform_random_op.cc, gaussian_random_op.cc, reshape_op.cc, transpose_op.cc,
concat_op.cc, split_op.cc, ...). RNG ops draw deterministic per-op keys from
the run key (see framework/lowering.LowerCtx.op_key) so forward and
vjp-recomputed backward see identical randomness.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import register_op
from ..framework.dtype import np_dtype
from .common import as_dtype, int64_t, x_of


def _resolve_shape_tensors(ins, attrs):
    """Merge ShapeTensorList input dims into the attr shape (reference
    reshape_op.cc / fill_constant_op.cc ShapeTensor[List] semantics).
    The tensor dims concretize here: shape-op outputs are trace-time
    constants under jit (a tensor's shape is static metadata), so
    `int()` succeeds; a dim computed from DATA is a genuine dynamic
    shape, which XLA cannot compile — rejected with an actionable
    error."""
    shape = list(attrs.get("shape", []))
    tl = ins.get("ShapeTensorList")
    if tl:
        pos = attrs.get("shape_tensor_positions")
        if pos is None:
            pos = list(range(len(tl)))
        for p, tv in zip(pos, tl):
            try:
                shape[int(p)] = int(np.asarray(tv).reshape(-1)[0])
            except jax.errors.TracerArrayConversionError:
                raise ValueError(
                    "a tensor dim in this op's shape depends on DATA, "
                    "not on input shapes; XLA programs have static "
                    "shapes — derive dims from `x.shape` / "
                    "layers.shape(x) (trace-time constants) or pass "
                    "python ints") from None
    return shape


@register_op("fill_constant", grad=False)
def fill_constant(ctx, ins, attrs):
    shape = tuple(int(s) for s in _resolve_shape_tensors(ins, attrs))
    dt = as_dtype(attrs)
    if int(np.prod(shape)) <= 16 and np.issubdtype(np.dtype(dt),
                                                   np.integer):
        # small INTEGER constants stay host-resident (numpy) so scalar
        # chains — e.g. the promoted `2` in `x.shape[0] * 2` — keep
        # shape arithmetic concrete (common.host_concrete); XLA treats
        # either form as a literal. Float constants (eps, lr) stay on
        # the jnp path so their arithmetic keeps device semantics.
        return {"Out": np.full(shape, attrs.get("value", 0.0),
                               dtype=dt)}
    return {"Out": jnp.full(shape, attrs.get("value", 0.0), dtype=dt)}


@register_op("fill_constant_batch_size_like", grad=False)
def fill_constant_batch_size_like(ctx, ins, attrs):
    ref = x_of(ins, "Input")
    shape = list(attrs["shape"])
    in_idx = attrs.get("input_dim_idx", 0)
    out_idx = attrs.get("output_dim_idx", 0)
    shape[out_idx] = ref.shape[in_idx]
    dt = as_dtype(attrs)
    return {"Out": jnp.full(tuple(shape), attrs.get("value", 0.0), dtype=dt)}


@register_op("fill_zeros_like", grad=False)
def fill_zeros_like(ctx, ins, attrs):
    x = x_of(ins)
    return {"Out": jnp.zeros_like(x)}


@register_op("fill_any_like", grad=False)
def fill_any_like(ctx, ins, attrs):
    x = x_of(ins)
    dt = np_dtype(attrs["dtype"]) if attrs.get("dtype") else x.dtype
    return {"Out": jnp.full(x.shape, attrs.get("value", 0.0), dtype=dt)}


@register_op("uniform_random", grad=False, needs_rng=True)
def uniform_random(ctx, ins, attrs):
    shape = tuple(int(s) for s in attrs["shape"])
    dt = as_dtype(attrs)
    key = ctx.op_key(attrs)
    return {"Out": jax.random.uniform(
        key, shape, dtype=dt, minval=attrs.get("min", -1.0),
        maxval=attrs.get("max", 1.0))}


@register_op("gaussian_random", grad=False, needs_rng=True)
def gaussian_random(ctx, ins, attrs):
    shape = tuple(int(s) for s in attrs["shape"])
    dt = as_dtype(attrs)
    key = ctx.op_key(attrs)
    out = jax.random.normal(key, shape, dtype=dt)
    return {"Out": out * attrs.get("std", 1.0) + attrs.get("mean", 0.0)}


@register_op("truncated_gaussian_random", grad=False, needs_rng=True)
def truncated_gaussian_random(ctx, ins, attrs):
    shape = tuple(int(s) for s in attrs["shape"])
    dt = as_dtype(attrs)
    key = ctx.op_key(attrs)
    out = jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype=dt)
    return {"Out": out * attrs.get("std", 1.0) + attrs.get("mean", 0.0)}


@register_op("randint", grad=False, needs_rng=True)
def randint(ctx, ins, attrs):
    shape = tuple(int(s) for s in attrs["shape"])
    dt = as_dtype(attrs, default="int64")
    key = ctx.op_key(attrs)
    return {"Out": jax.random.randint(
        key, shape, attrs.get("low", 0), attrs.get("high", 100)).astype(dt)}


@register_op("assign")
def assign(ctx, ins, attrs):
    return {"Out": x_of(ins)}


@register_op("assign_value", grad=False)
def assign_value(ctx, ins, attrs):
    vals = np.asarray(attrs["values"], dtype=np_dtype(attrs["dtype"]))
    shape = attrs.get("shape")
    if shape:
        vals = vals.reshape([int(s) for s in shape])
    return {"Out": jnp.asarray(vals)}


@register_op("cast")
def cast(ctx, ins, attrs):
    x = x_of(ins)
    return {"Out": x.astype(np_dtype(attrs["out_dtype"]))}


@register_op("reshape2")
def reshape2(ctx, ins, attrs):
    x = x_of(ins)
    shape = _resolve_shape_tensors(ins, attrs)
    # fluid semantics: 0 -> copy dim from input; single -1 inferred
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    return {"Out": x.reshape(tuple(shape)),
            "XShape": jnp.zeros((0,) + x.shape, dtype=x.dtype)}


@register_op("reshape")
def reshape(ctx, ins, attrs):
    x = x_of(ins)
    shape = _resolve_shape_tensors(ins, attrs)
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    return {"Out": x.reshape(tuple(shape))}


@register_op("transpose2")
def transpose2(ctx, ins, attrs):
    x = x_of(ins)
    perm = attrs.get("axis", attrs.get("perm"))
    return {"Out": jnp.transpose(x, perm),
            "XShape": jnp.zeros((0,) + x.shape, dtype=x.dtype)}


@register_op("transpose")
def transpose(ctx, ins, attrs):
    x = x_of(ins)
    perm = attrs.get("axis", attrs.get("perm"))
    return {"Out": jnp.transpose(x, perm)}


@register_op("concat")
def concat(ctx, ins, attrs):
    xs = ins["X"]
    return {"Out": jnp.concatenate(xs, axis=attrs.get("axis", 0))}


@register_op("split")
def split(ctx, ins, attrs):
    x = x_of(ins)
    axis = attrs.get("axis", 0)
    num = attrs.get("num", 0)
    sections = attrs.get("sections", [])
    if sections:
        idx = np.cumsum(sections[:-1]).tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": list(outs)}


@register_op("stack")
def stack(ctx, ins, attrs):
    return {"Y": jnp.stack(ins["X"], axis=attrs.get("axis", 0))}


@register_op("unstack")
def unstack(ctx, ins, attrs):
    x = x_of(ins)
    axis = attrs.get("axis", 0)
    n = x.shape[axis]
    outs = [jnp.squeeze(a, axis=axis) for a in jnp.split(x, n, axis=axis)]
    return {"Y": outs}


@register_op("squeeze2")
def squeeze2(ctx, ins, attrs):
    x = x_of(ins)
    axes = attrs.get("axes", [])
    if axes:
        axes = tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)
        out = jnp.squeeze(x, axis=axes) if axes else x
    else:
        out = jnp.squeeze(x)
    return {"Out": out, "XShape": jnp.zeros((0,) + x.shape, dtype=x.dtype)}


@register_op("unsqueeze2")
def unsqueeze2(ctx, ins, attrs):
    x = x_of(ins)
    out = x
    for a in sorted(attrs["axes"]):
        out = jnp.expand_dims(out, axis=a)
    return {"Out": out, "XShape": jnp.zeros((0,) + x.shape, dtype=x.dtype)}


@register_op("flatten2")
def flatten2(ctx, ins, attrs):
    x = x_of(ins)
    axis = attrs.get("axis", 1)
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    out = x.reshape(lead, -1)
    return {"Out": out, "XShape": jnp.zeros((0,) + x.shape, dtype=x.dtype)}


@register_op("flatten_contiguous_range")
def flatten_contiguous_range(ctx, ins, attrs):
    x = x_of(ins)
    start = attrs.get("start_axis", 1) % max(x.ndim, 1)
    stop = attrs.get("stop_axis", -1) % max(x.ndim, 1)
    mid = int(np.prod(x.shape[start:stop + 1]))
    shape = x.shape[:start] + (mid,) + x.shape[stop + 1:]
    return {"Out": x.reshape(shape),
            "XShape": jnp.zeros((0,) + x.shape, dtype=x.dtype)}


@register_op("slice")
def slice_op(ctx, ins, attrs):
    x = x_of(ins, "Input")
    axes = attrs["axes"]
    starts = attrs["starts"]
    ends = attrs["ends"]
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    out = x[tuple(idx)]
    decrease = attrs.get("decrease_axis", [])
    if decrease:
        out = jnp.squeeze(out, axis=tuple(decrease))
    return {"Out": out}


@register_op("strided_slice")
def strided_slice(ctx, ins, attrs):
    x = x_of(ins, "Input")
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(attrs["axes"], attrs["starts"], attrs["ends"],
                           attrs["strides"]):
        idx[a] = slice(s, e, st)
    return {"Out": x[tuple(idx)]}


@register_op("expand")
def expand(ctx, ins, attrs):
    x = x_of(ins)
    times = attrs["expand_times"]
    return {"Out": jnp.tile(x, times)}


@register_op("expand_v2")
def expand_v2(ctx, ins, attrs):
    x = x_of(ins)
    shape = list(attrs["shape"])
    # -1 keeps the input dim
    xshape = (1,) * (len(shape) - x.ndim) + x.shape
    tgt = tuple(xs if s == -1 else s for s, xs in zip(shape, xshape))
    return {"Out": jnp.broadcast_to(x.reshape(xshape), tgt)}


@register_op("expand_as_v2")
def expand_as_v2(ctx, ins, attrs):
    """fluid expand_as TILES x so each target dim is an integer multiple
    of x's dim (reference expand_as_op.cc: expand_times = y_dim/x_dim);
    plain broadcasting is the special case of 1-sized dims."""
    x = x_of(ins)
    shape = attrs.get("target_shape")
    if shape is None:
        # v2 names the target "Y"; fluid 1.x expand_as names it
        # "target_tensor" (reference expand_as_op.cc)
        tgt = ins.get("Y") or ins["target_tensor"]
        shape = tgt[0].shape
    shape = tuple(int(s) for s in shape)
    xshape = (1,) * (len(shape) - x.ndim) + tuple(x.shape)
    if any(t % xs for t, xs in zip(shape, xshape)):
        raise ValueError(
            f"expand_as: target {shape} must be integer multiples of "
            f"input {tuple(x.shape)} per dim")
    reps = tuple(t // xs for t, xs in zip(shape, xshape))
    return {"Out": jnp.tile(x.reshape(xshape), reps)}


@register_op("tile")
def tile(ctx, ins, attrs):
    x = x_of(ins)
    return {"Out": jnp.tile(x, attrs["repeat_times"])}


@register_op("gather")
def gather(ctx, ins, attrs):
    x = x_of(ins)
    index = x_of(ins, "Index")
    axis = attrs.get("axis", 0)
    if index.ndim == 2 and index.shape[1] == 1:
        index = index[:, 0]
    return {"Out": jnp.take(x, index, axis=axis)}


@register_op("gather_nd")
def gather_nd(ctx, ins, attrs):
    x = x_of(ins)
    index = x_of(ins, "Index")
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return {"Out": x[idx]}


@register_op("scatter")
def scatter(ctx, ins, attrs):
    x = x_of(ins)
    ids = x_of(ins, "Ids")
    updates = x_of(ins, "Updates")
    if ids.ndim == 2 and ids.shape[1] == 1:
        ids = ids[:, 0]
    if attrs.get("overwrite", True):
        out = x.at[ids].set(updates)
    else:
        out = x.at[ids].add(updates)
    return {"Out": out}


@register_op("one_hot_v2", grad=False)
def one_hot_v2(ctx, ins, attrs):
    """v2 semantics (one_hot_v2_op.cc:39): the depth axis is APPENDED to
    the input shape — [N, 1] stays [N, 1, depth]."""
    x = x_of(ins)
    depth = attrs["depth"]
    return {"Out": jax.nn.one_hot(x, depth, dtype=np_dtype(
        attrs.get("dtype", "float32")))}


@register_op("one_hot", grad=False)
def one_hot(ctx, ins, attrs):
    """v1 semantics (one_hot_op.cc): a trailing size-1 dim is replaced
    by the depth axis — [N, 1] becomes [N, depth]."""
    x = x_of(ins)
    if x.ndim >= 1 and x.shape[-1] == 1:
        x = x[..., 0]
    return {"Out": jax.nn.one_hot(x, attrs["depth"], dtype=np_dtype(
        attrs.get("dtype", "float32")))}


@register_op("shape", grad=False)
def shape_op(ctx, ins, attrs):
    """Returns NUMPY, deliberately: a tensor's shape is trace-time
    metadata, so downstream scalar arithmetic stays host-concrete (see
    common.host_concrete) and dims derived from it can feed
    ShapeTensorList inputs. jnp.asarray here would stage the constant
    as a tracer and lose the value."""
    x = x_of(ins, "Input")
    return {"Out": np.asarray(x.shape, dtype=np.int32)}


@register_op("range", grad=False)
def range_op(ctx, ins, attrs):
    start = attrs.get("start", 0)
    end = attrs.get("end")
    step = attrs.get("step", 1)
    dt = as_dtype(attrs, default="int64")
    return {"Out": jnp.arange(start, end, step, dtype=dt)}


@register_op("increment")
def increment(ctx, ins, attrs):
    x = x_of(ins)
    return {"Out": x + jnp.asarray(attrs.get("step", 1.0), x.dtype)}


@register_op("cumsum")
def cumsum(ctx, ins, attrs):
    x = x_of(ins)
    axis = attrs.get("axis", -1)
    if attrs.get("flatten", False):
        x = x.reshape(-1)
        axis = 0
    if attrs.get("reverse", False):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
    else:
        out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        out = out - x
    return {"Out": out}


@register_op("where")
def where(ctx, ins, attrs):
    cond = x_of(ins, "Condition")
    return {"Out": jnp.where(cond, x_of(ins), x_of(ins, "Y"))}


@register_op("arg_max", grad=False)
def arg_max(ctx, ins, attrs):
    x = x_of(ins)
    axis = attrs.get("axis", -1)
    out = jnp.argmax(x, axis=axis)
    if attrs.get("keepdims", False):
        out = jnp.expand_dims(out, axis)
    return {"Out": out.astype(as_dtype(attrs, default="int64"))}


@register_op("arg_min", grad=False)
def arg_min(ctx, ins, attrs):
    x = x_of(ins)
    axis = attrs.get("axis", -1)
    out = jnp.argmin(x, axis=axis)
    if attrs.get("keepdims", False):
        out = jnp.expand_dims(out, axis)
    return {"Out": out.astype(as_dtype(attrs, default="int64"))}


@register_op("argsort", grad=False)
def argsort(ctx, ins, attrs):
    x = x_of(ins)
    axis = attrs.get("axis", -1)
    descending = attrs.get("descending", False)
    key = -x if descending else x
    idx = jnp.argsort(key, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": out, "Indices": idx.astype(int64_t())}


@register_op("top_k_v2", grad=False)
def top_k_v2(ctx, ins, attrs):
    x = x_of(ins)
    k = attrs["k"]
    axis = attrs.get("axis", -1) % x.ndim
    moved = jnp.moveaxis(x, axis, -1)
    vals, idx = jax.lax.top_k(moved, k)
    if not attrs.get("largest", True):
        vals, idx = jax.lax.top_k(-moved, k)
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)
    return {"Out": vals, "Indices": idx.astype(int64_t())}


@register_op("top_k", grad=False)
def top_k(ctx, ins, attrs):
    x = x_of(ins)
    vals, idx = jax.lax.top_k(x, attrs["k"])
    return {"Out": vals, "Indices": idx.astype(int64_t())}


@register_op("index_select")
def index_select(ctx, ins, attrs):
    x = x_of(ins)
    index = x_of(ins, "Index")
    return {"Out": jnp.take(x, index, axis=attrs.get("dim", 0))}


@register_op("roll")
def roll(ctx, ins, attrs):
    x = x_of(ins)
    shifts = attrs["shifts"]
    axis = attrs.get("axis", None)
    return {"Out": jnp.roll(x, shifts,
                            axis=tuple(axis) if axis else None)}


@register_op("flip")
def flip(ctx, ins, attrs):
    x = x_of(ins)
    return {"Out": jnp.flip(x, axis=tuple(attrs["axis"]))}


@register_op("pad")
def pad(ctx, ins, attrs):
    x = x_of(ins)
    p = attrs["paddings"]
    widths = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": jnp.pad(x, widths,
                           constant_values=attrs.get("pad_value", 0.0))}


@register_op("pad2d")
def pad2d(ctx, ins, attrs):
    x = x_of(ins)
    p = attrs["paddings"]  # [top, bottom, left, right]
    mode = attrs.get("mode", "constant")
    widths = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if mode == "constant":
        return {"Out": jnp.pad(x, widths,
                               constant_values=attrs.get("pad_value", 0.0))}
    jmode = {"reflect": "reflect", "edge": "edge"}[mode]
    return {"Out": jnp.pad(x, widths, mode=jmode)}


@register_op("meshgrid")
def meshgrid(ctx, ins, attrs):
    outs = jnp.meshgrid(*ins["X"], indexing="ij")
    return {"Out": list(outs)}


@register_op("tril_triu")
def tril_triu(ctx, ins, attrs):
    x = x_of(ins)
    diag = attrs.get("diagonal", 0)
    if attrs.get("lower", True):
        return {"Out": jnp.tril(x, k=diag)}
    return {"Out": jnp.triu(x, k=diag)}


@register_op("diag_v2", grad=False)
def diag_v2(ctx, ins, attrs):
    x = x_of(ins)
    return {"Out": jnp.diag(x, k=attrs.get("offset", 0))}


@register_op("unique", grad=False, infer_shape=False)
def unique(ctx, ins, attrs):
    raise NotImplementedError(
        "unique has data-dependent output shape; on TPU use "
        "paddle_tpu.layers.unique_with_fill (static-shape variant)")


@register_op("print")
def print_op(ctx, ins, attrs):
    x = x_of(ins, "In")
    jax.debug.print(attrs.get("message", "") + " {}", x)
    return {"Out": x}


@register_op("feed", grad=False, infer_shape=False)
def feed(ctx, ins, attrs):
    return None  # executor binds feeds directly into the env


@register_op("fetch", grad=False, infer_shape=False)
def fetch(ctx, ins, attrs):
    return {"Out": x_of(ins)}


@register_op("recompute_barrier", grad=False, infer_shape=False)
def recompute_barrier(ctx, ins, attrs):
    """Identity that XLA may not optimize across: pins recomputed forward
    segments apart from the original forward so CSE can't re-materialize the
    activations that recompute (reference RecomputeOptimizer semantics,
    optimizer.py:3854) is trying to free. Same mechanism jax.checkpoint uses
    on its residuals."""
    xs = tuple(ins["X"])
    outs = jax.lax.optimization_barrier(xs)
    return {"Out": list(outs)}
